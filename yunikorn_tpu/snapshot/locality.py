"""Locality constraints: topology spread + pod (anti-)affinity encoding.

Reference predicates: PodTopologySpread and InterPodAffinity plugins (both in
the reference's reservation and allocation plugin sets,
pkg/plugin/predicates/predicate_manager.go:302-392). These are the
placement-dependent predicates — feasibility depends on where matching pods
already sit — which makes them the hard case for a batched solve (SURVEY.md §7
"hard parts"): pods placed earlier in the same batch change the counts later
pods must respect.

Encoding ("locality groups"):
  L distinct (topologyKey, labelSelector, namespaces) tuples referenced by the
  batch. For each:
    dom   [M]  int32  domain index of every node for that topology key (-1 =
                      node lacks the key)
    cnt0  [D]  int32  matching-pod count per domain from *existing* cluster
                      state (assigned pods in the shim cache)
    valid [D]  bool   domains that exist
  Per batch pod:
    contrib [N, L] bool — placing this pod increments the domain count of L
  Per constraint-group:
    refs [G, S] int32 → locality group index (-1 unused slot)
    kind [G, S] int32   1=spread(DoNotSchedule) 2=affinity 3=anti-affinity
                        (groups whose constraints overflow the encoding take
                        the exact host-evaluation fallback instead — see
                        host_locality_mask)
    skew [G, S] int32   maxSkew for spread slots
    seed [G, S] bool    affinity self-seeding (pod matches its own selector →
                        may start the first domain, K8s semantics)

Symmetric anti-affinity (K8s InterPodAffinity symmetry: an incoming pod may
not land in a domain where an existing pod's *required anti-affinity term*
matches it) is encoded with "holder" locality groups: contrib = pod holds the
term, cnt0 = existing holders per domain; every group whose pods match the
term's selector gets an ANTI slot referencing the holder group. Pod labels
join the constraint-group signature exactly when locality is in play
(locality_signature), so group-level slots are sound.

The solver (ops/assign.py) carries cnt as loop state: every accepted pod
scatter-adds into its domains, and the dynamic feasibility rules are
re-evaluated each round. Soft constraints (ScheduleAnyway spread, preferred
pod (anti-)affinity) ride the same counts as weighted score adjustments
(_loc_soft_scores) — prefer, never require.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.log.logger import log

logger = log("shim.snapshot")

MAX_LOCALITY_GROUPS = 8
MAX_CONSTRAINT_SLOTS = 6

KIND_NONE = 0
KIND_SPREAD = 1
KIND_AFFINITY = 2
KIND_ANTI_AFFINITY = 3
# soft (scoring-only) kinds: ScheduleAnyway spread and
# preferredDuringScheduling pod (anti-)affinity — evaluated from the same
# per-round domain counts as the hard rules, but adjust scores instead of
# feasibility (reference: PodTopologySpread / InterPodAffinity Score plugins,
# predicate_manager.go:302-392 allocation plugin list)
KIND_SOFT_SPREAD = 4
KIND_SOFT_AFFINITY = 5
KIND_SOFT_ANTI = 6
HOSTNAME_KEY = "kubernetes.io/hostname"

# score scale: a 100-weight preferred term contributes 0.25 (matches
# ops.predicates.group_preferred_bonus); soft spread penalizes 0.1 per count
# of imbalance above the minimum domain
SOFT_WEIGHT_SCALE = 0.25 / 100.0
SOFT_SPREAD_PENALTY = -0.1


def match_selector(selector: Optional[dict], labels: Dict[str, str]) -> bool:
    """K8s LabelSelector semantics (matchLabels AND matchExpressions)."""
    if selector is None:
        return False
    for k, v in (selector.get("matchLabels") or {}).items():
        if labels.get(k) != v:
            return False
    for expr in selector.get("matchExpressions") or []:
        key, op = expr.get("key"), expr.get("operator")
        values = expr.get("values") or []
        val = labels.get(key)
        if op == "In":
            if val not in values:
                return False
        elif op == "NotIn":
            if val in values:
                return False
        elif op == "Exists":
            if key not in labels:
                return False
        elif op == "DoesNotExist":
            if key in labels:
                return False
        else:
            return False
    return True


def _selector_signature(selector: Optional[dict]) -> tuple:
    if selector is None:
        return ()
    ml = tuple(sorted((selector.get("matchLabels") or {}).items()))
    me = tuple(
        (e.get("key"), e.get("operator"), tuple(e.get("values") or []))
        for e in (selector.get("matchExpressions") or [])
    )
    return (ml, me)


def _term_namespaces(term, pod: Pod) -> tuple:
    return tuple(sorted(term.namespaces)) if term.namespaces else (pod.namespace,)


@dataclasses.dataclass(frozen=True)
class LocSpec:
    """One locality tuple: where matching pods are counted."""

    topo_key: str
    selector_sig: tuple
    namespaces: tuple
    selector: Optional[dict] = dataclasses.field(compare=False, hash=False, default=None)

    def counts_pod(self, pod: Pod) -> bool:
        return pod.namespace in self.namespaces and match_selector(
            self.selector, pod.metadata.labels)


@dataclasses.dataclass(frozen=True)
class AntiTermSpec(LocSpec):
    """An anti-affinity term identity (for holder groups and symmetry)."""


def _pod_constraints(pod: Pod) -> List[Tuple[int, LocSpec, int]]:
    """Extract (kind, LocSpec, maxSkew) tuples from a pod's own spec."""
    out: List[Tuple[int, LocSpec, int]] = []
    for tsc in pod.spec.topology_spread_constraints:
        if tsc.when_unsatisfiable != "DoNotSchedule":
            continue  # soft constraints not filtered (scoring later)
        out.append((KIND_SPREAD,
                    LocSpec(tsc.topology_key, _selector_signature(tsc.label_selector),
                            (pod.namespace,), tsc.label_selector),
                    tsc.max_skew))
    if pod.spec.affinity is not None:
        for term in pod.spec.affinity.pod_affinity_required:
            out.append((KIND_AFFINITY,
                        LocSpec(term.topology_key or HOSTNAME_KEY,
                                _selector_signature(term.label_selector),
                                _term_namespaces(term, pod), term.label_selector),
                        0))
        for term in pod.spec.affinity.pod_anti_affinity_required:
            out.append((KIND_ANTI_AFFINITY,
                        LocSpec(term.topology_key or HOSTNAME_KEY,
                                _selector_signature(term.label_selector),
                                _term_namespaces(term, pod), term.label_selector),
                        0))
    return out


def _pod_soft_constraints(pod: Pod) -> List[Tuple[int, LocSpec, float]]:
    """(kind, LocSpec, scaled score weight) for the scoring-only constraints:
    ScheduleAnyway topology spread + preferred pod (anti-)affinity."""
    out: List[Tuple[int, LocSpec, float]] = []
    for tsc in pod.spec.topology_spread_constraints:
        if tsc.when_unsatisfiable != "ScheduleAnyway":
            continue
        out.append((KIND_SOFT_SPREAD,
                    LocSpec(tsc.topology_key, _selector_signature(tsc.label_selector),
                            (pod.namespace,), tsc.label_selector),
                    SOFT_SPREAD_PENALTY))
    if pod.spec.affinity is not None:
        for weight, term in pod.spec.affinity.pod_affinity_preferred:
            out.append((KIND_SOFT_AFFINITY,
                        LocSpec(term.topology_key or HOSTNAME_KEY,
                                _selector_signature(term.label_selector),
                                _term_namespaces(term, pod), term.label_selector),
                        float(weight) * SOFT_WEIGHT_SCALE))
        for weight, term in pod.spec.affinity.pod_anti_affinity_preferred:
            out.append((KIND_SOFT_ANTI,
                        LocSpec(term.topology_key or HOSTNAME_KEY,
                                _selector_signature(term.label_selector),
                                _term_namespaces(term, pod), term.label_selector),
                        -float(weight) * SOFT_WEIGHT_SCALE))
    return out


def _pod_anti_terms(pod: Pod) -> List[AntiTermSpec]:
    if pod.spec.affinity is None:
        return []
    return [
        AntiTermSpec(term.topology_key or HOSTNAME_KEY,
                     _selector_signature(term.label_selector),
                     _term_namespaces(term, pod), term.label_selector)
        for term in pod.spec.affinity.pod_anti_affinity_required
    ]


def all_anti_terms(cache) -> List[AntiTermSpec]:
    """Anti-affinity terms held by any pod in the cache (memoized by generation).

    Used for the symmetric check: incoming pods matching such a term must
    avoid domains holding its pods. Includes pending pods so in-batch pairs
    see each other.
    """
    gen = cache.anti_version()
    memo = getattr(cache, "_anti_terms_memo", None)
    if memo is not None and memo[0] == gen:
        return memo[1]
    seen: Dict[AntiTermSpec, None] = {}
    for pod in list(cache.pods_map.values()):
        for t in _pod_anti_terms(pod):
            seen.setdefault(t)
    out = list(seen)
    cache._anti_terms_memo = (gen, out)
    return out


def locality_signature(pod: Pod, cache) -> tuple:
    """The locality part of a pod's constraint-group signature.

    Empty for pods untouched by locality (keeps group dedup compact). When the
    pod has hard locality constraints OR matches an existing anti-affinity
    term (symmetry), the signature includes the pod's full label set so
    group-level locality slots are exact.
    """
    cons = _pod_constraints(pod)
    soft = _pod_soft_constraints(pod)
    matched_terms = tuple(
        (t.topo_key, t.selector_sig, t.namespaces)
        for t in all_anti_terms(cache)
        if t.counts_pod(pod)
    )
    if not cons and not soft and not matched_terms:
        return ()
    cons_sig = tuple((kind, spec.topo_key, spec.selector_sig, spec.namespaces, skew)
                     for kind, spec, skew in cons)
    soft_sig = tuple((kind, spec.topo_key, spec.selector_sig, spec.namespaces, w)
                     for kind, spec, w in soft)
    return (
        tuple(sorted(pod.metadata.labels.items())),
        pod.namespace,
        cons_sig,
        soft_sig,
        matched_terms,
    )


@dataclasses.dataclass
class LocalityBatch:
    """Dense arrays for the solver; None members mean 'no locality work'."""

    dom: np.ndarray          # [L, M] int32
    cnt0: np.ndarray         # [L, D] int32
    dom_valid: np.ndarray    # [L, D] bool
    contrib: np.ndarray      # [N, L] bool
    g_refs: np.ndarray       # [G, S] int32
    g_kind: np.ndarray       # [G, S] int32
    g_skew: np.ndarray       # [G, S] int32
    g_seed: np.ndarray       # [G, S] bool
    g_weight: np.ndarray     # [G, S] f32 scaled score weight (soft slots)
    # [L] int32: for a HOLDER group (contrib = pods holding anti term t), the
    # index of the primary group with the same (topo_key, selector, ns)
    # (contrib = pods MATCHING t), else -1. The solver's accept cap uses this
    # to mutually exclude a holder and a matcher landing in one domain in the
    # same round — illegal in either sequential order (the holder's own anti
    # rule vs the matcher, or the matcher's symmetry rule vs the holder).
    pair: np.ndarray = None  # type: ignore[assignment]
    num_groups: int = 0
    # groups whose constraints overflow the tensor encoding, evaluated exactly
    # on the host instead: gid -> [M] feasibility mask against existing
    # cluster state. The encoder serializes these groups (one pod per solve)
    # so intra-batch interactions cannot violate the constraints.
    fallback: Optional[Dict[int, np.ndarray]] = None
    # soft-constraint score adjustments that spilled out of the slot budget:
    # gid -> [M] float32, statically scored against existing state; the
    # encoder folds these into the batch's g_host_soft channel
    soft_static: Optional[Dict[int, np.ndarray]] = None


class _LocAccum:
    def __init__(self):
        self.keys: Dict[tuple, int] = {}
        self.specs: List[Tuple[LocSpec, bool]] = []  # (spec, is_holder_group)
        self.overflow = False

    def intern(self, spec: LocSpec, holder: bool) -> int:
        sig = (spec.topo_key, spec.selector_sig, spec.namespaces, holder)
        idx = self.keys.get(sig)
        if idx is None:
            if len(self.specs) >= MAX_LOCALITY_GROUPS:
                self.overflow = True
                return -2
            idx = len(self.specs)
            self.keys[sig] = idx
            self.specs.append((spec, holder))
        return idx


def _host_eval_env(cache, node_arrays, extra_placed=None):
    """Shared scaffolding for the host evaluation paths: node rows, placed
    (pod, node-idx) pairs, and a memoized per-topo-key domain-value map.

    extra_placed: optional [(Pod, node_name)] overlay of placements not yet
    visible in the cache (this cycle's committed allocations) — lets the
    fallback drain loop re-evaluate masks against intra-cycle state.
    """
    rows = list(node_arrays._idx_to_name.items())
    placed: List[Tuple[Pod, int]] = []
    for p in cache.pods_map.values():
        node_name = cache.assigned_pods.get(p.uid)
        if node_name is None:
            continue
        n_idx = node_arrays._name_to_idx.get(node_name)
        if n_idx is not None:
            placed.append((p, n_idx))
    if extra_placed:
        in_cache = {p.uid for p, _ in placed}
        for p, node_name in extra_placed:
            if p.uid in in_cache:
                continue  # assume already landed; don't double count
            n_idx = node_arrays._name_to_idx.get(node_name)
            if n_idx is not None:
                placed.append((p, n_idx))
    dom_cache: Dict[str, Dict[int, Optional[str]]] = {}

    def vals_of(topo_key: str) -> Dict[int, Optional[str]]:
        vals = dom_cache.get(topo_key)
        if vals is not None:
            return vals
        vals = {}
        for idx, name in rows:
            info = cache.get_node(name)
            if info is None:
                continue
            v = info.node.metadata.labels.get(topo_key)
            if topo_key == HOSTNAME_KEY and v is None:
                v = name
            vals[idx] = v
        dom_cache[topo_key] = vals
        return vals

    return rows, placed, vals_of


def host_locality_mask(pod: Pod, cache, node_arrays, extra_placed=None) -> np.ndarray:
    """Exact per-pod evaluation of locality constraints on the host.

    Fallback for constraint groups that overflow the tensor encoding
    (> MAX_LOCALITY_GROUPS distinct tuples or > MAX_CONSTRAINT_SLOTS slots):
    the same rules the in-solve _loc_rules_mask applies, evaluated in Python
    against *existing* cluster state — the reference's per-(pod,node) behavior
    (InterPodAffinity / PodTopologySpread filters). Callers must serialize
    such groups (at most one pod per solve) so intra-batch placements cannot
    violate the constraints; the core's fallback drain loop re-solves with an
    extra_placed overlay so an overflowing group costs rounds, not cycles.
    """
    M = node_arrays.capacity
    ok = np.zeros(M, bool)
    rows, placed, vals_of = _host_eval_env(cache, node_arrays, extra_placed)
    for idx, _name in rows:
        ok[idx] = True

    for kind, spec, skew in _pod_constraints(pod):
        vals = vals_of(spec.topo_key)
        counts: Dict[str, int] = {}
        for p, n_idx in placed:
            v = vals.get(n_idx)
            if v is not None and spec.counts_pod(p):
                counts[v] = counts.get(v, 0) + 1
        valid_domains = {v for v in vals.values() if v is not None}
        minc = min((counts.get(v, 0) for v in valid_domains), default=0)
        total = sum(counts.get(v, 0) for v in valid_domains)
        self_add = 1 if (kind == KIND_SPREAD and spec.counts_pod(pod)) else 0
        seed = kind == KIND_AFFINITY and spec.counts_pod(pod)
        eff_skew = max(1, skew) if kind == KIND_SPREAD else 0
        for idx, _name in rows:
            v = vals.get(idx)
            has_dom = v is not None
            cnt_at = counts.get(v, 0) if has_dom else 0
            if kind == KIND_SPREAD:
                good = has_dom and (cnt_at + self_add - minc <= eff_skew)
            elif kind == KIND_AFFINITY:
                good = has_dom and (cnt_at > 0 or (seed and total == 0))
            else:  # KIND_ANTI_AFFINITY
                good = (not has_dom) or cnt_at == 0
            if not good:
                ok[idx] = False

    # symmetry: existing pods' required anti-affinity terms that match this
    # pod block their holders' domains (holding ≠ matching: the primary anti
    # constraints above cannot stand in for this check)
    sym_terms = [t for t in all_anti_terms(cache) if t.counts_pod(pod)]
    if sym_terms:
        placed_terms = [(n_idx, set(_pod_anti_terms(p))) for p, n_idx in placed]
        for t in sym_terms:
            vals = vals_of(t.topo_key)
            holder_domains: set = set()
            for n_idx, terms in placed_terms:
                v = vals.get(n_idx)
                if v is not None and t in terms:
                    holder_domains.add(v)
            if not holder_domains:
                continue
            for idx, _name in rows:
                v = vals.get(idx)
                if v is not None and v in holder_domains:
                    ok[idx] = False
    return ok


def host_locality_soft_scores(pod: Pod, soft_cons, cache, node_arrays,
                              extra_placed=None) -> np.ndarray:
    """[M] float32 score adjustment for soft constraints scored on the host.

    Used when soft slots spill the tensor budget: same rules as the in-solve
    _loc_soft_scores but against *existing* cluster state only (exact for
    scoring the first pod; later pods re-score each cycle as the cache fills).
    Weights arrive pre-scaled (_pod_soft_constraints).
    """
    M = node_arrays.capacity
    scores = np.zeros((M,), np.float32)
    rows, placed, vals_of = _host_eval_env(cache, node_arrays, extra_placed)

    for kind, spec, weight in soft_cons:
        vals = vals_of(spec.topo_key)
        counts: Dict[str, int] = {}
        for p, n_idx in placed:
            v = vals.get(n_idx)
            if v is not None and spec.counts_pod(p):
                counts[v] = counts.get(v, 0) + 1
        valid_domains = {v for v in vals.values() if v is not None}
        minc = min((counts.get(v, 0) for v in valid_domains), default=0)
        self_add = 1 if (kind == KIND_SOFT_SPREAD and spec.counts_pod(pod)) else 0
        for idx, _name in rows:
            v = vals.get(idx)
            if v is None:
                continue
            cnt_at = counts.get(v, 0)
            if kind == KIND_SOFT_SPREAD:
                scores[idx] += weight * max(cnt_at + self_add - minc, 0)
            else:  # SOFT_AFFINITY (+w) / SOFT_ANTI (-w): per matching pod
                scores[idx] += weight * cnt_at
    return scores


def encode_locality(
    asks: Sequence,
    group_ids: Sequence[int],
    num_groups: int,
    node_arrays,
    cache,
    batch_n: int,
    batch_g: int,
    extra_placed=None,
) -> Optional[LocalityBatch]:
    """Build the LocalityBatch for a solve, or None if nothing needs it.

    Groups whose constraints cannot be encoded (slot or group overflow) get
    an exact host-evaluated feasibility mask in .fallback instead — the
    encoder serializes them to one pod per solve so they schedule correctly
    rather than starving; the core drains the rest in intra-cycle rounds
    (extra_placed carries this cycle's commitments into the mask).
    """
    accum = _LocAccum()
    g_refs = np.full((batch_g, MAX_CONSTRAINT_SLOTS), -1, np.int32)
    g_kind = np.zeros((batch_g, MAX_CONSTRAINT_SLOTS), np.int32)
    g_skew = np.zeros((batch_g, MAX_CONSTRAINT_SLOTS), np.int32)
    g_seed = np.zeros((batch_g, MAX_CONSTRAINT_SLOTS), bool)
    g_weight = np.zeros((batch_g, MAX_CONSTRAINT_SLOTS), np.float32)
    soft_static: Dict[int, np.ndarray] = {}
    seen_groups: set = set()
    any_constraint = False
    anti_terms = all_anti_terms(cache)

    fallback: Dict[int, np.ndarray] = {}

    def fall_back(gid: int, pod: Pod, why: str) -> None:
        # Constraints that overflow the tensor encoding are evaluated exactly
        # on the host instead of blocking the group (pods would starve with
        # no feedback); the encoder serializes the group to one pod per solve
        # and the core drains the remainder in intra-cycle fallback rounds.
        logger.info("locality constraints for group %d overflow the tensor "
                    "encoding (%s); falling back to host evaluation "
                    "(serialized to one pod per solve)", gid, why)
        fallback[gid] = host_locality_mask(pod, cache, node_arrays, extra_placed)

    for ask, gid in zip(asks, group_ids):
        if gid in seen_groups or ask.pod is None:
            continue
        seen_groups.add(gid)
        pod = ask.pod
        cons = _pod_constraints(pod)
        soft_cons = _pod_soft_constraints(pod)
        # symmetry: anti terms (held by anyone) whose selector matches this pod
        sym_slots = [t for t in anti_terms if t.counts_pod(pod)]
        if not cons and not soft_cons and not sym_slots:
            continue
        any_constraint = True
        # (l, kind, skew, seed, weight); hard slots carry weight 0
        slots: List[Tuple[int, int, int, bool, float]] = []
        ok = True
        for kind, spec, skew in cons:
            l_idx = accum.intern(spec, holder=False)
            if l_idx < 0:
                ok = False
                break
            seed = kind == KIND_AFFINITY and spec.counts_pod(pod)
            slots.append((l_idx, kind, max(1, skew) if kind == KIND_SPREAD else 0,
                          seed, 0.0))
        if ok:
            for t in sym_slots:
                # NOTE: even when the pod holds t itself, the primary slot is
                # not enough — it blocks domains with pods MATCHING t's
                # selector, while symmetry must block domains with pods
                # HOLDING t (a holder's own labels need not match its term).
                l_idx = accum.intern(t, holder=True)
                if l_idx < 0:
                    ok = False
                    break
                slots.append((l_idx, KIND_ANTI_AFFINITY, 0, False, 0.0))
        if not ok or len(slots) > MAX_CONSTRAINT_SLOTS:
            fall_back(gid, pod, "group or slot overflow")
            if soft_cons:
                soft_static[gid] = host_locality_soft_scores(
                    pod, soft_cons, cache, node_arrays, extra_placed)
            continue
        # soft (scoring) slots fill whatever budget remains; ones that don't
        # fit are scored statically against existing state instead (approximate
        # only w.r.t. this batch's own placements — scoring, not feasibility)
        soft_spill: List[Tuple[int, LocSpec, float]] = []
        for kind, spec, weight in soft_cons:
            if len(slots) >= MAX_CONSTRAINT_SLOTS:
                soft_spill.append((kind, spec, weight))
                continue
            l_idx = accum.intern(spec, holder=False)
            if l_idx < 0:
                soft_spill.append((kind, spec, weight))
                continue
            slots.append((l_idx, kind, 0, False, weight))
        if soft_spill:
            soft_static[gid] = host_locality_soft_scores(
                pod, soft_spill, cache, node_arrays, extra_placed)
        for s, (l, kind, skew, seed, weight) in enumerate(slots):
            g_refs[gid, s] = l
            g_kind[gid, s] = kind
            g_skew[gid, s] = skew
            g_seed[gid, s] = seed
            g_weight[gid, s] = weight
    if not any_constraint:
        return None

    L_pad = MAX_LOCALITY_GROUPS
    M = node_arrays.capacity

    # domains per locality group
    dom = np.full((L_pad, M), -1, np.int32)
    domain_tables: List[Dict[str, int]] = [dict() for _ in range(L_pad)]
    node_rows = [(idx, name) for idx, name in node_arrays._idx_to_name.items()]
    infos = {name: cache.get_node(name) for _, name in node_rows}
    for l, (spec, _holder) in enumerate(accum.specs):
        table = domain_tables[l]
        for idx, name in node_rows:
            info = infos.get(name)
            if info is None:
                continue
            val = info.node.metadata.labels.get(spec.topo_key)
            if spec.topo_key == HOSTNAME_KEY and val is None:
                val = name
            if val is None:
                continue
            d = table.get(val)
            if d is None:
                d = len(table)
                table[val] = d
            dom[l, idx] = d

    D = max(2, max((len(t) for t in domain_tables), default=2))
    Dp = 1
    while Dp < D:
        Dp *= 2
    cnt0 = np.zeros((L_pad, Dp), np.int32)
    dom_valid = np.zeros((L_pad, Dp), bool)
    for l, table in enumerate(domain_tables):
        for d in table.values():
            dom_valid[l, d] = True

    # existing pods per domain (assigned pods in the cache) + this cycle's
    # in-flight placements (committed allocations whose assume has not landed
    # in the cache yet — extra_placed, the locality-count analog of the
    # free/ports overlays: without it a spread/anti decision in cycle N+1
    # cannot see cycle N's still-in-flight pods)
    node_idx_of = node_arrays._name_to_idx
    specs = accum.specs

    def count_assigned(pod, node_name):
        n_idx = node_idx_of.get(node_name)
        if n_idx is None:
            return
        pod_terms = None
        for l, (spec, holder) in enumerate(specs):
            d = dom[l, n_idx]
            if d < 0:
                continue
            if holder:
                if pod_terms is None:
                    pod_terms = set(_pod_anti_terms(pod))
                counts = AntiTermSpec(spec.topo_key, spec.selector_sig,
                                      spec.namespaces, spec.selector) in pod_terms
            else:
                counts = spec.counts_pod(pod)
            if counts:
                cnt0[l, d] += 1

    for pod in list(cache.pods_map.values()):
        node_name = cache.assigned_pods.get(pod.uid)
        if node_name is not None:
            count_assigned(pod, node_name)
    if extra_placed:
        in_cache = cache.assigned_pods
        for pod, node_name in extra_placed:
            if pod.uid in in_cache:
                continue  # assume already landed; don't double count
            count_assigned(pod, node_name)

    # batch-pod contributions
    contrib = np.zeros((batch_n, L_pad), bool)
    for i, ask in enumerate(asks):
        if ask.pod is None:
            continue
        pod_terms = None
        for l, (spec, holder) in enumerate(specs):
            if holder:
                if pod_terms is None:
                    pod_terms = set(_pod_anti_terms(ask.pod))
                contrib[i, l] = AntiTermSpec(spec.topo_key, spec.selector_sig,
                                             spec.namespaces, spec.selector) in pod_terms
            else:
                contrib[i, l] = spec.counts_pod(ask.pod)

    # holder → primary pairing for the same-round mutual exclusion (see the
    # `pair` field docstring)
    pair = np.full((L_pad,), -1, np.int32)
    for l, (spec, holder) in enumerate(accum.specs):
        if holder:
            p = accum.keys.get(
                (spec.topo_key, spec.selector_sig, spec.namespaces, False))
            if p is not None:
                pair[l] = p

    return LocalityBatch(
        dom=dom, cnt0=cnt0, dom_valid=dom_valid, contrib=contrib,
        g_refs=g_refs, g_kind=g_kind, g_skew=g_skew, g_seed=g_seed,
        g_weight=g_weight, pair=pair,
        num_groups=len(accum.specs),
        fallback=fallback or None,
        soft_static=soft_static or None,
    )
