"""Snapshot encoder: incremental cluster state → dense device-ready arrays.

This layer replaces the role the reference's SchedulerCache plays for the
predicate plugins (pkg/cache/external/scheduler_cache.go feeding
pkg/plugin/predicates): instead of handing framework.NodeInfo objects to Go
plugins one (pod,node) pair at a time, it maintains the cluster as dense
host-side numpy buffers that upload to the TPU per solve:

  node arrays  free[M,R] f32, labels[M,W] u32, taints_hard[M,Wt] u32,
               taints_soft[M,Wt] u32, ports[M,Wp] u32, schedulable[M] bool,
               valid[M] bool
  pod batches  req[N,R] f32, group_id[N] i32, rank[N] f32, valid[N] bool
  constraint groups (deduped by signature — a deployment's pods share one):
               req/forb bitsets [G,T,W], any-of bitsets [G,T,E,W],
               tolerations [G,Wt], ports [G,Wp], host_mask [G,M]

Symbolic predicates (selectors, affinity expressions, tolerations) become
bitset tests via snapshot/vocab.py. Expressions that cannot be tensorized
(Gt/Lt) are evaluated per-group on the host into `host_mask` — still O(G·M)
vectorized numpy, never per-pod.

Incrementality: node rows are re-encoded only for nodes the SchedulerCache
marked dirty; groups are re-encoded only when the taint vocab grew (Exists
tolerations are expanded against the taint vocab at encode time).
"""
from __future__ import annotations

import dataclasses
import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from yunikorn_tpu.cache.external.scheduler_cache import NodeInfo, SchedulerCache
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Affinity, Node, Pod, Toleration
from yunikorn_tpu.common.resource import Resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.snapshot.vocab import (
    BitVocab,
    Vocabs,
    label_bit,
    label_key_bit,
    port_bit,
    taint_bit,
)

logger = log("shim.snapshot")

MAX_TERMS = 8        # OR-terms per group (nodeSelector + affinity terms)
MAX_ANYOF = 8        # multi-value In expressions per term
MAX_PREF_TERMS = 4   # preferredDuringScheduling terms per group (scoring)

# victim-table padding priority: bigger than any real pod priority (K8s
# priorities are int32), so padded slots never look preemptable on device
VICTIM_PRIO_PAD = 2**30


from yunikorn_tpu.snapshot.vocab import _next_pow2 as _bucket


# device-mirror array names (single source: NodeArrays dirty marking and
# DeviceNodeState uploads must agree, or a stale array is served as "clean").
# "topo" is the [M, 3] interned (slice, rack, ici-domain) coordinate tensor
# (topology/model.py): tiny, and it changes only when node OBJECTS change,
# so mirroring it as its own field costs a 12-byte-per-node upload on label
# churn and nothing on pod churn.
DEVICE_FIELDS = ("free_i", "cap_i", "labels", "taints_hard", "taints_soft",
                 "ports", "node_ok", "topo")

# victim-table mirror (the batched preemption planner's node-side state).
# Maintained lazily — sync_victims runs only on preemption-pressure cycles —
# and uploaded as its own field group so allocation-path refreshes never pay
# for it. One encode writes a node's whole table, so the group is
# dirty-tracked as a unit rather than per field. victim_app (the interned
# app/gang id column) stays HOST-side only: no kernel consumes it yet, so
# uploading it would be dead bytes on the pressure path.
VICTIM_FIELDS = ("victim_req", "victim_prio", "victim_valid")


def _set_bit(arr: np.ndarray, bit: int) -> None:
    arr[bit // 32] |= np.uint32(1 << (bit % 32))


def _term_needs_host(term) -> bool:
    """Would tensor-encoding this node-affinity term require per-expression
    host fallback (only sound when the term stands alone)?"""
    multi_in = 0
    for e in term.match_expressions:
        if e.operator == "In" and len(e.values) > 1:
            multi_in += 1
            if multi_in > MAX_ANYOF:
                return True
        elif e.operator in ("Gt", "Lt"):
            return True
    for e in term.match_fields:
        if e.key == "metadata.name" and e.operator == "In" and len(e.values) > 1:
            return True
    return False


def _node_matches_term(term, labels: Dict[str, str], node_name: str) -> bool:
    """Full K8s NodeSelectorTerm semantics for one node (host path).

    Mirrors the in-tree NodeAffinity filter: all matchExpressions and
    matchFields must hold; NotIn/DoesNotExist match when the key is absent."""
    for e in term.match_expressions:
        v = labels.get(e.key)
        if v is None and e.key == "kubernetes.io/hostname":
            v = node_name
        op = e.operator
        if op == "In":
            if v is None or v not in e.values:
                return False
        elif op == "NotIn":
            if v is not None and v in e.values:
                return False
        elif op == "Exists":
            if v is None:
                return False
        elif op == "DoesNotExist":
            if v is not None:
                return False
        elif op in ("Gt", "Lt"):
            try:
                iv, tv = int(v), int(e.values[0])
            except (TypeError, ValueError, IndexError):
                return False
            if op == "Gt" and not iv > tv:
                return False
            if op == "Lt" and not iv < tv:
                return False
        else:
            return False  # unknown operator: never matches (K8s errors out)
    for e in term.match_fields:
        if e.key != "metadata.name":
            return False
        if e.operator == "In":
            if node_name not in e.values:
                return False
        elif e.operator == "NotIn":
            if node_name in e.values:
                return False
        else:
            return False
    return True




@dataclasses.dataclass
class GroupSpec:
    """Decoded constraint signature for one group."""

    term_req: np.ndarray       # [T, W] u32
    term_forb: np.ndarray      # [T, W] u32
    term_valid: np.ndarray     # [T] bool
    anyof: np.ndarray          # [T, E, W] u32
    anyof_valid: np.ndarray    # [T, E] bool
    tolerations: np.ndarray    # [Wt] u32
    ports: np.ndarray          # [Wp] u32
    needs_host_eval: bool
    host_exprs: List[Tuple[str, str, str]]  # (key, op, value) Gt/Lt expressions
    taint_vocab_version: int
    pref_req: Optional[np.ndarray] = None    # [P, W] u32 preferred-term bits
    pref_forb: Optional[np.ndarray] = None   # [P, W] u32
    pref_weight: Optional[np.ndarray] = None # [P] f32 (0 = unused slot)
    # full required node-affinity term list, host-evaluated with exact OR
    # semantics when the tensor encoding can't express it (> MAX_TERMS terms,
    # or per-expression fallback needed inside a multi-term OR — ANDing a
    # per-expression host mask would wrongly constrain the other terms)
    host_affinity_terms: Optional[list] = None
    # preferred terms host-scored exactly (multi-value In / slot overflow)
    host_pref_terms: Optional[list] = None   # [(weight, term)]
    # DRA: (namespace, (claim names...)) — feasibility restricted to nodes
    # satisfying every claim (reference gates a DRA manager, context.go:116-130)
    claims: Optional[Tuple[str, tuple]] = None
    # volumes: (namespace, (pvc names...)) — nodes restricted by PV node
    # affinity / static matchability (vectorized FindPodVolumes; the
    # reference runs the volumebinding PreFilter inside the Predicates upcall)
    volumes: Optional[Tuple[str, tuple]] = None


@dataclasses.dataclass
class PodBatch:
    """One solve batch: everything the assignment kernel needs for N pods."""

    ask_keys: List[str]             # ask index -> allocation key (unpadded length)
    req: np.ndarray                 # [N, R] f32
    group_id: np.ndarray            # [N] i32
    rank: np.ndarray                # [N] f32 (lower = scheduled first)
    valid: np.ndarray               # [N] bool
    queue_id: np.ndarray            # [N] i32 (leaf queue index; -1 = no quota)
    # group tensors
    g_term_req: np.ndarray          # [G, T, W]
    g_term_forb: np.ndarray         # [G, T, W]
    g_term_valid: np.ndarray        # [G, T]
    g_anyof: np.ndarray             # [G, T, E, W]
    g_anyof_valid: np.ndarray       # [G, T, E]
    g_tol: np.ndarray               # [G, Wt]
    g_ports: np.ndarray             # [G, Wp]
    g_pref_req: np.ndarray          # [G, P, W] preferred-affinity bits
    g_pref_forb: np.ndarray         # [G, P, W]
    g_pref_weight: np.ndarray       # [G, P] f32
    g_host_mask: Optional[np.ndarray]  # [G, M] bool or None
    g_host_soft: Optional[np.ndarray]  # [G, M] f32 host-scored soft terms or None
    locality: Optional[object]         # snapshot.locality.LocalityBatch or None
    num_pods: int
    num_groups: int
    # ask indices parked by locality-fallback serialization ONLY (their host
    # mask can't see intra-batch placements); the core's fallback drain loop
    # re-solves these same-cycle with an extra_placed overlay. Pods parked
    # for DRA class serialization are NOT here — re-solving them before the
    # shim pins device allocations would race one inventory.
    deferred: List[int] = dataclasses.field(default_factory=list)
    # pre-locality host mask/soft (copies taken before the locality fold) +
    # per-group DRA claims: everything refresh_batch needs to re-fold the
    # placement-dependent state against a newer extra_placed overlay without
    # re-encoding groups (the pipelined core's dispatch-time delta replay)
    base_host_mask: Optional[np.ndarray] = None
    base_host_soft: Optional[np.ndarray] = None
    g_claims: List[Optional[tuple]] = dataclasses.field(default_factory=list)
    # False when the batch reads state the memo key cannot see (PVC/PV/
    # StorageClass/DRA object stores don't bump cache.generation): such a
    # batch must never be served from build_batch_cached's memo
    cacheable: bool = True
    # [G] bool: the group's constraints exceed what the device preemption
    # planner models (host-evaluated expressions, OR-affinity fallback, host
    # ports, DRA claims, volume restrictions) — asks in such groups take the
    # exact host planner instead
    g_preempt_host: Optional[np.ndarray] = None
    # [N, R] int32 DEVICE-resident req tensor (DeviceRowStore gather) —
    # attached by the core when the device gate+encode pipeline is on;
    # prepare_solve_args prefers it over re-uploading req when the solve
    # takes the persistent-device-state path. Values are pinned identical
    # to req.astype(int32). None = host req only.
    req_device: Optional[object] = None
    # topology steering (topology/score.TopoArgs), attached per cycle by
    # the core when solver.topology resolves on — prepare_solve_args folds
    # it into the solve args (refined group ids + the topo tuple). None =
    # the exact pre-topology program (the bit-identical-off contract).
    # Scope-gated by the core: never set on locality or host-port batches.
    topo: Optional[object] = None

    @property
    def placement_dependent(self) -> bool:
        """True when any encoded state depends on placements (locality
        counts, fallback masks, DRA class serialization): the pipelined
        dispatch must re-fold it when placements landed since encode."""
        return self.locality is not None or any(c is not None
                                                for c in self.g_claims)


class NodeArrays:
    """Incrementally maintained dense node-side state."""

    def __init__(self, vocabs: Vocabs, min_capacity: int = 128):
        from yunikorn_tpu.ops.preempt import MAX_VICTIMS_PER_NODE

        self.vocabs = vocabs
        self.capacity = min_capacity
        self._name_to_idx: Dict[str, int] = {}
        self._idx_to_name: Dict[int, str] = {}
        self._free_rows: List[int] = list(range(min_capacity))
        self._R = vocabs.resources.num_slots
        self._W = vocabs.labels.num_words
        self._Wt = vocabs.taints.num_words
        self._Wp = vocabs.ports.num_words
        self.victim_slots = MAX_VICTIMS_PER_NODE
        self._alloc_arrays()
        self.version = 0

    def _alloc_arrays(self) -> None:
        m = self.capacity
        self.free = np.zeros((m, self._R), np.float32)
        self.capacity_arr = np.zeros((m, self._R), np.float32)
        self.labels = np.zeros((m, self._W), np.uint32)
        self.taints_hard = np.zeros((m, self._Wt), np.uint32)
        self.taints_soft = np.zeros((m, self._Wt), np.uint32)
        self.ports = np.zeros((m, self._Wp), np.uint32)
        self.schedulable = np.zeros((m,), bool)
        self.valid = np.zeros((m,), bool)
        # fleet topology coordinates (topology/model.py): interned
        # (slice, rack, ici-domain) ids per node, -1 = unlabeled. The ICI
        # domain (col 2) is the contention/contiguity unit the solver
        # steers on; interning maps survive re-allocation like the other
        # symbol registries.
        self.topo = np.full((m, 3), -1, np.int32)
        self._topo_slice_ids: Dict[str, int] = getattr(
            self, "_topo_slice_ids", {})
        self._topo_rack_ids: Dict[str, int] = getattr(
            self, "_topo_rack_ids", {})
        self._topo_ici_ids: Dict[tuple, int] = getattr(
            self, "_topo_ici_ids", {})
        # per-node victim tables for the batched preemption planner:
        # MAX_VICTIMS_PER_NODE rows per node in eviction order (priority asc,
        # newest first — ops.preempt.victim_table is the single source of the
        # ordering). victim_prio pads with VICTIM_PRIO_PAD so empty slots
        # never pass the `< ask priority` eligibility test on device.
        V = self.victim_slots
        self.victim_req = np.zeros((m, V, self._R), np.int32)
        self.victim_prio = np.full((m, V), VICTIM_PRIO_PAD, np.int32)
        self.victim_valid = np.zeros((m, V), bool)
        self.victim_app = np.full((m, V), -1, np.int32)
        # row -> tuple of victim uids in table order (host-side identity for
        # turning a device-chosen (node, slot-prefix) back into releases)
        self.victim_uids: Dict[int, tuple] = getattr(self, "victim_uids", {})
        self.victim_version = getattr(self, "victim_version", 0)
        self._victim_dirty: bool = True
        # live nodes carrying PreferNoSchedule taints (gates the fused Pallas
        # kernel without scanning the padded arrays per solve)
        self._soft_taint_rows: set = getattr(self, "_soft_taint_rows", set())
        # delta tracking for the device-resident mirror (DeviceNodeState):
        # which device arrays are stale since the last take — pod churn only
        # touches free/ports, so the big rarely-changing symbol arrays
        # (labels/taints) and capacities skip the per-cycle upload. A shape
        # change (capacity growth, vocab repad) forces a full re-upload.
        self._dirty_fields: set = getattr(self, "_dirty_fields", set())
        self._full_dirty: bool = True

    def ensure_padding(self) -> None:
        """Repad arrays after external vocab growth (e.g. during group encode)."""
        self._maybe_grow()

    def _maybe_grow(self) -> None:
        grew = False
        if not self._free_rows:
            old = self.capacity
            self.capacity *= 2
            for arr_name in ("free", "capacity_arr", "labels", "taints_hard",
                             "taints_soft", "ports", "victim_req"):
                arr = getattr(self, arr_name)
                new = np.zeros((self.capacity,) + arr.shape[1:], arr.dtype)
                new[:old] = arr
                setattr(self, arr_name, new)
            for arr_name, fill in (("victim_prio", VICTIM_PRIO_PAD),
                                   ("victim_app", -1), ("topo", -1)):
                arr = getattr(self, arr_name)
                new = np.full((self.capacity,) + arr.shape[1:], fill, arr.dtype)
                new[:old] = arr
                setattr(self, arr_name, new)
            for arr_name in ("schedulable", "valid"):
                arr = getattr(self, arr_name)
                new = np.zeros((self.capacity,), arr.dtype)
                new[:old] = arr
                setattr(self, arr_name, new)
            vv = np.zeros((self.capacity,) + self.victim_valid.shape[1:], bool)
            vv[:old] = self.victim_valid
            self.victim_valid = vv
            self._free_rows = list(range(old, self.capacity))
            grew = True
        # vocab growth: re-pad the bitset/resource dims
        R, W = self.vocabs.resources.num_slots, self.vocabs.labels.num_words
        Wt, Wp = self.vocabs.taints.num_words, self.vocabs.ports.num_words
        if (R, W, Wt, Wp) != (self._R, self._W, self._Wt, self._Wp):
            def repad(arr, dim):
                if arr.shape[1] == dim:
                    return arr
                new = np.zeros((arr.shape[0], dim), arr.dtype)
                new[:, : arr.shape[1]] = arr
                return new

            self.free = repad(self.free, R)
            self.capacity_arr = repad(self.capacity_arr, R)
            self.labels = repad(self.labels, W)
            self.taints_hard = repad(self.taints_hard, Wt)
            self.taints_soft = repad(self.taints_soft, Wt)
            self.ports = repad(self.ports, Wp)
            if self.victim_req.shape[2] != R:
                new = np.zeros((self.victim_req.shape[0],
                                self.victim_req.shape[1], R), np.int32)
                new[:, :, : self.victim_req.shape[2]] = self.victim_req
                self.victim_req = new
            self._R, self._W, self._Wt, self._Wp = R, W, Wt, Wp
            grew = True
        if grew:
            self.version += 1
            self._full_dirty = True
            self._victim_dirty = True

    def index_of(self, name: str) -> Optional[int]:
        return self._name_to_idx.get(name)

    def name_of(self, idx: int) -> Optional[str]:
        return self._idx_to_name.get(idx)

    def encode_node(self, info: NodeInfo, schedulable: bool = True) -> int:
        """(Re-)encode one node row. Returns the row index."""
        rv = self.vocabs.resources
        # Intern all symbols first (may grow vocabs → repad before writing).
        node = info.node
        res_slots = [(rv.slot(name), value / rv.scale(name))
                     for name, value in info.available().resources.items()]
        cap_slots = [(rv.slot(name), value / rv.scale(name))
                     for name, value in info.allocatable.resources.items()]
        label_bits: List[int] = []
        for k, v in node.metadata.labels.items():
            label_bits.append(self.vocabs.labels.bit(label_bit(k, v)))
            label_bits.append(self.vocabs.labels.bit(label_key_bit(k)))
        # the node name is matchable via the well-known hostname label
        label_bits.append(self.vocabs.labels.bit(label_bit("kubernetes.io/hostname", node.name)))
        label_bits.append(self.vocabs.labels.bit(label_key_bit("kubernetes.io/hostname")))
        hard_bits: List[int] = []
        soft_bits: List[int] = []
        for t in node.spec.taints:
            b = self.vocabs.taints.bit(taint_bit(t.key, t.value, t.effect))
            if t.effect == constants.TAINT_EFFECT_PREFER_NO_SCHEDULE:
                soft_bits.append(b)
            else:
                hard_bits.append(b)
        port_bits: List[int] = []
        for pod in info.pods.values():
            for c in pod.spec.containers:
                for p in c.ports:
                    hp = p.get("hostPort")
                    if hp:
                        port_bits.append(self.vocabs.ports.bit(port_bit(p.get("protocol", "TCP"), hp)))
        # topology coordinates (topology/model.py): intern the slice/rack/
        # ici-domain label values; nodes without topology labels keep -1
        from yunikorn_tpu.topology.model import parse_topology_labels

        sl, rack, ici = parse_topology_labels(node.metadata.labels)
        topo_row = (
            self._intern(self._topo_slice_ids, sl),
            self._intern(self._topo_rack_ids, rack),
            self._intern(self._topo_ici_ids, ici),
        )

        self._maybe_grow()
        idx = self._name_to_idx.get(node.name)
        if idx is None:
            idx = self._free_rows.pop(0)
            self._name_to_idx[node.name] = idx
            self._idx_to_name[idx] = node.name

        self.free[idx] = 0.0
        for slot, val in res_slots:
            self.free[idx, slot] = val
        self.capacity_arr[idx] = 0.0
        for slot, val in cap_slots:
            self.capacity_arr[idx, slot] = val
        self.labels[idx] = 0
        for b in label_bits:
            _set_bit(self.labels[idx], b)
        self.taints_hard[idx] = 0
        for b in hard_bits:
            _set_bit(self.taints_hard[idx], b)
        self.taints_soft[idx] = 0
        for b in soft_bits:
            _set_bit(self.taints_soft[idx], b)
        if soft_bits:
            self._soft_taint_rows.add(idx)
        else:
            self._soft_taint_rows.discard(idx)
        self.ports[idx] = 0
        for b in port_bits:
            _set_bit(self.ports[idx], b)
        self.schedulable[idx] = schedulable and not node.spec.unschedulable
        self.valid[idx] = True
        self.topo[idx] = topo_row
        self.version += 1
        self._dirty_fields |= set(DEVICE_FIELDS)
        return idx

    @staticmethod
    def _intern(registry: Dict, key) -> int:
        if key is None:
            return -1
        v = registry.get(key)
        if v is None:
            v = registry[key] = len(registry)
        return v

    @property
    def num_ici_domains(self) -> int:
        """Distinct interned ICI domains ever seen (ids are dense, so this
        is also the [D] aggregate-array length the topology scorer sizes)."""
        return len(self._topo_ici_ids)

    @property
    def has_topology(self) -> bool:
        """Any live node carries an ICI-domain coordinate (the
        solver.topology=auto resolution input)."""
        return (self.num_ici_domains > 0
                and bool((self.topo[self.valid, 2] >= 0).any()))

    def update_free_row(self, name: str, info: NodeInfo) -> None:
        """Cheap path: refresh only the free-capacity row (pod churn)."""
        idx = self._name_to_idx.get(name)
        if idx is None:
            return
        rv = self.vocabs.resources
        avail = info.available().resources
        slots = [(rv.slot(n), v / rv.scale(n)) for n, v in avail.items()]
        # intern ALL symbols before _maybe_grow so a vocab word-boundary
        # crossing repads the arrays before any bit is written
        port_bits = []
        for pod in info.pods.values():
            for c in pod.spec.containers:
                for p in c.ports:
                    hp = p.get("hostPort")
                    if hp:
                        port_bits.append(self.vocabs.ports.bit(port_bit(p.get("protocol", "TCP"), hp)))
        self._maybe_grow()
        self.free[idx] = 0.0
        for slot, val in slots:
            self.free[idx, slot] = val
        self.ports[idx] = 0
        for b in port_bits:
            _set_bit(self.ports[idx], b)
        self.version += 1
        self._dirty_fields |= {"free_i", "ports"}

    def remove_node(self, name: str) -> None:
        idx = self._name_to_idx.pop(name, None)
        if idx is None:
            return
        self._idx_to_name.pop(idx, None)
        self.valid[idx] = False
        self.schedulable[idx] = False
        self.free[idx] = 0.0
        # clear symbol rows so freed slots never leak stale taints/labels
        self.labels[idx] = 0
        self.taints_hard[idx] = 0
        self.taints_soft[idx] = 0
        self.ports[idx] = 0
        self.topo[idx] = -1
        self._soft_taint_rows.discard(idx)
        self._clear_victim_row(idx)
        self._free_rows.append(idx)
        self.version += 1
        self._dirty_fields |= set(DEVICE_FIELDS)

    def set_schedulable(self, name: str, schedulable: bool) -> None:
        idx = self._name_to_idx.get(name)
        if idx is not None:
            self.schedulable[idx] = schedulable
            self.version += 1
            self._dirty_fields.add("node_ok")

    def _clear_victim_row(self, idx: int) -> None:
        if self.victim_valid[idx].any() or idx in self.victim_uids:
            self.victim_req[idx] = 0
            self.victim_prio[idx] = VICTIM_PRIO_PAD
            self.victim_valid[idx] = False
            self.victim_app[idx] = -1
            self.victim_uids.pop(idx, None)
            self.victim_version += 1
            self._victim_dirty = True

    def encode_victims(self, idx: int, rows, prios, apps, uids) -> None:
        """Write one node's victim table (rows already in eviction order and
        truncated to the slot budget — ops.preempt.victim_table's contract).
        rows: [n, <=R] int32 quantized freed-resource rows."""
        V = self.victim_slots
        n = min(len(uids), V)
        self.victim_req[idx] = 0
        self.victim_prio[idx] = VICTIM_PRIO_PAD
        self.victim_valid[idx] = False
        self.victim_app[idx] = -1
        for j in range(n):
            row = rows[j]
            self.victim_req[idx, j, : row.shape[0]] = row
            self.victim_prio[idx, j] = prios[j]
            self.victim_valid[idx, j] = True
            self.victim_app[idx, j] = apps[j]
        if n:
            self.victim_uids[idx] = tuple(uids[:n])
        else:
            self.victim_uids.pop(idx, None)
        self.victim_version += 1
        self._victim_dirty = True

    def take_victim_dirty(self) -> bool:
        """True when the victim tables changed since the last take (single
        consumer: DeviceNodeState's victim-group refresh)."""
        dirty, self._victim_dirty = self._victim_dirty, False
        return dirty

    def take_device_dirty(self) -> Tuple[bool, set]:
        """(full, fields) delta since the last take, for the device mirror.

        full=True forces a complete re-upload (shape change or first use);
        otherwise `fields` names the stale device arrays. Clears the
        tracker: there is exactly one consumer (the encoder's
        DeviceNodeState)."""
        full, fields = self._full_dirty, self._dirty_fields
        self._full_dirty = False
        self._dirty_fields = set()
        return full, fields

    @property
    def num_nodes(self) -> int:
        return len(self._name_to_idx)


class DeviceNodeState:
    """Persistent device-resident mirror of NodeArrays.

    Holds the solve's chunk-invariant node tensors (int32 free/capacity,
    symbol bitsets, node_ok) as committed JAX arrays so a cycle's solve
    transfers O(what changed), not everything: a clean cycle re-uses the
    previous buffers outright (zero host conversion, zero transfer), and a
    dirty cycle re-uploads only the STALE arrays — pod churn touches just
    free/ports, so the wide label/taint bitsets (the dominant bytes at 10k
    nodes) upload only when a node OBJECT changes. Replaced buffers are new
    arrays (never mutated in place), so a buffer referenced by an in-flight
    async solve stays valid — the pipelined cycle refreshes for solve N+1
    while solve N still runs.

    Field-level granularity is deliberate: a row-scatter (`at[idx].set`)
    would transfer less, but XLA specializes the scatter program on the
    index length — measured ~0.5 s compile per distinct dirty-row count on
    CPU, dwarfing the bytes it saved. Whole-array uploads are compile-free
    and O(ms) even at the 16k-row bucket.

    Never constructed at import/scheduler-construction time: creating one
    initializes the JAX backend, so the encoder builds it lazily at the
    first solve (the same point the runtime gates resolve).
    """

    FIELDS = DEVICE_FIELDS

    def __init__(self, nodes: NodeArrays):
        self.nodes = nodes
        self._arrays: Optional[dict] = None
        self._dims: Optional[tuple] = None
        self._mesh = None
        # victim-table mirror (refresh_victims): its own buffers + dirty
        # cycle so the allocation path never uploads it
        self._victim_arrays: Optional[dict] = None
        self._victim_dims: Optional[tuple] = None
        self._victim_mesh = None
        self.last_victim_refresh = "none"   # none | clean | full
        # statistics for tests / the bench smoke: how the last refresh ran
        self.last_refresh = "none"   # none | clean | fields | full
        self.last_fields: tuple = ()
        # host bytes handed to device_put since the last take_upload_bytes()
        # (accumulates across refreshes; the core's tracer/metrics consume it
        # per dispatch — a clean cycle reads 0, the observability contract
        # "near-zero transfer when nothing changed" becomes measurable)
        self.upload_bytes = 0
        # set by SnapshotEncoder.discard_device_mirror when a deadline-blown
        # dispatch was abandoned while (possibly) still inside this object on
        # its watchdog thread: the orphan's late buffer swaps land here,
        # unreferenced, and any dirty delta it consumed is restored on exit
        # so the replacement mirror never serves stale buffers as "clean"
        self.dead = False

    def take_upload_bytes(self) -> int:
        b, self.upload_bytes = self.upload_bytes, 0
        return b

    def _host_view(self, field):
        na = self.nodes
        if field == "free_i":
            return np.floor(na.free).astype(np.int32)
        if field == "cap_i":
            return np.floor(na.capacity_arr).astype(np.int32)
        if field == "node_ok":
            return na.valid & na.schedulable
        if field == "topo":
            return na.topo
        return getattr(na, {"taints_hard": "taints_hard",
                            "taints_soft": "taints_soft",
                            "labels": "labels",
                            "ports": "ports"}[field]).view(np.uint32)

    def _host_views(self):
        return {f: self._host_view(f) for f in self.FIELDS}

    def _put(self, arr, mesh):
        import jax

        if mesh is None:
            return jax.device_put(arr)
        from jax.sharding import NamedSharding, PartitionSpec as P

        # every mirror array is node-major; trailing dims (victim slot,
        # resource) stay replicated within the shard
        spec = P("nodes", *([None] * (arr.ndim - 1)))
        return jax.device_put(arr, NamedSharding(mesh, spec))

    def refresh(self, mesh=None) -> dict:
        """Bring the device mirror up to date; returns the array dict."""
        na = self.nodes
        if self.dead:
            raise MirrorDiscarded("device mirror was discarded")
        full, fields = na.take_device_dirty()
        try:
            return self._refresh_taken(na, full, fields, mesh)
        except Exception:
            # the delta was consumed above; a failed upload (transient
            # device/relay error) must not leave later cycles serving stale
            # buffers as "clean" — force a full re-upload on the next try
            na._full_dirty = True
            raise
        finally:
            # an orphaned mirror (discard_device_mirror ran while this call
            # was wedged on its watchdog thread): give back the delta it
            # consumed — the live replacement must see everything as dirty
            if self.dead:
                na._full_dirty = True

    def _refresh_taken(self, na, full, fields, mesh) -> dict:
        dims = (na.capacity, na._R, na._W, na._Wt, na._Wp)
        if (self._arrays is None or full or dims != self._dims
                or mesh is not self._mesh):
            views = self._host_views()
            self._arrays = {k: self._put(v, mesh) for k, v in views.items()}
            self._dims = dims
            self._mesh = mesh
            self.last_refresh, self.last_fields = "full", tuple(self.FIELDS)
            self.upload_bytes += sum(v.nbytes for v in views.values())
            return self._arrays
        if not fields:
            self.last_refresh, self.last_fields = "clean", ()
            return self._arrays
        fresh = dict(self._arrays)
        uploaded = 0
        for f in sorted(fields):
            view = self._host_view(f)
            fresh[f] = self._put(view, mesh)
            uploaded += view.nbytes
        # swap in only after every upload succeeded (no partial mirror)
        self._arrays = fresh
        self.last_refresh, self.last_fields = "fields", tuple(sorted(fields))
        self.upload_bytes += uploaded
        return self._arrays

    def refresh_victims(self, mesh=None) -> dict:
        """Bring the victim-table mirror up to date and return the base
        arrays merged with the victim group. Separate from refresh(): the
        allocation hot path never touches (or uploads) victim state; the
        preemption path pays for it only when the tables actually changed
        (same O(what changed) contract, group-granular)."""
        base = self.refresh(mesh=mesh)
        na = self.nodes
        vdims = (na.capacity, na.victim_slots, na._R)
        stale = na.take_victim_dirty()
        if (self._victim_arrays is None or stale or vdims != self._victim_dims
                or mesh is not self._victim_mesh):
            views = {f: getattr(na, f) for f in VICTIM_FIELDS}
            try:
                self._victim_arrays = {k: self._put(v, mesh)
                                       for k, v in views.items()}
            except Exception:
                # the dirty flag was consumed; a failed upload must not leave
                # later planners reading a stale mirror as "clean"
                na._victim_dirty = True
                raise
            self._victim_dims = vdims
            self._victim_mesh = mesh
            self.upload_bytes += sum(v.nbytes for v in views.values())
            self.last_victim_refresh = "full"
        else:
            self.last_victim_refresh = "clean"
        if self.dead:  # orphaned mid-call: see refresh()
            na._victim_dirty = True
        out = dict(base)
        out.update(self._victim_arrays)
        return out


class MirrorDiscarded(RuntimeError):
    """A device-mirror call outlived a discard_device_mirror (its dispatch
    was deadline-abandoned and a replacement mirror is live): it must bail
    without touching shared state, or it would race the scheduler thread."""


class DeviceRowStore:
    """Persistent device-resident quantized request rows ([cap, R] int32).

    The device half of the per-ask encoded-row cache (round 10 made
    re-DERIVING rows O(changed); this makes re-TRANSFERRING them O(changed)
    too): each allocation key owns a pool slot keyed by its core seq, a
    churn cycle uploads only the changed rows' RAW values — quantized on
    device by the jitted ops.gate_solve.encode_rows, bit-identical to the
    host quantize_request chain — and the batch's req tensor for the solve
    is a pure device gather over an O(n) int32 slot index. Slot 0 is the
    reserved all-zero row (batch padding). LRU-evicted past the same 2^18
    ceiling as the host row cache; vocab growth past the padded row width
    resets the pool (one full re-upload, counted in `resets`).

    Single-writer: the scheduler thread under the core lock (same
    discipline as NodeArrays). Batches hold materialized gather RESULTS,
    so eviction/reset can never corrupt an in-flight batch.
    """

    def __init__(self, vocabs: Vocabs, min_capacity: int = 1024,
                 max_rows: int = 1 << 18):
        from collections import OrderedDict

        self.vocabs = vocabs
        self._slot_of: "OrderedDict[str, list]" = OrderedDict()  # key -> [seq, slot]
        self._free: List[int] = []
        self._capacity = max(int(min_capacity), 2)
        self._max_rows = max_rows
        self._R: Optional[int] = None
        self.pool = None
        # transfer accounting (the O(changed) contract tests assert on)
        self.last_upload_rows = 0
        self.last_upload_bytes = 0
        self.upload_rows_total = 0
        self.resets = 0
        self._upload_bytes_acc = 0
        # one-deep gather memo: a no-change cycle (same slot index, no
        # uploads) reuses the previous device req outright — the batch-memo
        # discipline of round 6 applied to the gather dispatch (~1-2 ms of
        # jit dispatch otherwise paid by every clean cycle)
        self._gather_memo: Optional[tuple] = None  # (idx bytes, pool, req)

    def take_upload_bytes(self) -> int:
        """Row-data bytes uploaded since the last take (mirrors
        DeviceNodeState.take_upload_bytes for the cycle trace)."""
        b, self._upload_bytes_acc = self._upload_bytes_acc, 0
        return b

    def _reset(self, R: int) -> None:
        import jax.numpy as jnp

        if self.pool is not None:
            self.resets += 1
        self._slot_of.clear()
        self._free = []
        self._R = R
        self.pool = jnp.zeros((self._capacity, R), jnp.int32)

    def _grow(self, need: int) -> None:
        import jax.numpy as jnp

        new_cap = self._capacity
        while new_cap < need:
            new_cap *= 2
        if new_cap == self._capacity:
            return
        pad = jnp.zeros((new_cap - self._capacity, self._R), jnp.int32)
        self.pool = jnp.concatenate([self.pool, pad], axis=0)
        self._capacity = new_cap

    def _raw_row(self, resource) -> "np.ndarray":
        """Exact raw-value row over the padded slot space. Non-integral
        values pre-quantize on the host and ship q*scale, which the device
        ceil-div maps back to exactly q (integer values — the normal case —
        quantize fully on device)."""
        rv = self.vocabs.resources
        slots = [(rv.slot(name), name, value)
                 for name, value in resource.resources.items()]
        row = np.zeros((self._R,), np.int64)
        for slot, name, value in slots:
            if slot >= self._R:
                return None  # vocab grew mid-batch: caller resets
            if isinstance(value, int) or (isinstance(value, float)
                                          and value.is_integer()):
                row[slot] = int(value)
            else:
                q = math.ceil(rv.quantize(name, value))
                row[slot] = int(q) * rv.scale(name)
        return row

    def sync_and_gather(self, asks: Sequence[AllocationAsk], n_pad: int):
        """Ensure every ask's quantized row is pool-resident (uploading
        only new/changed rows through the jitted quantization) and return
        the [n_pad, R] int32 device req tensor in ask order (padding rows
        all-zero via slot 0). Returns None when the vocab width changed
        mid-call (the caller falls back to the host req for this cycle)."""
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        from yunikorn_tpu.ops import gate_solve

        rv = self.vocabs.resources
        R = rv.num_slots
        if self.pool is None or self._R != R:
            self._reset(R)
        slot_of = self._slot_of
        changed: List[tuple] = []      # (slot, raw row)
        idx = np.zeros((n_pad,), np.int32)
        for i, ask in enumerate(asks):
            key = ask.allocation_key
            rec = slot_of.get(key)
            if rec is not None and rec[0] == ask.seq:
                slot_of.move_to_end(key)
                idx[i] = rec[1]
                continue
            raw = self._raw_row(ask.resource)
            if raw is None:
                return None
            if rec is None:
                if self._free:
                    slot = self._free.pop()
                else:
                    # evict LRU once past the ceiling (floored at the live
                    # batch, same discipline as the host row cache)
                    while (len(slot_of) >= max(self._max_rows, len(asks))
                           and slot_of):
                        _, (_seq, s) = slot_of.popitem(last=False)
                        self._free.append(s)
                    if self._free:
                        slot = self._free.pop()
                    else:
                        slot = len(slot_of) + 1        # slot 0 reserved
                        self._grow(slot + 1)
                slot_of[key] = rec = [ask.seq, slot]
            else:
                rec[0] = ask.seq
                slot_of.move_to_end(key)
            changed.append((rec[1], raw))
            idx[i] = rec[1]
        self.last_upload_rows = len(changed)
        self.last_upload_bytes = 0
        with enable_x64():
            if changed:
                C_pad = _bucket(len(changed), 64)
                raw_m = np.zeros((C_pad, R), np.int64)
                slots_m = np.zeros((C_pad,), np.int32)
                for j, (slot, raw) in enumerate(changed):
                    raw_m[j] = raw
                    slots_m[j] = slot
                scales = np.ones((R,), np.float64)
                for name, slot, scale in rv.items():
                    scales[slot] = float(scale)
                from yunikorn_tpu.aot import runtime as aot_rt

                # deliberately NO pending_ok: the slot bookkeeping above
                # already recorded these rows as uploaded, so a
                # CompilePending raise here would leave the pool without
                # rows later gathers believe are present. The encode
                # program is tiny (~tens of ms) — a store miss compiles
                # inline and still persists for the next process.
                self.pool = aot_rt.aot_call(
                    "gate.encode_rows", gate_solve.encode_rows,
                    (self.pool, jnp.asarray(raw_m), jnp.asarray(scales),
                     jnp.asarray(slots_m)), {})
                self.last_upload_bytes = int(raw_m.nbytes + slots_m.nbytes
                                             + scales.nbytes)
                self.upload_rows_total += len(changed)
                self._upload_bytes_acc += self.last_upload_bytes
            key = idx.tobytes()
            memo = self._gather_memo
            if memo is not None and memo[1] is self.pool and memo[0] == key:
                return memo[2]
            req = gate_solve.gather_rows(self.pool, jnp.asarray(idx))
            self._gather_memo = (key, self.pool, req)
            return req


class SnapshotEncoder:
    """Maintains NodeArrays against a SchedulerCache + encodes pod batches."""

    def __init__(self, cache: SchedulerCache, vocabs: Optional[Vocabs] = None):
        self.cache = cache
        self.vocabs = vocabs or Vocabs()
        self.nodes = NodeArrays(self.vocabs)
        # LRU-bounded: locality signatures fold pod labels in, so label churn
        # on long-running clusters would otherwise grow this without bound
        from collections import OrderedDict

        self._group_cache: "OrderedDict[tuple, Tuple[int, GroupSpec]]" = OrderedDict()
        self._group_cache_max = 8192
        self._unschedulable_overrides: Dict[str, bool] = {}
        self._taint_version = 0
        # victim-table staleness: node names whose tables need re-encode at
        # the next sync_victims. Fed by sync_nodes (pod churn marks the node
        # dirty) and by the core's allocation bookkeeping hooks
        # (mark_victims_stale); consumed lazily so allocation-only cycles
        # never pay for victim encoding.
        self._victim_stale: set = set()
        self._victims_synced = False
        # app-id interning for the victim tables' app/gang column
        self._app_ids: Dict[str, int] = {}
        # device-resident node mirror, built lazily at the first solve (its
        # construction initializes the JAX backend). _mirror_mu + the epoch
        # make mirror entry atomic against discard_device_mirror: a
        # deadline-abandoned dispatch that finally unwedges finds its
        # captured epoch stale and bails (MirrorDiscarded) instead of
        # racing the live thread on the replacement mirror.
        self.device: Optional[DeviceNodeState] = None
        self._mirror_mu = threading.Lock()
        self._mirror_epoch = 0
        # one-deep built-batch memo: (key, extra fingerprint, batch)
        self._batch_cache: Optional[tuple] = None
        self.last_encode_cached = False
        # per-ask encoded-row cache (round 10): allocation_key -> (ask seq,
        # anti-term set identity, group signature, request signature,
        # quantized request row). Group/request signatures and the quantized
        # row are pure functions of (ask.pod, ask.resource, the anti-term
        # set): a re-submitted ask gets a fresh core seq (the same identity
        # rule build_batch_cached's memo key uses), and anti-term set churn
        # regenerates the memoized list object (locality.all_anti_terms,
        # keyed by cache.anti_version — the same invalidation feed that
        # marks nodes dirty for sync_nodes). A churn cycle therefore
        # re-derives signatures only for new/changed asks; unchanged rows
        # assemble straight from the cache. LRU-bounded like _group_cache.
        self._ask_row_cache: "OrderedDict[str, tuple]" = OrderedDict()
        # capacity >= the vector gate's 2^18-ask batch ceiling (gate._MAX_ASKS)
        # so even a maximal batch fits whole: a cap below the batch size would
        # evict this cycle's earliest-iterated entries every cycle — a steady-
        # state LRU thrash that silently re-derives O(batch - cap) rows.
        # build_batch additionally floors eviction at the live batch size so
        # legacy-gate batches beyond this ceiling cannot thrash either.
        self._ask_row_cache_max = 1 << 18
        # encode-cost accounting for the most recent build_batch: total rows
        # vs rows that actually re-derived signatures/quantization (the
        # O(changed) contract gate-smoke and the bench assert on)
        self.last_encode_rows = 0
        self.last_encode_rows_reencoded = 0
        # device-resident request-row pool (the device gate+encode pipeline;
        # lazy: constructing it initializes the JAX backend)
        self.row_store: Optional[DeviceRowStore] = None

    def device_row_store(self) -> DeviceRowStore:
        if self.row_store is None:
            self.row_store = DeviceRowStore(self.vocabs)
        return self.row_store

    def device_req(self, asks: Sequence[AllocationAsk], batch) -> object:
        """[N, R] int32 device req tensor for a built batch — the row
        store's O(changed)-upload gather. None when the store cannot serve
        this batch (vocab width raced the encode); the caller then uses the
        host batch.req for the cycle."""
        store = self.device_row_store()
        req = store.sync_and_gather(asks, batch.req.shape[0])
        if req is not None and req.shape[1] != batch.req.shape[1]:
            return None  # width drifted from the encoded batch: host path
        return req

    @property
    def mirror_epoch(self) -> int:
        """Capture BEFORE a supervised dispatch (on the scheduler thread)
        and pass to device_arrays/victim_arrays: a call whose dispatch was
        abandoned mid-wedge then finds the epoch advanced and bails."""
        with self._mirror_mu:
            return self._mirror_epoch

    def _check_epoch_locked(self, epoch: Optional[int]) -> None:
        if epoch is not None and epoch != self._mirror_epoch:
            raise MirrorDiscarded(
                f"mirror epoch {epoch} superseded by "
                f"{self._mirror_epoch} (dispatch was abandoned)")

    def ensure_mirror_epoch(self, epoch: Optional[int]) -> None:
        """Raise MirrorDiscarded when the captured epoch is stale (a
        discard happened since): checkpoints in longer dispatch code paths
        stop an unwedged zombie thread before it touches shared state."""
        with self._mirror_mu:
            self._check_epoch_locked(epoch)

    def _mirror_enter(self, epoch: Optional[int]) -> DeviceNodeState:
        """Epoch check + get-or-create, atomic against discard: a stale
        call can never install or grab the LIVE replacement mirror."""
        with self._mirror_mu:
            self._check_epoch_locked(epoch)
            if self.device is None:
                self.device = DeviceNodeState(self.nodes)
            return self.device

    def device_arrays(self, mesh=None, epoch: Optional[int] = None) -> dict:
        """Refresh and return the persistent device-resident node tensors."""
        return self._mirror_enter(epoch).refresh(mesh=mesh)

    def victim_arrays(self, mesh=None, epoch: Optional[int] = None) -> dict:
        """Refresh and return the device node tensors INCLUDING the victim
        tables (the batched preemption planner's inputs). Call sync_victims
        first so the tables reflect the current cache."""
        return self._mirror_enter(epoch).refresh_victims(mesh=mesh)

    def discard_device_mirror(self) -> None:
        """Orphan the device mirror after a deadline-abandoned dispatch.

        The supervisor's watchdog abandons (never kills) a wedged dispatch:
        the daemon thread may STILL be inside DeviceNodeState.refresh(),
        mutating buffers and dirty-field bookkeeping whenever it finally
        unwedges. Reusing that object from the next cycle would race those
        late writes (a torn dirty-field sync corrupts the mirror's capacity
        tensors — wrong placements, silently). Instead the mirror is
        replaced: the orphan is flagged dead so its exit path restores any
        dirty delta it consumed, its late buffer swaps land on an
        unreferenced object, and the successor starts cold (one full
        upload — the price of a blown deadline, not of every cycle). The
        epoch bump makes a zombie that never reached the mirror bail at
        entry instead of touching the replacement.

        Guarded seams only: the deadline protects DEVICE dispatches (the
        wedge-prone boundary — transfers, collectives, remote compile);
        host-side numpy sections have no real wedge mode and stay
        unguarded."""
        with self._mirror_mu:
            dev, self.device = self.device, None
            if dev is not None:
                dev.dead = True
            self._mirror_epoch += 1
        self.nodes._full_dirty = True
        self.nodes._victim_dirty = True

    def mark_victims_stale(self, node_name: str) -> None:
        """Core hook: allocation bookkeeping changed for this node (an
        allocation was committed, released or restored), so its pods'
        managed-ness — and therefore its victim table — may have changed
        without any cache-side pod event."""
        self._victim_stale.add(node_name)

    def sync_victims(self, app_of_pod: Dict[str, str], pc_lookup) -> int:
        """Re-encode victim tables for stale nodes (lazy incremental path).

        app_of_pod: victim pod uid -> application id — membership defines
        "yunikorn-managed" exactly like the host planner's filter; the app id
        is interned into the table's app/gang column. Returns the number of
        nodes re-encoded (0 on a clean sync: nothing uploads downstream).
        """
        import math

        from yunikorn_tpu.common.resource import get_pod_resource
        from yunikorn_tpu.ops.preempt import pod_priority, victim_table

        if not self._victims_synced:
            # first sync: every known node (cache and already-encoded rows)
            self._victim_stale |= set(self.cache.node_names())
            self._victim_stale |= set(self.nodes._name_to_idx)
            self._victims_synced = True
        if not self._victim_stale:
            return 0
        stale, self._victim_stale = self._victim_stale, set()
        rv = self.vocabs.resources
        managed = app_of_pod.__contains__
        count = 0
        # sorted: deterministic encode order (same discipline as sync_nodes)
        for name in sorted(stale):
            idx = self.nodes.index_of(name)
            if idx is None:
                continue
            # snapshot, not get_node: informer threads mutate the live
            # NodeInfo.pods dict under the cache lock, and victim_table
            # iterates it — the host planner's _NodeTables snapshots for
            # the same reason
            info = self.cache.snapshot_node(name)
            if info is None:
                self.nodes._clear_victim_row(idx)
                count += 1
                continue
            victims = victim_table(info, pc_lookup, managed)
            # intern all resource names BEFORE sizing rows (vocab growth
            # repads the arrays first — encode_node's discipline)
            slot_rows = [[(rv.slot(n), rv.quantize(n, val))
                          for n, val in get_pod_resource(v).resources.items()]
                         for v in victims]
            self.nodes.ensure_padding()
            rows = []
            for slots in slot_rows:
                row = np.zeros((rv.num_slots,), np.int32)
                for slot, val in slots:
                    # floor: freed capacity is UNDER-estimated so a device
                    # plan never promises an eviction the exact host search
                    # would refuse (integral device units are exact)
                    row[slot] = math.floor(val)
                rows.append(row)
            prios = []
            apps = []
            uids = []
            for v in victims:
                prios.append(pod_priority(v))
                app = app_of_pod.get(v.uid, "")
                aid = self._app_ids.get(app)
                if aid is None:
                    aid = self._app_ids[app] = len(self._app_ids)
                apps.append(aid)
                uids.append(v.uid)
            self.nodes.encode_victims(idx, rows, prios, apps, uids)
            count += 1
        return count

    @staticmethod
    def placed_fingerprint(extra_placed) -> tuple:
        """Order-insensitive identity of an extra_placed overlay, for the
        batch memo and the pipelined dispatch's delta detection."""
        if not extra_placed:
            return ()
        return tuple(sorted((p.uid, n) for p, n in extra_placed))

    def build_batch_cached(self, asks: Sequence[AllocationAsk],
                           ranks: Optional[Sequence[float]] = None,
                           extra_placed=None) -> PodBatch:
        """build_batch with a one-deep memo: a cycle whose ask set and
        cluster state are unchanged re-uses the previous batch outright, so
        a no-change cycle's encode cost is O(1) instead of O(N pods).

        The key covers the ask identity/order (ranks are positional), the
        node arrays version (rows, free state, vocab dims), and the cache
        generation (node/pod objects: host masks, locality counts). PVC/PV/
        StorageClass and DRA object stores do NOT bump the cache generation,
        so batches that read them are marked non-cacheable at build time and
        always re-encode. A hit with a different extra_placed overlay is
        only returned for placement-INdependent batches — placement-dependent
        ones must be refresh_batch()-ed by the caller (the pipelined
        dispatch does exactly that)."""
        key = (
            # (key, seq): a re-submitted ask keeps its allocation key but
            # gets a fresh core sequence number — its resource/spec may have
            # changed, so key-only identity would serve a stale req tensor
            tuple((a.allocation_key, a.seq) for a in asks),
            self.nodes.version,
            self.cache.generation(),
            None if ranks is None else tuple(ranks),
        )
        fp = self.placed_fingerprint(extra_placed)
        cached = self._batch_cache
        if cached is not None and cached[0] == key and (
                cached[1] == fp or not cached[2].placement_dependent):
            self.last_encode_cached = True
            self.last_encode_rows = cached[2].num_pods
            self.last_encode_rows_reencoded = 0
            batch = cached[2]
            if cached[1] != fp:
                # placement-independent: the overlay only matters to solve
                # inputs computed at dispatch (free/ports deltas)
                self._batch_cache = (key, fp, batch)
            return batch
        self.last_encode_cached = False
        batch = self.build_batch(asks, ranks=ranks, extra_placed=extra_placed)
        if batch.cacheable:
            self._batch_cache = (key, fp, batch)
        else:
            self._batch_cache = None
        return batch

    # ------------------------------------------------------------------ nodes
    def sync_nodes(self, full: bool = False) -> None:
        """Re-encode dirty (or all) nodes from the scheduler cache.

        Pod churn only changes a node's free capacity, so those nodes take a
        cheap O(R) free-row refresh; only nodes whose node OBJECT changed
        (labels/taints/allocatable/new) pay the full symbol re-encode.
        """
        if full:
            names = set(self.cache.node_names())
            # also drop rows for nodes no longer in the cache
            for name in list(self.nodes._name_to_idx):
                if name not in names:
                    self.nodes.remove_node(name)
            dirty, objects = names, names
        else:
            dirty, objects = self.cache.take_dirty_nodes()
        # pod churn invalidates the node's victim table too; the tables are
        # re-encoded lazily at the next sync_victims, not here — allocation
        # cycles must not pay for preemption state they never read
        self._victim_stale |= set(dirty)
        # sorted: dirty/objects are SETS — hash-order iteration would make
        # node row assignment (and every downstream tensor: label bitsets,
        # locality domain ids, solve inputs) vary with PYTHONHASHSEED across
        # processes. Deterministic encodings are load-bearing for the
        # sharded-vs-single bit-identity contract and for differential tests.
        for name in sorted(dirty):
            info = self.cache.get_node(name)
            if info is None:
                self.nodes.remove_node(name)
                continue
            if name in objects or self.nodes.index_of(name) is None:
                sched = self._unschedulable_overrides.get(name, True)
                self.nodes.encode_node(info, schedulable=sched)
            else:
                self.nodes.update_free_row(name, info)
        # taint vocab may have grown; bump group invalidation version
        self._taint_version = self.vocabs.taints.used_bits()

    def set_node_schedulable(self, name: str, schedulable: bool) -> None:
        """Core-driven schedulable state (DRAIN vs READY), kept across re-encodes."""
        self._unschedulable_overrides[name] = schedulable
        self.nodes.set_schedulable(name, schedulable)

    # ------------------------------------------------------------------- pods
    def _group_signature(self, pod: Pod, terms=None) -> tuple:
        # signatures are pure functions of the pod spec + the anti-affinity
        # term set; cache per pod, invalidated when the term set regenerates.
        # Callers in a loop pass `terms` (one lock acquisition per batch, not
        # one per pod).
        if terms is None:
            from yunikorn_tpu.snapshot.locality import all_anti_terms

            terms = all_anti_terms(self.cache)
        cached = getattr(pod, "_yk_sig_cache", None)
        if cached is not None and cached[0] is terms:
            return cached[1]
        sig = self._compute_group_signature(pod)
        try:
            pod._yk_sig_cache = (terms, sig)
        except AttributeError:
            pass
        return sig

    def _compute_group_signature(self, pod: Pod) -> tuple:
        sel = tuple(sorted(pod.spec.node_selector.items()))
        pref = tuple(
            (w,
             tuple((x.key, x.operator, tuple(x.values)) for x in t.match_expressions),
             tuple((x.key, x.operator, tuple(x.values)) for x in t.match_fields))
            for w, t in (pod.spec.affinity.node_preferred_terms if pod.spec.affinity else [])
        )
        tols = tuple(
            (t.key, t.operator, t.value, t.effect) for t in pod.spec.tolerations
        )
        aff: tuple = ()
        if pod.spec.affinity is not None:
            parts = []
            for term in pod.spec.affinity.node_required_terms:
                exprs = tuple(
                    (e.key, e.operator, tuple(e.values)) for e in term.match_expressions
                ) + tuple(
                    ("__field__" + e.key, e.operator, tuple(e.values)) for e in term.match_fields
                )
                parts.append(exprs)
            aff = tuple(parts)
        ports = tuple(
            sorted(
                (p.get("protocol", "TCP"), p["hostPort"])
                for c in pod.spec.containers
                for p in c.ports
                if p.get("hostPort")
            )
        )
        # Placement-dependent constraints ride the signature too — but ONLY for
        # pods that actually have them (or match an existing anti-affinity
        # term): unconstrained pods keep the compact signature so group dedup
        # stays effective (snapshot/locality.py owns the semantics).
        from yunikorn_tpu.snapshot.locality import locality_signature

        loc_sig = locality_signature(pod, self.cache)
        # DRA claims are per-pod identities; pods sharing an identical claim
        # list share a group (the host mask then holds for every member)
        claims_sig = ((pod.namespace, tuple(sorted(pod.spec.resource_claims)))
                      if pod.spec.resource_claims else ())
        # PVC claims likewise: the volume mask is claim-specific, so pods
        # with different claims must not share a group
        vol_sig = self._volume_claims_of(pod) or ()
        return (sel, tols, aff, ports, pref, loc_sig, claims_sig, vol_sig)

    def _encode_group(self, pod: Pod) -> GroupSpec:
        W = self.vocabs.labels.num_words
        Wt = self.vocabs.taints.num_words
        Wp = self.vocabs.ports.num_words
        lv, tv, pv = self.vocabs.labels, self.vocabs.taints, self.vocabs.ports

        # --- node selector + affinity terms ---
        base_req = np.zeros((W,), np.uint32)
        for k, v in pod.spec.node_selector.items():
            _set_bit(base_req, lv.bit(label_bit(k, v)))

        affinity_terms = (
            pod.spec.affinity.node_required_terms if pod.spec.affinity else []
        )
        n_terms = max(1, len(affinity_terms))
        host_exprs: List[Tuple[str, str, str]] = []
        host_affinity_terms: Optional[list] = None
        term_req = np.zeros((MAX_TERMS, W), np.uint32)
        term_forb = np.zeros((MAX_TERMS, W), np.uint32)
        term_valid = np.zeros((MAX_TERMS,), bool)
        anyof = np.zeros((MAX_TERMS, MAX_ANYOF, W), np.uint32)
        anyof_valid = np.zeros((MAX_TERMS, MAX_ANYOF), bool)
        # OR-of-terms the tensors can't hold exactly is host-evaluated in
        # full: per-expression host fallback (Gt/Lt, anyof overflow,
        # matchFields multi-In) composes by AND, which is only sound inside a
        # single term; with >1 term (or >MAX_TERMS terms) the whole affinity
        # moves to the host path (reference never approximates a predicate,
        # predicate_manager.go:202-250).
        if affinity_terms and (
            n_terms > MAX_TERMS
            or (n_terms > 1 and any(_term_needs_host(t) for t in affinity_terms))
        ):
            host_affinity_terms = list(affinity_terms)
            n_terms = 1  # tensor side only enforces the node selector
        for t in range(n_terms):
            term_valid[t] = True
            term_req[t] = base_req
            if host_affinity_terms is None and t < len(affinity_terms):
                e_idx = 0
                for e in affinity_terms[t].match_expressions:
                    if e.operator == "In":
                        if len(e.values) == 1:
                            _set_bit(term_req[t], lv.bit(label_bit(e.key, e.values[0])))
                        else:
                            if e_idx >= MAX_ANYOF:
                                logger.warning("pod %s: too many multi-value In exprs; host fallback", pod.key())
                                host_exprs.append((e.key, "In", ",".join(e.values)))
                                continue
                            for v in e.values:
                                _set_bit(anyof[t, e_idx], lv.bit(label_bit(e.key, v)))
                            anyof_valid[t, e_idx] = True
                            e_idx += 1
                    elif e.operator == "NotIn":
                        for v in e.values:
                            _set_bit(term_forb[t], lv.bit(label_bit(e.key, v)))
                    elif e.operator == "Exists":
                        _set_bit(term_req[t], lv.bit(label_key_bit(e.key)))
                    elif e.operator == "DoesNotExist":
                        _set_bit(term_forb[t], lv.bit(label_key_bit(e.key)))
                    elif e.operator in ("Gt", "Lt"):
                        host_exprs.append((e.key, e.operator, e.values[0] if e.values else "0"))
                    else:
                        logger.warning("unsupported node-affinity operator %s", e.operator)
                for e in affinity_terms[t].match_fields:
                    # metadata.name is the only supported field (as in K8s);
                    # it is matchable through the hostname label bits
                    if e.key != "metadata.name":
                        logger.warning("unsupported matchFields key %s", e.key)
                    elif e.operator == "In":
                        if len(e.values) == 1:
                            _set_bit(term_req[t], lv.bit(label_bit("kubernetes.io/hostname", e.values[0])))
                        else:
                            host_exprs.append(("metadata.name", "In", ",".join(e.values)))
                    elif e.operator == "NotIn":
                        for v in e.values:
                            _set_bit(term_forb[t], lv.bit(label_bit("kubernetes.io/hostname", v)))
                    else:
                        logger.warning("unsupported matchFields operator %s", e.operator)

        # --- preferred node affinity (scoring): weighted single terms ---
        # Terms the bitset rows can express exactly (single-value In, NotIn,
        # Exists, DoesNotExist; no matchFields) go to the tensors; anything
        # else — multi-value In, Gt/Lt, matchFields, slot overflow — is
        # host-scored exactly instead of approximated.
        pref_req = np.zeros((MAX_PREF_TERMS, W), np.uint32)
        pref_forb = np.zeros((MAX_PREF_TERMS, W), np.uint32)
        pref_weight = np.zeros((MAX_PREF_TERMS,), np.float32)
        preferred = (pod.spec.affinity.node_preferred_terms
                     if pod.spec.affinity else [])
        host_pref_terms: list = []

        def _pref_exact(pterm) -> bool:
            if pterm.match_fields:
                return False
            return all(
                (pe.operator == "In" and len(pe.values) == 1)
                or pe.operator in ("NotIn", "Exists", "DoesNotExist")
                for pe in pterm.match_expressions
            )

        pi = 0
        for weight, pterm in preferred:
            if pi >= MAX_PREF_TERMS or not _pref_exact(pterm):
                host_pref_terms.append((float(weight), pterm))
                continue
            pref_weight[pi] = float(weight)
            for pe in pterm.match_expressions:
                if pe.operator == "In":
                    _set_bit(pref_req[pi], lv.bit(label_bit(pe.key, pe.values[0])))
                elif pe.operator == "NotIn":
                    for v in pe.values:
                        _set_bit(pref_forb[pi], lv.bit(label_bit(pe.key, v)))
                elif pe.operator == "Exists":
                    _set_bit(pref_req[pi], lv.bit(label_key_bit(pe.key)))
                elif pe.operator == "DoesNotExist":
                    _set_bit(pref_forb[pi], lv.bit(label_key_bit(pe.key)))
            pi += 1

        # --- tolerations (expand Exists against the current taint vocab) ---
        tol = np.zeros((Wt,), np.uint32)
        for t in pod.spec.tolerations:
            effects = (
                [t.effect]
                if t.effect
                else [constants.TAINT_EFFECT_NO_SCHEDULE,
                      constants.TAINT_EFFECT_PREFER_NO_SCHEDULE,
                      constants.TAINT_EFFECT_NO_EXECUTE]
            )
            if t.operator == "Exists" and not t.key:
                tol[:] = np.uint32(0xFFFFFFFF)  # tolerate everything
                continue
            for eff in effects:
                if t.operator == "Exists":
                    # tolerate every known (key, value, eff) triple with this key
                    for sym, bit in self.vocabs.taints.symbols():
                        if sym[1] == t.key and sym[3] == eff:
                            _set_bit(tol, bit)
                    # and intern a marker so future encodes see the key
                    _set_bit(tol, tv.bit(taint_bit(t.key, t.value or "", eff)))
                else:
                    b = tv.lookup(taint_bit(t.key, t.value, eff))
                    if b >= 0:
                        _set_bit(tol, b)
        # --- host ports ---
        ports = np.zeros((Wp,), np.uint32)
        for c in pod.spec.containers:
            for p in c.ports:
                hp = p.get("hostPort")
                if hp:
                    _set_bit(ports, pv.bit(port_bit(p.get("protocol", "TCP"), hp)))

        return GroupSpec(
            term_req=term_req,
            term_forb=term_forb,
            term_valid=term_valid,
            anyof=anyof,
            anyof_valid=anyof_valid,
            tolerations=tol,
            ports=ports,
            needs_host_eval=(bool(host_exprs) or host_affinity_terms is not None
                             or bool(pod.spec.resource_claims)),
            host_exprs=host_exprs,
            taint_vocab_version=self.vocabs.taints.used_bits(),
            pref_req=pref_req,
            pref_forb=pref_forb,
            pref_weight=pref_weight,
            host_affinity_terms=host_affinity_terms,
            host_pref_terms=host_pref_terms or None,
            claims=((pod.namespace, tuple(sorted(pod.spec.resource_claims)))
                    if pod.spec.resource_claims else None),
            volumes=self._volume_claims_of(pod),
        )

    @staticmethod
    def _volume_claims_of(pod: Pod):
        names = sorted(v.pvc_claim_name for v in pod.spec.volumes
                       if v.pvc_claim_name)
        return (pod.namespace, tuple(names)) if names else None

    def _host_rows(self):
        """[(node idx, NodeInfo)] — one cache read per node, shared by the
        host-evaluation passes within one build_batch."""
        return [(idx, self.cache.get_node(name))
                for idx, name in list(self.nodes._idx_to_name.items())]

    def _volume_mask(self, volumes: Tuple[str, tuple],
                     rows=None) -> Optional[np.ndarray]:
        """[capacity] bool mask of nodes where every claim is satisfiable, or
        None when the claims impose no node restriction (the common case).

        Mirrors VolumeBinder.find_pod_volumes group-wise: bound claims pin to
        their PV's node affinity; unbound claims allow nodes with a matching
        Available PV, any node when dynamically provisionable (class unknown
        or has a provisioner), and nothing otherwise. The per-(pod,node)
        reference equivalent is the volumebinding PreFilter inside the
        Predicates upcall (predicate_manager.go:302-392)."""
        from yunikorn_tpu.common.volumes import pv_matches_claim

        ns, names = volumes
        M = self.nodes.capacity
        mask: Optional[np.ndarray] = None
        if rows is None:
            rows = self._host_rows()           # one cache pass per call

        def label_mask(affinity: Dict[str, str]) -> np.ndarray:
            out = np.zeros((M,), bool)
            for idx, info in rows:
                if info is None:
                    continue
                labels = info.node.metadata.labels
                if all(labels.get(k) == v for k, v in affinity.items()):
                    out[idx] = True
            return out

        for name in names:
            pvc = self.cache.get_pvc_obj(ns, name)
            if pvc is None:
                # unknown claim: leave unrestricted — the task-level PVC
                # sanity check and assume-time find fail it with a message
                continue
            if pvc.bound:
                pv = self.cache.get_pv_obj(pvc.volume_name)
                if pv is not None and pv.node_affinity:
                    m = label_mask(pv.node_affinity)
                    mask = m if mask is None else (mask & m)
                continue
            sc = self.cache.get_storage_class_obj(pvc.storage_class)
            if sc is None:
                continue                       # unknown class: optimistic
            if sc.provisioner:
                segments = self.cache.csi_fitting_segments(
                    sc, pvc.requested_storage)
                if segments is None:
                    continue                   # untracked: provisionable anywhere
            else:
                segments = []                  # no provisioner: static PVs only
            # static PVs first (same order as the binder: a pre-provisioned
            # PV satisfies the claim even when no capacity segment covers the
            # node), then capacity-tracked provisioning widens the mask
            allowed = np.zeros((M,), bool)
            unrestricted = False
            key = f"{ns}/{name}"
            for pv in self.cache.list_pv_objs():
                if not pv_matches_claim(pv, pvc, None, key):
                    continue
                if not pv.node_affinity:
                    unrestricted = True
                    break
                allowed |= label_mask(pv.node_affinity)
            if unrestricted:
                continue
            if segments:
                for idx, info in rows:
                    if info is not None and not allowed[idx] and any(
                            cap.covers_node(info.node) for cap in segments):
                        allowed[idx] = True
            mask = allowed if mask is None else (mask & allowed)
        return mask

    def _host_eval_mask(self, spec: GroupSpec, rows=None) -> np.ndarray:
        """Evaluate non-tensorizable expressions for every node.

        Single pass over the node table per call (one cache read per node, not
        per expression); expression dispatch happens inside the pass.
        """
        M = self.nodes.capacity
        mask = np.ones((M,), bool)
        if rows is None:
            rows = self._host_rows()
        for key, op, raw in spec.host_exprs:
            in_values = set(raw.split(",")) if op == "In" else None
            for idx, info in rows:
                if info is None:
                    continue
                name = info.node.name
                if key == "metadata.name":
                    if op == "In":
                        mask[idx] &= name in in_values
                    continue
                val = info.node.metadata.labels.get(key)
                if val is None:
                    mask[idx] = False
                elif op == "In":
                    mask[idx] &= val in in_values
                elif op in ("Gt", "Lt"):
                    try:
                        ival, target = int(val), int(raw)
                    except ValueError:
                        mask[idx] = False
                        continue
                    mask[idx] &= (ival > target) if op == "Gt" else (ival < target)
        if spec.host_affinity_terms is not None:
            # OR-of-terms node affinity, exact K8s semantics
            for idx, info in rows:
                if info is None:
                    continue
                labels = info.node.metadata.labels
                name = info.node.name
                mask[idx] &= any(
                    _node_matches_term(t, labels, name)
                    for t in spec.host_affinity_terms
                )
        if spec.claims is not None:
            ns, names = spec.claims
            allowed = self.cache.dra_feasible_nodes(ns, names)
            if allowed is not None:
                for idx, info in rows:
                    if info is None or info.node.name not in allowed:
                        mask[idx] = False
        return mask

    def _host_pref_scores(self, spec: GroupSpec, rows=None) -> np.ndarray:
        """[M] score adjustment from host-evaluated preferred terms (same
        scale as ops.predicates.group_preferred_bonus: weight/100 * 0.25)."""
        M = self.nodes.capacity
        scores = np.zeros((M,), np.float32)
        if rows is None:
            rows = self._host_rows()
        for idx, info in rows:
            if info is None:
                continue
            labels = info.node.metadata.labels
            s = 0.0
            for weight, pterm in spec.host_pref_terms:
                if _node_matches_term(pterm, labels, info.node.name):
                    s += weight / 100.0 * 0.25
            scores[idx] = s
        return scores

    def build_batch(
        self,
        asks: Sequence[AllocationAsk],
        ranks: Optional[Sequence[float]] = None,
        queue_ids: Optional[Sequence[int]] = None,
        min_batch: int = 64,
        extra_placed=None,
    ) -> PodBatch:
        """Encode a list of pending asks into one padded solve batch.

        extra_placed: [(Pod, node_name)] intra-cycle placements not yet in
        the cache, overlaid onto host-evaluated locality masks/scores (used
        by the core's locality-fallback drain rounds).
        """
        rv = self.vocabs.resources
        n = len(asks)
        N = _bucket(max(n, 1), min_batch)
        R = rv.num_slots

        # group dedup
        from yunikorn_tpu.snapshot.locality import all_anti_terms

        anti_terms = all_anti_terms(self.cache)
        # hoisted: used_bits() takes the vocab lock — calling it per ask cost
        # ~0.2s of the 50k-pod encode. Concurrent vocab growth (a node gains a
        # previously unseen taint mid-encode) is then invisible until the next
        # batch — one cycle of snapshot staleness, same class of tradeoff as
        # the node-array sync point.
        taint_bits = self.vocabs.taints.used_bits()

        # ---- per-ask encoded-row cache resolution ----
        # One pass resolving every ask's (group signature, request signature,
        # quantized row): unchanged asks (same allocation key + seq, same
        # anti-term set object) come straight out of the cache; only new or
        # changed asks pay the signature walks and quantization. Distinct
        # fresh request shapes still quantize once (a deployment's pods all
        # ask the same).
        ask_cache = self._ask_row_cache
        resolved: List[tuple] = []
        fresh_rows: Dict[tuple, np.ndarray] = {}
        n_reencoded = 0
        for ask in asks:
            pod = ask.pod
            key = ask.allocation_key
            rec = ask_cache.get(key) if pod is not None else None
            if rec is not None and rec[0] == ask.seq and rec[1] is anti_terms:
                ask_cache.move_to_end(key)
                resolved.append((rec[2], rec[3], rec[4]))
                continue
            n_reencoded += 1
            gsig: tuple = ("<none>",) if pod is None \
                else self._group_signature(pod, anti_terms)
            rsig = tuple(sorted(ask.resource.resources.items()))
            row = fresh_rows.get(rsig)
            if row is None:
                row = fresh_rows[rsig] = self.quantize_request(ask.resource)
                if row.shape[0] > R:
                    # vocab grew past the padded width: restart wider (the
                    # records already cached make the retry near-free)
                    return self.build_batch(asks, ranks, queue_ids, min_batch,
                                            extra_placed=extra_placed)
            resolved.append((gsig, rsig, row))
            if pod is not None:
                ask_cache[key] = (ask.seq, anti_terms, gsig, rsig, row)
        # floor the cap at the batch just encoded (the legacy gate path has
        # no batch ceiling): every live row was touched above, so eviction
        # only ever drops stale entries, never this cycle's rows
        while len(ask_cache) > max(self._ask_row_cache_max, n):
            ask_cache.popitem(last=False)
        self.last_encode_rows = n
        self.last_encode_rows_reencoded = n_reencoded

        group_specs: List[GroupSpec] = []
        group_ids: List[int] = []
        sig_to_gid: Dict[tuple, int] = {}
        for ask, (sig, _rsig, _row) in zip(asks, resolved):
            pod = ask.pod
            gid = sig_to_gid.get(sig)
            if gid is not None:
                # re-encode if the taint vocab grew since this group was cached
                if group_specs[gid].taint_vocab_version != taint_bits and pod is not None:
                    group_specs[gid] = self._encode_group(pod)
                    # the spec was stamped with the (possibly grown) version
                    taint_bits = group_specs[gid].taint_vocab_version
            else:
                gid = len(group_specs)
                sig_to_gid[sig] = gid
                if pod is None:
                    spec = self._empty_group()
                else:
                    cached = self._group_cache.get(sig)
                    if cached is not None and cached[1].taint_vocab_version == taint_bits:
                        spec = cached[1]
                        self._group_cache.move_to_end(sig)
                    else:
                        spec = self._encode_group(pod)
                        taint_bits = spec.taint_vocab_version  # may have grown
                        self._group_cache[sig] = (0, spec)
                        self._group_cache.move_to_end(sig)
                        while len(self._group_cache) > self._group_cache_max:
                            self._group_cache.popitem(last=False)
                group_specs.append(spec)
            group_ids.append(gid)

        # Group encoding may have grown the vocabs past a word boundary; repad
        # the node arrays now so group and node tensors agree on W/Wt/Wp.
        self.nodes.ensure_padding()
        G = _bucket(max(len(group_specs), 1), 4)
        W = self.vocabs.labels.num_words
        Wt = self.vocabs.taints.num_words
        Wp = self.vocabs.ports.num_words

        # requests: scatter the resolved quantized rows grouped by shape
        # signature — one vectorized assignment per distinct shape (large
        # batches are dominated by identical shapes). Cached rows may predate
        # vocab growth (shorter than R, never longer): the slice pads.
        req = np.zeros((N, R), np.float32)
        # sig -> (quantized row, row indices asking for it)
        sig_rows: Dict[tuple, Tuple[np.ndarray, list]] = {}
        for i, (_gsig, rsig, row) in enumerate(resolved):
            entry = sig_rows.get(rsig)
            if entry is None:
                sig_rows[rsig] = (row, [i])
            else:
                entry[1].append(i)
        for row, idxs in sig_rows.values():
            req[np.asarray(idxs, np.int64), : row.shape[0]] = row

        g_term_req = np.zeros((G, MAX_TERMS, W), np.uint32)
        g_term_forb = np.zeros((G, MAX_TERMS, W), np.uint32)
        g_term_valid = np.zeros((G, MAX_TERMS), bool)
        g_anyof = np.zeros((G, MAX_TERMS, MAX_ANYOF, W), np.uint32)
        g_anyof_valid = np.zeros((G, MAX_TERMS, MAX_ANYOF), bool)
        g_tol = np.zeros((G, Wt), np.uint32)
        g_ports = np.zeros((G, Wp), np.uint32)
        g_pref_req = np.zeros((G, MAX_PREF_TERMS, W), np.uint32)
        g_pref_forb = np.zeros((G, MAX_PREF_TERMS, W), np.uint32)
        g_pref_weight = np.zeros((G, MAX_PREF_TERMS), np.float32)
        host_mask: Optional[np.ndarray] = None
        host_soft: Optional[np.ndarray] = None
        host_rows = None
        for gi, spec in enumerate(group_specs):
            T, Wg = spec.term_req.shape
            g_term_req[gi, :T, :Wg] = spec.term_req
            g_term_forb[gi, :T, :Wg] = spec.term_forb
            g_term_valid[gi, :T] = spec.term_valid
            g_anyof[gi, :T, :, :Wg] = spec.anyof
            g_anyof_valid[gi, :T] = spec.anyof_valid
            g_tol[gi, : spec.tolerations.shape[0]] = spec.tolerations
            g_ports[gi, : spec.ports.shape[0]] = spec.ports
            if spec.pref_req is not None:
                g_pref_req[gi, :, : spec.pref_req.shape[1]] = spec.pref_req
                g_pref_forb[gi, :, : spec.pref_forb.shape[1]] = spec.pref_forb
                g_pref_weight[gi] = spec.pref_weight
            if spec.needs_host_eval or spec.host_pref_terms:
                if host_rows is None:
                    host_rows = self._host_rows()
            if spec.needs_host_eval:
                if host_mask is None:
                    host_mask = np.ones((G, self.nodes.capacity), bool)
                host_mask[gi] = self._host_eval_mask(spec, host_rows)
            if spec.host_pref_terms:
                if host_soft is None:
                    host_soft = np.zeros((G, self.nodes.capacity), np.float32)
                host_soft[gi] = self._host_pref_scores(spec, host_rows)

        # volume feasibility: claims restrict candidate nodes by PV node
        # affinity / static matchability (vectorized FindPodVolumes)
        vol_mask_cache: Dict[Tuple[str, tuple], Optional[np.ndarray]] = {}
        for gi, spec in enumerate(group_specs):
            if spec.volumes is None:
                continue
            vm = vol_mask_cache.get(spec.volumes, False)
            if vm is False:
                if host_rows is None:
                    host_rows = self._host_rows()
                vm = vol_mask_cache[spec.volumes] = self._volume_mask(
                    spec.volumes, host_rows)
            if vm is None:
                continue  # unconstrained
            if host_mask is None:
                host_mask = np.ones((G, self.nodes.capacity), bool)
            host_mask[gi] &= vm

        rank_arr = np.zeros((N,), np.float32)
        if ranks is not None:
            rank_arr[:n] = np.asarray(list(ranks), np.float32)
        else:
            rank_arr[:n] = np.arange(n, dtype=np.float32)
        rank_arr[n:] = np.float32(1e30)

        queue_arr = np.full((N,), -1, np.int32)
        if queue_ids is not None:
            queue_arr[:n] = np.asarray(list(queue_ids), np.int32)

        gid_arr = np.zeros((N,), np.int32)
        gid_arr[:n] = np.asarray(group_ids, np.int32)
        valid = np.zeros((N,), bool)
        valid[:n] = True

        # pre-locality copies + per-group claims ride on the batch so the
        # pipelined dispatch can re-fold against a newer extra_placed
        base_host_mask = None if host_mask is None else host_mask.copy()
        base_host_soft = None if host_soft is None else host_soft.copy()
        g_claims = [spec.claims for spec in group_specs]
        # volume/DRA stores don't bump cache.generation: their masks go
        # stale invisibly, so these batches are excluded from the memo
        cacheable = all(spec.volumes is None and spec.claims is None
                        for spec in group_specs)
        g_preempt_host = np.zeros((G,), bool)
        for gi, spec in enumerate(group_specs):
            g_preempt_host[gi] = bool(
                spec.needs_host_eval or spec.host_affinity_terms is not None
                or spec.ports.any() or spec.claims is not None
                or spec.volumes is not None)

        locality, host_mask, host_soft, valid, deferred = self._fold_locality(
            asks, group_ids, len(group_specs), g_claims, N, G,
            host_mask, host_soft, valid, extra_placed)

        return PodBatch(
            ask_keys=[a.allocation_key for a in asks],
            req=req,
            group_id=gid_arr,
            rank=rank_arr,
            valid=valid,
            queue_id=queue_arr,
            g_term_req=g_term_req,
            g_term_forb=g_term_forb,
            g_term_valid=g_term_valid,
            g_anyof=g_anyof,
            g_anyof_valid=g_anyof_valid,
            g_tol=g_tol,
            g_ports=g_ports,
            g_pref_req=g_pref_req,
            g_pref_forb=g_pref_forb,
            g_pref_weight=g_pref_weight,
            g_host_mask=host_mask,
            g_host_soft=host_soft,
            locality=locality,
            num_pods=n,
            num_groups=len(group_specs),
            deferred=deferred,
            base_host_mask=base_host_mask,
            base_host_soft=base_host_soft,
            g_claims=g_claims,
            cacheable=cacheable,
            g_preempt_host=g_preempt_host,
        )

    def _fold_locality(self, asks, group_ids, num_groups, g_claims, N, G,
                       host_mask, host_soft, valid, extra_placed):
        """Encode locality and fold its placement-dependent outputs.

        Shared by build_batch (fresh arrays) and refresh_batch (copies of the
        batch's base arrays): locality counts/fallback masks/soft statics +
        the serialization pass that parks fallback/DRA pods. Mutates and
        returns (locality, host_mask, host_soft, valid, deferred)."""
        from yunikorn_tpu.snapshot.locality import encode_locality

        locality = encode_locality(asks, group_ids, num_groups,
                                   self.nodes, self.cache, N, G,
                                   extra_placed=extra_placed)

        if locality is not None and locality.soft_static:
            # soft constraints that spilled the slot budget: statically scored
            # on the host, folded into the same channel as host-scored
            # preferred node affinity
            if host_soft is None:
                host_soft = np.zeros((G, self.nodes.capacity), np.float32)
            for gid, s in locality.soft_static.items():
                host_soft[gid] += s[: self.nodes.capacity]

        if locality is not None and locality.fallback:
            # Overflowed locality groups: exact host mask evaluated against
            # existing state (serialized below — the mask is static w.r.t.
            # this batch)
            if host_mask is None:
                host_mask = np.ones((G, self.nodes.capacity), bool)
            for gid, fb in locality.fallback.items():
                host_mask[gid] &= fb[: self.nodes.capacity]

        # Serialization (one shared pass): at most one pod per solve for
        # (a) each locality-fallback group — its host mask can't see
        # intra-batch placements — and (b) each device class with unallocated
        # DRA claims — cross-GROUP: two groups demanding the same class would
        # otherwise race one device inventory. Later pods retry next cycle
        # against fresh state.
        n = len(asks)
        serial_keys_of: Dict[int, tuple] = {}
        for gi in range(num_groups):
            keys: list = []
            if locality is not None and locality.fallback and gi in locality.fallback:
                keys.append(("loc", gi))
            if g_claims[gi] is not None:
                ns, names = g_claims[gi]
                keys.extend(("dra", c)
                            for c in self.cache.dra_unallocated_classes(ns, names))
            if keys:
                serial_keys_of[gi] = tuple(keys)
        deferred: List[int] = []
        if serial_keys_of:
            seen_keys: set = set()
            for i in range(n):
                keys = serial_keys_of.get(group_ids[i])
                if not keys:
                    continue
                if any(k in seen_keys for k in keys):
                    valid[i] = False
                    # drainable same-cycle only when every blocking key is a
                    # locality one (DRA inventory needs the shim's assume)
                    if all(k[0] == "loc" for k in keys):
                        deferred.append(i)
                else:
                    seen_keys.update(keys)
        return locality, host_mask, host_soft, valid, deferred

    def refresh_batch(self, batch: PodBatch, asks: Sequence[AllocationAsk],
                      extra_placed=None) -> PodBatch:
        """Re-fold a batch's placement-dependent state against a newer
        extra_placed overlay — the pipelined cycle's dispatch-time delta
        replay: the batch was encoded while the previous solve was still in
        flight, and allocations that committed in between must be visible to
        this solve's locality counts, fallback masks, and DRA serialization.
        Group/pod tensors are reused untouched (they are placement-invariant);
        returns a new PodBatch sharing them, so a cached batch is never
        mutated."""
        N = batch.valid.shape[0]
        G = batch.g_tol.shape[0]
        n = batch.num_pods
        group_ids = [int(batch.group_id[i]) for i in range(n)]

        def widen(arr, fill, dtype):
            # node capacity may have grown since encode; new rows were never
            # host-evaluated, so they stay ineligible for this batch (False /
            # 0 fill — conservative, same as a node registering mid-cycle)
            if arr is None:
                return None
            M = self.nodes.capacity
            if arr.shape[1] == M:
                return arr.copy()
            out = np.full((arr.shape[0], M), fill, dtype)
            w = min(arr.shape[1], M)
            out[:, :w] = arr[:, :w]
            return out

        host_mask = widen(batch.base_host_mask, False, bool)
        host_soft = widen(batch.base_host_soft, np.float32(0.0), np.float32)
        valid = np.zeros((N,), bool)
        valid[:n] = True
        locality, host_mask, host_soft, valid, deferred = self._fold_locality(
            asks, group_ids, batch.num_groups, batch.g_claims, N, G,
            host_mask, host_soft, valid, extra_placed)
        return dataclasses.replace(
            batch, g_host_mask=host_mask, g_host_soft=host_soft,
            locality=locality, valid=valid, deferred=deferred)

    def quantize_request(self, r: Resource) -> np.ndarray:
        """Resource → device-unit row [R] (ceil, request semantics).

        Interns every resource name *before* sizing the row, so vocab growth
        mid-call cannot produce an out-of-range slot or a short row.
        """
        rv = self.vocabs.resources
        slots = [(rv.slot(name), name, value) for name, value in r.resources.items()]
        out = np.zeros((rv.num_slots,), np.float32)
        for slot, name, value in slots:
            out[slot] = math.ceil(rv.quantize(name, value))
        return out

    def _empty_group(self) -> GroupSpec:
        W = self.vocabs.labels.num_words
        Wt = self.vocabs.taints.num_words
        Wp = self.vocabs.ports.num_words
        spec = GroupSpec(
            term_req=np.zeros((MAX_TERMS, W), np.uint32),
            term_forb=np.zeros((MAX_TERMS, W), np.uint32),
            term_valid=np.zeros((MAX_TERMS,), bool),
            anyof=np.zeros((MAX_TERMS, MAX_ANYOF, W), np.uint32),
            anyof_valid=np.zeros((MAX_TERMS, MAX_ANYOF), bool),
            tolerations=np.zeros((Wt,), np.uint32),
            ports=np.zeros((Wp,), np.uint32),
            needs_host_eval=False,
            host_exprs=[],
            taint_vocab_version=self.vocabs.taints.used_bits(),
            pref_req=np.zeros((MAX_PREF_TERMS, W), np.uint32),
            pref_forb=np.zeros((MAX_PREF_TERMS, W), np.uint32),
            pref_weight=np.zeros((MAX_PREF_TERMS,), np.float32),
        )
        spec.term_valid[0] = True
        return spec
