"""Vocabularies: mapping symbolic cluster state onto fixed tensor shapes.

The hard part of putting a K8s-class scheduler on a TPU (SURVEY.md §7 "hard
parts") is that predicates are symbolic — label selectors, taints, resource
names — while XLA wants fixed shapes. The resolution here:

  - **ResourceVocab**: resource names → column slots of the [*, R] resource
    matrices, each with a scale divisor chosen so quantities stay inside
    float32's exact-integer range (cpu → millicores, memory → MiB, ...).
  - **BitVocab**: interned symbols → bit positions in [*, W] uint32 bitsets.
    Used for label (key,value) pairs, taint (key,value,effect) triples and
    host ports. For every (key,value) label bit we also intern a (key,*) bit
    so Exists/DoesNotExist operators become plain mask tests.

Vocab growth changes W/R and forces an XLA recompile, so sizes grow in
power-of-two buckets and stay sticky (a recompile happens at most log2 times
per dimension over a cluster's life).
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common.resource import CPU, EPHEMERAL_STORAGE, MEMORY, PODS

WORD_BITS = 32


def _next_pow2(n: int, minimum: int) -> int:
    v = minimum
    while v < n:
        v *= 2
    return v


class ResourceVocab:
    """Resource name → (slot, scale). Slots 0..3 are pinned well-known resources."""

    PINNED: List[Tuple[str, int]] = [
        (CPU, 1),                      # already millicores
        (MEMORY, 2**20),               # bytes → MiB
        (PODS, 1),
        (EPHEMERAL_STORAGE, 2**20),    # bytes → MiB
    ]

    def __init__(self, min_slots: int = 8):
        self._lock = locking.Mutex()
        self._slots: Dict[str, int] = {}
        self._scales: Dict[str, int] = {}
        self._min_slots = min_slots
        for name, scale in self.PINNED:
            self._slots[name] = len(self._slots)
            self._scales[name] = scale

    def slot(self, name: str) -> int:
        with self._lock:
            idx = self._slots.get(name)
            if idx is None:
                idx = len(self._slots)
                self._slots[name] = idx
                self._scales[name] = 1
            return idx

    def scale(self, name: str) -> int:
        with self._lock:
            return self._scales.get(name, 1)

    def quantize(self, name: str, value: int) -> float:
        """Host value → device units (ceil for requests is the caller's choice)."""
        return value / self.scale(name)

    @property
    def num_slots(self) -> int:
        """Padded slot count (the R dimension)."""
        with self._lock:
            return _next_pow2(len(self._slots), self._min_slots)

    def used_slots(self) -> int:
        with self._lock:
            return len(self._slots)

    def items(self) -> List[Tuple[str, int, int]]:
        with self._lock:
            return [(n, i, self._scales[n]) for n, i in self._slots.items()]


class BitVocab:
    """Interned symbols → bit positions; exposes word count W (padded)."""

    def __init__(self, min_words: int = 4):
        self._lock = locking.Mutex()
        self._bits: Dict[object, int] = {}
        self._min_words = min_words

    def bit(self, symbol: object) -> int:
        with self._lock:
            idx = self._bits.get(symbol)
            if idx is None:
                idx = len(self._bits)
                self._bits[symbol] = idx
            return idx

    def lookup(self, symbol: object) -> int:
        """Like bit() but returns -1 instead of interning unknown symbols."""
        with self._lock:
            return self._bits.get(symbol, -1)

    @property
    def num_words(self) -> int:
        with self._lock:
            return _next_pow2(max(1, (len(self._bits) + WORD_BITS - 1) // WORD_BITS), self._min_words)

    def used_bits(self) -> int:
        with self._lock:
            return len(self._bits)

    def symbols(self) -> List[Tuple[object, int]]:
        with self._lock:
            return list(self._bits.items())


# Symbol constructors -------------------------------------------------------

ANY = "*"


def label_bit(key: str, value: str) -> Tuple[str, str, str]:
    return ("label", key, value)


def label_key_bit(key: str) -> Tuple[str, str, str]:
    """The (key, *) presence bit backing Exists/DoesNotExist."""
    return ("label", key, ANY)


def taint_bit(key: str, value: str, effect: str) -> Tuple[str, str, str, str]:
    return ("taint", key, value, effect)


def port_bit(protocol: str, port: int) -> Tuple[str, str, int]:
    return ("port", protocol or "TCP", port)


class Vocabs:
    """The bundle a snapshot encoder works against."""

    def __init__(self):
        self.resources = ResourceVocab()
        self.labels = BitVocab()
        self.taints = BitVocab()
        self.ports = BitVocab()
