"""yunikorn_tpu: a TPU-native batch scheduling framework.

Capability-equivalent to apache/yunikorn-k8shim + in-process yunikorn-core, with
the per-pod scheduling loop reframed as a batched constraint solve on TPU
(JAX/XLA/Pallas). See SURVEY.md for the capability blueprint.
"""

__version__ = "0.1.0"
