"""Informer-backed namespace and priority-class caches for admission.

Role-equivalent to pkg/admission/namespace_cache.go:33-170 (tri-state
enableYuniKorn / generateAppId namespace annotations) and
priority_class_cache.go:34-120 (allow-preemption annotation).
"""
from __future__ import annotations

from typing import Dict, Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants

TRI_TRUE = 1
TRI_FALSE = 0
TRI_UNSET = -1


def _tri(value: Optional[str]) -> int:
    if value is None:
        return TRI_UNSET
    return TRI_TRUE if value.strip().lower() == "true" else TRI_FALSE


class NamespaceCache:
    def __init__(self):
        self._lock = locking.Mutex()
        self._flags: Dict[str, tuple] = {}  # ns -> (enableYuniKorn, generateAppId)

    def namespace_updated(self, name: str, annotations: Dict[str, str]) -> None:
        with self._lock:
            self._flags[name] = (
                _tri(annotations.get(constants.ANNOTATION_ENABLE_YUNIKORN)),
                _tri(annotations.get(constants.ANNOTATION_GENERATE_APP_ID)),
            )

    def namespace_deleted(self, name: str) -> None:
        with self._lock:
            self._flags.pop(name, None)

    def enable_yunikorn(self, ns: str) -> int:
        with self._lock:
            return self._flags.get(ns, (TRI_UNSET, TRI_UNSET))[0]

    def generate_app_id(self, ns: str) -> int:
        with self._lock:
            return self._flags.get(ns, (TRI_UNSET, TRI_UNSET))[1]


class PriorityClassCache:
    def __init__(self):
        self._lock = locking.Mutex()
        self._allow: Dict[str, bool] = {}

    def priority_class_updated(self, name: str, annotations: Dict[str, str]) -> None:
        with self._lock:
            self._allow[name] = (
                annotations.get(constants.ANNOTATION_ALLOW_PREEMPTION) != constants.FALSE
            )

    def priority_class_deleted(self, name: str) -> None:
        with self._lock:
            self._allow.pop(name, None)

    def is_preemption_allowed(self, name: str) -> bool:
        """Default True for unknown classes (reference behavior)."""
        with self._lock:
            return self._allow.get(name, True)


def attach_informers(api_provider, conf_holder, ns_cache: NamespaceCache,
                     pc_cache: PriorityClassCache,
                     namespace: str = "yunikorn") -> None:
    """Wire the admission controller's informer-fed state (reference
    cmd/admissioncontroller/main.go:55-110 starts namespace + priorityclass
    informers and the conf hot-reload; am_conf.go:85-394 reloads the
    standalone conf from the yunikorn configmaps)."""
    from yunikorn_tpu.client.interfaces import InformerType, ResourceEventHandlers

    def on_ns(ns) -> None:
        ns_cache.namespace_updated(ns.metadata.name, dict(ns.metadata.annotations))

    def on_ns_deleted(ns) -> None:
        ns_cache.namespace_deleted(ns.metadata.name)

    def on_pc(pc) -> None:
        pc_cache.priority_class_updated(pc.name, dict(pc.metadata.annotations))

    def on_pc_deleted(pc) -> None:
        pc_cache.priority_class_deleted(pc.name)

    _cms: Dict[str, Dict[str, str]] = {}

    def is_yunikorn_cm(cm) -> bool:
        return (cm.metadata.namespace == namespace
                and cm.metadata.name in ("yunikorn-defaults", "yunikorn-configs"))

    def _rebuild() -> None:
        flat: Dict[str, str] = {}
        for name in ("yunikorn-defaults", "yunikorn-configs"):
            flat.update(_cms.get(name, {}))
        conf_holder.update(flat)

    def on_cm(cm) -> None:
        _cms[cm.metadata.name] = dict(cm.data)
        _rebuild()

    def on_cm_deleted(cm) -> None:
        _cms.pop(cm.metadata.name, None)
        _rebuild()

    api_provider.add_event_handler(InformerType.NAMESPACE, ResourceEventHandlers(
        add_fn=on_ns, update_fn=lambda old, new: on_ns(new), delete_fn=on_ns_deleted))
    api_provider.add_event_handler(InformerType.PRIORITY_CLASS, ResourceEventHandlers(
        add_fn=on_pc, update_fn=lambda old, new: on_pc(new), delete_fn=on_pc_deleted))
    api_provider.add_event_handler(InformerType.CONFIGMAP, ResourceEventHandlers(
        filter_fn=is_yunikorn_cm,
        add_fn=on_cm, update_fn=lambda old, new: on_cm(new), delete_fn=on_cm_deleted))
