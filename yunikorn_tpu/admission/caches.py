"""Informer-backed namespace and priority-class caches for admission.

Role-equivalent to pkg/admission/namespace_cache.go:33-170 (tri-state
enableYuniKorn / generateAppId namespace annotations) and
priority_class_cache.go:34-120 (allow-preemption annotation).
"""
from __future__ import annotations

from typing import Dict, Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants

TRI_TRUE = 1
TRI_FALSE = 0
TRI_UNSET = -1


def _tri(value: Optional[str]) -> int:
    if value is None:
        return TRI_UNSET
    return TRI_TRUE if value.strip().lower() == "true" else TRI_FALSE


class NamespaceCache:
    def __init__(self):
        self._lock = locking.Mutex()
        self._flags: Dict[str, tuple] = {}  # ns -> (enableYuniKorn, generateAppId)

    def namespace_updated(self, name: str, annotations: Dict[str, str]) -> None:
        with self._lock:
            self._flags[name] = (
                _tri(annotations.get(constants.ANNOTATION_ENABLE_YUNIKORN)),
                _tri(annotations.get(constants.ANNOTATION_GENERATE_APP_ID)),
            )

    def namespace_deleted(self, name: str) -> None:
        with self._lock:
            self._flags.pop(name, None)

    def enable_yunikorn(self, ns: str) -> int:
        with self._lock:
            return self._flags.get(ns, (TRI_UNSET, TRI_UNSET))[0]

    def generate_app_id(self, ns: str) -> int:
        with self._lock:
            return self._flags.get(ns, (TRI_UNSET, TRI_UNSET))[1]


class PriorityClassCache:
    def __init__(self):
        self._lock = locking.Mutex()
        self._allow: Dict[str, bool] = {}

    def priority_class_updated(self, name: str, annotations: Dict[str, str]) -> None:
        with self._lock:
            self._allow[name] = (
                annotations.get(constants.ANNOTATION_ALLOW_PREEMPTION) != constants.FALSE
            )

    def priority_class_deleted(self, name: str) -> None:
        with self._lock:
            self._allow.pop(name, None)

    def is_preemption_allowed(self, name: str) -> bool:
        """Default True for unknown classes (reference behavior)."""
        with self._lock:
            return self._allow.get(name, True)
