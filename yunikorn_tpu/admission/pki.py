"""Self-managed PKI for the admission webhook.

Role-equivalent to pkg/admission/webhook_manager.go:57-799's cert handling +
pki/certs.go:39-199: self-signed CA pairs (12-month expiry, keep the best of
two and rotate the older — reference :644-770), server certificates signed by
the freshest CA, and the caBundle used to patch webhook configurations.
"""
from __future__ import annotations

import dataclasses
import datetime
from typing import List, Optional, Tuple

# The PKI needs the `cryptography` package, which is not part of the baked
# build environment (the scheduler path never touches it; only the admission
# webhook binary does). Importing this MODULE stays safe either way — the
# first actual PKI operation raises a clear RuntimeError instead of a deep
# ModuleNotFoundError at import time (see TESTING.md: the webhook/PKI test
# tier skips when the package is absent).
try:
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    HAVE_CRYPTOGRAPHY = True
    _IMPORT_ERROR: Optional[BaseException] = None
except ImportError as _e:  # pragma: no cover - environment-dependent
    HAVE_CRYPTOGRAPHY = False
    _IMPORT_ERROR = _e
    x509 = hashes = serialization = rsa = NameOID = None  # type: ignore


def _require_cryptography() -> None:
    if not HAVE_CRYPTOGRAPHY:
        raise RuntimeError(
            "the admission webhook's PKI requires the 'cryptography' "
            f"package, which is not installed: {_IMPORT_ERROR}")


CA_VALIDITY_DAYS = 365        # 12-month expiry (reference webhook_manager.go)
SERVER_VALIDITY_DAYS = 365


@dataclasses.dataclass
class CertPair:
    cert_pem: bytes
    key_pem: bytes

    @property
    def certificate(self) -> x509.Certificate:
        return x509.load_pem_x509_certificate(self.cert_pem)

    def expires_at(self) -> datetime.datetime:
        return self.certificate.not_valid_after_utc

    def seconds_until_expiry(self) -> float:
        return (self.expires_at() - datetime.datetime.now(datetime.timezone.utc)).total_seconds()


def _new_key() -> rsa.RSAPrivateKey:
    _require_cryptography()
    return rsa.generate_private_key(public_exponent=65537, key_size=2048)


def _key_pem(key: rsa.RSAPrivateKey) -> bytes:
    return key.private_bytes(
        serialization.Encoding.PEM,
        serialization.PrivateFormat.TraditionalOpenSSL,
        serialization.NoEncryption(),
    )


def generate_ca(common_name: str = "yunikorn-admission-ca") -> CertPair:
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, common_name)])
    cert = (
        x509.CertificateBuilder()
        .subject_name(name)
        .issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=CA_VALIDITY_DAYS))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None), critical=True)
        .add_extension(x509.KeyUsage(
            digital_signature=True, key_cert_sign=True, crl_sign=True,
            content_commitment=False, key_encipherment=False, data_encipherment=False,
            key_agreement=False, encipher_only=False, decipher_only=False,
        ), critical=True)
        .sign(key, hashes.SHA256())
    )
    return CertPair(cert.public_bytes(serialization.Encoding.PEM), _key_pem(key))


def generate_server_cert(ca: CertPair, dns_names: List[str]) -> CertPair:
    ca_cert = ca.certificate
    ca_key = serialization.load_pem_private_key(ca.key_pem, password=None)
    key = _new_key()
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, dns_names[0])]))
        .issuer_name(ca_cert.subject)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=SERVER_VALIDITY_DAYS))
        .add_extension(
            x509.SubjectAlternativeName([x509.DNSName(n) for n in dns_names]),
            critical=False,
        )
        .sign(ca_key, hashes.SHA256())
    )
    return CertPair(cert.public_bytes(serialization.Encoding.PEM), _key_pem(key))


class CACollection:
    """Best-of-two CA rotation (reference webhook_manager.go:644-770).

    Two CA pairs are kept; the freshest signs server certs; when the older one
    crosses the rotation threshold it is regenerated. The combined bundle (both
    CAs) is what webhook configurations carry so rotation never breaks trust.
    """

    ROTATE_BEFORE_SECONDS = 90 * 24 * 3600.0

    def __init__(self, pairs: Optional[List[CertPair]] = None):
        _require_cryptography()
        self.pairs: List[CertPair] = pairs or [generate_ca(), generate_ca()]

    def best(self) -> CertPair:
        return max(self.pairs, key=lambda p: p.expires_at())

    def rotate_if_needed(self) -> bool:
        rotated = False
        for i, pair in enumerate(self.pairs):
            if pair.seconds_until_expiry() < self.ROTATE_BEFORE_SECONDS:
                self.pairs[i] = generate_ca()
                rotated = True
        return rotated

    def ca_bundle(self) -> bytes:
        return b"".join(p.cert_pem for p in self.pairs)

    def server_credentials(self, dns_names: List[str]) -> Tuple[CertPair, bytes]:
        return generate_server_cert(self.best(), dns_names), self.ca_bundle()
