"""Admission controller configuration with hot reload.

Role-equivalent to pkg/admission/conf/am_conf.go:85-394: `admissionController.*`
keys from the same two ConfigMaps the scheduler uses, regex-list filtering
options, access-control settings, atomic swap on reload.
"""
from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Pattern

from yunikorn_tpu.locking import locking
from yunikorn_tpu.log.logger import log

logger = log("admission.conf")

PREFIX = "admissionController."

AM_FILTERING_PROCESS_NAMESPACES = PREFIX + "filtering.processNamespaces"
AM_FILTERING_BYPASS_NAMESPACES = PREFIX + "filtering.bypassNamespaces"
AM_FILTERING_LABEL_NAMESPACES = PREFIX + "filtering.labelNamespaces"
AM_FILTERING_NO_LABEL_NAMESPACES = PREFIX + "filtering.noLabelNamespaces"
AM_FILTERING_GENERATE_UNIQUE_APP_IDS = PREFIX + "filtering.generateUniqueAppId"
AM_FILTERING_DEFAULT_QUEUE = PREFIX + "filtering.defaultQueue"
AM_ACCESS_CONTROL_BYPASS_AUTH = PREFIX + "accessControl.bypassAuth"
AM_ACCESS_CONTROL_TRUST_CONTROLLERS = PREFIX + "accessControl.trustControllers"
AM_ACCESS_CONTROL_SYSTEM_USERS = PREFIX + "accessControl.systemUsers"
AM_ACCESS_CONTROL_EXTERNAL_USERS = PREFIX + "accessControl.externalUsers"
AM_ACCESS_CONTROL_EXTERNAL_GROUPS = PREFIX + "accessControl.externalGroups"
AM_WEBHOOK_SCHEDULER_SERVICE_ADDRESS = PREFIX + "webHook.schedulerServiceAddress"
AM_WEBHOOK_AM_SERVICE_NAME = PREFIX + "webHook.amServiceName"

DEFAULT_BYPASS_NAMESPACES = "^kube-system$"
DEFAULT_SYSTEM_USERS = "^system:serviceaccount:kube-system:"
DEFAULT_QUEUE = "root.default"


def _compile_list(raw: str) -> List[Pattern]:
    out = []
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            out.append(re.compile(part))
        except re.error as e:
            logger.error("invalid regex %r ignored: %s", part, e)
    return out


@dataclasses.dataclass
class AdmissionConf:
    process_namespaces: List[Pattern] = dataclasses.field(default_factory=list)
    bypass_namespaces: List[Pattern] = dataclasses.field(
        default_factory=lambda: _compile_list(DEFAULT_BYPASS_NAMESPACES))
    label_namespaces: List[Pattern] = dataclasses.field(default_factory=list)
    no_label_namespaces: List[Pattern] = dataclasses.field(default_factory=list)
    generate_unique_app_ids: bool = False
    default_queue: str = DEFAULT_QUEUE
    bypass_auth: bool = False
    trust_controllers: bool = True
    system_users: List[Pattern] = dataclasses.field(
        default_factory=lambda: _compile_list(DEFAULT_SYSTEM_USERS))
    external_users: List[Pattern] = dataclasses.field(default_factory=list)
    external_groups: List[Pattern] = dataclasses.field(default_factory=list)
    scheduler_service_address: str = "yunikorn-service:9080"
    am_service_name: str = "yunikorn-admission-controller-service"
    namespace: str = "yunikorn"

    # -- filtering decisions (reference admission_controller.go:469-538) ----
    @staticmethod
    def _matches(patterns: List[Pattern], value: str) -> bool:
        return any(p.search(value) for p in patterns)

    def should_process_namespace(self, ns: str) -> bool:
        if self._matches(self.bypass_namespaces, ns):
            return False
        if self.process_namespaces:
            return self._matches(self.process_namespaces, ns)
        return True

    def should_label_namespace(self, ns: str) -> bool:
        if self._matches(self.no_label_namespaces, ns):
            return False
        if self.label_namespaces:
            return self._matches(self.label_namespaces, ns)
        return True

    def is_system_user(self, user: str) -> bool:
        return self._matches(self.system_users, user)

    def is_external_user(self, user: str) -> bool:
        return self._matches(self.external_users, user)

    def is_external_group(self, group: str) -> bool:
        return self._matches(self.external_groups, group)


def parse_admission_conf(flat: Dict[str, str], namespace: str = "yunikorn") -> AdmissionConf:
    def b(key: str, default: bool) -> bool:
        v = flat.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes")

    return AdmissionConf(
        process_namespaces=_compile_list(flat.get(AM_FILTERING_PROCESS_NAMESPACES, "")),
        bypass_namespaces=_compile_list(flat.get(AM_FILTERING_BYPASS_NAMESPACES,
                                                 DEFAULT_BYPASS_NAMESPACES)),
        label_namespaces=_compile_list(flat.get(AM_FILTERING_LABEL_NAMESPACES, "")),
        no_label_namespaces=_compile_list(flat.get(AM_FILTERING_NO_LABEL_NAMESPACES, "")),
        generate_unique_app_ids=b(AM_FILTERING_GENERATE_UNIQUE_APP_IDS, False),
        default_queue=flat.get(AM_FILTERING_DEFAULT_QUEUE, DEFAULT_QUEUE),
        bypass_auth=b(AM_ACCESS_CONTROL_BYPASS_AUTH, False),
        trust_controllers=b(AM_ACCESS_CONTROL_TRUST_CONTROLLERS, True),
        system_users=_compile_list(flat.get(AM_ACCESS_CONTROL_SYSTEM_USERS, DEFAULT_SYSTEM_USERS)),
        external_users=_compile_list(flat.get(AM_ACCESS_CONTROL_EXTERNAL_USERS, "")),
        external_groups=_compile_list(flat.get(AM_ACCESS_CONTROL_EXTERNAL_GROUPS, "")),
        scheduler_service_address=flat.get(AM_WEBHOOK_SCHEDULER_SERVICE_ADDRESS,
                                           "yunikorn-service:9080"),
        am_service_name=flat.get(AM_WEBHOOK_AM_SERVICE_NAME,
                                 "yunikorn-admission-controller-service"),
        namespace=namespace,
    )


class AdmissionConfHolder:
    def __init__(self):
        self._lock = locking.Mutex()
        self._conf = AdmissionConf()

    def get(self) -> AdmissionConf:
        with self._lock:
            return self._conf

    def update(self, flat: Dict[str, str]) -> AdmissionConf:
        conf = parse_admission_conf(flat)
        with self._lock:
            self._conf = conf
        logger.info("admission controller configuration reloaded")
        return conf
