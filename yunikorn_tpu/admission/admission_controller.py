"""Admission controller: mutating + validating webhook logic.

Role-equivalent to pkg/admission/admission_controller.go: `mutate` dispatch by
kind (:125-156), processPod (:157-217 — user-info injection unless bypassAuth,
skip yunikorn's own pods, namespace filtering, schedulerName patch :368-375,
appID/queue labels util.go:32-66, preemption policy from PriorityClass
:377-415), processWorkload (:218-281 — Deployments/StatefulSets/... get
user-info on their pod templates), processPodUpdate (:282-321 — user-info
immutability), validateConf (:435-467 — proxies the new configmap to the
scheduler's validate endpoint).

Works on K8s-wire-shaped dicts (AdmissionReview in, AdmissionResponse with a
base64 JSONPatch out), so it is drop-in compatible with real API-server
payloads even though the rest of the framework uses the K8s-lite object model.
"""
from __future__ import annotations

import base64
import json
from typing import Callable, Dict, List, Optional

from yunikorn_tpu.admission.caches import (
    NamespaceCache,
    PriorityClassCache,
    TRI_FALSE,
    TRI_TRUE,
)
from yunikorn_tpu.admission.conf import AdmissionConf
from yunikorn_tpu.common import constants
from yunikorn_tpu.log.logger import log

logger = log("admission")

WORKLOAD_KINDS = ("Deployment", "DaemonSet", "StatefulSet", "ReplicaSet", "Job", "CronJob")


class AdmissionDenied(Exception):
    """Raised inside mutation when the request must be REJECTED (external
    authentication violations) — unlike internal errors, which fail open."""


class AdmissionController:
    def __init__(self, conf: AdmissionConf,
                 namespace_cache: Optional[NamespaceCache] = None,
                 pc_cache: Optional[PriorityClassCache] = None,
                 validate_conf_fn: Optional[Callable[[str], tuple]] = None,
                 conf_holder=None):
        # with a holder, every request reads the LIVE conf (standalone-binary
        # hot reload, reference am_conf.go:85-394); else the snapshot given
        self._conf = conf
        self._conf_holder = conf_holder
        self.namespaces = namespace_cache or NamespaceCache()
        self.priority_classes = pc_cache or PriorityClassCache()
        # seam to the scheduler's /ws/v1/validate-conf (in-process or HTTP)
        self._validate_conf_fn = validate_conf_fn

    @property
    def conf(self) -> AdmissionConf:
        return self._conf_holder.get() if self._conf_holder is not None else self._conf

    # ------------------------------------------------------------------ mutate
    def mutate(self, review: Dict) -> Dict:
        """AdmissionReview dict in → AdmissionReview dict out (reference :125-156)."""
        request = review.get("request") or {}
        uid = request.get("uid", "")
        kind = ((request.get("kind") or {}).get("kind", ""))
        namespace = request.get("namespace", "")
        operation = request.get("operation", "CREATE")
        patch: List[Dict] = []

        try:
            obj = request.get("object") or {}
            if kind == "Pod":
                if operation == "CREATE":
                    patch = self._process_pod(obj, request, namespace)
                elif operation == "UPDATE":
                    old = request.get("oldObject") or {}
                    err = self._process_pod_update(obj, old)
                    if err:
                        return _review_response(uid, allowed=False, message=err)
            elif kind in WORKLOAD_KINDS and operation in ("CREATE", "UPDATE"):
                # ReplicaSet created BY a controller (system user): never
                # touch the spec — patching it spawns a fresh ReplicaSet and
                # loops forever (reference shouldProcessWorkload :330-344).
                # Deliberately independent of trustControllers.
                user = ((request.get("userInfo") or {}).get("username", ""))
                if kind == "ReplicaSet" and self.conf.is_system_user(user):
                    patch = []
                else:
                    old = (request.get("oldObject") or {}
                           if operation == "UPDATE" else {})
                    patch = self._process_workload(obj, request, namespace,
                                                   kind, old)
        except AdmissionDenied as e:
            return _review_response(uid, allowed=False, message=str(e))
        except Exception as e:  # admission must fail open on internal errors
            logger.exception("mutation failed")
            return _review_response(uid, allowed=True, message=str(e))

        return _review_response(uid, allowed=True, patch=patch)

    # ---------------------------------------------------------- pod mutation
    def _process_pod(self, pod: Dict, request: Dict, namespace: str) -> List[Dict]:
        patch: List[Dict] = []
        meta = pod.get("metadata") or {}
        labels = dict(meta.get("labels") or {})
        annotations = dict(meta.get("annotations") or {})
        spec = pod.get("spec") or {}

        if not self._should_process(namespace, labels, annotations):
            # even unprocessed namespaces may get user info (reference order)
            return self._user_info_patch(annotations, request, [])

        # never mutate the scheduler's own pods
        if labels.get(constants.LABEL_APP) in ("yunikorn", "yunikorn-admission-controller"):
            return []

        patch = self._user_info_patch(annotations, request, patch)

        # schedulerName patch (reference updateSchedulerName :368-375)
        if spec.get("schedulerName") != constants.SCHEDULER_NAME:
            patch.append({"op": "add" if "schedulerName" not in spec else "replace",
                          "path": "/spec/schedulerName",
                          "value": constants.SCHEDULER_NAME})

        # appID/queue labels (reference util.go:32-66 updatePodLabel)
        if self._should_label(namespace, labels, annotations):
            new_labels = dict(labels)
            has_app_id = any(labels.get(k) for k in (
                constants.CANONICAL_LABEL_APP_ID, constants.LABEL_APPLICATION_ID,
                constants.LABEL_SPARK_APP_ID)) or annotations.get(constants.ANNOTATION_APP_ID)
            if not has_app_id:
                ns = namespace or "default"
                if self._generate_unique(namespace):
                    app_id = f"{ns}-{meta.get('uid', meta.get('name', 'autogen'))}"
                else:
                    app_id = f"yunikorn-{ns}-autogen"
                new_labels[constants.LABEL_APPLICATION_ID] = app_id
            has_queue = (labels.get(constants.CANONICAL_LABEL_QUEUE_NAME)
                         or labels.get(constants.LABEL_QUEUE_NAME)
                         or annotations.get(constants.ANNOTATION_QUEUE_NAME))
            if not has_queue and self.conf.default_queue:
                new_labels[constants.LABEL_QUEUE_NAME] = self.conf.default_queue
            if new_labels != labels:
                patch.append({"op": "add" if not meta.get("labels") else "replace",
                              "path": "/metadata/labels",
                              "value": new_labels})

        # preemption policy from PriorityClass (reference :377-415)
        pc_name = spec.get("priorityClassName", "")
        if pc_name and not self.priority_classes.is_preemption_allowed(pc_name):
            new_annotations = dict(annotations)
            new_annotations[constants.ANNOTATION_ALLOW_PREEMPTION] = constants.FALSE
            patch.append({"op": "add" if not meta.get("annotations") else "replace",
                          "path": "/metadata/annotations",
                          "value": new_annotations})
        return patch

    def _user_info_patch(self, annotations: Dict[str, str], request: Dict,
                         patch: List[Dict]) -> List[Dict]:
        """Inject the user-info annotation (reference processPod auth part)."""
        if self.conf.bypass_auth:
            return patch
        user_info = request.get("userInfo") or {}
        username = user_info.get("username", "")
        groups = list(user_info.get("groups") or [])
        if self.conf.trust_controllers and self.conf.is_system_user(username):
            return patch
        existing = annotations.get(constants.ANNOTATION_USER_INFO)
        if existing is not None:
            self._check_user_info_annotation(existing, username, groups)
            return patch          # allowed external identity: keep as set
        new_annotations = dict(annotations)
        new_annotations[constants.ANNOTATION_USER_INFO] = json.dumps(
            {"user": username or constants.DEFAULT_USER, "groups": groups})
        patch.append({"op": "add" if not annotations else "replace",
                      "path": "/metadata/annotations",
                      "value": new_annotations})
        return patch

    def _check_user_info_annotation(self, annotation: str, username: str,
                                    groups: List[str]) -> None:
        """A pre-set user-info annotation is only acceptable from an allowed
        external identity, and must parse as valid user info (reference
        checkUserInfoAnnotation :346-365 — deny, never silently overwrite)."""
        allowed = (self.conf.is_external_user(username)
                   or any(self.conf.is_external_group(g) for g in groups))
        if not allowed:
            raise AdmissionDenied(
                f"user {username} with groups [{','.join(groups)}] is not "
                f"allowed to set user annotation")
        try:
            info = json.loads(annotation)
        except (TypeError, json.JSONDecodeError):
            raise AdmissionDenied(
                f"invalid user info annotation: {annotation!r}")
        if (not isinstance(info, dict)
                or not isinstance(info.get("user", ""), str)
                or not isinstance(info.get("groups", []), list)):
            raise AdmissionDenied(
                f"invalid user info annotation: {annotation!r}")

    def _process_pod_update(self, new: Dict, old: Dict) -> Optional[str]:
        """User-info immutability (reference :282-321)."""
        if self.conf.bypass_auth:
            return None
        old_info = ((old.get("metadata") or {}).get("annotations") or {}).get(
            constants.ANNOTATION_USER_INFO)
        new_info = ((new.get("metadata") or {}).get("annotations") or {}).get(
            constants.ANNOTATION_USER_INFO)
        if old_info is not None and new_info != old_info:
            return f"annotation {constants.ANNOTATION_USER_INFO} is immutable"
        return None

    # ----------------------------------------------------- workload mutation
    def _process_workload(self, obj: Dict, request: Dict, namespace: str,
                          kind: str, old: Optional[Dict] = None) -> List[Dict]:
        """Inject user info into pod templates (reference :218-281)."""
        meta = obj.get("metadata") or {}
        labels = dict(meta.get("labels") or {})
        annotations = dict(meta.get("annotations") or {})
        if not self._should_process(namespace, labels, annotations):
            return []
        if self.conf.bypass_auth:
            return []
        user_info = request.get("userInfo") or {}
        username = user_info.get("username", "")
        if self.conf.trust_controllers and self.conf.is_system_user(username):
            return []
        template_path = "/spec/jobTemplate/spec/template" if kind == "CronJob" \
            else "/spec/template"
        spec = obj.get("spec") or {}
        if kind == "CronJob":
            template = ((spec.get("jobTemplate") or {}).get("spec") or {}).get("template") or {}
        else:
            template = spec.get("template") or {}
        t_meta = template.get("metadata") or {}
        t_annotations = dict(t_meta.get("annotations") or {})
        existing = t_annotations.get(constants.ANNOTATION_USER_INFO)
        if existing is not None:
            # an UNCHANGED annotation on UPDATE is the one this controller
            # injected at CREATE — scale/apply by the original submitter must
            # not be denied for "setting" it (reference compares old vs new)
            if existing == self._old_template_user_info(old or {}, kind):
                return []
            # template (re)sets the identity: allowed externals keep it,
            # everyone else is denied (same rule as bare pods)
            self._check_user_info_annotation(
                existing, username, list(user_info.get("groups") or []))
            return []
        t_annotations[constants.ANNOTATION_USER_INFO] = json.dumps(
            {"user": username or constants.DEFAULT_USER,
             "groups": list(user_info.get("groups") or [])})
        return [{
            "op": "add" if not t_meta.get("annotations") else "replace",
            "path": f"{template_path}/metadata/annotations",
            "value": t_annotations,
        }]

    @staticmethod
    def _old_template_user_info(old: Dict, kind: str) -> Optional[str]:
        spec = old.get("spec") or {}
        if kind == "CronJob":
            template = ((spec.get("jobTemplate") or {}).get("spec") or {}).get(
                "template") or {}
        else:
            template = spec.get("template") or {}
        return ((template.get("metadata") or {}).get("annotations") or {}).get(
            constants.ANNOTATION_USER_INFO)

    # ------------------------------------------------------------- filtering
    def _should_process(self, namespace: str, labels: Dict, annotations: Dict) -> bool:
        if annotations.get(constants.ANNOTATION_IGNORE_APPLICATION) == constants.TRUE:
            return False
        flag = self.namespaces.enable_yunikorn(namespace)
        if flag == TRI_TRUE:
            return True
        if flag == TRI_FALSE:
            return False
        return self.conf.should_process_namespace(namespace)

    def _should_label(self, namespace: str, labels: Dict, annotations: Dict) -> bool:
        flag = self.namespaces.generate_app_id(namespace)
        if flag == TRI_TRUE:
            return True
        if flag == TRI_FALSE:
            return False
        return self.conf.should_label_namespace(namespace)

    def _generate_unique(self, namespace: str) -> bool:
        return self.conf.generate_unique_app_ids

    # ------------------------------------------------------------ validation
    def validate_conf(self, review: Dict) -> Dict:
        """ConfigMap validation webhook (reference validateConf :435-467)."""
        request = review.get("request") or {}
        uid = request.get("uid", "")
        obj = request.get("object") or {}
        meta = obj.get("metadata") or {}
        if meta.get("name") not in (constants.CONFIGMAP_NAME, constants.DEFAULT_CONFIGMAP_NAME):
            return _review_response(uid, allowed=True)
        if request.get("operation") == "DELETE":
            return _review_response(uid, allowed=True)
        data = obj.get("data") or {}
        queues_yaml = data.get("queues.yaml", "")
        if self._validate_conf_fn is None:
            return _review_response(uid, allowed=True)
        ok, message = self._validate_conf_fn(queues_yaml)
        return _review_response(uid, allowed=ok, message=message)


def _review_response(uid: str, allowed: bool, patch: Optional[List[Dict]] = None,
                     message: str = "") -> Dict:
    response: Dict = {"uid": uid, "allowed": allowed}
    if message:
        response["result"] = {"message": message}
    if patch:
        response["patchType"] = "JSONPatch"
        response["patch"] = base64.b64encode(json.dumps(patch).encode()).decode()
    return {"apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "response": response}


def decode_patch(review_response: Dict) -> List[Dict]:
    """Test helper: extract the JSONPatch from a mutate() result."""
    raw = (review_response.get("response") or {}).get("patch")
    if not raw:
        return []
    return json.loads(base64.b64decode(raw))
