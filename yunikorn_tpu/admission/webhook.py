"""Webhook HTTP server + webhook manager.

Role-equivalent to the admission-controller binary's server
(pkg/cmd/admissioncontroller/main.go:55-110: HTTPS on :9089 with /health,
/mutate, /validate-conf; SIGUSR1 cert reload) and the WebhookManager's
install/patch of the webhook configurations with the caBundle
(webhook_manager.go:185-379). Serving is stdlib http.server; TLS uses the
self-managed PKI when enabled (plain HTTP is the in-process test mode).
"""
from __future__ import annotations

import json
import ssl
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from yunikorn_tpu.admission.admission_controller import AdmissionController
from yunikorn_tpu.admission.pki import CACollection
from yunikorn_tpu.log.logger import log

logger = log("admission.webhook")

MUTATE_PATH = "/mutate"
VALIDATE_CONF_PATH = "/validate-conf"
HEALTH_PATH = "/health"


class WebhookServer:
    def __init__(self, controller: AdmissionController, host: str = "127.0.0.1",
                 port: int = 9089, use_tls: bool = False,
                 cas: Optional[CACollection] = None):
        self.controller = controller
        self.host = host
        self.port = port
        self.use_tls = use_tls
        self.cas = cas
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        controller = self.controller

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                logger.debug("webhook: " + fmt, *args)

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode() if not isinstance(payload, bytes) else payload
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == HEALTH_PATH:
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self._reply(400, {"error": "invalid JSON"})
                    return
                if self.path == MUTATE_PATH:
                    self._reply(200, controller.mutate(review))
                elif self.path == VALIDATE_CONF_PATH:
                    self._reply(200, controller.validate_conf(review))
                else:
                    self._reply(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.use_tls:
            if self.cas is None:
                self.cas = CACollection()
            server_pair, _ = self.cas.server_credentials([self.host, "localhost"])
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile(suffix=".pem") as certf, \
                    tempfile.NamedTemporaryFile(suffix=".pem") as keyf:
                certf.write(server_pair.cert_pem)
                certf.flush()
                keyf.write(server_pair.key_pem)
                keyf.flush()
                ctx.load_cert_chain(certf.name, keyf.name)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket, server_side=True)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="admission-webhook", daemon=True)
        self._thread.start()
        logger.info("admission webhook serving on %s:%d (tls=%s)",
                    self.host, self.port, self.use_tls)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class WebhookManager:
    """Maintains the webhook registrations + caBundle (reference :57-799).

    Against a real cluster this installs/patches Mutating/Validating
    WebhookConfiguration objects; here it renders the manifests so an adapter
    (or operator) can apply them, and owns CA rotation.
    """

    def __init__(self, conf, cas: Optional[CACollection] = None):
        self.conf = conf
        self.cas = cas or CACollection()

    def mutating_webhook_config(self) -> dict:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "yunikorn-admission-controller-cfg"},
            "webhooks": [{
                "name": "admission-webhook.yunikorn.validator",
                "clientConfig": {
                    "service": {"name": self.conf.am_service_name,
                                "namespace": self.conf.namespace,
                                "path": MUTATE_PATH},
                    "caBundle": self.cas.ca_bundle().decode(),
                },
                "rules": [
                    {"operations": ["CREATE", "UPDATE"], "apiGroups": [""],
                     "apiVersions": ["v1"], "resources": ["pods"]},
                    {"operations": ["CREATE", "UPDATE"],
                     "apiGroups": ["apps", "batch"],
                     "apiVersions": ["v1"],
                     "resources": ["deployments", "daemonsets", "statefulsets",
                                   "replicasets", "jobs", "cronjobs"]},
                ],
                "failurePolicy": "Fail",
                "sideEffects": "None",
                "admissionReviewVersions": ["v1"],
            }],
        }

    def validating_webhook_config(self) -> dict:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "yunikorn-admission-controller-cfg"},
            "webhooks": [{
                "name": "admission-webhook.yunikorn.conf-validator",
                "clientConfig": {
                    "service": {"name": self.conf.am_service_name,
                                "namespace": self.conf.namespace,
                                "path": VALIDATE_CONF_PATH},
                    "caBundle": self.cas.ca_bundle().decode(),
                },
                "rules": [{"operations": ["CREATE", "UPDATE"], "apiGroups": [""],
                           "apiVersions": ["v1"], "resources": ["configmaps"]}],
                "failurePolicy": "Ignore",
                "sideEffects": "None",
                "admissionReviewVersions": ["v1"],
            }],
        }

    def wait_for_certificate_expiration_seconds(self) -> float:
        """Time until the next CA rotation is due (reference :223-232)."""
        return min(
            p.seconds_until_expiry() - CACollection.ROTATE_BEFORE_SECONDS
            for p in self.cas.pairs
        )

    def run_certificate_expiration_loop(self, stop_event,
                                        on_rotated=None) -> "threading.Thread":
        """Background re-registration loop (reference WaitForCertificateExpiration
        :223-232): sleep until the next rotation is due, rotate the CA pair,
        and re-render/patch the webhook configurations so the caBundle stays
        valid. on_rotated(mutating_cfg, validating_cfg) applies the patch —
        against a real cluster, an Update of both WebhookConfigurations."""

        def loop():
            while not stop_event.is_set():
                wait = max(1.0, self.wait_for_certificate_expiration_seconds())
                if stop_event.wait(timeout=wait):
                    return
                if self.cas.rotate_if_needed():
                    logger.info("certificate rotation performed; "
                                "re-registering webhooks")
                    if on_rotated is not None:
                        try:
                            on_rotated(self.mutating_webhook_config(),
                                       self.validating_webhook_config())
                        except Exception:
                            logger.exception("webhook re-registration failed")

        t = threading.Thread(target=loop, name="cert-expiration", daemon=True)
        t.start()
        return t
