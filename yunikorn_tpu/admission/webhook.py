"""Webhook HTTP server + webhook manager.

Role-equivalent to the admission-controller binary's server
(pkg/cmd/admissioncontroller/main.go:55-110: HTTPS on :9089 with /health,
/mutate, /validate-conf; SIGUSR1 cert reload) and the WebhookManager's
install/patch of the webhook configurations with the caBundle
(webhook_manager.go:185-379). Serving is stdlib http.server; TLS uses the
self-managed PKI when enabled (plain HTTP is the in-process test mode).
"""
from __future__ import annotations

import json
import ssl
import tempfile
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from yunikorn_tpu.admission.admission_controller import AdmissionController
from yunikorn_tpu.admission.pki import CACollection
from yunikorn_tpu.log.logger import log

logger = log("admission.webhook")

MUTATE_PATH = "/mutate"
VALIDATE_CONF_PATH = "/validate-conf"
HEALTH_PATH = "/health"


class WebhookServer:
    def __init__(self, controller: AdmissionController, host: str = "127.0.0.1",
                 port: int = 9089, use_tls: bool = False,
                 cas: Optional[CACollection] = None):
        self.controller = controller
        self.host = host
        self.port = port
        self.use_tls = use_tls
        self.cas = cas
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        controller = self.controller

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                logger.debug("webhook: " + fmt, *args)

            def _reply(self, code: int, payload) -> None:
                body = json.dumps(payload).encode() if not isinstance(payload, bytes) else payload
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == HEALTH_PATH:
                    self._reply(200, {"status": "ok"})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):
                length = int(self.headers.get("Content-Length", "0"))
                try:
                    review = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError:
                    self._reply(400, {"error": "invalid JSON"})
                    return
                if self.path == MUTATE_PATH:
                    self._reply(200, controller.mutate(review))
                elif self.path == VALIDATE_CONF_PATH:
                    self._reply(200, controller.validate_conf(review))
                else:
                    self._reply(404, {"error": "not found"})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        if self.use_tls:
            if self.cas is None:
                self.cas = CACollection()
            server_pair, _ = self.cas.server_credentials([self.host, "localhost"])
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            with tempfile.NamedTemporaryFile(suffix=".pem") as certf, \
                    tempfile.NamedTemporaryFile(suffix=".pem") as keyf:
                certf.write(server_pair.cert_pem)
                certf.flush()
                keyf.write(server_pair.key_pem)
                keyf.flush()
                ctx.load_cert_chain(certf.name, keyf.name)
            self._httpd.socket = ctx.wrap_socket(self._httpd.socket, server_side=True)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="admission-webhook", daemon=True)
        self._thread.start()
        logger.info("admission webhook serving on %s:%d (tls=%s)",
                    self.host, self.port, self.use_tls)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None


class WebhookManager:
    """Maintains the webhook registrations + caBundle (reference :57-799).

    Renders the Mutating/Validating WebhookConfiguration manifests, owns CA
    rotation, and — given an API client — installs/patches them against the
    cluster (reference InstallWebhooks, webhook_manager.go:185-379: create
    when absent, update in place when the stored object drifts from desired,
    notably after a caBundle rotation).
    """

    WEBHOOK_PATHS = {
        "MutatingWebhookConfiguration":
            "/apis/admissionregistration.k8s.io/v1/mutatingwebhookconfigurations",
        "ValidatingWebhookConfiguration":
            "/apis/admissionregistration.k8s.io/v1/validatingwebhookconfigurations",
    }

    def __init__(self, conf, cas: Optional[CACollection] = None):
        self.conf = conf
        self.cas = cas or CACollection()

    # ------------------------------------------------------- cluster install
    def install_webhooks(self, client) -> None:
        """Create-or-update both WebhookConfigurations through the API.

        client: anything with request_json(method, path, body) —
        RealKubeClient in production, the fake API server's client in tests.
        """
        for cfg in (self.mutating_webhook_config(),
                    self.validating_webhook_config()):
            self._apply_webhook_config(client, cfg)

    def _apply_webhook_config(self, client, cfg: dict) -> None:
        import urllib.error

        base = self.WEBHOOK_PATHS[cfg["kind"]]
        name = cfg["metadata"]["name"]
        try:
            existing = client.request_json("GET", f"{base}/{name}")
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise
            client.request_json("POST", base, cfg)
            logger.info("installed %s %s", cfg["kind"], name)
            return
        if not self._webhooks_drifted(existing.get("webhooks"), cfg["webhooks"]):
            return                               # up to date (common case)
        # preserve resourceVersion for optimistic concurrency on the replace
        rv = (existing.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            cfg = {**cfg, "metadata": {**cfg["metadata"], "resourceVersion": rv}}
        client.request_json("PUT", f"{base}/{name}", cfg)
        logger.info("updated %s %s (caBundle/rules drift)", cfg["kind"], name)

    @staticmethod
    def _webhooks_drifted(existing, desired) -> bool:
        """Compare only the fields this manager owns, with server-side
        defaults stripped. A real apiserver defaults matchPolicy/
        timeoutSeconds/namespaceSelector/... on the webhook, scope on each
        rule, and port on the service ref; a verbatim comparison would see
        permanent drift and rewrite the configurations on every startup and
        rotation. (A false positive only costs one redundant PUT.)"""
        def norm(w: dict) -> dict:
            cc = dict(w.get("clientConfig") or {})
            svc = dict(cc.get("service") or {})
            if svc.get("port") == 443:           # server default
                svc.pop("port")
            cc["service"] = svc
            rules = []
            for r in w.get("rules") or []:
                r = dict(r)
                if r.get("scope") == "*":        # server default
                    r.pop("scope")
                rules.append(r)
            return {"name": w.get("name"), "clientConfig": cc, "rules": rules,
                    "failurePolicy": w.get("failurePolicy"),
                    "sideEffects": w.get("sideEffects"),
                    "admissionReviewVersions": w.get("admissionReviewVersions")}

        if existing is None or len(existing) != len(desired):
            return True
        return any(norm(h) != norm(w) for h, w in zip(existing, desired))

    def mutating_webhook_config(self) -> dict:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "MutatingWebhookConfiguration",
            "metadata": {"name": "yunikorn-admission-controller-cfg"},
            "webhooks": [{
                "name": "admission-webhook.yunikorn.validator",
                "clientConfig": {
                    "service": {"name": self.conf.am_service_name,
                                "namespace": self.conf.namespace,
                                "path": MUTATE_PATH},
                    "caBundle": self.cas.ca_bundle().decode(),
                },
                "rules": [
                    {"operations": ["CREATE", "UPDATE"], "apiGroups": [""],
                     "apiVersions": ["v1"], "resources": ["pods"]},
                    {"operations": ["CREATE", "UPDATE"],
                     "apiGroups": ["apps", "batch"],
                     "apiVersions": ["v1"],
                     "resources": ["deployments", "daemonsets", "statefulsets",
                                   "replicasets", "jobs", "cronjobs"]},
                ],
                "failurePolicy": "Fail",
                "sideEffects": "None",
                "admissionReviewVersions": ["v1"],
            }],
        }

    def validating_webhook_config(self) -> dict:
        return {
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingWebhookConfiguration",
            "metadata": {"name": "yunikorn-admission-controller-cfg"},
            "webhooks": [{
                "name": "admission-webhook.yunikorn.conf-validator",
                "clientConfig": {
                    "service": {"name": self.conf.am_service_name,
                                "namespace": self.conf.namespace,
                                "path": VALIDATE_CONF_PATH},
                    "caBundle": self.cas.ca_bundle().decode(),
                },
                "rules": [{"operations": ["CREATE", "UPDATE"], "apiGroups": [""],
                           "apiVersions": ["v1"], "resources": ["configmaps"]}],
                "failurePolicy": "Ignore",
                "sideEffects": "None",
                "admissionReviewVersions": ["v1"],
            }],
        }

    def wait_for_certificate_expiration_seconds(self) -> float:
        """Time until the next CA rotation is due (reference :223-232)."""
        return min(
            p.seconds_until_expiry() - CACollection.ROTATE_BEFORE_SECONDS
            for p in self.cas.pairs
        )

    def run_certificate_expiration_loop(self, stop_event,
                                        on_rotated=None) -> "threading.Thread":
        """Background re-registration loop (reference WaitForCertificateExpiration
        :223-232): sleep until the next rotation is due, rotate the CA pair,
        and re-render/patch the webhook configurations so the caBundle stays
        valid. on_rotated(mutating_cfg, validating_cfg) applies the patch —
        against a real cluster, an Update of both WebhookConfigurations."""

        def loop():
            while not stop_event.is_set():
                wait = max(1.0, self.wait_for_certificate_expiration_seconds())
                if stop_event.wait(timeout=wait):
                    return
                if self.cas.rotate_if_needed():
                    logger.info("certificate rotation performed; "
                                "re-registering webhooks")
                    if on_rotated is not None:
                        try:
                            on_rotated(self.mutating_webhook_config(),
                                       self.validating_webhook_config())
                        except Exception:
                            logger.exception("webhook re-registration failed")

        t = threading.Thread(target=loop, name="cert-expiration", daemon=True)
        t.start()
        return t
