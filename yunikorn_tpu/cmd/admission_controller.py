"""yunikorn-admission-controller binary.

Role-equivalent to pkg/cmd/admissioncontroller/main.go:55-110: build the
caches + webhook manager (cert handling + webhook registration manifests),
serve HTTPS on :9089 with /health /mutate /validate-conf, reload certs on
SIGUSR1, exit on SIGINT/SIGTERM.

Usage:
    python -m yunikorn_tpu.cmd.admission_controller [--port 9089] [--no-tls]
"""
from __future__ import annotations

import argparse
import signal
import sys
import threading

from yunikorn_tpu.admission.admission_controller import AdmissionController
from yunikorn_tpu.admission.caches import NamespaceCache, PriorityClassCache
from yunikorn_tpu.admission.conf import AdmissionConfHolder
from yunikorn_tpu.admission.pki import CACollection
from yunikorn_tpu.admission.webhook import WebhookManager, WebhookServer
from yunikorn_tpu.log.logger import log

logger = log("admission")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="yunikorn-tpu admission controller")
    parser.add_argument("--port", type=int, default=9089)
    parser.add_argument("--host", type=str, default="0.0.0.0")
    parser.add_argument("--no-tls", action="store_true")
    parser.add_argument("--kubeconfig", type=str, default="",
                        help="watch namespaces/priorityclasses/configmaps in "
                             "a real cluster (conf hot-reload)")
    args = parser.parse_args(argv)

    holder = AdmissionConfHolder()
    conf = holder.get()
    cas = CACollection()
    manager = WebhookManager(conf, cas)
    ns_cache, pc_cache = NamespaceCache(), PriorityClassCache()
    controller = AdmissionController(
        conf,
        namespace_cache=ns_cache,
        pc_cache=pc_cache,
        conf_holder=holder,
    )
    provider = None
    if args.kubeconfig:
        from yunikorn_tpu.admission.caches import attach_informers
        from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider

        provider = RealAPIProvider(KubeConfig.load(args.kubeconfig),
                                   namespace=conf.namespace)
        attach_informers(provider, holder, ns_cache, pc_cache,
                         namespace=conf.namespace)
        provider.start()
        # register the webhooks with the current caBundle (reference
        # main.go: wm.InstallWebhooks before serving)
        manager.install_webhooks(provider.get_client())
    server = WebhookServer(controller, host=args.host, port=args.port,
                           use_tls=not args.no_tls, cas=cas)
    port = server.start()
    logger.info("admission controller on :%d (tls=%s)", port, not args.no_tls)

    stop = threading.Event()

    def on_rotated(mutating_cfg, validating_cfg):
        # restart the TLS server so it serves a cert signed by the fresh CA
        # (same reload the SIGUSR1 path performs), then re-patch the cluster's
        # WebhookConfigurations so their caBundle matches the new CA
        logger.info("applying rotated certificates (server restart)")
        server.stop()
        server.start()
        if provider is not None:
            manager.install_webhooks(provider.get_client())

    # background cert re-registration (reference WaitForCertificateExpiration
    # :223-232 + main.go restart-on-rotation)
    manager.run_certificate_expiration_loop(stop, on_rotated=on_rotated)

    def handle_term(signum, frame):
        stop.set()

    def handle_usr1(signum, frame):
        # cert reload (reference main.go:99-110)
        logger.info("SIGUSR1: rotating certificates")
        cas.rotate_if_needed()
        server.stop()
        server.start()

    signal.signal(signal.SIGINT, handle_term)
    signal.signal(signal.SIGTERM, handle_term)
    if hasattr(signal, "SIGUSR1"):
        signal.signal(signal.SIGUSR1, handle_usr1)
    stop.wait()
    server.stop()
    if provider is not None:
        provider.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
