"""yunikorn-scheduler binary.

Role-equivalent to pkg/cmd/shim/main.go:38-70: bootstrap configmaps, start the
core in-process, create + run the shim, expose the REST API, wait for
SIGINT/SIGTERM. The cluster backend is selectable: the in-memory FakeCluster
(default — also the kwok-style perf mode) or a real-K8s adapter when one is
installed.

Usage:
    python -m yunikorn_tpu.cmd.scheduler [--nodes N] [--rest-port P]
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

from yunikorn_tpu.cache.context import Context
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.fake import FakeCluster
from yunikorn_tpu.client.synthetic import make_kwok_nodes
from yunikorn_tpu.conf.schedulerconf import get_holder
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.shim.scheduler import KubernetesShim
from yunikorn_tpu.utils.jaxtools import ensure_compilation_cache
from yunikorn_tpu.webapp.rest import RestServer

logger = log("shim")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="yunikorn-tpu scheduler")
    parser.add_argument("--nodes", type=int, default=0,
                        help="pre-create N synthetic kwok-style nodes")
    parser.add_argument("--rest-port", type=int, default=9080)
    parser.add_argument("--queues-yaml", type=str, default="",
                        help="path to a queues.yaml config file")
    parser.add_argument("--kubeconfig", type=str, default="",
                        help="schedule against a real cluster via this "
                             "kubeconfig (kind/kwok); default: FakeCluster")
    parser.add_argument("--prewarm", type=str, default="",
                        help="warm standard solve buckets at startup in "
                             "the background, e.g. '1024x4096,16384x65536' "
                             "(nodes x pods); removes the first-cycle XLA "
                             "compile stall (persistent cache fills too). "
                             "Covers the resolved runtime variant: policy x "
                             "mesh x pallas gate x the pipelined cycle's "
                             "persistent device-resident node buffers. With "
                             "--aot-store the warmup LOADS prebuilt "
                             "executables instead of compiling")
    parser.add_argument("--aot-store", type=str,
                        default=os.environ.get("YK_AOT_STORE", ""),
                        help="AOT executable store directory (see "
                             "scripts/aot_build.py): serialized compiled "
                             "solver executables keyed by fingerprint — a "
                             "fresh process with a prebuilt store serves "
                             "its first cycle with zero XLA compiles. "
                             "Default: $YK_AOT_STORE, else conf "
                             "solver.aotStore")
    parser.add_argument("--trace-out", type=str, default="",
                        help="dump the cycle tracer as Chrome trace-event "
                             "JSON to this path at shutdown (the live ring "
                             "is always available at /debug/traces)")
    parser.add_argument("--shards", type=str, default="",
                        help="control-plane shards (core/shard.py): 'auto' "
                             "or a count in [1, 64]. N >= 2 runs N pipelined "
                             "CoreScheduler shards over disjoint topology-"
                             "aligned node partitions, coupled through the "
                             "exact global quota ledger + stranded-ask "
                             "repair. Default: conf solver.shards (auto=1)")
    parser.add_argument("--policy", type=str, default="",
                        choices=("", "greedy", "optimal", "learned", "all"),
                        help="solver.policy override: learned/all dispatch "
                             "the two-tower scorer (policy/) behind the "
                             "differential oracle. Unknown values reject "
                             "here, matching the configmap validation")
    parser.add_argument("--policy-checkpoint", type=str, default="",
                        help="learned-policy checkpoint prefix "
                             "(scripts/policy_train.py output). Default: "
                             "conf solver.policyCheckpoint. A checkpoint "
                             "failing validation is REJECTED at load and "
                             "the learned arm skips")
    parser.add_argument("--shard-epoch-seconds", type=float, default=0.0,
                        help="re-seed the shard partition every N seconds "
                             "(0 = never): moved ICI domains migrate "
                             "between shards so fragmentation cannot "
                             "ossify")
    parser.add_argument("--ledger-endpoint", type=str, default="",
                        help="couple the sharded control plane to a quota "
                             "ledger served at host:port in ANOTHER "
                             "process (core/ledger_service.py): every "
                             "reserve/confirm/release rides the RPC "
                             "boundary with deadlines, idempotent replay, "
                             "circuit breaker and degraded-mode admission. "
                             "Default: conf solver.ledgerEndpoint; empty = "
                             "in-process direct ledger")
    parser.add_argument("--ledger-serve", action="store_true",
                        help="host the ledger authority behind a local "
                             "socket in THIS process and couple the shards "
                             "through LedgerClient anyway (the single-box "
                             "service shape; peers join via "
                             "--ledger-endpoint). Requires --shards >= 2")
    args = parser.parse_args(argv)

    ensure_compilation_cache()

    queues_yaml = ""
    if args.queues_yaml:
        with open(args.queues_yaml) as f:
            queues_yaml = f.read()
    holder = get_holder()

    if args.kubeconfig:
        if args.nodes:
            logger.warning("--nodes is ignored with --kubeconfig (nodes come "
                           "from the cluster)")
        # real cluster: bootstrap configmaps BEFORE informers, then build the
        # provider from the bootstrapped conf (QPS/DRA may come from the
        # cluster's configmaps) — reference client/bootstrap.go:28 ordering
        from yunikorn_tpu.client.kube import (
            KubeConfig, RealKubeClient, RealAPIProvider, load_bootstrap_configmaps)

        kc = KubeConfig.load(args.kubeconfig)
        boot_client = RealKubeClient(kc)
        maps, binary_maps = load_bootstrap_configmaps(
            boot_client, holder.get().namespace)
        if queues_yaml:
            maps.append({"queues.yaml": queues_yaml})
            binary_maps.append({})
        holder.update_config_maps(maps, initial=True, binary_maps=binary_maps)
        conf0 = holder.get()
        provider = RealAPIProvider(kc, qps=conf0.kube_qps, burst=conf0.kube_burst,
                                   enable_dra=conf0.enable_dra,
                                   namespace=conf0.namespace)
        cluster = provider
    else:
        holder.update_config_maps([{"queues.yaml": queues_yaml}], initial=True)
        cluster = FakeCluster()
        if args.nodes:
            for node in make_kwok_nodes(args.nodes):
                cluster.add_node(node)

    from yunikorn_tpu.core.scheduler import SolverOptions
    from yunikorn_tpu.robustness.supervisor import SupervisorOptions

    # AOT executable store (aot/): install BEFORE the core so the first
    # scheduling cycle already dispatches through it; seeds the jax
    # persistent cache from the store mirror before any compile
    aot_rt = None
    store_path = args.aot_store or holder.get().solver_aot_store
    if store_path:
        from yunikorn_tpu import aot

        aot_rt = aot.install(
            store_path,
            background=holder.get().solver_aot_background != "false")
        logger.info("aot store attached at %s (%d entries, background "
                    "compile %s)", store_path, aot_rt.store.entry_count(),
                    "on" if aot_rt.background else "off")

    from yunikorn_tpu.obs.slo import SloOptions

    cache = SchedulerCache()
    from yunikorn_tpu.core.shard import make_core_scheduler, resolve_shards

    n_shards = resolve_shards(args.shards or holder.get().solver_shards)
    solver_opts = SolverOptions.from_conf(holder.get())
    if args.policy:
        solver_opts.policy = args.policy
    if args.policy_checkpoint:
        solver_opts.policy_checkpoint = args.policy_checkpoint
    from yunikorn_tpu.obs.flightrec import FlightRecorderOptions
    from yunikorn_tpu.robustness.failover import FailoverOptions

    from yunikorn_tpu.core.ledger_service import LedgerClientOptions

    ledger_endpoint = (args.ledger_endpoint
                       or holder.get().solver_ledger_endpoint)
    core = make_core_scheduler(
        cache, shards=n_shards,
        solver_options=solver_opts,
        trace_spans=holder.get().obs_trace_spans,
        supervisor_options=SupervisorOptions.from_conf(holder.get()),
        slo_options=SloOptions.from_conf(holder.get()),
        epoch_seconds=args.shard_epoch_seconds,
        failover_options=FailoverOptions.from_conf(holder.get()),
        journey_capacity=holder.get().obs_journey_capacity,
        flightrec_options=FlightRecorderOptions.from_conf(holder.get()),
        delivery_high_water=holder.get().solver_delivery_high_water,
        ledger_endpoint=ledger_endpoint, ledger_serve=args.ledger_serve,
        ledger_client_options=LedgerClientOptions.from_conf(holder.get()))
    if n_shards > 1:
        logger.info("control-plane sharding: %d shards (epoch %ss, "
                    "failover stale budget %ss)",
                    n_shards, args.shard_epoch_seconds or "off",
                    holder.get().robustness_failover_stale_s)
        if args.ledger_serve:
            logger.info("ledger service: authority on %s (fail-closed=%s)",
                        core.ledger_server.endpoint,
                        holder.get().robustness_ledger_fail_closed)
        elif ledger_endpoint:
            logger.info("ledger service: coupling to remote authority at "
                        "%s", ledger_endpoint)
    if aot_rt is not None:
        # hit/miss/compile metrics land in this core's /metrics; compile
        # spans land on its cycle timeline
        aot_rt.attach(registry=core.obs, tracer=core.tracer,
                      cycle_id_fn=lambda: core.supervisor.cycle_id)
    context = Context(cluster, core, cache=cache)
    shim = KubernetesShim(cluster, core, context=context)
    rest = RestServer(core, context, port=args.rest_port)

    core.start()
    shim.run()
    port = rest.start()
    logger.info("scheduler up; REST on :%d", port)

    if args.prewarm:
        from yunikorn_tpu.utils.jaxtools import prewarm_buckets

        # sharded front end: warm against the primary shard's resolved
        # variant (every shard runs the same program family; per-shard
        # AOT namespaces mean a shard's first dispatch may still compile)
        prewarm_buckets(args.prewarm, core=getattr(core, "primary", core))

    stop = threading.Event()

    def handle_signal(signum, frame):
        logger.info("signal %s received, shutting down", signum)
        stop.set()

    signal.signal(signal.SIGINT, handle_signal)
    signal.signal(signal.SIGTERM, handle_signal)
    stop.wait()
    rest.stop()
    core.stop()   # before the shim: no callbacks into a stopped dispatcher
    shim.stop()
    if args.trace_out:
        import json

        with open(args.trace_out, "w") as f:
            json.dump(core.tracer.chrome_trace(), f)
        logger.info("cycle trace written to %s", args.trace_out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
