"""On-disk AOT executable store: serialized PJRT executables per fingerprint.

The compile-cost problem this solves (ROADMAP "cold-start elimination"): a
new scheduler process pays the full XLA compile for every solver bucket it
touches — ~400 s at the 50k-pod bucket through the TPU relay, where the
jax persistent compilation cache does not populate (the relay compiles
remotely and returns only the loaded executable). `--prewarm` merely
re-traces and re-compiles per process. This store keeps the COMPILED
artifact itself: `jax.experimental.serialize_executable` bytes written once
by an offline builder (scripts/aot_build.py) or by the first process that
compiled, and deserialized by every later process in milliseconds.

Store layout (one directory):

  entries/<path>-<key>.aotx    one executable: MAGIC + sha256(body) + body,
                               body = pickle of {"manifest", "payload",
                               "in_tree", "out_tree"}
  entries/<path>-<key>.json    human-readable manifest sidecar (debugging;
                               best-effort, never load-bearing)
  quarantine/                  corrupt/truncated entries moved here on read
                               failure — a bad artifact falls through to a
                               normal compile, never crashes the ladder
  xla_cache/                   mirrored jax persistent-cache entries
                               (save/restore_persistent_cache): the local
                               half of the relay cache gap — backends that
                               refuse executable serialization still get
                               their persistent-cache entries carried
                               between hosts/processes via the store

Durability discipline: writes are atomic (tmp file + os.replace in the same
directory), reads verify magic + digest before unpickling, and the total
entry size is LRU-capped (mtime refreshed on every hit; oldest entries
evicted past `max_bytes`). Everything here is an optimization: every
failure path returns None / logs and lets the caller compile.
"""
from __future__ import annotations

import hashlib
import json
import os
import pickle
import shutil
import tempfile
import time
from typing import Optional, Tuple

from yunikorn_tpu.log.logger import log

logger = log("aot.store")

_MAGIC = b"YKAOT1\n"
_DIGEST_LEN = 32

# default LRU size cap for the entries/ directory (env-overridable by the
# binaries that construct the store)
DEFAULT_MAX_BYTES = 4 << 30


def _safe_name(path: str) -> str:
    return "".join(c if (c.isalnum() or c in "._-") else "_" for c in path)


class AotStore:
    """Filesystem-backed executable store. Thread-safe for the operations
    the runtime performs concurrently (put from a compile thread, get from
    the scheduler thread): every mutation is an atomic rename and readers
    verify integrity, so the worst race outcome is a miss."""

    def __init__(self, root: str, max_bytes: int = 0):
        self.root = os.path.abspath(root)
        self.entries_dir = os.path.join(self.root, "entries")
        self.quarantine_dir = os.path.join(self.root, "quarantine")
        self.xla_cache_dir = os.path.join(self.root, "xla_cache")
        self.max_bytes = int(max_bytes) if max_bytes else int(
            os.environ.get("YK_AOT_STORE_MAX_BYTES", DEFAULT_MAX_BYTES))
        for d in (self.entries_dir, self.quarantine_dir, self.xla_cache_dir):
            os.makedirs(d, exist_ok=True)
        # counters surfaced through AotRuntime.stats()
        self.corrupt_quarantined = 0
        self.evicted = 0

    # ------------------------------------------------------------ entry I/O
    def _entry_path(self, path: str, key: str) -> str:
        return os.path.join(self.entries_dir, f"{_safe_name(path)}-{key}.aotx")

    def get(self, path: str, key: str) -> Optional[Tuple[dict, bytes, object, object]]:
        """Read + verify one entry. Returns (manifest, payload, in_tree,
        out_tree) or None (missing OR corrupt — corrupt entries are moved to
        quarantine/ so they cannot poison later processes)."""
        fp = self._entry_path(path, key)
        try:
            with open(fp, "rb") as f:
                blob = f.read()
        except OSError:
            return None
        try:
            if (len(blob) < len(_MAGIC) + _DIGEST_LEN
                    or not blob.startswith(_MAGIC)):
                raise ValueError("bad magic/truncated header")
            digest = blob[len(_MAGIC):len(_MAGIC) + _DIGEST_LEN]
            body = blob[len(_MAGIC) + _DIGEST_LEN:]
            if hashlib.sha256(body).digest() != digest:
                raise ValueError("digest mismatch (truncated or bit-rotted)")
            rec = pickle.loads(body)
            manifest = rec["manifest"]
            payload = rec["payload"]
            in_tree, out_tree = rec["in_tree"], rec["out_tree"]
        except Exception as e:
            self._quarantine(fp, reason=f"{type(e).__name__}: {e}")
            return None
        try:  # refresh LRU recency on hit; never load-bearing
            now = time.time()
            os.utime(fp, (now, now))
        except OSError:
            pass
        return manifest, payload, in_tree, out_tree

    def put(self, path: str, key: str, manifest: dict, payload: bytes,
            in_tree, out_tree) -> bool:
        """Atomically write one entry (+ manifest sidecar), then enforce the
        LRU size cap. Returns False on any I/O failure (logged, swallowed —
        the executable still lives in the caller's memory cache)."""
        fp = self._entry_path(path, key)
        body = pickle.dumps({"manifest": manifest, "payload": payload,
                             "in_tree": in_tree, "out_tree": out_tree},
                            protocol=pickle.HIGHEST_PROTOCOL)
        blob = _MAGIC + hashlib.sha256(body).digest() + body
        try:
            fd, tmp = tempfile.mkstemp(dir=self.entries_dir, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    f.write(blob)
                os.replace(tmp, fp)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
            with open(fp[:-5] + ".json", "w") as f:
                json.dump({"manifest": manifest, "bytes": len(blob),
                           "written_at": time.time()}, f, indent=1,
                          default=str)
        except Exception:
            logger.exception("aot store write failed for %s", fp)
            return False
        self._enforce_cap()
        return True

    def _quarantine(self, fp: str, reason: str) -> None:
        base = os.path.basename(fp)
        dst = os.path.join(self.quarantine_dir, f"{base}.{int(time.time())}")
        try:
            os.replace(fp, dst)
        except OSError:
            try:  # cross-device or permission trouble: drop it instead
                os.unlink(fp)
                dst = "(deleted)"
            except OSError:
                return
        self.corrupt_quarantined += 1
        logger.warning("aot store entry %s is corrupt (%s); quarantined to "
                       "%s — the caller will recompile", base, reason, dst)

    def _enforce_cap(self) -> None:
        """Evict oldest-mtime entries until the total is under max_bytes."""
        try:
            items = []
            total = 0
            for name in os.listdir(self.entries_dir):
                if not name.endswith(".aotx"):
                    continue
                fp = os.path.join(self.entries_dir, name)
                try:
                    st = os.stat(fp)
                except OSError:
                    continue
                items.append((st.st_mtime, st.st_size, fp))
                total += st.st_size
            if total <= self.max_bytes:
                return
            for _, size, fp in sorted(items):
                try:
                    os.unlink(fp)
                    try:
                        os.unlink(fp[:-5] + ".json")
                    except OSError:
                        pass
                except OSError:
                    continue
                self.evicted += 1
                total -= size
                logger.info("aot store evicted %s (LRU size cap %d bytes)",
                            os.path.basename(fp), self.max_bytes)
                if total <= self.max_bytes:
                    return
        except Exception:
            logger.exception("aot store LRU enforcement failed")

    # ------------------------------------------------- persistent-cache sync
    # The local half of the relay cache gap (ISSUE satellite): executables
    # the backend refuses to serialize still leave jax persistent-cache
    # entries on backends where that cache works — mirroring those files
    # into the store lets an offline builder's cache ride along with the
    # exported executables and seed a fresh host's cache before first use.

    def save_persistent_cache(self, cache_dir: Optional[str] = None) -> int:
        """Copy new jax persistent-cache entries into the store. Returns the
        number of files copied."""
        from yunikorn_tpu.utils.jaxtools import compile_cache_dir

        src = cache_dir or compile_cache_dir()
        return self._sync_dir(src, self.xla_cache_dir)

    def restore_persistent_cache(self, cache_dir: Optional[str] = None) -> int:
        """Copy mirrored persistent-cache entries back into the live jax
        cache directory (missing files only). Call before the first compile."""
        from yunikorn_tpu.utils.jaxtools import compile_cache_dir

        dst = cache_dir or compile_cache_dir()
        return self._sync_dir(self.xla_cache_dir, dst)

    @staticmethod
    def _sync_dir(src: str, dst: str) -> int:
        copied = 0
        try:
            os.makedirs(dst, exist_ok=True)
            for name in os.listdir(src):
                s = os.path.join(src, name)
                d = os.path.join(dst, name)
                if not os.path.isfile(s) or os.path.exists(d):
                    continue
                try:
                    fd, tmp = tempfile.mkstemp(dir=dst, suffix=".tmp")
                    os.close(fd)
                    shutil.copyfile(s, tmp)
                    os.replace(tmp, d)
                    copied += 1
                except OSError:
                    continue
        except OSError:
            return copied
        return copied

    # -------------------------------------------------------- introspection
    def entry_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.entries_dir)
                       if n.endswith(".aotx"))
        except OSError:
            return 0

    def stats(self) -> dict:
        return {"root": self.root, "entries": self.entry_count(),
                "quarantined": self.corrupt_quarantined,
                "evicted": self.evicted}
