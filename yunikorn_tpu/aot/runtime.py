"""AOT dispatch runtime: route jitted solver calls through stored executables.

Every jitted solver entry point (assign solve + chunked, the gate scan,
encode_rows, the preemption and pack solves, and their mesh-sharded
variants) funnels its calls through `aot_call` / `aot_compile`. With no
runtime installed both helpers are a no-op passthrough to the jitted
function — production default, zero behavior change. With a runtime
installed (`--aot-store` / conf solver.aotStore):

  hit   — the call's fingerprint resolves to an executable, either already
          in the in-memory cache or deserialized from the store in
          milliseconds; the deserialized `Compiled` runs WITHOUT any
          trace or XLA compile. First production cycle in a fresh process
          costs artifact-load, not minutes of compile.
  miss  — inline mode: lower+compile (timed into `jit_compile_ms{path}` and
          a `compile` tracer span), install in memory, serialize into the
          store in the background so the NEXT process hits.
        — background mode (`pending_ok=True`, conf solver.aotBackground):
          raise `CompilePending` immediately and compile on a daemon
          thread. The supervised ladder classifies CompilePending as
          persistent → the device tier's breaker opens and cycles keep
          serving on the cpu/host tiers; once the thread finishes, the
          breaker's half-open probe finds the executable in memory and
          reclaims the device tier. A cold process is degraded for
          seconds, never wedged for minutes.

The fingerprint manifest keys everything that changes the compiled program:
the path name, the dynamic-arg pytree structure + per-leaf (shape, dtype,
weak_type), the static kwargs, jax/jaxlib versions, backend platform +
device count (topology), the x64 mode, and any caller extra (the mesh
tag). Changing any component misses the store and recompiles — pinned by
tests/test_aot_store.py.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Callable, Dict, Optional, Tuple

import jax

from yunikorn_tpu.log.logger import log

logger = log("aot.runtime")


class CompilePending(RuntimeError):
    """The executable for this dispatch is being compiled in the background;
    the supervised ladder should serve this cycle from a lower tier."""


# jit_compile_ms histogram ladder: XLA solver compiles run seconds to
# MINUTES (~400 s at the 50k bucket through the relay) — the generic
# MS_BUCKETS top out at 10 s and would clamp every real compile into +Inf
COMPILE_MS_BUCKETS = (100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0, 30000.0, 60000.0, 120000.0, 300000.0,
                      600000.0)


def _to_specs(args):
    """Array leaves → ShapeDtypeStructs (shape/dtype/sharding), other
    leaves kept: the background/retry compile threads must not pin the
    cycle's live tensors, and both must capture shardings identically."""
    return jax.tree_util.tree_map(
        lambda a: (jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=getattr(a, "sharding",
                                                         None))
                   if hasattr(a, "shape") and hasattr(a, "dtype") else a),
        tuple(args))


_CODE_VERSION: Optional[str] = None


def _code_version() -> str:
    """Hash of the solver-bearing source files, computed once per process.

    The fingerprint manifest must invalidate when the CODE that traces a
    program changes, not only when shapes/statics/jax versions do — a store
    surviving a scheduler upgrade would otherwise silently serve the OLD
    algorithm's executables forever, with every compile counter reading
    zero ("healthy"). Hashing the ops/models/parallel sources (plus the
    locality encoding constants) is deliberately broad: a code change that
    did NOT alter the traced programs costs one store rebuild; a stale
    executable serving stale placements is unbounded.
    """
    global _CODE_VERSION
    if _CODE_VERSION is not None:
        return _CODE_VERSION
    import os

    import yunikorn_tpu

    pkg = os.path.dirname(os.path.abspath(yunikorn_tpu.__file__))
    h = hashlib.sha256()
    targets = []
    # policy/ is included because the learned solve variant traces through
    # the feature extractor + towers (a scorer code change must invalidate
    # stored learned executables exactly like a solver code change)
    for sub in ("ops", "models", "parallel", "policy"):
        d = os.path.join(pkg, sub)
        try:
            targets.extend(os.path.join(d, n) for n in os.listdir(d)
                           if n.endswith(".py"))
        except OSError:
            pass
    targets.append(os.path.join(pkg, "snapshot", "locality.py"))
    for fp in sorted(targets):
        try:
            with open(fp, "rb") as f:
                h.update(os.path.basename(fp).encode())
                h.update(f.read())
        except OSError:
            continue
    _CODE_VERSION = h.hexdigest()[:16]
    return _CODE_VERSION


def _leaf_sig(x) -> tuple:
    """Stable signature of one dynamic-arg leaf. Arrays (numpy, jax, and
    ShapeDtypeStruct specs) key on (shape, dtype, weak_type); Python scalars
    key on their TYPE only — a traced scalar's value never changes the
    program, and keying on it would mint one store entry per seed."""
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype),
                bool(getattr(x, "weak_type", False)))
    return ("py", type(x).__name__)


class AotRuntime:
    def __init__(self, store, *, background_compile: bool = False,
                 versions: Optional[Tuple[str, str]] = None,
                 backend: Optional[Tuple[str, int]] = None,
                 code_version: Optional[str] = None):
        """store: an aot.store.AotStore. background_compile: misses raise
        CompilePending (when the caller allows) instead of compiling inline.
        versions/backend/code_version are injectable for invalidation
        tests; by default they are read from the live jax install/backend
        and the solver sources (_code_version)."""
        self.store = store
        self.background = bool(background_compile)
        if versions is None:
            import jaxlib

            versions = (jax.__version__, jaxlib.__version__)
        self._versions = versions
        self._code_version = code_version or _code_version()
        self._backend = backend  # resolved lazily: reading it dials the backend
        self._mu = threading.Lock()
        self._mem: Dict[str, object] = {}         # key -> stages.Compiled
        self._pending: set = set()                # keys compiling in background
        self._failed: set = set()                 # background compile failed →
                                                  # later calls compile inline
        self._refused_keys: set = set()           # fingerprints that won't
                                                  # serialize (permanent)
        self._refused_logged: set = set()         # paths already diagnosed
        self._serialize_refused = False           # backend-wide latch
        self._saves_ok = 0                        # successful store writes
        self._bg_threads: list = []               # in-flight saves AND
                                                  # background compiles
        # per-path compile tally: feeds the modules' jit_cache_entries so
        # the core's jc-delta accounting (solve_compile_total etc.) still
        # sees aot compiles, which bypass the jit wrappers' caches
        self.compiles_by_path: Dict[str, int] = {}
        # plain counters: always live, whether or not a registry is attached
        # (bench + smoke read these; /metrics reads the registry mirrors)
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.loads = 0
        self._m_hits = self._m_misses = self._h_compile_ms = None
        self._tracer = None
        self._cycle_id_fn: Callable[[], int] = lambda: 0

    # ---------------------------------------------------------------- wiring
    def attach(self, registry=None, tracer=None,
               cycle_id_fn: Optional[Callable[[], int]] = None) -> None:
        """Bind the process's metrics registry / cycle tracer (re-binding to
        a newer core is fine — last writer wins)."""
        if registry is not None:
            self._m_hits = registry.counter(
                "aot_store_hits_total",
                "solver dispatches served from an AOT-stored executable "
                "(memory or disk) with zero trace+compile")
            self._m_misses = registry.counter(
                "aot_store_misses_total",
                "solver dispatches whose fingerprint missed the AOT store, "
                "by path", labelnames=("path",))
            self._h_compile_ms = registry.histogram(
                "jit_compile_ms",
                "XLA trace+compile latency of AOT-managed solver paths (ms)",
                labelnames=("path",), buckets=COMPILE_MS_BUCKETS)
        if tracer is not None:
            self._tracer = tracer
        if cycle_id_fn is not None:
            self._cycle_id_fn = cycle_id_fn

    # ----------------------------------------------------------- fingerprint
    def _backend_sig(self) -> Tuple[str, int]:
        if self._backend is None:
            devs = jax.devices()
            self._backend = (devs[0].platform, len(devs))
        return self._backend

    def manifest(self, path: str, args, static_kwargs: dict,
                 extra: tuple = ()) -> dict:
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(args)
        platform, n_dev = self._backend_sig()
        return {
            "path": path,
            "jax": self._versions[0],
            "jaxlib": self._versions[1],
            "code": self._code_version,
            "backend": platform,
            "topology": n_dev,
            # thread-local-aware: int64 only canonicalizes to itself under
            # the x64 mode the caller (e.g. the gate's enable_x64) is in
            "x64": str(jax.dtypes.canonicalize_dtype(np.int64)) == "int64",
            "tree": str(treedef),
            "leaves": [_leaf_sig(x) for x in leaves],
            "static": sorted((k, repr(v)) for k, v in static_kwargs.items()),
            "extra": [repr(e) for e in extra]
            # control-plane sharding (core/shard.py): each shard's
            # dispatches run inside namespace(...) — folding it here gives
            # every shard its own executable namespace in the shared store.
            # Unset (every pre-shard caller) adds nothing, so all existing
            # fingerprints are byte-identical to before.
            + ([f"ns={_tls.namespace}"]
               if getattr(_tls, "namespace", None) else []),
        }

    @staticmethod
    def _key(manifest: dict) -> str:
        return hashlib.sha256(repr(sorted(
            (k, str(v)) for k, v in manifest.items()
        )).encode()).hexdigest()[:24]

    # -------------------------------------------------------------- dispatch
    def dispatch(self, path: str, fn, args: tuple, static_kwargs: dict,
                 *, pending_ok: bool = False, extra: tuple = (),
                 lower_cm=None):
        """Run one solver call through the store. Returns fn's result (the
        exact out_tree the jitted function produces). lower_cm: optional
        context manager (the GSPMD mesh) entered around lower()."""
        manifest = self.manifest(path, args, static_kwargs, extra)
        key = self._key(manifest)
        comp = self._mem.get(key)
        if comp is None:
            comp = self._load(path, key)
        if comp is not None:
            try:
                out = comp(*args)
            except TypeError as e:
                # aval/pytree mismatch = fingerprint bug or stale artifact:
                # drop it and compile — never fail the dispatch
                with self._mu:
                    self._mem.pop(key, None)
                logger.warning(
                    "aot executable for %s (%s) rejected its args (%s); "
                    "dropping the entry and recompiling", path, key, e)
            else:
                self._count_hit()
                return out
        self._count_miss(path)
        if (pending_ok and self.background and key not in self._failed):
            self._spawn_compile(path, key, manifest, fn, args, static_kwargs,
                                lower_cm)
            raise CompilePending(
                f"aot: no stored executable for {path} (key {key}); "
                "background compile started — serve from a lower tier")
        comp = self._compile(path, key, manifest, fn, args, static_kwargs,
                             lower_cm)
        return comp(*args)

    def build(self, path: str, fn, args: tuple, static_kwargs: dict,
              *, extra: tuple = (), lower_cm=None) -> bool:
        """compile_only entry (prewarm / offline builder): ensure the
        fingerprint's executable exists in memory, loading from the store
        when possible, compiling+persisting otherwise. args may be
        ShapeDtypeStructs. Returns True when the store (not a compile)
        supplied it."""
        manifest = self.manifest(path, args, static_kwargs, extra)
        key = self._key(manifest)
        if key in self._mem:
            return True
        if self._load(path, key) is not None:
            return True
        self._compile(path, key, manifest, fn, args, static_kwargs, lower_cm)
        return False

    # ------------------------------------------------------------- internals
    def _load(self, path: str, key: str):
        rec = self.store.get(path, key) if self.store is not None else None
        if rec is None:
            return None
        manifest, payload, in_tree, out_tree = rec
        try:
            from jax.experimental.serialize_executable import (
                deserialize_and_load,
            )

            t0 = time.perf_counter()
            comp = deserialize_and_load(payload, in_tree, out_tree)
            load_ms = (time.perf_counter() - t0) * 1000
        except Exception as e:
            logger.warning("aot: deserialize of %s (%s) failed (%s: %s); "
                           "recompiling", path, key, type(e).__name__, e)
            return None
        with self._mu:
            self._mem[key] = comp
            self.loads += 1
        logger.info("aot: loaded %s (%s) from store in %.1f ms",
                    path, key, load_ms)
        return comp

    @staticmethod
    def _lower_compile(fn, args, static_kwargs, lower_cm, *,
                       x64: bool = False, no_cache: bool = False):
        from contextlib import nullcontext

        from jax.experimental import enable_x64

        with (enable_x64() if x64 else nullcontext()), \
                (_no_persistent_cache() if no_cache else nullcontext()), \
                (lower_cm if lower_cm is not None else nullcontext()):
            return fn.lower(*args, **static_kwargs).compile()

    def _compile(self, path: str, key: str, manifest: dict, fn, args,
                 static_kwargs, lower_cm):
        t0 = time.perf_counter()
        compiled = self._lower_compile(fn, args, static_kwargs, lower_cm)
        dt_ms = (time.perf_counter() - t0) * 1000
        with self._mu:
            self._mem[key] = compiled
            self.compiles += 1
            self.compiles_by_path[path] = \
                self.compiles_by_path.get(path, 0) + 1
        if self._h_compile_ms is not None:
            self._h_compile_ms.observe(dt_ms, path=path)
        if self._tracer is not None:
            try:
                now = time.time()
                self._tracer.add("compile", self._cycle_id_fn(),
                                 now - dt_ms / 1000, now, path=path, key=key)
            except Exception:
                pass
        logger.info("aot: compiled %s (%s) in %.0f ms", path, key, dt_ms)
        # persist off-thread: serialization of a big executable is pure CPU
        # + disk and must not sit on the scheduling path. The save thread
        # gets the material for a forced-true-compile retry (specs, not the
        # caller's live arrays): an executable SERVED from the jax
        # persistent cache serializes without its object code, and only a
        # fresh compile can produce a storable artifact then.
        retry = (fn, _to_specs(args), static_kwargs, lower_cm,
                 bool(manifest.get("x64")))
        t = threading.Thread(target=self._save, name="aot-save", daemon=True,
                             args=(path, key, manifest, compiled, retry))
        self._track(t)
        t.start()
        return compiled

    def _track(self, t: threading.Thread) -> None:
        with self._mu:
            self._bg_threads = [x for x in self._bg_threads if x.is_alive()]
            self._bg_threads.append(t)

    def _spawn_compile(self, path, key, manifest, fn, args, static_kwargs,
                       lower_cm) -> None:
        with self._mu:
            if key in self._pending:
                return
            self._pending.add(key)
        # hold specs, not the cycle's real arrays: the thread outlives the
        # dispatch and must not pin hundreds of MB of batch tensors
        specs = _to_specs(args)

        # the dispatch may be running under a thread-local dtype mode (the
        # gate scan lowers int64 programs inside enable_x64); the compile
        # thread must re-enter it or lowering would canonicalize the int64
        # avals down to int32 and bake a wrong-signature program under this
        # fingerprint
        x64 = bool(manifest.get("x64"))

        def run():
            from contextlib import nullcontext

            from jax.experimental import enable_x64

            try:
                with (enable_x64() if x64 else nullcontext()):
                    self._compile(path, key, manifest, fn, specs,
                                  static_kwargs, lower_cm)
            except Exception:
                with self._mu:
                    self._failed.add(key)
                logger.exception(
                    "aot: background compile of %s (%s) failed; later "
                    "dispatches will compile inline", path, key)
            finally:
                with self._mu:
                    self._pending.discard(key)

        t = threading.Thread(target=run, name="aot-compile", daemon=True)
        self._track(t)
        t.start()

    @staticmethod
    def _refusal_permanent(exc: BaseException) -> bool:
        """Whether a serialize/validate failure will repeat for this exact
        program (latch it) vs a transient condition (just skip this save).
        A permanent latch on a transient MemoryError/OSError would strip a
        whole variant's cold-start coverage for the process lifetime."""
        if isinstance(exc, (NotImplementedError, TypeError, ValueError)):
            return True
        if isinstance(exc, (MemoryError, OSError)):
            return False
        msg = str(exc)
        return any(tok in msg for tok in
                   ("UNIMPLEMENTED", "INVALID_ARGUMENT", "Symbols not found",
                    "not supported", "unsupported"))

    def _serialize_validated(self, compiled):
        from jax.experimental.serialize_executable import (
            deserialize_and_load,
            serialize,
        )

        payload, in_tree, out_tree = serialize(compiled)
        # round-trip validation BEFORE the artifact can reach another
        # process: a backend may serialize without error yet emit a
        # payload that cannot load (the persistent-cache "Symbols not
        # found" class) — such an entry must never be written
        deserialize_and_load(payload, in_tree, out_tree)
        return payload, in_tree, out_tree

    def _save(self, path: str, key: str, manifest: dict, compiled,
              retry=None) -> None:
        if self.store is None or key in self._refused_keys:
            # a variant that already refused never re-pays the (potentially
            # multi-GB) serialize+validate just to drop the result again
            return
        try:
            try:
                rec = self._serialize_validated(compiled)
            except Exception as e:
                # ONE specific failure class earns a retry: an executable
                # SERVED from the jax persistent cache carries no object
                # code and loads back with "Symbols not found" — a fresh
                # compile with cache lookups suppressed produces a storable
                # artifact; pay it once, here on the save thread, off the
                # scheduling path. Anything else (a genuinely
                # unserializable Mosaic variant, transient OOM/IO) must NOT
                # burn a full recompile just to fail again.
                if retry is None or "Symbols not found" not in str(e):
                    raise
                fn, specs, static_kwargs, lower_cm, x64 = retry
                logger.info(
                    "aot: %s (%s) did not serialize (%s: %s); retrying "
                    "with a forced true compile (persistent-cache-served "
                    "executables carry no object code)", path, key,
                    type(e).__name__, str(e)[:120])
                fresh = self._lower_compile(fn, specs, static_kwargs,
                                            lower_cm, x64=x64,
                                            no_cache=True)
                rec = self._serialize_validated(fresh)
        except Exception as e:
            # the relay cache gap's OTHER half: a program that refuses
            # serialization (e.g. a Mosaic-kernel variant). Latched per
            # FINGERPRINT and only for permanent failures — a refusing
            # pallas variant must not stop the plain-XLA variants of the
            # same path from persisting, and a transient MemoryError must
            # not latch anything. Loud once per path, instead of the old
            # silent recompile-per-process.
            permanent = self._refusal_permanent(e)
            with self._mu:
                if permanent:
                    self._refused_keys.add(key)
                first_for_path = path not in self._refused_logged
                self._refused_logged.add(path)
                backend_wide = (permanent and self._saves_ok == 0
                                and not self._serialize_refused)
                if backend_wide:
                    self._serialize_refused = True
            if first_for_path:
                logger.warning(
                    "aot: %s (%s) failed executable serialization on "
                    "backend %r (%s: %s) — its cold starts will pay the "
                    "compile%s", path, key, self._backend_sig()[0],
                    type(e).__name__, str(e)[:200],
                    "; variant latched, will not re-attempt" if permanent
                    else "; transient, later compiles will retry")
            if backend_wide:
                logger.warning(
                    "aot: no program has serialized on backend %r — "
                    "exported-executable cold starts are unavailable; the "
                    "jax persistent cache (mirrored via store xla_cache/) "
                    "is the only remaining cold-start softener",
                    self._backend_sig()[0])
            return
        if self.store.put(path, key, manifest, *rec):
            with self._mu:
                self._saves_ok += 1

    def _count_hit(self) -> None:
        with self._mu:
            self.hits += 1
        if self._m_hits is not None:
            self._m_hits.inc()

    def _count_miss(self, path: str) -> None:
        with self._mu:
            self.misses += 1
        if self._m_misses is not None:
            self._m_misses.inc(path=path)

    def flush(self, timeout: Optional[float] = 60.0) -> None:
        """Join in-flight background work — store writes AND background
        compiles (a compile that finishes spawns a fresh save thread, so
        the snapshot is re-taken until quiescent or the deadline passes).
        The offline builder (and the atexit hook install() registers) calls
        this before process exit: a daemon thread inside XLA during
        interpreter teardown aborts the process, and its work would be
        lost anyway."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            with self._mu:
                threads = [t for t in self._bg_threads if t.is_alive()]
            if not threads:
                return
            for t in threads:
                t.join(None if deadline is None
                       else max(deadline - time.time(), 0.01))
            if deadline is not None and time.time() >= deadline:
                return

    def stats(self) -> dict:
        with self._mu:
            out = {"hits": self.hits, "misses": self.misses,
                   "compiles": self.compiles, "loads": self.loads,
                   "pending": len(self._pending), "failed": len(self._failed)}
        if self.store is not None:
            out["store"] = self.store.stats()
        return out


# ---------------------------------------------------------------- singleton
# One process-wide runtime: the solver call sites consult it through the
# helpers below. None (the default) = AOT disabled, zero-overhead
# passthrough; installed by cmd/scheduler.py, bench.py, scripts/aot_build.py
# or a test.
_runtime: Optional[AotRuntime] = None
_tls = threading.local()


def get_runtime() -> Optional[AotRuntime]:
    return _runtime


class bypass:
    """Context manager: make aot_call a plain passthrough on THIS thread.

    The supervised cpu re-jit tier runs the same program with identical
    avals under jax.default_device(cpu) — its fingerprint would collide
    with the device tier's stored executable and a "hit" would silently
    run the dispatch on the device being degraded away from. Thread-local
    because supervised dispatches execute on per-call watchdog threads.
    """

    def __enter__(self):
        self._prev = getattr(_tls, "bypass", False)
        _tls.bypass = True
        return self

    def __exit__(self, *exc):
        _tls.bypass = self._prev
        return False


class namespace:
    """Context manager: fingerprint every aot_call inside the block under
    `ns` (a shard's executable namespace, core/shard.py). Thread-local for
    the same reason bypass is — supervised dispatches run on per-call
    watchdog threads (SupervisedExecutor.dispatch_cm enters this there)."""

    def __init__(self, ns: Optional[str]):
        self._ns = ns

    def __enter__(self):
        self._prev = getattr(_tls, "namespace", None)
        _tls.namespace = self._ns
        return self

    def __exit__(self, *exc):
        _tls.namespace = self._prev
        return False


def set_runtime(rt: Optional[AotRuntime]) -> Optional[AotRuntime]:
    global _runtime
    prev, _runtime = _runtime, rt
    return prev


_cache_flip_mu = threading.Lock()


class _no_persistent_cache:
    """Context manager: suppress jax persistent-compilation-cache lookups
    for compiles inside the block, process-wide but scoped and restored.

    Why not simply flip the flag: compilation_cache.is_cache_used memoizes
    its decision at the first compile, so reset_cache() (files untouched)
    must clear the memo on BOTH transitions. Why at all: an executable
    SERVED from the persistent cache serializes without its object code on
    XLA:CPU ("Symbols not found" in the consuming process) — a storable
    artifact requires a true compile. Scoped (vs disabling the cache for
    the whole process) so every program NOT routed through the AOT layer
    keeps its persistent-cache cold-start softening, and the store's
    xla_cache/ mirror stays meaningful. Serialized by a lock: concurrent
    unscoped compiles during the window merely skip the cache (harmless);
    two scoped blocks must not interleave their restores."""

    def __enter__(self):
        _cache_flip_mu.acquire()
        self._prev = None
        try:
            self._prev = bool(jax.config.jax_enable_compilation_cache)
            if self._prev:
                from jax._src import compilation_cache as cc

                cc.reset_cache()
                jax.config.update("jax_enable_compilation_cache", False)
        except Exception:
            self._prev = None
        return self

    def __exit__(self, *exc):
        try:
            if self._prev:
                from jax._src import compilation_cache as cc

                jax.config.update("jax_enable_compilation_cache", True)
                cc.reset_cache()
        except Exception:
            pass
        finally:
            _cache_flip_mu.release()
        return False


def install(store_path: str, *, background: bool = False,
            max_bytes: int = 0) -> AotRuntime:
    """Create store + runtime at store_path and install as the process
    singleton. Also seeds the live jax persistent cache from the store's
    mirror BEFORE the first compile — the cache stays enabled for every
    program the AOT layer does not manage (tiny jit ops, overlay programs),
    while AOT-managed artifacts that fail to serialize because their
    executable was cache-served are re-compiled true on the save thread
    (see _save / _no_persistent_cache)."""
    from yunikorn_tpu.aot.store import AotStore

    store = AotStore(store_path, max_bytes=max_bytes)
    restored = store.restore_persistent_cache()
    if restored:
        logger.info("aot: restored %d persistent-cache entries from the "
                    "store mirror", restored)
    rt = AotRuntime(store, background_compile=background)
    set_runtime(rt)
    # in-flight store writes serialize through XLA; letting them race
    # interpreter teardown aborts the process (observed SIGABRT)
    import atexit

    atexit.register(rt.flush, 120.0)
    return rt


def compile_count(*prefixes: str) -> int:
    """Total aot-layer compiles whose path starts with any prefix (0 with no
    runtime). The ops modules fold this into their jit_cache_entries() so
    the core's jc-delta compile accounting (solve_compile_total, the gate's
    and preempt's `compiled` span args) keeps working when AOT routes
    around the jit wrappers — fn.lower().compile() never populates
    fn._cache_size(), so without this every store-miss compile would be
    mislabelled a cache hit."""
    rt = _runtime
    if rt is None:
        return 0
    with rt._mu:
        return sum(v for p, v in rt.compiles_by_path.items()
                   if p.startswith(prefixes))


def pending_enabled() -> bool:
    """Whether supervised device-tier callers should opt into
    CompilePending degradation (runtime installed AND background mode)."""
    rt = _runtime
    return rt is not None and rt.background


def aot_call(path: str, fn, args: tuple, static_kwargs: Optional[dict] = None,
             *, pending_ok: bool = False, extra: tuple = (), lower_cm=None):
    """Call a jitted solver entry point through the AOT runtime (store-hit
    executables skip trace+compile entirely). No runtime installed → plain
    passthrough call."""
    static_kwargs = static_kwargs or {}
    rt = _runtime
    if rt is None or getattr(_tls, "bypass", False):
        return fn(*args, **static_kwargs)
    return rt.dispatch(path, fn, args, static_kwargs, pending_ok=pending_ok,
                       extra=extra, lower_cm=lower_cm)


def aot_compile(path: str, fn, args: tuple,
                static_kwargs: Optional[dict] = None, *, extra: tuple = (),
                lower_cm=None) -> None:
    """compile_only analog of aot_call (prewarm/builder path): ensure the
    executable exists, loading it from the store instead of compiling when
    possible. No runtime → classic lower().compile() into the jit caches."""
    from contextlib import nullcontext

    static_kwargs = static_kwargs or {}
    rt = _runtime
    if rt is None:
        with (lower_cm if lower_cm is not None else nullcontext()):
            fn.lower(*args, **static_kwargs).compile()
        return
    rt.build(path, fn, args, static_kwargs, extra=extra, lower_cm=lower_cm)
