"""AOT executable store: cold-start elimination for the jitted solver paths.

See aot/store.py (the on-disk artifact store) and aot/runtime.py (the
dispatch runtime the solver call sites consult). Offline builder:
scripts/aot_build.py; process wiring: cmd/scheduler.py `--aot-store`,
bench.py `YK_AOT_STORE`; design note: docs/COMPONENTS.md.
"""
from yunikorn_tpu.aot.runtime import (  # noqa: F401
    AotRuntime,
    CompilePending,
    aot_call,
    aot_compile,
    get_runtime,
    install,
    pending_enabled,
    set_runtime,
)
from yunikorn_tpu.aot.store import AotStore  # noqa: F401
