"""Node scoring policies.

The reference delegates scoring to yunikorn-core's node-sorting policies
(binpacking / fair, configured per partition in queues.yaml). Here scoring is a
device function over the node state; policies are pure and separable where
possible (a per-node score shared by every pod in the batch maximizes fusion and
avoids an [N, M] materialization), with an optional MXU alignment term that is a
[C, R] × [R, M] matmul computed per pod-chunk.

Policies:
  binpacking — prefer nodes with the least normalized free capacity (tight
               packing, the reference's bin-packing e2e behavior)
  spread     — prefer nodes with the most normalized free capacity
               (resource_fairness behavior)
  align      — binpacking plus a request/free alignment dot-product, so pods
               go to nodes whose free-resource *shape* matches the request
               (reduces stranding of unbalanced capacity; MXU-friendly)
"""
from __future__ import annotations

import jax.numpy as jnp

POLICIES = ("binpacking", "spread", "align")


def node_base_scores(free_i32, capacity_i32, policy: str) -> jnp.ndarray:
    """Per-node score [M] shared by all pods; higher is better."""
    free = free_i32.astype(jnp.float32)
    cap = jnp.maximum(capacity_i32.astype(jnp.float32), 1.0)
    # mean normalized free capacity in [0, 1]
    norm_free = jnp.mean(free / cap, axis=1)
    if policy == "spread":
        return norm_free
    # binpacking and align share the packed base
    return 1.0 - norm_free


def alignment_scores(req_chunk_i32, free_i32, capacity_i32) -> jnp.ndarray:
    """[C, M] request/free shape-alignment bonus (MXU matmul).

    Normalized dot product between the request vector and each node's free
    vector. Scaled small so the packing base dominates and alignment breaks
    ties.
    """
    cap = jnp.maximum(capacity_i32.astype(jnp.float32), 1.0)
    free_n = free_i32.astype(jnp.float32) / cap                     # [M, R]
    req = req_chunk_i32.astype(jnp.float32)
    req_n = req / jnp.maximum(jnp.linalg.norm(req, axis=1, keepdims=True), 1e-6)
    return 0.125 * (req_n @ free_n.T)                                # [C, M]
