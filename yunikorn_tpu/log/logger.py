"""Hierarchical, hot-reloadable component loggers.

Role-equivalent to the reference's pkg/log/logger.go: 26 named loggers (:55-92),
per-logger levels resolved from config keys ``log.<name>.level`` with dotted-parent
inheritance (:139-161), and an atomic swap of the logging config on hot reload
(:217-285). Built on the stdlib ``logging`` module; the "filtered core" trick
(filtered_core.go) maps onto per-logger level caps.
"""
from __future__ import annotations

import logging
import sys
from typing import Dict, Optional

from yunikorn_tpu.locking import locking

_ROOT_NAME = "yunikorn"

# The named logger handles (reference logger.go:55-92 defines the analogous set).
HANDLES = [
    "admission",
    "admission.client",
    "admission.conf",
    "admission.utils",
    "admission.webhook",
    "core",
    "core.config",
    "core.scheduler",
    "core.placement",
    "core.queue",
    "deprecation",
    "dispatcher",
    "kubernetes",
    "rmproxy",
    "shim",
    "shim.cache.application",
    "shim.cache.context",
    "shim.cache.external",
    "shim.cache.node",
    "shim.cache.placeholder",
    "shim.cache.task",
    "shim.client",
    "shim.config",
    "shim.context",
    "shim.dispatcher",
    "shim.fsm",
    "shim.predicates",
    "shim.resources",
    "shim.scheduler",
    "shim.snapshot",
    "shim.solver",
    "shim.utils",
    "test",
]

_LEVELS = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "dpanic": logging.CRITICAL,
    "panic": logging.CRITICAL,
    "fatal": logging.CRITICAL,
    # zap also accepts numeric levels -1..5
    "-1": logging.DEBUG,
    "0": logging.INFO,
    "1": logging.WARNING,
    "2": logging.ERROR,
    "3": logging.CRITICAL,
    "4": logging.CRITICAL,
    "5": logging.CRITICAL,
}

_lock = locking.Mutex()
_configured = False
_current_config: Dict[str, str] = {}


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    with _lock:
        if _configured:
            return
        root = logging.getLogger(_ROOT_NAME)
        if not root.handlers:
            h = logging.StreamHandler(sys.stderr)
            h.setFormatter(
                logging.Formatter(
                    fmt="%(asctime)s %(levelname)s %(name)s %(message)s",
                    datefmt="%Y-%m-%dT%H:%M:%S",
                )
            )
            root.addHandler(h)
        root.setLevel(logging.INFO)
        root.propagate = False
        _configured = True


def log(handle: str = "shim") -> logging.Logger:
    """Return the named component logger (reference: log.Log(handle), logger.go:108)."""
    _ensure_configured()
    return logging.getLogger(f"{_ROOT_NAME}.{handle}")


def resolve_level(handle: str, config: Dict[str, str]) -> Optional[int]:
    """Resolve ``log.<handle>.level`` with dotted-parent inheritance.

    ``log.shim.cache.task.level`` falls back to ``log.shim.cache.level`` →
    ``log.shim.level`` → ``log.level`` (reference logger.go:139-161).
    """
    parts = handle.split(".")
    while parts:
        key = "log." + ".".join(parts) + ".level"
        if key in config:
            return _LEVELS.get(config[key].strip().lower())
        parts.pop()
    if "log.level" in config:
        return _LEVELS.get(config["log.level"].strip().lower())
    return None


def update_logging_config(config: Dict[str, str]) -> None:
    """Atomically apply per-logger levels from a flattened configmap.

    Unknown level strings are ignored (the reference warns and keeps the old
    level). Called on config hot-reload (reference logger.go:217-285).
    """
    _ensure_configured()
    with _lock:
        global _current_config
        _current_config = dict(config)
        root_level = resolve_level("", config)
        root = logging.getLogger(_ROOT_NAME)
        root.setLevel(root_level if root_level is not None else logging.INFO)
        for handle in HANDLES:
            lvl = resolve_level(handle, config)
            lg = logging.getLogger(f"{_ROOT_NAME}.{handle}")
            # NOTSET => inherit from parent, matching dotted inheritance.
            lg.setLevel(lvl if lvl is not None else logging.NOTSET)


def current_config() -> Dict[str, str]:
    with _lock:
        return dict(_current_config)
