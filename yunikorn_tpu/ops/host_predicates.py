"""Host-side reference predicates.

Exact scalar implementations of the same semantics the device kernels encode
(ops/predicates.py). Used by the preemption victim search (one node at a time,
off the solver hot path) and as the ground-truth oracle in property tests.
"""
from __future__ import annotations

from typing import Iterable, Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Node, Pod


def node_selector_matches(pod: Pod, node: Node) -> bool:
    labels = node.metadata.labels
    for k, v in pod.spec.node_selector.items():
        if labels.get(k) != v:
            return False
    if pod.spec.affinity is None or not pod.spec.affinity.node_required_terms:
        return True
    # OR of terms, AND of expressions
    for term in pod.spec.affinity.node_required_terms:
        ok = True
        for e in term.match_expressions:
            val = labels.get(e.key)
            if e.operator == "In":
                ok = val in e.values
            elif e.operator == "NotIn":
                ok = val not in e.values
            elif e.operator == "Exists":
                ok = e.key in labels
            elif e.operator == "DoesNotExist":
                ok = e.key not in labels
            elif e.operator in ("Gt", "Lt"):
                try:
                    ival, target = int(val), int(e.values[0])
                except (TypeError, ValueError, IndexError):
                    ok = False
                else:
                    ok = ival > target if e.operator == "Gt" else ival < target
            else:
                ok = False
            if not ok:
                break
        for e in term.match_fields:
            if e.key == "metadata.name":
                if e.operator == "In":
                    ok = ok and node.name in e.values
                elif e.operator == "NotIn":
                    ok = ok and node.name not in e.values
        if ok:
            return True
    return False


def tolerates_node_taints(pod: Pod, node: Node) -> bool:
    for taint in node.spec.taints:
        if taint.effect == constants.TAINT_EFFECT_PREFER_NO_SCHEDULE:
            continue  # soft
        tolerated = False
        for tol in pod.spec.tolerations:
            if tol.effect and tol.effect != taint.effect:
                continue
            if tol.operator == "Exists":
                if not tol.key or tol.key == taint.key:
                    tolerated = True
                    break
            else:
                if tol.key == taint.key and tol.value == taint.value:
                    tolerated = True
                    break
        if not tolerated:
            return False
    return True


def host_ports_of(pod: Pod) -> set:
    out = set()
    for c in pod.spec.containers:
        for p in c.ports:
            hp = p.get("hostPort")
            if hp:
                out.add((p.get("protocol", "TCP"), hp))
    return out


def ports_conflict(pod: Pod, existing_pods: Iterable[Pod]) -> bool:
    wanted = host_ports_of(pod)
    if not wanted:
        return False
    for other in existing_pods:
        if wanted & host_ports_of(other):
            return True
    return False


def pod_fits_node(pod: Pod, node: Node, free, existing_pods: Iterable[Pod]) -> Optional[str]:
    """Full host check. Returns None when feasible, else the failing reason."""
    from yunikorn_tpu.common.resource import get_pod_resource

    if node.spec.unschedulable:
        return "node unschedulable"
    if not node_selector_matches(pod, node):
        return "node selector/affinity mismatch"
    if not tolerates_node_taints(pod, node):
        return "untolerated taints"
    if ports_conflict(pod, existing_pods):
        return "host port conflict"
    if not get_pod_resource(pod).fits_in(free):
        return "insufficient resources"
    return None
