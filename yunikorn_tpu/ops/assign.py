"""Conflict-free batched assignment: the TPU replacement for the sequential
per-pod scheduling cycle.

The reference schedules one pod at a time: the core picks a pod, probes nodes
via predicate upcalls, assumes the allocation, and the next pod sees updated
capacity (SURVEY.md §3.2). That serialization is exactly what a TPU removes.
Here all N pending pods are assigned in a few data-parallel rounds inside one
jitted program (`lax.while_loop`):

  round:
    1. per-node base score from current free capacity (models/policies.py)
    2. chunked best-node: for each pod chunk [C], compute the fit margin
       against all nodes (static unroll over R — no [N,M,R] tensor is ever
       materialized), mask with the group feasibility matrix, argmax → each
       pod's preferred node. `lax.map` over chunks keeps peak memory at
       [C, M] instead of [N, M].
    3. conflict resolution: sort pods by (preferred node, rank); within each
       node segment compute running int32 prefix sums of requests and accept
       the prefix that fits the node's free capacity. Pods rejected by the
       prefix rule retry next round against updated capacities.
    4. commit: scatter-subtract accepted requests from node free capacity.

  terminate when a round accepts nothing, everyone is assigned, or max_rounds.

Rank is the total scheduling order (queue fair-share + priority + FIFO),
computed by the caller; within a node segment the prefix rule preserves it,
mirroring the ordering guarantees the reference's sequential loop provides
(gang FIFO assertions, reference test gang_scheduling_test.go).

Int32 everywhere for resources: quantities are integral in device units
(vocab scales), comparisons are exact, and segment-relative prefix sums are
correct under int32 wraparound as long as any single node segment's sum stays
below 2^31 (graft note: per-segment sums are bounded by ~node capacity × batch;
batches are capped well below that).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from yunikorn_tpu.models.policies import alignment_scores, node_base_scores
from yunikorn_tpu.ops.predicates import group_feasibility, group_preferred_bonus, group_soft_penalty

# plain Python float (weak-typed, promotes to f32 inside jit): a module-level
# jnp constant would initialize the JAX backend at import — the scheduler
# binary must not dial the TPU before it means to
NEG_INF = -3.0e38


@dataclasses.dataclass
class SolveResult:
    assigned: jnp.ndarray      # [N] int32: node row index, -1 if unassigned
    free_after: jnp.ndarray    # [M, R] int32
    rounds: jnp.ndarray        # int32 scalar

    def block_until_ready(self):
        self.assigned.block_until_ready()
        return self


def _loc_round_stats(loc, cnt):
    """Per-locality-group (min over valid domains, total) of current counts."""
    _, _, dom_valid = loc[0], loc[1], loc[2]
    big = jnp.int32(2**30)
    minc = jnp.min(jnp.where(dom_valid, cnt, big), axis=1)             # [L]
    total = jnp.sum(jnp.where(dom_valid, cnt, 0), axis=1)              # [L]
    return minc, total


def _loc_rules_mask(gid_rows, dom_cols, loc, cnt, minc, total, contrib_rows):
    """Evaluate locality rules for pods (rows) × nodes (cols).

    gid_rows: [C] group ids; dom_cols: None for all-nodes [C, M] evaluation or
    [C] node ids for per-pod single-node checks; contrib_rows: [C, L] whether
    each pod itself counts toward each locality group (K8s selfMatchNum — a
    spread constraint whose selector does not match the pod itself adds 0).
    Returns a bool mask of shape [C, M] or [C].
    """
    from yunikorn_tpu.snapshot.locality import (
        KIND_AFFINITY,
        KIND_ANTI_AFFINITY,
        KIND_SPREAD,
    )

    loc_dom = loc[0]
    g_refs, g_kind, g_skew, g_seed = loc[4], loc[5], loc[6], loc[7]
    L, M = loc_dom.shape
    D = cnt.shape[1]
    S = g_refs.shape[1]
    per_node = dom_cols is None
    ok = None
    for s in range(S):
        l = g_refs[gid_rows, s]                                        # [C]
        kind = g_kind[gid_rows, s]
        skew = g_skew[gid_rows, s]
        seed = g_seed[gid_rows, s]
        lc = jnp.clip(l, 0, L - 1)
        self_add = jnp.take_along_axis(contrib_rows, lc[:, None], axis=1)[:, 0]
        self_add = self_add.astype(jnp.int32)                          # [C]
        if per_node:
            dom_row = loc_dom[lc]                                      # [C, M]
        else:
            dom_row = loc_dom[lc, dom_cols]                            # [C]
        cnt_row = cnt[lc]                                              # [C, D]
        dcl = jnp.clip(dom_row, 0, D - 1)
        if per_node:
            cnt_at = jnp.take_along_axis(cnt_row, dcl, axis=1)         # [C, M]
            expand = lambda x: x[:, None]
        else:
            cnt_at = jnp.take_along_axis(cnt_row, dcl[:, None], axis=1)[:, 0]  # [C]
            expand = lambda x: x
        has_dom = dom_row >= 0
        spread_ok = has_dom & (cnt_at + expand(self_add) - expand(minc[lc]) <= expand(skew))
        aff_ok = has_dom & ((cnt_at > 0) | (expand(seed) & (expand(total[lc]) == 0)))
        anti_ok = (~has_dom) | (cnt_at == 0)
        rule_ok = jnp.where(expand(kind) == KIND_SPREAD, spread_ok,
                   jnp.where(expand(kind) == KIND_AFFINITY, aff_ok,
                    jnp.where(expand(kind) == KIND_ANTI_AFFINITY, anti_ok, True)))
        rule_ok = jnp.where(expand(l >= 0), rule_ok, True)
        ok = rule_ok if ok is None else (ok & rule_ok)
    return ok


def _loc_soft_scores(gid_rows, dom_cols, loc, cnt, minc, contrib_rows):
    """Score adjustments from soft locality slots for pods (rows) × nodes.

    Same row/col conventions as _loc_rules_mask. Soft spread penalizes
    imbalance above the current minimum domain; soft (anti-)affinity adds the
    slot's pre-scaled weight per matching pod in the domain. Hard slots carry
    weight 0 and contribute nothing.
    """
    from yunikorn_tpu.snapshot.locality import KIND_SOFT_SPREAD

    loc_dom = loc[0]
    g_refs, g_kind, g_weight = loc[4], loc[5], loc[8]
    L, M = loc_dom.shape
    D = cnt.shape[1]
    S = g_refs.shape[1]
    per_node = dom_cols is None
    out = None
    for s in range(S):
        l = g_refs[gid_rows, s]                                        # [C]
        kind = g_kind[gid_rows, s]
        w = g_weight[gid_rows, s]
        lc = jnp.clip(l, 0, L - 1)
        self_add = jnp.take_along_axis(contrib_rows, lc[:, None], axis=1)[:, 0]
        self_add = self_add.astype(jnp.int32)
        if per_node:
            dom_row = loc_dom[lc]                                      # [C, M]
        else:
            dom_row = loc_dom[lc, dom_cols]                            # [C]
        cnt_row = cnt[lc]                                              # [C, D]
        dcl = jnp.clip(dom_row, 0, D - 1)
        if per_node:
            cnt_at = jnp.take_along_axis(cnt_row, dcl, axis=1)         # [C, M]
            expand = lambda x: x[:, None]
        else:
            cnt_at = jnp.take_along_axis(cnt_row, dcl[:, None], axis=1)[:, 0]
            expand = lambda x: x
        has_dom = dom_row >= 0
        spread_pen = jnp.maximum(
            cnt_at + expand(self_add) - expand(minc[lc]), 0).astype(jnp.float32)
        val = jnp.where(expand(kind) == KIND_SOFT_SPREAD, spread_pen,
                        cnt_at.astype(jnp.float32))
        adj = jnp.where(has_dom & expand(l >= 0), expand(w) * val, 0.0)
        out = adj if out is None else out + adj
    return out


def _best_nodes_chunked(req, group_id, group_feas, group_soft, free, capacity,
                        base_scores, chunk: int, policy: str, loc=None, cnt=None,
                        minc=None, total=None, has_loc_soft=True):
    """For every pod: (best node, any feasible?) without materializing [N, M]."""
    N, R = req.shape
    M = free.shape[0]
    n_chunks = N // chunk

    def one_chunk(c):
        start = c * chunk
        creq = lax.dynamic_slice(req, (start, 0), (chunk, R))          # [C, R]
        cgid = lax.dynamic_slice(group_id, (start,), (chunk,))         # [C]
        cfeas = group_feas[cgid]                                       # [C, M]
        # fit margin: min_r (free - req); static unroll over R
        margin = jnp.full((chunk, M), jnp.int32(2**30))
        for r in range(R):
            margin = jnp.minimum(margin, free[:, r][None, :] - creq[:, r][:, None])
        ok = cfeas & (margin >= 0)
        scores = jnp.broadcast_to(base_scores[None, :], (chunk, M)) + group_soft[cgid]
        if loc is not None:
            ccontrib = lax.dynamic_slice(loc[3], (start, 0), (chunk, loc[3].shape[1]))
            ok &= _loc_rules_mask(cgid, None, loc, cnt, minc, total, ccontrib)
            if has_loc_soft:
                scores = scores + _loc_soft_scores(cgid, None, loc, cnt, minc, ccontrib)
        if policy == "align":
            scores = scores + alignment_scores(creq, free, capacity)
        scores = jnp.where(ok, scores, NEG_INF)
        best = jnp.argmax(scores, axis=1).astype(jnp.int32)            # [C]
        feasible = jnp.any(ok, axis=1)                                 # [C]
        return best, feasible

    best, feasible = lax.map(one_chunk, jnp.arange(n_chunks))
    return best.reshape(N), feasible.reshape(N)


def _water_fill_proposals(req, group_id, rank, active, group_feas, free,
                          base_scores, group_soft, loc=None, cnt=None,
                          minc=None, group_contrib=None):
    """Capacity-aware proposals: the batched analog of "fill nodes in score order".

    Plain per-pod argmax herds every pod in a constraint group onto the same
    best node, so each round fills only one node per group (observed on TPU:
    16 rounds × 110 pods/node). Instead, for each group: order its feasible
    nodes by score, cumsum their free capacity, cumsum the rank-ordered demand
    of the group's pods, and propose pod i to the node whose cumulative
    capacity first covers pod i's cumulative demand. For homogeneous pods this
    reproduces exact sequential bin-packing in ONE round.

    Returns proposals [N] int32 (node row, or M when the group's total
    capacity is exhausted before this pod's position).
    """
    N, R = req.shape
    M = free.shape[0]
    G = group_feas.shape[0]

    # rank order of pods (global; group-wise prefix sums are masked cumsums)
    pod_order = jnp.argsort(rank)
    sreq = req[pod_order].astype(jnp.float32)                  # [N, R]
    sgid = group_id[pod_order]
    sactive = active[pod_order]

    def per_group(g):
        feas = group_feas[g]                                   # [M]
        score = base_scores + group_soft[g]
        if loc is not None:
            score = score + _loc_soft_scores(
                jnp.reshape(g, (1,)), None, loc, cnt, minc,
                group_contrib[jnp.reshape(g, (1,))])[0]
        score = jnp.where(feas, score, NEG_INF)
        node_order = jnp.argsort(-score)                       # feasible first
        ofree = jnp.where(feas[node_order, None], free[node_order].astype(jnp.float32), 0.0)
        cumF = jnp.cumsum(ofree, axis=0)                       # [M, R]
        mine = sactive & (sgid == g)
        demand = jnp.where(mine[:, None], sreq, 0.0)
        C = jnp.cumsum(demand, axis=0)                         # [N, R] inclusive
        pos = jnp.zeros((N,), jnp.int32)
        for r in range(R):
            # both sides are monotone; sort-based rank beats binary-search
            # gathers on TPU by ~4x
            pos = jnp.maximum(
                pos,
                jnp.searchsorted(cumF[:, r], C[:, r] - 0.5, method="sort").astype(jnp.int32),
            )
        ok = pos < M
        node = jnp.where(ok & mine, node_order[jnp.clip(pos, 0, M - 1)], M)
        return jnp.where(mine, node, M).astype(jnp.int32)

    per_group_nodes = jax.vmap(per_group)(jnp.arange(G))       # [G, N] in sorted pod order
    chosen_sorted = jnp.min(per_group_nodes, axis=0)           # each pod active in ≤1 group
    # min works because non-members hold M; a pod's own group value is ≤ M
    proposals = jnp.full((N,), M, jnp.int32).at[pod_order].set(chosen_sorted)
    return proposals


def _loc_capped_flags(loc):
    """Per locality group: is it referenced by a spread/anti (capped) slot,
    by an affinity slot (for seeding caps), or by a ScheduleAnyway spread
    slot (for the balance allowance)? Computed once per solve."""
    from yunikorn_tpu.snapshot.locality import (
        KIND_AFFINITY,
        KIND_ANTI_AFFINITY,
        KIND_SOFT_SPREAD,
        KIND_SPREAD,
    )

    loc_dom = loc[0]
    g_refs, g_kind = loc[4], loc[5]
    L = loc_dom.shape[0]
    capped = []
    aff = []
    soft_spread = []
    for l in range(L):
        ref_l = g_refs == l
        capped.append(jnp.any(ref_l & ((g_kind == KIND_SPREAD) | (g_kind == KIND_ANTI_AFFINITY))))
        aff.append(jnp.any(ref_l & (g_kind == KIND_AFFINITY)))
        soft_spread.append(jnp.any(ref_l & (g_kind == KIND_SOFT_SPREAD)))
    return jnp.stack(capped), jnp.stack(aff), jnp.stack(soft_spread)


def _loc_accept_cap(accept_sorted, snode, scontrib, loc, M, total,
                    capped_l, aff_l, allowance_l):
    """Cap accepted pods contributing to a locality group per (group, domain)
    per round: 1 for hard spread/anti groups, `allowance_l` (≈ remaining /
    domains) for ScheduleAnyway spread groups so a batch balances without
    throttling throughput.

    Contribution — not the pod's own constraint slots — is what changes the
    counts, so the cap keys on contrib: a plain pod whose labels match another
    pod's anti-affinity selector is capped alongside it (symmetry holds even
    within one round). Affinity groups cap only while *seeding* (total==0),
    and then per GROUP (one domain seeds per round) so a self-affinitized
    group cannot split across domains.

    Counts only update between rounds; without this cap several pods could
    land in one domain in a single round and overshoot maxSkew or violate
    anti-affinity. One-per-domain-per-round is exact for anti-affinity and
    converges for spread.
    """
    loc_dom = loc[0]
    L, _ = loc_dom.shape
    N = accept_sorted.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    node_cl = jnp.clip(snode, 0, M - 1)
    for l in range(L):
        seeding = aff_l[l] & (total[l] == 0)
        cap_now = (allowance_l[l] < N) | seeding
        limit = jnp.where(capped_l[l] | seeding, 1, allowance_l[l])
        dom_i = loc_dom[l, node_cl]                                    # [N]
        active = cap_now & scontrib[:, l] & (dom_i >= 0) & (snode < M) & accept_sorted
        # seeding caps per GROUP (key 0); spread/anti per domain
        key = jnp.where(active, jnp.where(seeding, 0, dom_i), (M + 2) + idx)
        order2 = jnp.argsort(key)                                      # stable
        k2 = key[order2]
        act2 = active[order2]
        seg_start = jnp.concatenate([jnp.array([True]), k2[1:] != k2[:-1]])
        c = jnp.cumsum(act2.astype(jnp.int32))
        head = lax.cummax(jnp.where(seg_start, idx, 0))
        base = jnp.where(head > 0, c[jnp.maximum(head - 1, 0)], 0)
        within = c - base                                              # inclusive
        keep2 = (~act2) | (within <= limit)
        keep = jnp.zeros((N,), bool).at[order2].set(keep2)
        accept_sorted = accept_sorted & keep
    return accept_sorted


def _loc_update_counts(cnt, loc, accepted, best, M):
    """Scatter-add this round's placements into the domain counts."""
    loc_dom, contrib = loc[0], loc[3]
    L = loc_dom.shape[0]
    D = cnt.shape[1]
    node_cl = jnp.clip(best, 0, M - 1)
    for l in range(L):
        dom_i = loc_dom[l, node_cl]                                    # [N]
        add = accepted & contrib[:, l] & (dom_i >= 0) & (best >= 0) & (best < M)
        cnt = cnt.at[l, jnp.clip(dom_i, 0, D - 1)].add(add.astype(jnp.int32))
    return cnt


def _segment_prefix_accept(snode, sreq, free_ext, M):
    """Accept the per-node-segment prefix of sorted requests that fits.

    snode: [N] int32 sorted node ids (M = dummy/no-candidate, sorts last)
    sreq:  [N, R] int32 requests in sorted order
    free_ext: [M+1, R] int32
    returns accept_sorted [N] bool
    """
    N = snode.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    seg_start = jnp.concatenate([jnp.array([True]), snode[1:] != snode[:-1]])
    # index of each row's segment head via running max
    head = lax.cummax(jnp.where(seg_start, idx, 0))
    cums = jnp.cumsum(sreq, axis=0, dtype=jnp.int32)                   # wraps ok
    base = jnp.where((head > 0)[:, None], cums[jnp.maximum(head - 1, 0)], 0)
    prefix = cums - base                                               # [N, R]
    node_free = free_ext[snode]                                        # [N, R]
    fits = jnp.all(prefix <= node_free, axis=1)
    return fits & (snode < M)


@functools.partial(
    jax.jit,
    static_argnames=("max_rounds", "chunk", "policy", "use_pallas",
                     "pallas_interpret", "has_loc_soft", "pallas_has_soft"),
)
def solve(
    req,            # [N, R] int32
    group_id,       # [N] int32
    rank,           # [N] float32 — lower schedules first
    valid,          # [N] bool
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
    g_tol, g_ports,                                   # group tensors
    g_pref_req, g_pref_forb, g_pref_weight,           # preferred-affinity scoring
    node_labels, node_taints, node_taints_soft, node_ports, node_ok,  # node symbol state
    free,           # [M, R] int32
    capacity,       # [M, R] int32
    host_group_mask=None,   # [G, M] bool or None
    host_group_soft=None,   # [G, M] float32 or None (host-scored soft terms)
    loc=None,       # locality tuple: (dom [L,M], cnt0 [L,D], dom_valid [L,D],
                    #  contrib [N,L], g_refs [G,S], g_kind, g_skew, g_seed,
                    #  g_weight [G,S] f32 — soft-slot score weights)
    *,
    max_rounds: int = 16,
    chunk: int = 512,
    policy: str = "binpacking",
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    has_loc_soft: bool = True,
    pallas_has_soft: bool = True,
):
    """One batched solve. Returns (assigned [N] int32, free_after, rounds).

    has_loc_soft=False (static) skips the soft-locality scoring pass for
    batches whose locality slots are all hard (the common case) — the pass
    provably sums to zero when every g_weight is 0.

    use_pallas routes the per-round best-node computation through the fused
    Pallas kernel (ops/pallas_kernels.py). Only separable scoring policies are
    fused and locality constraints fall back to the XLA path (they need the
    dynamic per-round masks).
    """
    N, R = req.shape
    M = free.shape[0]
    chunk = min(chunk, N)
    assert N % chunk == 0, "batch size must be a multiple of the chunk size"

    group_feas = group_feasibility(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, node_labels, node_taints, node_ports, node_ok,
    )
    if host_group_mask is not None:
        group_feas = group_feas & host_group_mask
    # scoring halves: PreferNoSchedule taints penalize, preferred node
    # affinity terms reward — one [G, M] adjustment shared by the round paths
    group_soft = group_soft_penalty(g_tol, node_taints_soft) + group_preferred_bonus(
        g_pref_req, g_pref_forb, g_pref_weight, node_labels)          # [G, M]
    if host_group_soft is not None:
        # preferred terms the tensor encoding can't express exactly
        # (multi-value In, slot overflow) — scored on the host, same scale
        group_soft = group_soft + host_group_soft

    has_loc = loc is not None
    free_ext0 = jnp.concatenate([free, jnp.zeros((1, R), jnp.int32)], axis=0)
    cnt0 = loc[1] if has_loc else jnp.zeros((1, 1), jnp.int32)
    if has_loc:
        loc_capped_l, loc_aff_l, loc_softspread_l = _loc_capped_flags(loc)
        # per-group contribution flags (all pods in a group share them — the
        # signature folds labels in whenever locality applies): lets the
        # water-fill score soft locality per group
        if has_loc_soft:
            G = group_feas.shape[0]
            L = loc[0].shape[0]
            group_contrib = (jnp.zeros((G, L), jnp.int32)
                             .at[group_id].max(loc[3].astype(jnp.int32))
                             .astype(bool))
        else:
            group_contrib = None
    else:
        group_contrib = None
    init = (
        free_ext0,
        ~valid,                                     # "done" = assigned or invalid
        jnp.full((N,), -1, jnp.int32),              # assignment
        jnp.int32(0),                               # round counter
        jnp.int32(0),                               # consecutive no-progress rounds
        cnt0,                                       # locality domain counts
    )

    def cond(state):
        _, done, _, rnd, stalls, _ = state
        # water-fill and argmax rounds alternate; only give up after both stall
        return (stalls < 2) & (rnd < max_rounds) & ~jnp.all(done)

    def body(state):
        free_ext, done, assigned, rnd, stalls, cnt = state
        cur_free = free_ext[:M]
        base_scores = node_base_scores(cur_free, capacity, policy)
        active = ~done
        if has_loc:
            minc, total = _loc_round_stats(loc, cnt)
        else:
            minc = total = None

        proposals = _water_fill_proposals(req, group_id, rank, active, group_feas,
                                          cur_free, base_scores, group_soft,
                                          loc if has_loc_soft else None,
                                          cnt, minc, group_contrib)
        prop_fits = jnp.all(free_ext[proposals] >= req, axis=1) & (proposals < M)
        if has_loc:
            # proposals must also satisfy the dynamic locality rules
            prop_fits &= _loc_rules_mask(group_id, jnp.clip(proposals, 0, M - 1),
                                         loc, cnt, minc, total, loc[3])

        def with_argmax(_):
            # exact per-pod argmax; guarantees ≥1 accept per contended node
            if use_pallas and not has_loc and policy != "align":
                from yunikorn_tpu.ops.pallas_kernels import pallas_best_nodes

                best, feasible = pallas_best_nodes(
                    req, group_id, group_feas, group_soft, cur_free,
                    base_scores, interpret=pallas_interpret,
                    has_soft=pallas_has_soft)
            else:
                best, feasible = _best_nodes_chunked(
                    req, group_id, group_feas, group_soft, cur_free, capacity,
                    base_scores, chunk, policy, loc, cnt, minc, total,
                    has_loc_soft,
                )
            merged = jnp.where(prop_fits, proposals, best)
            return merged, active & (feasible | prop_fits)

        def water_only(_):
            return proposals, active & prop_fits

        # even rounds: cheap water-fill only (hits ~100% on homogeneous loads);
        # odd rounds add the exact argmax fallback for what water-fill missed
        best, cand = lax.cond(rnd % 2 == 1, with_argmax, water_only, None)

        node_key = jnp.where(cand, best, M)
        order = jnp.lexsort((rank, node_key))       # primary: node, secondary: rank
        snode = node_key[order]
        sreq = req[order]
        accept_sorted = _segment_prefix_accept(snode, sreq, free_ext, M)
        if has_loc:
            # soft-spread groups get a per-domain allowance of ceil(remaining
            # pods / domains): the batch balances across domains within a
            # round at full throughput, then re-scores with fresh counts
            remaining = jnp.sum((active[:, None] & loc[3]).astype(jnp.int32), axis=0)
            n_dom = jnp.maximum(jnp.sum(loc[2].astype(jnp.int32), axis=1), 1)
            soft_allow = jnp.maximum((remaining + n_dom - 1) // n_dom, 1)
            allowance_l = jnp.where(loc_capped_l, 1,
                                    jnp.where(loc_softspread_l, soft_allow, N))
            accept_sorted = _loc_accept_cap(accept_sorted, snode, loc[3][order],
                                            loc, M, total, loc_capped_l,
                                            loc_aff_l, allowance_l)
        # commit accepted capacity
        delta = jnp.where(accept_sorted[:, None], sreq, 0)
        free_ext = free_ext.at[snode].add(-delta)
        free_ext = free_ext.at[M].set(0)
        accepted = jnp.zeros((N,), bool).at[order].set(accept_sorted)
        assigned = jnp.where(accepted, best, assigned)
        if has_loc:
            cnt = _loc_update_counts(cnt, loc, accepted, best, M)
        done = done | accepted
        progress = jnp.any(accept_sorted)
        stalls = jnp.where(progress, 0, stalls + 1)
        return free_ext, done, assigned, rnd + 1, stalls, cnt

    free_ext, done, assigned, rounds, _, _ = lax.while_loop(cond, body, init)
    return assigned, free_ext[:M], rounds


def pad2d(arr, width, fill):
    """Pad or clamp the second dim of a [G, m] host array to `width` — the
    node capacity may have grown (or a sharded view may be narrower) since
    the batch was encoded."""
    import numpy as np

    if arr.shape[1] == width:
        return arr
    out = np.full((arr.shape[0], width), fill, arr.dtype)
    out[:, : min(arr.shape[1], width)] = arr[:, :width]
    return out


def solve_batch(batch, node_arrays, *, max_rounds=16, chunk=512, policy="binpacking",
                free_delta=None, use_pallas=False, pallas_interpret=False,
                device=None, node_mask=None,
                compile_only=False) -> Optional[SolveResult]:
    """Convenience host wrapper: numpy in → SolveResult out.

    free_delta: optional [capacity, R] float array subtracted from node free
    capacity before the solve (the core's in-flight allocation overlay).
    node_mask: optional [capacity] bool restricting candidate nodes (the
    multi-partition case: one encoder holds every cache node, each
    partition's solve sees only its own).
    compile_only: AOT-lower and compile this shape/static-variant without
    executing (bucket prewarm) — fills the jit + persistent caches at zero
    device time; returns None.
    """
    import numpy as np

    na = node_arrays
    free_i = np.floor(na.free).astype(np.int32)
    if free_delta is not None:
        # overlay may be narrower/shorter than the (possibly grown) node arrays
        d = np.zeros_like(free_i)
        rows = min(free_i.shape[0], free_delta.shape[0])
        cols = min(free_i.shape[1], free_delta.shape[1])
        d[:rows, :cols] = np.ceil(free_delta[:rows, :cols]).astype(np.int32)
        free_i = free_i - d
    cap_i = np.floor(na.capacity_arr).astype(np.int32)
    node_ok = na.valid & na.schedulable
    if node_mask is not None:
        node_ok = node_ok & node_mask[: node_ok.shape[0]]
    host_mask = batch.g_host_mask
    if host_mask is not None:
        host_mask = pad2d(host_mask, na.capacity, False)
    host_soft = getattr(batch, "g_host_soft", None)
    if host_soft is not None:
        host_soft = pad2d(host_soft, na.capacity, np.float32(0.0))
    loc = None
    if batch.locality is not None:
        lb = batch.locality
        loc = (lb.dom, lb.cnt0, lb.dom_valid, lb.contrib,
               lb.g_refs, lb.g_kind, lb.g_skew, lb.g_seed, lb.g_weight)
    np_args = (
        batch.req.astype(np.int32),
        batch.group_id,
        batch.rank,
        batch.valid,
        batch.g_term_req.view(np.uint32),
        batch.g_term_forb.view(np.uint32),
        batch.g_term_valid,
        batch.g_anyof.view(np.uint32),
        batch.g_anyof_valid,
        batch.g_tol.view(np.uint32),
        batch.g_ports.view(np.uint32),
        batch.g_pref_req.view(np.uint32),
        batch.g_pref_forb.view(np.uint32),
        batch.g_pref_weight,
        na.labels.view(np.uint32),
        na.taints_hard.view(np.uint32),
        na.taints_soft.view(np.uint32),
        na.ports.view(np.uint32),
        node_ok,
        free_i,
        cap_i,
        host_mask,
        host_soft,
        loc,
    )
    solve_kwargs = dict(
        max_rounds=max_rounds,
        chunk=chunk,
        policy=policy,
        # the fused kernel takes the combined [G, M] soft adjustment (soft
        # taints + preferred affinity + host-scored terms); only dynamic
        # locality and the align policy fall back to the XLA path (handled
        # inside solve)
        use_pallas=use_pallas,
        pallas_interpret=pallas_interpret,
        has_loc_soft=(batch.locality is not None
                      and bool(np.any(batch.locality.g_weight))),
        # no-soft batches take the kernel variant without the soft DMA/matmul
        pallas_has_soft=(bool(batch.g_pref_weight.any())
                         or host_soft is not None
                         or bool(np.any(na.taints_soft))),
    )
    if compile_only:
        # specs instead of arrays: no host->device transfer at all
        specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), np_args)
        solve.lower(*specs, **solve_kwargs).compile()
        return None
    solve_args = jax.tree_util.tree_map(jnp.asarray, np_args)
    assigned, free_after, rounds = solve(*solve_args, **solve_kwargs)
    return SolveResult(assigned=assigned, free_after=free_after, rounds=rounds)
