"""Conflict-free batched assignment: the TPU replacement for the sequential
per-pod scheduling cycle.

The reference schedules one pod at a time: the core picks a pod, probes nodes
via predicate upcalls, assumes the allocation, and the next pod sees updated
capacity (SURVEY.md §3.2). That serialization is exactly what a TPU removes.
Here all N pending pods are assigned in a few data-parallel rounds inside one
jitted program (`lax.while_loop`):

  round:
    1. per-node base score from current free capacity (models/policies.py)
    2. chunked best-node: for each pod chunk [C], compute the fit margin
       against all nodes (static unroll over R — no [N,M,R] tensor is ever
       materialized), mask with the group feasibility matrix, argmax → each
       pod's preferred node. `lax.map` over chunks keeps peak memory at
       [C, M] instead of [N, M].
    3. conflict resolution: sort pods by (preferred node, rank); within each
       node segment compute running int32 prefix sums of requests and accept
       the prefix that fits the node's free capacity. Pods rejected by the
       prefix rule retry next round against updated capacities.
    4. commit: scatter-subtract accepted requests from node free capacity.

  terminate when a round accepts nothing, everyone is assigned, or max_rounds.

Rank is the total scheduling order (queue fair-share + priority + FIFO),
computed by the caller; within a node segment the prefix rule preserves it,
mirroring the ordering guarantees the reference's sequential loop provides
(gang FIFO assertions, reference test gang_scheduling_test.go).

Int32 everywhere for resources: quantities are integral in device units
(vocab scales), comparisons are exact, and segment-relative prefix sums are
correct under int32 wraparound as long as any single node segment's sum stays
below 2^31 (graft note: per-segment sums are bounded by ~node capacity × batch;
batches are capped well below that).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from yunikorn_tpu.models.policies import alignment_scores, node_base_scores
from yunikorn_tpu.ops.predicates import group_feasibility, group_preferred_bonus, group_soft_penalty

# plain Python float (weak-typed, promotes to f32 inside jit): a module-level
# jnp constant would initialize the JAX backend at import — the scheduler
# binary must not dial the TPU before it means to
NEG_INF = -3.0e38

# ---- topology steering weights (solver.topology, topology/score.py) ----
# Scores are base_scores ∈ [0,1] plus soft adjustments of comparable scale.
# The gang term must DOMINATE base-score differences (a gang member must
# prefer its planned ICI domain over a marginally better-packed node in a
# foreign domain) without being able to override feasibility — it is a
# score, argmax/water-fill ordering only. The contention/empty terms are
# deliberately mild: tie-breakers between otherwise comparable nodes, the
# BandPilot-style "avoid co-tenant-loaded interconnects" pressure.
TOPO_GANG_W = 8.0        # node is in the gang's planned ICI domain
TOPO_CONTENTION_W = 0.25  # × co-tenant busy fraction of the node's domain
TOPO_EMPTY_W = 0.5       # the node's domain is co-tenant-free


@dataclasses.dataclass
class SolveResult:
    assigned: jnp.ndarray      # [N] int32: node row index, -1 if unassigned
    free_after: jnp.ndarray    # [M, R] int32
    rounds: jnp.ndarray        # int32 scalar
    # [N] int32: solve round at which each pod was accepted (-1 unassigned);
    # chained chunk solves offset later chunks so the order is global. The
    # differential fuzzer replays this order against a host oracle.
    accept_round: Optional[jnp.ndarray] = None

    def block_until_ready(self):
        self.assigned.block_until_ready()
        return self


def _loc_round_stats(loc, cnt):
    """Per-locality-group (min over valid domains, total) of current counts."""
    _, _, dom_valid = loc[0], loc[1], loc[2]
    big = jnp.int32(2**30)
    minc = jnp.min(jnp.where(dom_valid, cnt, big), axis=1)             # [L]
    total = jnp.sum(jnp.where(dom_valid, cnt, 0), axis=1)              # [L]
    return minc, total


def _loc_rules_mask(gid_rows, dom_cols, loc, cnt, minc, total, contrib_rows):
    """Evaluate locality rules for pods (rows) × nodes (cols).

    gid_rows: [C] group ids; dom_cols: None for all-nodes [C, M] evaluation or
    [C] node ids for per-pod single-node checks; contrib_rows: [C, L] whether
    each pod itself counts toward each locality group (K8s selfMatchNum — a
    spread constraint whose selector does not match the pod itself adds 0).
    Returns a bool mask of shape [C, M] or [C].
    """
    from yunikorn_tpu.snapshot.locality import (
        KIND_AFFINITY,
        KIND_ANTI_AFFINITY,
        KIND_SPREAD,
    )

    loc_dom = loc[0]
    g_refs, g_kind, g_skew, g_seed = loc[4], loc[5], loc[6], loc[7]
    L, M = loc_dom.shape
    D = cnt.shape[1]
    S = g_refs.shape[1]
    per_node = dom_cols is None
    ok = None
    for s in range(S):
        l = g_refs[gid_rows, s]                                        # [C]
        kind = g_kind[gid_rows, s]
        skew = g_skew[gid_rows, s]
        seed = g_seed[gid_rows, s]
        lc = jnp.clip(l, 0, L - 1)
        self_add = jnp.take_along_axis(contrib_rows, lc[:, None], axis=1)[:, 0]
        self_add = self_add.astype(jnp.int32)                          # [C]
        if per_node:
            dom_row = loc_dom[lc]                                      # [C, M]
        else:
            dom_row = loc_dom[lc, dom_cols]                            # [C]
        cnt_row = cnt[lc]                                              # [C, D]
        dcl = jnp.clip(dom_row, 0, D - 1)
        if per_node:
            cnt_at = jnp.take_along_axis(cnt_row, dcl, axis=1)         # [C, M]
            expand = lambda x: x[:, None]
        else:
            cnt_at = jnp.take_along_axis(cnt_row, dcl[:, None], axis=1)[:, 0]  # [C]
            expand = lambda x: x
        has_dom = dom_row >= 0
        spread_ok = has_dom & (cnt_at + expand(self_add) - expand(minc[lc]) <= expand(skew))
        aff_ok = has_dom & ((cnt_at > 0) | (expand(seed) & (expand(total[lc]) == 0)))
        anti_ok = (~has_dom) | (cnt_at == 0)
        rule_ok = jnp.where(expand(kind) == KIND_SPREAD, spread_ok,
                   jnp.where(expand(kind) == KIND_AFFINITY, aff_ok,
                    jnp.where(expand(kind) == KIND_ANTI_AFFINITY, anti_ok, True)))
        rule_ok = jnp.where(expand(l >= 0), rule_ok, True)
        ok = rule_ok if ok is None else (ok & rule_ok)
    return ok


def _loc_soft_scores(gid_rows, dom_cols, loc, cnt, minc, contrib_rows):
    """Score adjustments from soft locality slots for pods (rows) × nodes.

    Same row/col conventions as _loc_rules_mask. Soft spread penalizes
    imbalance above the current minimum domain; soft (anti-)affinity adds the
    slot's pre-scaled weight per matching pod in the domain. Hard slots carry
    weight 0 and contribute nothing.
    """
    from yunikorn_tpu.snapshot.locality import KIND_SOFT_SPREAD

    loc_dom = loc[0]
    g_refs, g_kind, g_weight = loc[4], loc[5], loc[8]
    L, M = loc_dom.shape
    D = cnt.shape[1]
    S = g_refs.shape[1]
    per_node = dom_cols is None
    out = None
    for s in range(S):
        l = g_refs[gid_rows, s]                                        # [C]
        kind = g_kind[gid_rows, s]
        w = g_weight[gid_rows, s]
        lc = jnp.clip(l, 0, L - 1)
        self_add = jnp.take_along_axis(contrib_rows, lc[:, None], axis=1)[:, 0]
        self_add = self_add.astype(jnp.int32)
        if per_node:
            dom_row = loc_dom[lc]                                      # [C, M]
        else:
            dom_row = loc_dom[lc, dom_cols]                            # [C]
        cnt_row = cnt[lc]                                              # [C, D]
        dcl = jnp.clip(dom_row, 0, D - 1)
        if per_node:
            cnt_at = jnp.take_along_axis(cnt_row, dcl, axis=1)         # [C, M]
            expand = lambda x: x[:, None]
        else:
            cnt_at = jnp.take_along_axis(cnt_row, dcl[:, None], axis=1)[:, 0]
            expand = lambda x: x
        has_dom = dom_row >= 0
        spread_pen = jnp.maximum(
            cnt_at + expand(self_add) - expand(minc[lc]), 0).astype(jnp.float32)
        val = jnp.where(expand(kind) == KIND_SOFT_SPREAD, spread_pen,
                        cnt_at.astype(jnp.float32))
        adj = jnp.where(has_dom & expand(l >= 0), expand(w) * val, 0.0)
        out = adj if out is None else out + adj
    return out


def _best_nodes_chunked(req, group_id, group_feas, group_soft, free, capacity,
                        base_scores, chunk: int, policy: str,
                        score_cols: int = 0, node_dom=None, pref_pod=None,
                        learned_emb=None):
    """For every pod: (best node, any feasible?) without materializing [N, M].

    Locality rules/scores arrive pre-folded into group_feas/group_soft (the
    per-round [G, M] hoist in `solve`), so this stage is pure gather + fit.
    node_dom/pref_pod (topology steering): per-pod preferred-ICI-domain
    bonus — a gang pod whose contiguous proposal failed still prefers its
    planned domain in the argmax fallback.
    learned_emb (solver.policy=learned): (pod_emb [N, E], node_emb [M, E])
    two-tower embeddings — the learned score augments the score matrix as a
    per-chunk [C, E] x [E, M] matmul. An untrained checkpoint embeds every
    pod to the zero vector (policy/net.init_params), so the augmentation is
    exactly 0 and the argmax is bit-identical to the greedy program.
    """
    N, R = req.shape
    M = free.shape[0]
    n_chunks = N // chunk

    def one_chunk(c):
        start = c * chunk
        creq = lax.dynamic_slice(req, (start, 0), (chunk, R))          # [C, R]
        cgid = lax.dynamic_slice(group_id, (start,), (chunk,))         # [C]
        cfeas = group_feas[cgid]                                       # [C, M]
        # fit margin: min_r (free - req); static unroll over R
        margin = jnp.full((chunk, M), jnp.int32(2**30))
        for r in range(R):
            margin = jnp.minimum(margin, free[:, r][None, :] - creq[:, r][:, None])
        ok = cfeas & (margin >= 0)
        scores = jnp.broadcast_to(base_scores[None, :], (chunk, M)) + group_soft[cgid]
        if policy == "align":
            s = score_cols if score_cols > 0 else R
            scores = scores + alignment_scores(
                creq[:, :s], free[:, :s], capacity[:, :s])
        if node_dom is not None and pref_pod is not None:
            cpref = lax.dynamic_slice(pref_pod, (start,), (chunk,))    # [C]
            in_pref = ((cpref[:, None] >= 0) & (node_dom[None, :] >= 0)
                       & (node_dom[None, :] == cpref[:, None]))
            scores = scores + jnp.where(in_pref, TOPO_GANG_W, 0.0)
        if learned_emb is not None:
            pod_emb, node_emb = learned_emb
            cemb = lax.dynamic_slice(
                pod_emb, (start, 0), (chunk, pod_emb.shape[1]))        # [C, E]
            scores = scores + cemb @ node_emb.T
        scores = jnp.where(ok, scores, NEG_INF)
        best = jnp.argmax(scores, axis=1).astype(jnp.int32)            # [C]
        feasible = jnp.any(ok, axis=1)                                 # [C]
        return best, feasible

    best, feasible = lax.map(one_chunk, jnp.arange(n_chunks))
    return best.reshape(N), feasible.reshape(N)


def _water_fill_proposals(req, group_id, rank, active, group_feas, free,
                          base_scores, group_soft, g_rr_dom=None,
                          g_capped=None):
    """Capacity-aware proposals: the batched analog of "fill nodes in score order".

    Plain per-pod argmax herds every pod in a constraint group onto the same
    best node, so each round fills only one node per group (observed on TPU:
    16 rounds × 110 pods/node). Instead, for each group: order its feasible
    nodes by score, cumsum their free capacity, cumsum the rank-ordered demand
    of the group's pods, and propose pod i to the node whose cumulative
    capacity first covers pod i's cumulative demand. For homogeneous pods this
    reproduces exact sequential bin-packing in ONE round.

    Groups under a per-domain locality cap (hard spread / anti-affinity —
    g_capped, with g_rr_dom [G, M] giving each node's domain for the group's
    tightest capped slot) take ROUND-ROBIN proposals instead: the group's
    k-th pod goes to the k-th node of an ordering that rotates across domains
    (best node of each domain, then second-best of each, ...). Capacity fill
    would pile a whole group onto one node → one domain → the accept cap
    trims it to ~1 pod/round; rotation lets a balanced spread land in one
    round (paired with the level-fill accept cap).

    Returns proposals [N] int32 (node row, or M when the group's total
    capacity is exhausted before this pod's position).
    """
    N, R = req.shape
    M = free.shape[0]
    G = group_feas.shape[0]

    # rank order of pods (global; group-wise prefix sums are masked cumsums)
    pod_order = jnp.argsort(rank)
    sreq = req[pod_order]                                      # [N, R] int32
    sgid = group_id[pod_order]
    sactive = active[pod_order]
    idx_m = jnp.arange(M, dtype=jnp.int32)

    def per_group(g):
        feas = group_feas[g]                                   # [M]
        score = jnp.where(feas, base_scores + group_soft[g], NEG_INF)
        node_order = jnp.argsort(-score)                       # feasible first
        # SATURATING int32 scans: exact below the cap, monotone always, and
        # integer-assoc — bit-identical under any GSPMD sharding (an f32
        # cumsum loses integrality past 2^24; a plain int32 cumsum WRAPS at
        # cluster scale: 10k nodes x 256GiB in MiB units = 2.6e9 > 2^31,
        # breaking searchsorted's monotonicity precondition). Saturating add
        # min(a+b, CAP) is associative for non-negatives; positions past the
        # saturation point degrade to a conservative proposal that prop_fits
        # re-checks, so correctness never depends on the cap.
        CAP = jnp.int32(2**30 - 1)
        sat_add = lambda a, b: jnp.minimum(a + b, CAP)
        ofree = jnp.minimum(jnp.where(feas[node_order, None],
                                      jnp.maximum(free[node_order], 0), 0), CAP)
        cumF = lax.associative_scan(sat_add, ofree, axis=0)    # [M, R]
        mine = sactive & (sgid == g)
        demand = jnp.minimum(jnp.where(mine[:, None], sreq, 0), CAP)
        C = lax.associative_scan(sat_add, demand, axis=0)      # [N, R] inclusive
        pos = jnp.zeros((N,), jnp.int32)
        for r in range(R):
            # both sides are monotone (free clamped ≥0); side="left" finds the
            # first node whose cumulative capacity covers this pod's demand;
            # sort-based rank beats binary-search gathers on TPU by ~4x
            pos = jnp.maximum(
                pos,
                jnp.searchsorted(cumF[:, r], C[:, r], side="left",
                                 method="sort").astype(jnp.int32),
            )
        ok = pos < M
        node = jnp.where(ok & mine, node_order[jnp.clip(pos, 0, M - 1)], M)
        wf_prop = jnp.where(mine, node, M).astype(jnp.int32)
        if g_rr_dom is None:
            return wf_prop
        # ---- round-robin proposals for locality-capped groups ----
        dom_s = g_rr_dom[g][node_order]                        # [M] in score order
        ord2 = jnp.argsort(dom_s, stable=True)                 # domains together
        k2 = dom_s[ord2]
        seg_start = jnp.concatenate([jnp.array([True]), k2[1:] != k2[:-1]])
        head = lax.cummax(jnp.where(seg_start, idx_m, 0))
        within = idx_m - head                                  # rank inside domain
        wr = jnp.zeros((M,), jnp.int32).at[ord2].set(within)
        # rotate across domains tier by tier (wr primary), but inside a tier
        # keep SCORE order (idx_m = position in score order): the best node
        # of the best-scoring domain leads, so soft preferences still steer
        wr_eff = jnp.where(feas[node_order], wr, jnp.int32(2**30))
        rr_order = node_order[jnp.lexsort((idx_m, wr_eff))]
        n_feas = jnp.sum(feas.astype(jnp.int32))
        kk = jnp.cumsum(mine.astype(jnp.int32)) - 1            # within-group rank
        rr_node = rr_order[jnp.clip(kk % jnp.maximum(n_feas, 1), 0, M - 1)]
        rr_prop = jnp.where(mine & (n_feas > 0), rr_node, M).astype(jnp.int32)
        return jnp.where(g_capped[g], rr_prop, wf_prop)

    per_group_nodes = jax.vmap(per_group)(jnp.arange(G))       # [G, N] in sorted pod order
    chosen_sorted = jnp.min(per_group_nodes, axis=0)           # each pod active in ≤1 group
    # min works because non-members hold M; a pod's own group value is ≤ M
    proposals = jnp.full((N,), M, jnp.int32).at[pod_order].set(chosen_sorted)
    return proposals


def _loc_capped_flags(loc):
    """Per locality group: which slot kinds reference it, and the tightest
    spread skew across referencing slots. Computed once per solve.

    Returns (spread_l, aff_l, soft_spread_l, anti_l, min_skew_l)."""
    from yunikorn_tpu.snapshot.locality import (
        KIND_AFFINITY,
        KIND_ANTI_AFFINITY,
        KIND_SOFT_SPREAD,
        KIND_SPREAD,
    )

    loc_dom = loc[0]
    g_refs, g_kind, g_skew = loc[4], loc[5], loc[6]
    L = loc_dom.shape[0]
    big = jnp.int32(2**30)
    spread = []
    aff = []
    soft_spread = []
    anti = []
    min_skew = []
    for l in range(L):
        ref_l = g_refs == l
        is_spread = ref_l & (g_kind == KIND_SPREAD)
        spread.append(jnp.any(is_spread))
        anti.append(jnp.any(ref_l & (g_kind == KIND_ANTI_AFFINITY)))
        aff.append(jnp.any(ref_l & (g_kind == KIND_AFFINITY)))
        soft_spread.append(jnp.any(ref_l & (g_kind == KIND_SOFT_SPREAD)))
        min_skew.append(jnp.min(jnp.where(is_spread, g_skew, big)))
    return (jnp.stack(spread), jnp.stack(aff), jnp.stack(soft_spread),
            jnp.stack(anti), jnp.stack(min_skew))


def _loc_accept_cap(accept_sorted, snode, scontrib, sgid, loc, M, cnt, total,
                    spread_l, aff_l, anti_l, min_skew_l, allowance_l,
                    g_ref_masks, pair_l, g_capped):
    """Cap same-round accepts so every round has a legal sequentialization.

    Each cap binds only pods whose GROUP references the locality group with a
    slot of the cap's kind (g_ref_masks): only those make count-dependent
    decisions of that kind this round. Contributing pods without such a slot
    (plain pods matching someone's selector, affinity pods sharing a spread
    group's locality tuple) sequentialize after the constrained pods of the
    round, so capping them could only starve them. Per-kind caps:

    - anti-affinity: 1 referencing pod per domain (a second one would see
      cnt>0 only next round — exact), plus the holder↔matcher mutual
      exclusion below.
    - affinity while *seeding* (total==0): 1 seed-slot pod per locality
      group per round (one domain seeds) so a self-affinitized cohort cannot
      split across domains.
    - hard spread: LEVEL FILL — from the tentative per-domain counts t_d of
      spread-referencing accepts, compute the fixed point with the TIGHTEST
      skew among referencing slots
          level = skew + min_valid_d(cnt_d + a_d),  a_d = min(t_d, level - cnt_d)
      then bound each ROW by its own slot's skew around the projected
      post-fill minimum: within_d(r) <= skew_r + min_valid(cnt + a) - cnt_d.
      For uniform skews this equals the plain level fill (one-round balanced
      fill: 18 pods / 3 zones / skew 1 lands in one round, not six — round-3
      throughput fix); heterogeneous skews get their own headroom, and every
      joint accept is legal in ascending-count order because the projected
      minimum only grows as the round's accepts land.
    - ScheduleAnyway spread: `allowance_l` (≈ remaining/domains) as before.
    """
    loc_dom, dom_valid = loc[0], loc[2]
    L, _ = loc_dom.shape
    D = cnt.shape[1]
    N = accept_sorted.shape[0]
    big = jnp.int32(2**30)
    idx = jnp.arange(N, dtype=jnp.int32)
    node_cl = jnp.clip(snode, 0, M - 1)
    g_ref_spread, g_ref_anti, g_ref_seed, g_ref_soft, g_skew_l = g_ref_masks

    def seg_keep(active, key, limit_row, counted=None):
        """Keep mask: within each key segment, each ACTIVE row must have its
        inclusive prefix count of COUNTED rows within limit_row (prefix rule
        in the caller's rank-sorted order). `counted` defaults to `active`;
        a wider counted set charges rows the cap does not remove (same-round
        contributors that are hard-constrained elsewhere and therefore
        cannot be sequenced after the capped rows) against the budget."""
        if counted is None:
            counted = active
        relevant = active | counted
        order2 = jnp.argsort(jnp.where(relevant, key, (M + 2) + idx))  # stable
        k2 = jnp.where(relevant, key, (M + 2) + idx)[order2]
        act2 = active[order2]
        cnt2 = counted[order2]
        seg_start = jnp.concatenate([jnp.array([True]), k2[1:] != k2[:-1]])
        c = jnp.cumsum(cnt2.astype(jnp.int32))
        head = lax.cummax(jnp.where(seg_start, idx, 0))
        base = jnp.where(head > 0, c[jnp.maximum(head - 1, 0)], 0)
        within = c - base                                              # inclusive
        keep2 = (~act2) | (within <= limit_row[order2])
        return jnp.zeros((N,), bool).at[order2].set(keep2)

    # Removal passes run in a deliberate order, all BEFORE the spread level
    # fill: the fill's tentative counts must only include accepts that
    # survive, or a domain's projected minimum could rest on rows a later
    # pass removes (a spread+anti-holder pod blocked by the pair exclusion
    # would otherwise still prop up the level other domains were filled
    # against). Within the removals, the per-domain anti CAP precedes the
    # holder↔matcher pair EXCLUSION: the cap trims same-domain matchers to
    # one, so a self-matching holder left alone in a domain survives the
    # exclusion (others_p == 0). Exclusion-first would let two self-anti
    # pods contesting one feasible node block EACH OTHER every round — a
    # livelock the fuzzer hit (both pods feasible only on one green-free
    # node, neither ever placed).
    for l in range(L):
        dom_i = loc_dom[l, node_cl]                                    # [N]
        on_dom = (dom_i >= 0) & (snode < M)

        # anti-affinity: 1 per domain per round, capping referencing pods.
        # The budget also COUNTS same-round contributors that carry a hard
        # constraint of their own (g_capped): such a pod may be pinned early
        # in any sequentialization by its own rule, so an anti pod accepted
        # after it in the same domain could be legal in NO order (fuzz
        # finding: a zone-spread blue and a host-anti pod jointly accepted
        # onto one node, each individually legal vs round-start counts).
        counted_anti = (accept_sorted & scontrib[:, l] & on_dom
                        & (g_ref_anti[sgid, l] | g_capped[sgid]))
        an_active = (anti_l[l] & accept_sorted & scontrib[:, l]
                     & g_ref_anti[sgid, l] & on_dom)
        accept_sorted = accept_sorted & seg_keep(
            an_active, dom_i, jnp.ones((N,), jnp.int32),
            counted=counted_anti)

    # holder↔matcher mutual exclusion: for a holder group l (contrib = pods
    # HOLDING anti term t) paired with primary group p (contrib = pods
    # MATCHING t's selector), a holder may not be accepted into a domain
    # where a matcher is accepted this same round (other than itself): the
    # holder's own anti rule vs the matcher and the matcher's symmetry rule
    # vs the holder each kill one of the two sequential orders. Blocked
    # holders retry next round, where the updated counts separate them.
    for l in range(L):
        lp = pair_l[l]
        has_pair = lp >= 0
        lp_cl = jnp.clip(lp, 0, L - 1)
        contrib_p = jnp.take(scontrib, lp_cl, axis=1)                  # [N]
        dom_i = loc_dom[l, node_cl]
        dom_cl = jnp.clip(dom_i, 0, D - 1)
        on_node = (dom_i >= 0) & (snode < M) & accept_sorted
        acc_p = on_node & contrib_p
        t_p = jnp.zeros((D,), jnp.int32).at[dom_cl].add(acc_p.astype(jnp.int32))
        others_p = t_p[dom_cl] - acc_p.astype(jnp.int32)
        blocked = (has_pair & on_node & scontrib[:, l] & (others_p > 0))
        accept_sorted = accept_sorted & ~blocked

    for l in range(L):
        dom_i = loc_dom[l, node_cl]                                    # [N]
        on_dom = (dom_i >= 0) & (snode < M)

        # affinity seeding: 1 seed-slot pod per locality group per round —
        # AFTER the pair exclusion (so the single seed slot is never awarded
        # to a pod the exclusion then removes) and, like every removal, in
        # its own full pass BEFORE the spread fill loop below (the fill's
        # projected minimum must only rest on surviving accepts)
        seeding = aff_l[l] & (total[l] == 0)
        se_active = (seeding & accept_sorted & scontrib[:, l]
                     & g_ref_seed[sgid, l] & on_dom)
        accept_sorted = accept_sorted & seg_keep(
            se_active, jnp.zeros((N,), jnp.int32), jnp.ones((N,), jnp.int32))

    for l in range(L):
        dom_i = loc_dom[l, node_cl]                                    # [N]
        dom_cl = jnp.clip(dom_i, 0, D - 1)
        on_dom = (dom_i >= 0) & (snode < M)

        # hard spread: level fill over the spread-referencing accepts that
        # survived the removal passes above. As with the anti cap, the
        # budget COUNTS same-round contributors that are hard-constrained
        # anywhere (they may be pinned early in any legal order); plain
        # contributors still sequentialize last and stay uncounted.
        sp_active = (spread_l[l] & accept_sorted & scontrib[:, l]
                     & g_ref_spread[sgid, l] & on_dom)
        counted_sp = (spread_l[l] & accept_sorted & scontrib[:, l] & on_dom
                      & (g_ref_spread[sgid, l] | g_capped[sgid]))
        t = jnp.zeros((D,), jnp.int32).at[dom_cl].add(counted_sp.astype(jnp.int32))
        cl = cnt[l]
        valid = dom_valid[l]
        skew = jnp.where(min_skew_l[l] < big, min_skew_l[l], 0)
        level = skew + jnp.min(jnp.where(valid, cl + t, big))
        for _ in range(8):
            # monotone fixed point; iterations bound the level from above,
            # so early exit is safe-by-construction
            a_sp = jnp.minimum(t, jnp.maximum(level - cl, 0))
            level = skew + jnp.min(jnp.where(valid, cl + a_sp, big))
        a_spread = jnp.minimum(t, jnp.maximum(level - cl, 0))          # [D]
        minc_proj = jnp.min(jnp.where(valid, cl + a_spread, big))
        # per-row bound: own skew around the projected post-fill minimum
        # (== a_spread for rows at the tightest skew; extra headroom for
        # larger-skew rows sequentialized after the level fill)
        skew_row = jnp.minimum(g_skew_l[sgid, l], big - 1)
        limit_row = jnp.maximum(
            skew_row + minc_proj - cl[dom_cl],
            jnp.minimum(a_spread[dom_cl], jnp.int32(2**30 - 1)))
        accept_sorted = accept_sorted & seg_keep(sp_active, dom_i, limit_row,
                                                 counted=counted_sp)

        # ScheduleAnyway spread: per-domain allowance for pacing (scoring
        # constraint — balance across domains within a round, then re-score)
        so_active = ((allowance_l[l] < N) & accept_sorted & scontrib[:, l]
                     & g_ref_soft[sgid, l] & on_dom)
        accept_sorted = accept_sorted & seg_keep(
            so_active, dom_i, jnp.full((N,), allowance_l[l], jnp.int32))
    return accept_sorted


def _loc_update_counts(cnt, loc, accepted, best, M):
    """Scatter-add this round's placements into the domain counts."""
    loc_dom, contrib = loc[0], loc[3]
    L = loc_dom.shape[0]
    D = cnt.shape[1]
    node_cl = jnp.clip(best, 0, M - 1)
    for l in range(L):
        dom_i = loc_dom[l, node_cl]                                    # [N]
        add = accepted & contrib[:, l] & (dom_i >= 0) & (best >= 0) & (best < M)
        cnt = cnt.at[l, jnp.clip(dom_i, 0, D - 1)].add(add.astype(jnp.int32))
    return cnt


def _segment_prefix_accept(snode, sreq, free, M):
    """Accept the per-node-segment prefix of sorted requests that fits.

    snode: [N] int32 sorted node ids (M = dummy/no-candidate, sorts last)
    sreq:  [N, R] int32 requests in sorted order
    free:  [M, R] int32 — dummy rows (snode == M) are masked explicitly
           rather than read from an extended [M+1] array: the odd row
           count shards UNEVENLY under GSPMD, and XLA:CPU's partitioner
           was observed to zero local row (M // n_shards) of every shard
           when scattering into the padded dimension (the root cause of
           the two seed-era test_parallel free_after mismatches)
    returns accept_sorted [N] bool
    """
    N = snode.shape[0]
    idx = jnp.arange(N, dtype=jnp.int32)
    seg_start = jnp.concatenate([jnp.array([True]), snode[1:] != snode[:-1]])
    # index of each row's segment head via running max
    head = lax.cummax(jnp.where(seg_start, idx, 0))
    cums = jnp.cumsum(sreq, axis=0, dtype=jnp.int32)                   # wraps ok
    base = jnp.where((head > 0)[:, None], cums[jnp.maximum(head - 1, 0)], 0)
    prefix = cums - base                                               # [N, R]
    real = snode < M
    node_free = jnp.where(real[:, None],
                          free[jnp.clip(snode, 0, M - 1)], 0)          # [N, R]
    fits = jnp.all(prefix <= node_free, axis=1)
    return fits & real


def _hoist_group_state(g_term_req, g_term_forb, g_term_valid, g_anyof,
                       g_anyof_valid, g_tol, g_ports, g_pref_req, g_pref_forb,
                       g_pref_weight, node_labels, node_taints,
                       node_taints_soft, node_ports, node_ok,
                       host_group_mask, host_group_soft):
    """Pod-independent [G, M] feasibility mask + soft score adjustment.

    Shared by the monolithic solve and the chunked scan — in the chained
    path this runs ONCE for the whole batch, not once per chunk (the per-chunk
    recompute was the dominant cost of the round-4 host-side chain)."""
    group_feas = group_feasibility(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, node_labels, node_taints, node_ports, node_ok,
    )
    if host_group_mask is not None:
        group_feas = group_feas & host_group_mask
    # scoring halves: PreferNoSchedule taints penalize, preferred node
    # affinity terms reward — one [G, M] adjustment shared by the round paths
    group_soft = group_soft_penalty(g_tol, node_taints_soft) + group_preferred_bonus(
        g_pref_req, g_pref_forb, g_pref_weight, node_labels)          # [G, M]
    if host_group_soft is not None:
        # preferred terms the tensor encoding can't express exactly
        # (multi-value In, slot overflow) — scored on the host, same scale
        group_soft = group_soft + host_group_soft
    return group_feas, group_soft


def _hoist_loc_state(loc, group_id_full, G):
    """Chunk-invariant locality precomputation: per-group capped flags,
    contribution flags, and round-robin domain rows for the water-fill.

    group_id_full / loc[3] must cover the FULL batch (not one chunk): a
    group's contribution flags are shared by all its pods, so computing them
    from the whole batch is both correct per chunk and hoistable."""
    (spread_l, aff_l, softspread_l, anti_l, min_skew_l) = _loc_capped_flags(loc)
    L = loc[0].shape[0]
    # per-group contribution flags (all pods in a group share them — the
    # signature folds labels in whenever locality applies): locality
    # rules/scores are evaluated once per round per GROUP, [G, L] → [G, M]
    group_contrib = (jnp.zeros((G, L), jnp.int32)
                     .at[group_id_full].max(loc[3].astype(jnp.int32))
                     .astype(bool))
    # per-group round-robin domain rows for the water-fill: the first
    # hard-spread/anti slot's locality group defines the domain partition
    # its proposals rotate across; -1 row = plain capacity fill
    from yunikorn_tpu.snapshot.locality import (
        KIND_ANTI_AFFINITY as _K_ANTI,
        KIND_SPREAD as _K_SPREAD,
    )

    g_refs_t, g_kind_t = loc[4], loc[5]
    S = g_refs_t.shape[1]
    l_ref = jnp.full((G,), -1, jnp.int32)
    for s in range(S - 1, -1, -1):  # first capped slot wins
        is_capped_slot = (((g_kind_t[:, s] == _K_SPREAD) |
                           (g_kind_t[:, s] == _K_ANTI)) &
                          (g_refs_t[:, s] >= 0))
        l_ref = jnp.where(is_capped_slot, g_refs_t[:, s], l_ref)
    g_capped = l_ref >= 0
    g_rr_dom = jnp.where(g_capped[:, None],
                         loc[0][jnp.clip(l_ref, 0, L - 1)], -1)
    # Per-kind [G, L] applicability masks for the accept caps: a cap binds
    # only pods whose group references l with a slot of THAT kind. A pod that
    # merely contributes (a plain pod matching someone's selector) or that
    # references l through a different kind (an affinity pod sharing the
    # spread group's locality tuple) makes no count-dependent decision of
    # that kind — its same-round placements sequentialize after the
    # constrained pods — so capping it could only starve it (fuzz findings:
    # plain contributors starved at a saturated spread level; an affinity
    # pod starved by the spread level of a group it never spread-references).
    from yunikorn_tpu.snapshot.locality import (
        KIND_AFFINITY as _K_AFF,
        KIND_SOFT_SPREAD as _K_SOFT_SPREAD,
    )

    g_ref_spread = jnp.zeros((G, L), bool)
    g_ref_anti = jnp.zeros((G, L), bool)
    g_ref_seed = jnp.zeros((G, L), bool)
    g_ref_soft = jnp.zeros((G, L), bool)
    # per-(group, locality group) spread skew: groups sharing a locality
    # tuple may carry DIFFERENT maxSkew values; the accept cap must bound
    # each row by ITS OWN skew, not the tightest one (fuzz finding: a skew-2
    # pod starved by a skew-1 group's level)
    big = jnp.int32(2**30)
    g_skew_l = jnp.full((G, L), big)
    g_seed_t = loc[7]
    g_skew_t = loc[6]
    gidx = jnp.arange(G)
    for s in range(S):
        l_s = jnp.clip(g_refs_t[:, s], 0, L - 1)
        k_s = g_kind_t[:, s]
        has = g_refs_t[:, s] >= 0
        is_sp = has & (k_s == _K_SPREAD)
        g_ref_spread = g_ref_spread.at[gidx, l_s].max(is_sp)
        g_ref_anti = g_ref_anti.at[gidx, l_s].max(has & (k_s == _K_ANTI))
        g_ref_seed = g_ref_seed.at[gidx, l_s].max(
            has & (k_s == _K_AFF) & g_seed_t[:, s])
        g_ref_soft = g_ref_soft.at[gidx, l_s].max(has & (k_s == _K_SOFT_SPREAD))
        g_skew_l = g_skew_l.at[gidx, l_s].min(jnp.where(is_sp, g_skew_t[:, s], big))
    return (spread_l, aff_l, softspread_l, anti_l, min_skew_l,
            group_contrib, g_capped, g_rr_dom,
            (g_ref_spread, g_ref_anti, g_ref_seed, g_ref_soft, g_skew_l))


def _seg_sat_scan(vals, seg_start):
    """Segmented SATURATING inclusive scan along axis 0.

    vals [L, R] int32 (non-negative, pre-clipped to CAP); seg_start [L]
    bool marks segment heads. The operator ((f1,s1),(f2,s2)) -> (f1|f2,
    where(f2, s2, min(s1+s2, CAP))) is associative for non-negative values
    (the saturating-add argument from _water_fill_proposals, lifted to
    segments), so the scan is exact below the cap and conservatively large
    at it — positions degraded by saturation overflow to the fallback
    proposal, never to a wrong accept."""
    CAP = jnp.int32(2**30 - 1)

    def op(a, b):
        fa, sa = a
        fb, sb = b
        return fa | fb, jnp.where(fb, sb, jnp.minimum(sa + sb, CAP))

    flags = seg_start[:, None]
    _, out = lax.associative_scan(op, (jnp.broadcast_to(flags, vals.shape),
                                       vals), axis=0)
    return out


def _topo_gang_proposals(pref_pod, rank, active, req, free, node_dom,
                         base_scores):
    """ICI-contiguous gang proposals: the segmented per-domain water-fill.

    Every steered pod (pref_pod >= 0, its gang's planned target domain from
    topology/score.plan_gang_domains) is proposed into its domain by the
    same capacity-coverage rule the per-group water-fill uses — nodes of
    the domain ordered best-score-first, cumulative free capacity vs the
    rank-ordered cumulative demand of the domain's steered pods — but
    computed for ALL domains at once with ONE merged sort per resource
    column: O((M+N) log(M+N) · R) per round, independent of how many gangs
    the batch carries — topology steering adds a near-constant round cost
    instead of multiplying the per-group water-fill's vmap.

    Returns proposals [N] int32 (node row, or M when the pod is unsteered,
    its domain's capacity is exhausted at its position, or saturation made
    the position conservative — all of which fall back to the base
    proposal / argmax and from there to ordinary spill behavior).
    """
    N, R = req.shape
    M = free.shape[0]
    CAP = jnp.int32(2**30 - 1)
    BIG = jnp.int32(2**30)
    idx_m = jnp.arange(M, dtype=jnp.int32)

    # domain-major node order, best score first inside a domain; unlabeled
    # nodes form a trailing segment no pod key can reach (BIG vs BIG+1)
    dkey_n = jnp.where(node_dom >= 0, node_dom, BIG)
    order_n = jnp.lexsort((idx_m, -base_scores, dkey_n))
    nd_s = dkey_n[order_n]                                         # [M]
    nfree = jnp.minimum(jnp.maximum(free[order_n], 0), CAP)
    seg_n = jnp.concatenate([jnp.array([True]), nd_s[1:] != nd_s[:-1]])
    cumF = _seg_sat_scan(nfree, seg_n)                             # [M, R]

    mine = active & (pref_pod >= 0)
    dkey_p = jnp.where(mine, pref_pod, BIG + 1)
    order_p = jnp.lexsort((rank, dkey_p))
    pd_s = dkey_p[order_p]                                         # [N]
    dem = jnp.minimum(jnp.where(mine[order_p, None], req[order_p], 0), CAP)
    seg_p = jnp.concatenate([jnp.array([True]), pd_s[1:] != pd_s[:-1]])
    cumD = _seg_sat_scan(dem, seg_p)                               # [N, R]

    # per-domain searchsorted via one merged sort per column: pods sort
    # BEFORE nodes on equal values (side="left" semantics), and a pod's
    # in-segment count of preceding nodes is exactly the first node
    # position whose cumulative capacity covers its cumulative demand
    L = M + N
    keys_dom = jnp.concatenate([nd_s, pd_s])
    keys_tag = jnp.concatenate([jnp.ones((M,), jnp.int32),
                                jnp.zeros((N,), jnp.int32)])
    idx_l = jnp.arange(L, dtype=jnp.int32)
    pos = jnp.zeros((N,), jnp.int32)
    for r in range(R):
        keys_val = jnp.concatenate([cumF[:, r], cumD[:, r]])
        o = jnp.lexsort((keys_tag, keys_val, keys_dom))
        isnode = keys_tag[o]
        c = jnp.cumsum(isnode)
        seg = jnp.concatenate([jnp.array([True]),
                               keys_dom[o][1:] != keys_dom[o][:-1]])
        head = lax.cummax(jnp.where(seg, idx_l, 0))
        base = jnp.where(head > 0, c[jnp.maximum(head - 1, 0)], 0)
        pos_elem = (c - base) - isnode          # nodes strictly before
        pos_all = jnp.zeros((L,), jnp.int32).at[o].set(pos_elem)
        pos = jnp.maximum(pos, pos_all[M:])                        # [N]

    dom_lo = jnp.searchsorted(nd_s, pd_s, side="left",
                              method="sort").astype(jnp.int32)
    dom_hi = jnp.searchsorted(nd_s, pd_s, side="right",
                              method="sort").astype(jnp.int32)
    ok = mine[order_p] & (pos < dom_hi - dom_lo)
    node_s = jnp.where(ok, order_n[jnp.clip(dom_lo + pos, 0, M - 1)], M)
    return jnp.full((N,), M, jnp.int32).at[order_p].set(
        node_s.astype(jnp.int32))


def _topo_node_adj(topo):
    """The node-level topology score term (the BandPilot contention
    penalty): co-tenant busy fraction of the node's ICI domain, plus a
    domain-empty bonus. Group-independent, so callers fold the returned
    [M] adjustment into every group_soft row — the whole steered-solve
    cost stays independent of how many gangs the batch carries (the
    per-gang preferred-domain term is per-POD: _topo_gang_proposals for
    the proposal stage, the pref gather in _best_nodes_chunked for the
    argmax fallback).

    topo = (node_dom [M] i32 node → ICI-domain id (-1 unlabeled),
            pref_pod [N] i32 planned target domain per ask (-1 none),
            dom_busy [D] i32 co-tenant busy units per domain,
            dom_cap [D] i32 capacity units per domain)
    """
    node_dom, _pref_pod, dom_busy, dom_cap = topo
    D = dom_busy.shape[0]
    dcl = jnp.clip(node_dom, 0, D - 1)
    has_dom = node_dom >= 0
    busy = dom_busy[dcl].astype(jnp.float32)
    frac = busy / jnp.maximum(dom_cap[dcl].astype(jnp.float32), 1.0)
    return jnp.where(
        has_dom,
        TOPO_EMPTY_W * (busy == 0).astype(jnp.float32)
        - TOPO_CONTENTION_W * frac,
        0.0)                                                       # [M]


def _learned_chunk_pass(pod_emb, node_emb, group_id, group_feas, group_soft,
                        free, capacity, base_scores, req, active, tau, key,
                        chunk: int, policy: str, score_cols: int = 0,
                        node_dom=None, pref_pod=None, argmax: bool = False):
    """Fused per-chunk pass for solver.policy=learned (follow-up (e) done).

    One lax.map computes the fit-margin mask ONCE per chunk and derives both
    consumers from it:

    1. Gated learned proposal override. For each active pod, the two-tower
       score picks a candidate node among the pod's feasible-and-fitting
       nodes, with seeded Gumbel exploration (tau-scaled — identical-featured
       nodes score identically, and a plain argmax would herd every pod onto
       the lowest row index, the same failure _water_fill_proposals
       documents). The override only fires when the CHOSEN node's raw
       learned score beats the pod's feasible-mean by GATE_MARGIN — a
       shift-invariant confidence gate, so an untrained or garbage-zero
       checkpoint (score identically 0) can NEVER override a proposal and
       the learned program stays bit-identical to greedy.
    2. When `argmax` (odd rounds): the exact per-pod argmax that
       _best_nodes_chunked computes, with the learned [C, E] x [E, M] score
       augmentation reusing the SAME ls matmul — previously both the margin
       and the matmul ran twice (two lax.map bodies; XLA CSE across them is
       not guaranteed).

    Returns (props [N] int32 with M = no override, best [N] int32,
    feasible [N] bool); best/feasible are zeros when argmax=False so the two
    variants stay pytree-compatible as lax.cond branches. Fit is re-checked
    by the round loop's prop_fits exactly like every other proposal source.
    """
    from yunikorn_tpu.policy.net import GATE_MARGIN

    N, R = req.shape
    M = free.shape[0]
    E = pod_emb.shape[1]
    n_chunks = N // chunk

    def one_chunk(c):
        start = c * chunk
        cemb = lax.dynamic_slice(pod_emb, (start, 0), (chunk, E))
        creq = lax.dynamic_slice(req, (start, 0), (chunk, R))
        cgid = lax.dynamic_slice(group_id, (start,), (chunk,))
        cfeas = group_feas[cgid]                                   # [C, M]
        margin = jnp.full((chunk, M), jnp.int32(2**30))
        for r in range(R):
            margin = jnp.minimum(margin,
                                 free[:, r][None, :] - creq[:, r][:, None])
        ok = cfeas & (margin >= 0)
        ls = cemb @ node_emb.T                                     # [C, M]
        nf = jnp.sum(ok.astype(jnp.int32), axis=1)
        lmean = (jnp.sum(jnp.where(ok, ls, 0.0), axis=1)
                 / jnp.maximum(nf.astype(jnp.float32), 1.0))
        g = jax.random.gumbel(jax.random.fold_in(key, c), (chunk, M))
        u = jnp.where(ok, ls + tau * g, NEG_INF)
        pick = jnp.argmax(u, axis=1).astype(jnp.int32)
        ls_best = jnp.take_along_axis(ls, pick[:, None], axis=1)[:, 0]
        good = (nf > 0) & (ls_best - lmean > GATE_MARGIN)
        prop = jnp.where(good, pick, M)
        if not argmax:
            z = jnp.zeros((chunk,), jnp.int32)
            return prop, z, z.astype(bool)
        scores = (jnp.broadcast_to(base_scores[None, :], (chunk, M))
                  + group_soft[cgid])
        if policy == "align":
            s = score_cols if score_cols > 0 else R
            scores = scores + alignment_scores(
                creq[:, :s], free[:, :s], capacity[:, :s])
        if node_dom is not None and pref_pod is not None:
            cpref = lax.dynamic_slice(pref_pod, (start,), (chunk,))
            in_pref = ((cpref[:, None] >= 0) & (node_dom[None, :] >= 0)
                       & (node_dom[None, :] == cpref[:, None]))
            scores = scores + jnp.where(in_pref, TOPO_GANG_W, 0.0)
        scores = jnp.where(ok, scores + ls, NEG_INF)
        best = jnp.argmax(scores, axis=1).astype(jnp.int32)
        feasible = jnp.any(ok, axis=1)
        return prop, best, feasible

    props, best, feasible = lax.map(one_chunk, jnp.arange(n_chunks))
    return (jnp.where(active, props.reshape(N), M),
            best.reshape(N), feasible.reshape(N))


def _learned_prep(learned, req, rank, capacity, score_cols: int, salt=None):
    """Hoisted pod-side state of the learned scorer for one pod slice:
    (params, pod embeddings, PRNG key, capacity inv_scale). rank is unused
    by the v1 feature schema but rides the signature so a future version
    can fold ordering in without touching call sites. salt: extra fold for
    the exploration key — the chunked scan passes its slice index so two
    pod slices never share Gumbel noise (same-row pods across slices would
    otherwise herd onto identical nodes)."""
    from yunikorn_tpu.policy import features as _pf
    from yunikorn_tpu.policy import net as _pnet

    params, seed = learned
    R = req.shape[1]
    sc = score_cols if score_cols > 0 else R
    inv_sc = _pf.inv_capacity_scale(capacity[:, :sc])
    pod_f = _pf.pod_features(req[:, :sc], inv_sc)
    pod_emb = _pnet.pod_tower(params, pod_f)
    key = jax.random.PRNGKey(seed)
    if salt is not None:
        key = jax.random.fold_in(key, salt)
    return (params, pod_emb, key, inv_sc)


def _solve_rounds(req, group_id, rank, valid, group_feas, group_soft,
                  free0, cnt0, capacity, loc, loc_hoist, *,
                  max_rounds, chunk, policy, use_pallas, pallas_interpret,
                  has_loc_soft, pallas_soft, score_cols, topo_rt=None,
                  learned_rt=None):
    """The assignment round loop for one pod slice against hoisted group
    state. free0 [M, R] and cnt0 [L, D] carry across chained chunks; the
    return keeps their shapes so a lax.scan can thread them. The free
    carry is exactly [M, R] — no extended dummy row: an [M+1] row count
    shards unevenly under GSPMD and XLA:CPU's partitioner miscompiled the
    dummy-row scatter (see _segment_prefix_accept).

    topo_rt (topology steering, solver.topology): (node_dom [M], pref_pod
    [N]) — the node-level contention term is already folded into
    group_soft by the caller; this adds the per-pod gang-domain steering:
    the ICI-contiguous proposals from the segmented per-domain fill
    (_topo_gang_proposals) override the group water-fill proposal wherever
    they name a feasible node, and the argmax fallback carries the same
    preferred-domain bonus per pod. Nothing here scales with gang count —
    the bit-identical-off contract holds because topo_rt=None recovers the
    exact pre-topology round body.

    learned_rt (solver.policy=learned, from _learned_prep): (params,
    pod_emb [N, E], PRNG key, inv_scale) — the node tower re-embeds the
    CURRENT free capacity each round (tiny [M, F] x [F, H] matmuls, the
    same per-round refresh the base score gets), the gated learned
    proposals override the water-fill where the scorer is confident
    (strictly positive advantage — see _learned_chunk_pass), and the argmax
    stage's score matrix is augmented with the same bilinear term.
    learned_rt=None (and equally a zero/untrained checkpoint) recovers the
    exact greedy round body — the untrained-is-inert contract."""
    N, R = req.shape
    M = free0.shape[0]
    has_loc = loc is not None
    if has_loc:
        (loc_spread_l, loc_aff_l, loc_softspread_l, loc_anti_l,
         loc_min_skew_l, group_contrib, g_capped, g_rr_dom,
         g_ref_masks) = loc_hoist
    else:
        group_contrib = None
        g_capped = None
        g_rr_dom = None
    init = (
        free0,
        ~valid,                                     # "done" = assigned or invalid
        jnp.full((N,), -1, jnp.int32),              # assignment
        jnp.full((N,), -1, jnp.int32),              # accept round per pod
        jnp.int32(0),                               # round counter
        jnp.int32(0),                               # consecutive no-progress rounds
        cnt0,                                       # locality domain counts
    )

    def cond(state):
        _, done, _, _, rnd, stalls, _ = state
        # water-fill and argmax rounds alternate; only give up after both stall
        return (stalls < 2) & (rnd < max_rounds) & ~jnp.all(done)

    sc = score_cols if score_cols > 0 else R

    def body(state):
        cur_free, done, assigned, around, rnd, stalls, cnt = state
        base_scores = node_base_scores(cur_free[:, :sc], capacity[:, :sc],
                                       policy)
        active = ~done
        if has_loc:
            minc, total = _loc_round_stats(loc, cnt)
            # hoist: locality rules/scores per GROUP for this round — one
            # [G, M] mask/adjustment shared by every downstream stage
            gidx = jnp.arange(group_feas.shape[0], dtype=jnp.int32)
            loc_mask_g = _loc_rules_mask(gidx, None, loc, cnt, minc, total,
                                         group_contrib)               # [G, M]
            feas_round = group_feas & loc_mask_g
            soft_round = (group_soft + _loc_soft_scores(gidx, None, loc, cnt,
                                                        minc, group_contrib)
                          if has_loc_soft else group_soft)
        else:
            loc_mask_g = None
            feas_round, soft_round = group_feas, group_soft

        proposals = _water_fill_proposals(req, group_id, rank, active,
                                          feas_round, cur_free, base_scores,
                                          soft_round, g_rr_dom, g_capped)
        learned_best = None
        if learned_rt is not None:
            from yunikorn_tpu.policy import features as _pf
            from yunikorn_tpu.policy import net as _pnet

            l_params, pod_emb, l_key, inv_sc = learned_rt
            node_emb = _pnet.node_tower(
                l_params, _pf.node_features(cur_free[:, :sc],
                                            capacity[:, :sc], inv_sc))
            # one fused chunk pass: the fit margin and the [C, E] x [E, M]
            # matmul are shared between the gated proposal and the odd-round
            # argmax (the two lax.cond branches trace the pass with and
            # without the argmax tail, so even rounds pay only the proposal)
            fused = lambda do_argmax: _learned_chunk_pass(
                pod_emb, node_emb, group_id, feas_round, soft_round,
                cur_free, capacity, base_scores, req, active,
                l_params["tau"], jax.random.fold_in(l_key, rnd), chunk,
                policy, score_cols,
                node_dom=topo_rt[0] if topo_rt is not None else None,
                pref_pod=topo_rt[1] if topo_rt is not None else None,
                argmax=do_argmax)
            lprop, am_best, am_feas = lax.cond(
                rnd % 2 == 1, lambda _: fused(True), lambda _: fused(False),
                None)
            learned_best = (am_best, am_feas)
            # confident learned proposals override the water-fill; the topo
            # gang proposals below still win over both (gang contiguity is
            # a structural constraint, the learned term a packing score)
            proposals = jnp.where(lprop < M, lprop, proposals)
        if topo_rt is not None:
            # the segmented per-domain gang fill: its proposal wins
            # wherever it names a feasible node — fit is re-checked by
            # prop_fits below exactly like every other proposal
            node_dom_t, pref_pod = topo_rt
            tprop = _topo_gang_proposals(pref_pod, rank, active, req,
                                         cur_free, node_dom_t, base_scores)
            tp_ok = ((tprop < M)
                     & feas_round[group_id, jnp.clip(tprop, 0, M - 1)])
            proposals = jnp.where(tp_ok, tprop, proposals)
        prop_real = proposals < M
        prop_fits = prop_real & jnp.all(
            jnp.where(prop_real[:, None],
                      cur_free[jnp.clip(proposals, 0, M - 1)] - req, -1) >= 0,
            axis=1)
        if has_loc:
            # proposals must also satisfy the dynamic locality rules
            prop_fits &= loc_mask_g[group_id, jnp.clip(proposals, 0, M - 1)]

        def with_argmax(_):
            # exact per-pod argmax; guarantees ≥1 accept per contended node
            if (use_pallas and policy != "align" and topo_rt is None
                    and learned_rt is None):
                # the fused kernel has no per-pod domain-bonus or learned
                # embedding input; the steered argmax takes the XLA path
                # (proposals — where the steering mostly lands — are
                # kernel-independent anyway)
                from yunikorn_tpu.ops.pallas_kernels import pallas_best_nodes

                best, feasible = pallas_best_nodes(
                    req, group_id, feas_round, soft_round, cur_free,
                    base_scores, interpret=pallas_interpret,
                    has_soft=pallas_soft)
            elif learned_best is not None:
                # already computed by the fused learned pass above
                best, feasible = learned_best
            else:
                best, feasible = _best_nodes_chunked(
                    req, group_id, feas_round, soft_round, cur_free, capacity,
                    base_scores, chunk, policy, score_cols,
                    node_dom=topo_rt[0] if topo_rt is not None else None,
                    pref_pod=topo_rt[1] if topo_rt is not None else None)
            merged = jnp.where(prop_fits, proposals, best)
            return merged, active & (feasible | prop_fits)

        def water_only(_):
            return proposals, active & prop_fits

        # even rounds: cheap water-fill only (hits ~100% on homogeneous loads);
        # odd rounds add the exact argmax fallback for what water-fill missed
        best, cand = lax.cond(rnd % 2 == 1, with_argmax, water_only, None)

        node_key = jnp.where(cand, best, M)
        order = jnp.lexsort((rank, node_key))       # primary: node, secondary: rank
        snode = node_key[order]
        sreq = req[order]
        accept_sorted = _segment_prefix_accept(snode, sreq, cur_free, M)
        if has_loc:
            # soft-spread groups get a per-domain allowance of ceil(remaining
            # pods / domains): the batch balances across domains within a
            # round at full throughput, then re-scores with fresh counts
            remaining = jnp.sum((active[:, None] & loc[3]).astype(jnp.int32), axis=0)
            n_dom = jnp.maximum(jnp.sum(loc[2].astype(jnp.int32), axis=1), 1)
            soft_allow = jnp.maximum((remaining + n_dom - 1) // n_dom, 1)
            allowance_l = jnp.where(loc_spread_l | loc_anti_l, N,
                                    jnp.where(loc_softspread_l, soft_allow, N))
            accept_sorted = _loc_accept_cap(accept_sorted, snode, loc[3][order],
                                            group_id[order], loc, M, cnt, total,
                                            loc_spread_l, loc_aff_l, loc_anti_l,
                                            loc_min_skew_l, allowance_l,
                                            g_ref_masks, loc[9], g_capped)
        # commit accepted capacity (accepted rows always have snode < M;
        # rejected rows carry a zero delta, so the clipped scatter target
        # for dummy rows receives nothing)
        delta = jnp.where(accept_sorted[:, None], sreq, 0)
        cur_free = cur_free.at[jnp.clip(snode, 0, M - 1)].add(-delta)
        accepted = jnp.zeros((N,), bool).at[order].set(accept_sorted)
        assigned = jnp.where(accepted, best, assigned)
        around = jnp.where(accepted, rnd, around)
        if has_loc:
            cnt = _loc_update_counts(cnt, loc, accepted, best, M)
        done = done | accepted
        progress = jnp.any(accept_sorted)
        stalls = jnp.where(progress, 0, stalls + 1)
        return cur_free, done, assigned, around, rnd + 1, stalls, cnt

    (free_out, done, assigned, around, rounds, _,
     cnt_final) = lax.while_loop(cond, body, init)
    return assigned, around, free_out, rounds, cnt_final


@functools.partial(
    jax.jit,
    static_argnames=("max_rounds", "chunk", "policy", "use_pallas",
                     "pallas_interpret", "has_loc_soft", "pallas_has_soft",
                     "score_cols"),
)
def solve(
    req,            # [N, R] int32
    group_id,       # [N] int32
    rank,           # [N] float32 — lower schedules first
    valid,          # [N] bool
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
    g_tol, g_ports,                                   # group tensors
    g_pref_req, g_pref_forb, g_pref_weight,           # preferred-affinity scoring
    node_labels, node_taints, node_taints_soft, node_ports, node_ok,  # node symbol state
    free,           # [M, R] int32
    capacity,       # [M, R] int32
    host_group_mask=None,   # [G, M] bool or None
    host_group_soft=None,   # [G, M] float32 or None (host-scored soft terms)
    loc=None,       # locality tuple: (dom [L,M], cnt0 [L,D], dom_valid [L,D],
                    #  contrib [N,L], g_refs [G,S], g_kind, g_skew, g_seed,
                    #  g_weight [G,S] f32 — soft-slot score weights,
                    #  pair [L] int32 — holder→primary group pairing)
    topo=None,      # topology steering tuple (see _topo_node_adj /
                    # _topo_gang_proposals); None = the exact pre-topology
                    # program (the solver.topology=off contract)
    learned=None,   # learned-policy tuple (params pytree, seed i32) — the
                    # two-tower scorer (policy/net.py) augments the score
                    # matrix and gates proposal overrides; None = the exact
                    # pre-policy program (solver.policy=learned off contract)
    *,
    max_rounds: int = 16,
    chunk: int = 512,
    policy: str = "binpacking",
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    has_loc_soft: bool = True,
    pallas_has_soft: bool = True,
    score_cols: int = 0,
):
    """One batched solve. Returns (assigned [N] int32, accept_round [N]
    int32, free_after [M, R], rounds, cnt_final).

    score_cols > 0 restricts SCORING to the first score_cols resource
    columns; feasibility always uses all of them. prepare_solve_args appends
    capacity-1 synthetic columns per requested host port beyond score_cols —
    the round loop's free tracking then enforces intra-batch port
    exclusivity (two batch pods cannot share a port on one node) without
    ports distorting the packing score.

    has_loc_soft=False (static) skips the soft-locality scoring pass for
    batches whose locality slots are all hard (the common case) — the pass
    provably sums to zero when every g_weight is 0.

    use_pallas routes the per-round best-node computation through the fused
    Pallas kernel (ops/pallas_kernels.py). Locality batches work too: the
    dynamic per-round rules/scores are hoisted to [G, M] adjustments (pods in
    a group share locality state by construction — the constraint-group
    signature folds pod labels in whenever locality applies,
    snapshot/locality.py locality_signature) and folded into the kernel's
    feasibility/soft inputs. Only the align policy (per-pod alignment scores)
    stays on the XLA path.
    """
    N, R = req.shape
    M = free.shape[0]
    chunk = min(chunk, N)
    assert N % chunk == 0, "batch size must be a multiple of the chunk size"

    group_feas, group_soft = _hoist_group_state(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
        node_labels, node_taints, node_taints_soft, node_ports, node_ok,
        host_group_mask, host_group_soft)
    topo_rt = None
    if topo is not None:
        # node-level contention/empty-domain term folded into every group
        # row (group-independent), per-pod gang steering threaded into the
        # round loop; the core never sets topo on locality batches
        group_soft = group_soft + _topo_node_adj(topo)[None, :]
        topo_rt = (topo[0], topo[1])

    # learned scorer (solver.policy=learned): pod embeddings hoisted once,
    # node embeddings re-derived per round from current free capacity
    learned_rt = (_learned_prep(learned, req, rank, capacity, score_cols)
                  if learned is not None else None)

    has_loc = loc is not None
    cnt0 = loc[1] if has_loc else jnp.zeros((1, 1), jnp.int32)
    # the pallas kernel needs its soft input whenever the per-round hoist
    # folds soft-locality scores into it (both flags are static)
    pallas_soft = pallas_has_soft or has_loc_soft
    loc_hoist = (_hoist_loc_state(loc, group_id, group_feas.shape[0])
                 if has_loc else None)
    assigned, around, free_after, rounds, cnt_final = _solve_rounds(
        req, group_id, rank, valid, group_feas, group_soft, free, cnt0,
        capacity, loc, loc_hoist, max_rounds=max_rounds, chunk=chunk,
        policy=policy, use_pallas=use_pallas, pallas_interpret=pallas_interpret,
        has_loc_soft=has_loc_soft, pallas_soft=pallas_soft,
        score_cols=score_cols, topo_rt=topo_rt, learned_rt=learned_rt)
    # cnt_final rides out so the chunked scan path can reuse _solve_rounds
    # with carried locality domain counts
    return assigned, around, free_after, rounds, cnt_final


@functools.partial(
    jax.jit,
    static_argnames=("chunk_pods", "max_rounds", "chunk", "policy",
                     "use_pallas", "pallas_interpret", "has_loc_soft",
                     "pallas_has_soft", "score_cols"),
)
def solve_chunked(
    req, group_id, rank, valid,
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
    g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
    node_labels, node_taints, node_taints_soft, node_ports, node_ok,
    free, capacity, host_group_mask=None, host_group_soft=None, loc=None,
    topo=None, learned=None,
    *,
    chunk_pods: int,
    max_rounds: int = 16,
    chunk: int = 512,
    policy: str = "binpacking",
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    has_loc_soft: bool = True,
    pallas_has_soft: bool = True,
    score_cols: int = 0,
):
    """Chained fixed-shape chunk solves inside ONE compiled program.

    Batches above the configured `max_batch` cap run here: a `lax.scan` over
    rank-ordered [chunk_pods]-pod slices, carrying (free capacity, locality
    domain counts) chunk to chunk. A later chunk sees capacity net of earlier
    chunks, exactly like later pods in the reference's sequential cycle
    (reference scheduler_callback.go:196-198 — its loop is fully sequential).

    vs the round-4 host-side chain this hoists the [G, M] group feasibility /
    soft scoring and the locality precomputation OUT of the chain (computed
    once, closed over by the scan body), transfers chunk-invariant node/group
    tensors once, and dispatches one program instead of K — the three
    regression sources the r4 chain measured at 5.4× warm-path cost.

    PRECONDITION: pod rows must already be sorted by rank (solve_batch /
    solve_sharded sort + unsort around this call) — chunk boundaries
    supersede rank priority, so unsorted input would let a low-priority pod
    in an early chunk take capacity from a high-priority pod in a later one.
    """
    N, R = req.shape
    M = free.shape[0]
    mb = chunk_pods
    assert N % mb == 0, "batch size must be a multiple of chunk_pods"
    K = N // mb
    chunk = min(chunk, mb)
    assert mb % chunk == 0, "chunk_pods must be a multiple of the chunk size"

    group_feas, group_soft = _hoist_group_state(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
        node_labels, node_taints, node_taints_soft, node_ports, node_ok,
        host_group_mask, host_group_soft)
    if topo is not None:
        # hoisted OUT of the chain like the group state: one score fold
        # shared by every chunk (see solve)
        group_soft = group_soft + _topo_node_adj(topo)[None, :]

    has_loc = loc is not None
    pallas_soft = pallas_has_soft or has_loc_soft
    loc_hoist = (_hoist_loc_state(loc, group_id, group_feas.shape[0])
                 if has_loc else None)
    cnt0 = loc[1] if has_loc else jnp.zeros((1, 1), jnp.int32)

    xs = (req.reshape(K, mb, R), group_id.reshape(K, mb),
          rank.reshape(K, mb), valid.reshape(K, mb))
    if has_loc:
        xs = xs + (loc[3].reshape(K, mb, loc[3].shape[1]),)
    if topo is not None:
        xs = xs + (topo[1].reshape(K, mb),)            # pref_pod

    def scan_body(carry, x):
        free_k, cnt, round_base, slice_idx = carry
        topo_rt_k = None
        if topo is not None:
            x, cpref = x[:-1], x[-1]
            topo_rt_k = (topo[0], cpref)
        if has_loc:
            creq, cgid, crank, cvalid, ccontrib = x
            l = list(loc)
            l[3] = ccontrib
            loc_k = tuple(l)
        else:
            creq, cgid, crank, cvalid = x
            loc_k = None
        # learned pod embeddings are per-chunk (features are a pure
        # function of the chunk's request rows; params/seed chunk-
        # invariant); the slice index salts the exploration key so slices
        # never share Gumbel noise
        learned_rt_k = (_learned_prep(learned, creq, crank, capacity,
                                      score_cols, salt=slice_idx)
                        if learned is not None else None)
        a_k, ar_k, free_k, r_k, cnt = _solve_rounds(
            creq, cgid, crank, cvalid, group_feas, group_soft, free_k, cnt,
            capacity, loc_k, loc_hoist, max_rounds=max_rounds, chunk=chunk,
            policy=policy, use_pallas=use_pallas,
            pallas_interpret=pallas_interpret, has_loc_soft=has_loc_soft,
            pallas_soft=pallas_soft, score_cols=score_cols,
            topo_rt=topo_rt_k, learned_rt=learned_rt_k)
        # offset accept rounds so the chain's order is globally monotone (a
        # later chunk's round 0 happens after every earlier chunk's rounds)
        ar_k = jnp.where(ar_k >= 0, ar_k + round_base, -1)
        return ((free_k, cnt, round_base + r_k, slice_idx + 1),
                (a_k, ar_k, r_k))

    (free_after, cnt, _, _), (assigned_k, around_k, rounds_k) = lax.scan(
        scan_body, (free, cnt0, jnp.int32(0), jnp.int32(0)), xs)
    return (assigned_k.reshape(N), around_k.reshape(N), free_after,
            jnp.sum(rounds_k), cnt)


# Pod-bucket cap above which a batch runs as a chained chunk solve
# (solve_chunked: one compiled lax.scan program over [max_batch]-pod slices
# with carried free capacity + locality counts). Defaults to the north-star
# bucket so the monolithic program — measurably the fastest warm path (r4
# verdict: 3.38 s vs 18.2 s warm at 50k/10k on CPU) — is what production
# runs; operators whose environment makes large-shape compiles expensive
# (e.g. a remote_compile relay) can lower `solver.maxBatch` and pay only a
# mild warm cost because the chain is a single program with group state
# hoisted out (see solve_chunked).
MAX_SOLVE_PODS = 65536

# positional indexes into prepare_solve_args' tuple, derived from one named
# list so a reorder/insertion in its return breaks loudly at import time
SOLVE_ARG_NAMES = (
    "req", "group_id", "rank", "valid",
    "g_term_req", "g_term_forb", "g_term_valid", "g_anyof", "g_anyof_valid",
    "g_tol", "g_ports", "g_pref_req", "g_pref_forb", "g_pref_weight",
    "node_labels", "node_taints", "node_taints_soft", "node_ports", "node_ok",
    "free", "capacity", "host_mask", "host_soft", "loc", "topo",
)
_ARG_RANK = SOLVE_ARG_NAMES.index("rank")
_ARG_LOC = SOLVE_ARG_NAMES.index("loc")
_ARG_TOPO = SOLVE_ARG_NAMES.index("topo")


def _unsort(order, *arrays):
    """Invert a _sort_pods_by_rank permutation on pod-dim result arrays
    (device gather — stays async). Shared by solve_batch and solve_sharded."""
    import numpy as np

    inv = np.empty_like(order)
    inv[order] = np.arange(order.shape[0])
    inv_d = jnp.asarray(inv)
    return tuple(a[inv_d] for a in arrays)


def _sort_pods_by_rank(np_args):
    """Stable host-side sort of the pod-dimension args by rank.

    The chunked scan's chunk boundaries supersede rank priority (a later
    chunk only sees leftover capacity), so the chained path sorts pod rows by
    rank first and the caller unsorts `assigned` with the returned
    permutation (None when already sorted — the CoreScheduler path, which
    assigns ranks in sorted ask order)."""
    import numpy as np

    rank = np.asarray(np_args[_ARG_RANK])
    order = np.argsort(rank, kind="stable")
    if (order == np.arange(order.shape[0])).all():
        return np_args, None
    out = list(np_args)
    for i in range(4):  # req, group_id, rank, valid
        out[i] = np.asarray(np_args[i])[order]
    loc = np_args[_ARG_LOC]
    if loc is not None:
        l = list(loc)
        l[3] = np.asarray(loc[3])[order]          # contrib [N, L]
        out[_ARG_LOC] = tuple(l)
    topo = np_args[_ARG_TOPO]
    if topo is not None:
        t = list(topo)
        t[1] = np.asarray(topo[1])[order]         # pref_pod [N]
        out[_ARG_TOPO] = tuple(t)
    return tuple(out), order


def apply_free_delta(free_i, free_delta):
    """Subtract the core's in-flight overlay from integer free capacity.

    Single source for the overlay arithmetic (ceil to device units, clip to
    the possibly-differing shapes) shared by the allocation solve's host and
    device-mirror paths AND the preemption planner's arg prep — the
    planners' view of free capacity must never drift from the solver's.
    free_i may be host numpy or a committed device array.
    """
    import numpy as np

    M, R = free_i.shape
    d = np.zeros((M, R), np.int32)
    rows = min(M, free_delta.shape[0])
    cols = min(R, free_delta.shape[1])
    d[:rows, :cols] = np.ceil(free_delta[:rows, :cols]).astype(np.int32)
    if isinstance(free_i, np.ndarray):
        return free_i - d
    import jax.numpy as jnp_mod

    return free_i - jnp_mod.asarray(d)


def pad2d(arr, width, fill):
    """Pad or clamp the second dim of a [G, m] host array to `width` — the
    node capacity may have grown (or a sharded view may be narrower) since
    the batch was encoded."""
    import numpy as np

    if arr.shape[1] == width:
        return arr
    out = np.full((arr.shape[0], width), fill, arr.dtype)
    out[:, : min(arr.shape[1], width)] = arr[:, :width]
    return out


def prepare_solve_args(batch, node_arrays, *, free_delta=None, node_mask=None,
                       ports_delta=None, device_state=None,
                       allow_req_device=True):
    """Assemble the positional numpy args + static kwargs for `solve`.

    Shared by solve_batch (single device) and parallel.mesh.solve_sharded
    (node-dim GSPMD) so the two paths cannot drift: same dtype views, same
    overlay/mask handling, same static-variant selection.

    free_delta: optional [capacity, R] float array subtracted from node free
    capacity before the solve (the core's in-flight allocation overlay).
    node_mask: optional [capacity] bool restricting candidate nodes (the
    multi-partition case: one encoder holds every cache node, each
    partition's solve sees only its own).
    ports_delta: optional [capacity, Wp] u32 port mask OR-ed into node port
    occupancy (in-flight allocations' host ports — see _inflight_ports).
    device_state: optional dict of persistent device-resident node tensors
    (SnapshotEncoder.device_arrays, refreshed to match node_arrays): the
    chunk-invariant node-side inputs then transfer O(changed rows) per cycle
    instead of O(M), with the overlays applied as (async-dispatched) device
    ops. Batches requesting host ports bypass it — the synthetic port
    columns reshape free/capacity per batch.
    """
    import numpy as np

    na = node_arrays
    g_ports_u32 = batch.g_ports.view(np.uint32)
    use_device = device_state is not None and not g_ports_u32.any()
    # device-resident req (DeviceRowStore gather, values pinned identical
    # to req.astype(int32)): skips the per-cycle [N, R] host upload — with
    # the O(changed) row-store uploads, a churn cycle's pod-request
    # transfer is changed rows + an int32 index, not the whole tensor.
    # Only on the persistent-device-state path: the host/port paths below
    # concatenate and fancy-index req on the host.
    req_dev = getattr(batch, "req_device", None) if allow_req_device else None
    if use_device and req_dev is not None \
            and tuple(req_dev.shape) == batch.req.shape:
        req_i = req_dev
    else:
        req_i = batch.req.astype(np.int32)
    score_cols = req_i.shape[1]
    if use_device:
        import jax.numpy as jnp

        dev = device_state
        free_i = dev["free_i"]
        if free_delta is not None:
            free_i = apply_free_delta(free_i, free_delta)
        cap_i = dev["cap_i"]
        node_ports_u32 = dev["ports"]
        if ports_delta is not None:
            pd = np.zeros(node_ports_u32.shape, np.uint32)
            rows = min(pd.shape[0], ports_delta.shape[0])
            cols = min(pd.shape[1], ports_delta.shape[1])
            pd[:rows, :cols] = ports_delta[:rows, :cols]
            node_ports_u32 = node_ports_u32 | jnp.asarray(pd)
        node_ok = dev["node_ok"]
        if node_mask is not None:
            node_ok = node_ok & jnp.asarray(node_mask[: node_ok.shape[0]])
        return _finish_solve_args(batch, req_i, score_cols, dev["labels"],
                                  dev["taints_hard"], dev["taints_soft"],
                                  node_ports_u32, node_ok, free_i, cap_i, na,
                                  topo_mirror=dev.get("topo"))
    free_i = np.floor(na.free).astype(np.int32)
    if free_delta is not None:
        # overlay may be narrower/shorter than the (possibly grown) node arrays
        free_i = apply_free_delta(free_i, free_delta)
    cap_i = np.floor(na.capacity_arr).astype(np.int32)
    # node port occupancy = cache-visible pods + in-flight allocations (an
    # allocation committed last cycle whose assume hasn't landed holds its
    # ports just as firmly)
    node_ports_u32 = na.ports.view(np.uint32)
    if ports_delta is not None:
        pd = np.zeros_like(node_ports_u32)
        rows = min(pd.shape[0], ports_delta.shape[0])
        cols = min(pd.shape[1], ports_delta.shape[1])
        pd[:rows, :cols] = ports_delta[:rows, :cols]
        node_ports_u32 = node_ports_u32 | pd
    # intra-batch host-port exclusivity: each port bit any group requests
    # becomes a capacity-1 synthetic resource column. The static group
    # feasibility (g_ports vs node_ports) only sees EXISTING pods; without
    # these columns two batch pods wanting one port could share a node.
    # Bucketed column count (next power of two, min 4) bounds the number of
    # compiled shape variants.
    if g_ports_u32.any():
        union = np.bitwise_or.reduce(g_ports_u32, axis=0)      # [Wp]
        port_bits = [(w, b) for w in range(union.shape[0])
                     for b in range(32) if (int(union[w]) >> b) & 1]
        P = len(port_bits)
        P_pad = max(4, 1 << (P - 1).bit_length())
        Np, M_ = req_i.shape[0], free_i.shape[0]
        req_ext = np.zeros((Np, P_pad), np.int32)
        free_ext = np.zeros((M_, P_pad), np.int32)
        cap_ext = np.zeros((M_, P_pad), np.int32)
        gid = batch.group_id[:Np]
        Wn = node_ports_u32.shape[1]
        for j, (w, b) in enumerate(port_bits):
            req_ext[:, j] = (g_ports_u32[gid, w] >> np.uint32(b)) & 1
            if w < Wn:
                occupied = (node_ports_u32[:, w] >> np.uint32(b)) & 1
                free_ext[:, j] = 1 - occupied.astype(np.int32)
            else:
                free_ext[:, j] = 1
            cap_ext[:, j] = 1
        req_i = np.concatenate([req_i, req_ext], axis=1)
        free_i = np.concatenate([free_i, free_ext], axis=1)
        cap_i = np.concatenate([cap_i, cap_ext], axis=1)
    node_ok = na.valid & na.schedulable
    if node_mask is not None:
        node_ok = node_ok & node_mask[: node_ok.shape[0]]
    return _finish_solve_args(batch, req_i, score_cols,
                              na.labels.view(np.uint32),
                              na.taints_hard.view(np.uint32),
                              na.taints_soft.view(np.uint32),
                              node_ports_u32, node_ok, free_i, cap_i, na)


def _finish_solve_args(batch, req_i, score_cols, labels, taints_hard,
                       taints_soft, node_ports, node_ok, free_i, cap_i, na,
                       topo_mirror=None):
    """Common tail of prepare_solve_args: pod/group args + static kwargs.
    Node-side inputs may be host numpy or persistent device arrays — the two
    variants produce identical avals, so they share one compiled program.
    topo_mirror: the persistent device mirror's [M, 3] topo tensor (the
    use_device path) — the node→domain column then rides the mirror
    (O(node-object-change) transfer) instead of re-uploading per cycle."""
    import numpy as np

    host_mask = batch.g_host_mask
    if host_mask is not None:
        host_mask = pad2d(host_mask, na.capacity, False)
    host_soft = getattr(batch, "g_host_soft", None)
    if host_soft is not None:
        host_soft = pad2d(host_soft, na.capacity, np.float32(0.0))
    loc = None
    if batch.locality is not None:
        lb = batch.locality
        loc = (lb.dom, lb.cnt0, lb.dom_valid, lb.contrib,
               lb.g_refs, lb.g_kind, lb.g_skew, lb.g_seed, lb.g_weight,
               lb.pair)
    # topology steering (solver.topology, topology/score.TopoArgs) rides
    # its own slot. batch.topo is attached per cycle by the core — None
    # keeps the exact pre-topology arg tuple (the bit-identical-off
    # contract).
    topo = None
    topo_args = getattr(batch, "topo", None)
    if topo_args is not None and loc is None:
        M_ = free_i.shape[0]
        if topo_mirror is not None and topo_mirror.shape[0] == M_:
            # device path: the node→domain column comes from the persistent
            # mirror (already resident; a tiny device-side slice)
            node_dom = topo_mirror[:, 2]
        else:
            node_dom = topo_args.node_dom
            if node_dom.shape[0] != M_:
                # node capacity grew since the fold: unlabeled-pad the tail
                nd = np.full((M_,), -1, np.int32)
                nd[: min(M_, node_dom.shape[0])] = node_dom[:M_]
                node_dom = nd
        pref = topo_args.pref_pod
        if pref.shape[0] != req_i.shape[0]:
            pp = np.full((req_i.shape[0],), -1, np.int32)
            pp[: min(pp.shape[0], pref.shape[0])] = pref[: pp.shape[0]]
            pref = pp
        topo = (node_dom, pref, topo_args.dom_busy, topo_args.dom_cap)
    np_args = (
        req_i,
        batch.group_id,
        batch.rank,
        batch.valid,
        batch.g_term_req.view(np.uint32),
        batch.g_term_forb.view(np.uint32),
        batch.g_term_valid,
        batch.g_anyof.view(np.uint32),
        batch.g_anyof_valid,
        batch.g_tol.view(np.uint32),
        batch.g_ports.view(np.uint32),
        batch.g_pref_req.view(np.uint32),
        batch.g_pref_forb.view(np.uint32),
        batch.g_pref_weight,
        labels,
        taints_hard,
        taints_soft,
        node_ports,
        node_ok,
        free_i,
        cap_i,
        host_mask,
        host_soft,
        loc,
        topo,
    )
    assert len(np_args) == len(SOLVE_ARG_NAMES)
    static_kwargs = dict(
        has_loc_soft=(batch.locality is not None
                      and bool(np.any(batch.locality.g_weight))),
        # no-soft batches take the kernel variant without the soft DMA/matmul
        # (topology steering is itself a soft-score channel)
        pallas_has_soft=(bool(batch.g_pref_weight.any())
                         or host_soft is not None
                         or topo is not None
                         or bool(np.any(na.taints_soft))),
        # scoring ignores the synthetic port columns appended above
        score_cols=score_cols,
    )
    return np_args, static_kwargs


def jit_cache_entries() -> int:
    """Compiled-variant count across the solve entry points (the in-process
    jit caches; the persistent on-disk XLA cache is jaxtools' concern).

    The scheduler reads this around each dispatch to tell a compile-cache
    hit from a fresh trace+compile — a production cycle landing on an
    unwarmed bucket shows up as a `solve_compile_total` increment plus a
    `compiled: true` arg on its trace span instead of an anonymous stall.
    Returns -1 when the jit internals don't expose cache sizes.
    """
    from yunikorn_tpu.aot import runtime as aot_rt

    total = aot_rt.compile_count("assign.", "mesh.solve")
    for fn in (solve, solve_chunked):
        try:
            total += fn._cache_size()
        except Exception:
            return -1
    return total


def solve_batch(batch, node_arrays, *, max_rounds=16, chunk=512, policy="binpacking",
                free_delta=None, use_pallas=False, pallas_interpret=False,
                device=None, node_mask=None, ports_delta=None,
                compile_only=False, max_batch=MAX_SOLVE_PODS,
                device_state=None, aot_pending=False,
                learned=None, aot_extra=()) -> Optional[SolveResult]:
    """Convenience host wrapper: numpy in → SolveResult out.

    See prepare_solve_args for free_delta / node_mask / device_state
    semantics (device_state = persistent device-resident node tensors; the
    pipelined core threads them through so node state transfers once per
    change, not once per cycle).
    compile_only: AOT-lower and compile this shape/static-variant without
    executing (bucket prewarm) — fills the jit + persistent caches at zero
    device time; returns None. With an AOT runtime installed (aot/), the
    executable is loaded from the store instead of compiled when the
    fingerprint matches, and persisted after a fresh compile.
    max_batch: batches above this run as ONE compiled chained chunk program
    (solve_chunked: lax.scan over rank-ordered [max_batch]-pod slices with
    capacity + locality-count carry) — see MAX_SOLVE_PODS.
    aot_pending: supervised device-tier callers opt in — an AOT-store miss
    in background-compile mode raises aot.CompilePending instead of paying
    the XLA compile inline, and the caller's ladder serves the cycle from a
    lower tier while the compile thread populates the store.
    learned: (params pytree, seed) — run the solve as the LEARNED-policy
    variant (two-tower score augmentation + gated proposal overrides; see
    policy/). The params ride as traced leaves, so a same-shape checkpoint
    swap re-uses the compiled program; callers MUST also pass the
    checkpoint hash via aot_extra so the AOT store can never serve an
    executable fingerprinted for a different checkpoint (belt and braces —
    the core passes ("policy", <hash>)).
    aot_extra: extra components folded into the AOT fingerprint manifest.
    """
    from yunikorn_tpu.aot import runtime as aot_rt

    mb = 1 << (max(int(max_batch), 64).bit_length() - 1)
    np_args, static_kwargs = prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        # the chunked path rank-sorts pod args on the host — a device req
        # there would bounce device→host→device; use the host rows
        allow_req_device=batch.req.shape[0] <= mb)
    learned_tail = ()
    if learned is not None:
        learned_tail = ((jax.tree_util.tree_map(jnp.asarray, learned[0]),
                         jnp.asarray(learned[1], jnp.int32)),)
    solve_kwargs = dict(
        max_rounds=max_rounds,
        chunk=chunk,
        policy=policy,
        # the fused kernel takes the combined [G, M] soft adjustment (soft
        # taints + preferred affinity + host-scored terms + per-round hoisted
        # locality scores); only the align policy falls back to the XLA path
        # (handled inside solve)
        use_pallas=use_pallas,
        pallas_interpret=pallas_interpret,
        **static_kwargs,
    )
    N = np_args[0].shape[0]
    if N > mb:
        # N and mb are both powers of two (encoder bucket / rounding above):
        # one compiled lax.scan program over [mb]-pod rank-ordered slices
        np_args_s, order = _sort_pods_by_rank(np_args)
        ck = dict(solve_kwargs, chunk_pods=mb)
        if compile_only:
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                (*np_args_s, *learned_tail))
            aot_rt.aot_compile("assign.solve_chunked", solve_chunked,
                               specs, ck, extra=aot_extra)
            return None
        solve_args = jax.tree_util.tree_map(jnp.asarray, np_args_s)
        assigned, around, free_after, rounds, _ = aot_rt.aot_call(
            "assign.solve_chunked", solve_chunked,
            (*solve_args, *learned_tail), ck,
            pending_ok=aot_pending, extra=aot_extra)
        if order is not None:
            assigned, around = _unsort(order, assigned, around)
        return SolveResult(assigned=assigned, free_after=free_after,
                           rounds=rounds, accept_round=around)
    if compile_only:
        # specs instead of arrays: no host->device transfer at all
        specs = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (*np_args, *learned_tail))
        aot_rt.aot_compile("assign.solve", solve, specs, solve_kwargs,
                           extra=aot_extra)
        return None
    solve_args = jax.tree_util.tree_map(jnp.asarray, np_args)
    assigned, around, free_after, rounds, _ = aot_rt.aot_call(
        "assign.solve", solve, (*solve_args, *learned_tail), solve_kwargs,
        pending_ok=aot_pending, extra=aot_extra)
    return SolveResult(assigned=assigned, free_after=free_after, rounds=rounds,
                       accept_round=around)
