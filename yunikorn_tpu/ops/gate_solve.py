"""Device-resident admission gate: the bounded-pass scan on XLA.

The host vectorized gate (core/gate.py host_scan) already reformulated the
legacy per-ask admission walk into segmented prefix-scan passes, but two
costs remained on the host thread: the passes themselves (numpy, GIL-bound,
serial with everything else the scheduler does) and their DATA-DEPENDENT
count — the adversarial 85%-held saturated trace degrades to ~13 passes
(docs/PERF.md round-10), because each pass finalizes only the violators
whose prefixes are provably exact and defers the chain behind them.

This module moves the scan into ONE jitted XLA program with a pass budget
that is bounded BY CONSTRUCTION:

  max_passes = ceil(log2(n_pad)) + GATE_PASS_SLACK

a `lax.while_loop` whose trip count can never exceed that bound, whatever
the trace looks like (the CvxCluster/POP playbook: replace data-dependent
sequential control flow with a fixed-shape parallel program). Real traces
converge well inside the bound (the saturated 50k trace needs ~13 < 16+4);
an adversarial trace that does not leaves a (tiny) undecided remainder that
`core/gate.finish_leftovers` decides exactly on the host — O(leftovers)
work, and the differential oracle pins the result identical to the host
scan and transitively to the legacy loop either way.

Formulation notes (why this is not a transliteration of host_scan):

- *No scatters.* XLA:CPU lowers `.at[].max/min` scatters an order of
  magnitude slower than gathers; every ask-level aggregation ("does any of
  this ask's rows violate?") instead runs as a segmented 1-D cumsum over a
  PRECOMPUTED ask-sorted permutation of the membership rows, broadcast back
  with pure gathers. Status lives at ROW granularity inside the kernel (all
  rows of an ask always agree); the [n] ask vector is reassembled on the
  host from one numpy scatter after materialization.
- *No compaction.* The host scan shrinks its arrays between passes; the
  device program keeps fixed shapes and masks decided rows — that is what
  makes it one compile per bucket.
- *Host-exact pass ordering.* Two segmented [M, K] cumsums per pass — the
  admitted-only prefix right after this pass's admissions (feeding the
  definite-hold sweep, host_scan's exact rule order: a stale sweep was
  measured to nearly double the pass count on the saturated shape) and the
  not-held prefix after the sweep (feeding the next pass's violator test,
  which the first-violator hold rule's exactness proof requires to exclude
  every held row).
- *Narrowest exact dtype per cycle.* The scan runs in int32 whenever the
  cycle's worst-case prefix sum and budget magnitudes provably fit (checked
  against the exact per-column sums before upload — the same discipline as
  core/gate's _check_magnitude ceilings), int64 otherwise; most traces
  except raw memory-bytes columns fit int32, which halves the kernel's
  memory traffic.

Exactness: the gate's arithmetic is EXACT integer (budgets up to 2^61, see
core/gate.py's caps) — the int64 variant runs under
jax.experimental.enable_x64 (thread-local; the f32 assignment solve in the
same process is untouched).

Semantics pinned bit-identical to host_scan (same admitted set, order, held
count) by tests/test_gate_device.py across randomized trees/limits/gang and
pipelined seed/exclude traces — the same differential-oracle pattern
(gateVerify) that pinned the host scan to the legacy loop.
"""
from __future__ import annotations

import functools
import math
import time

import jax
import numpy as np

from yunikorn_tpu.core import gate as gate_mod
from yunikorn_tpu.snapshot.vocab import _next_pow2

# extra passes past ceil(log2(n_pad)): covers the small non-logarithmic tail
# real traces show (the saturated 50k shape converges in ~13-14 where
# ceil(log2(65536)) alone allows 16)
GATE_PASS_SLACK = 4

_INF = gate_mod._INF


def max_passes_for(n: int) -> int:
    """The pass budget for a batch of n asks (after bucketing): the bound
    the acceptance criterion and gate_bench assert against."""
    n_pad = _next_pow2(max(n, 1), 256)
    return max(int(math.ceil(math.log2(n_pad))), 1) + GATE_PASS_SLACK


@functools.partial(jax.jit, static_argnames=("max_passes",))
def _gate_scan(reqw, bm2, rstatus0, seg_first_t, perm_a, inv_perm_a,
               seg_first_a, seg_last_a, *, max_passes: int):
    """Masked bounded-pass admission over padded row-level shapes.

    reqw      [M, K] int64  weighted charge per membership row (0 on pads)
    bm2       [M, K] int64  budget-minus-own-request per row (_INF-ish pads)
    rstatus0  [M] int8      0 undecided / 1 decided (pads)
    seg_first_t [M] int32   first row index of this row's TRACKER segment
    perm_a / inv_perm_a / seg_first_a / seg_last_a [M] int32
                            ask-sorted view: permutation, its inverse, and
                            per-row first/last index of the row's ASK
                            segment within the sorted view

    Returns (rstatus [M] int8 with -1 = held, passes int32).
    """
    import jax.numpy as jnp
    from jax import lax

    def seg_excl(mask):
        """Segmented EXCLUSIVE cumsum of the rows' weighted charges where
        `mask` holds ([M] bool → [M, K])."""
        X = jnp.where(mask[:, None], reqw, 0)
        base = jnp.cumsum(X, axis=0) - X
        return base - base[seg_first_t]

    def ask_any(flag):
        """Broadcast per-ask OR of a row-level flag back to rows: segmented
        count over the ask-sorted view, pure gathers + one 1-D cumsum."""
        s = flag.astype(jnp.int32)[perm_a]
        cs = jnp.cumsum(s)
        tot = cs[seg_last_a] - cs[seg_first_a] + s[seg_first_a]
        return tot[inv_perm_a] > 0

    def body(carry):
        rstatus, ov, passes = carry
        undec = rstatus == 0
        # violator: the one-sided over-estimate check (charges of every
        # not-yet-held predecessor + own row) fails in any tracker
        row_viol = undec & jnp.any(ov > bm2, axis=1)
        va = ask_any(row_viol)
        # every undecided non-violator admits
        rstatus = jnp.where(undec & ~va, jnp.int8(1), rstatus)
        # a violator holds iff NO earlier (ask-level) violator shares any
        # tracker — its prefix is then exact; otherwise defer: the earlier
        # violator's removal could free budget
        vr = (va & undec).astype(jnp.int32)
        cs = jnp.cumsum(vr) - vr
        blocked_row = undec & ((cs - cs[seg_first_t]) > 0)
        ba = ask_any(blocked_row)
        rstatus = jnp.where(undec & va & ~ba, jnp.int8(-1), rstatus)
        # definite-hold sweep against the admitted prefix INCLUDING this
        # pass's admissions (host_scan's exact rule order): admitted usage
        # only grows, so an ask whose own request no longer fits can never
        # admit
        ad = seg_excl(rstatus == jnp.int8(1))
        live = rstatus == 0
        sa = ask_any(live & jnp.any(ad > bm2, axis=1))
        rstatus = jnp.where(live & sa, jnp.int8(-1), rstatus)
        # next pass's over-estimate excludes every hold this pass took —
        # the first-violator rule's exactness proof needs that
        return rstatus, seg_excl(rstatus != jnp.int8(-1)), passes + 1

    def cond(carry):
        rstatus, _ov, passes = carry
        return (passes < max_passes) & jnp.any(rstatus == 0)

    rstatus, _ov, passes = lax.while_loop(
        cond, body,
        (rstatus0, seg_excl(rstatus0 != jnp.int8(-1)), jnp.int32(0)))
    return rstatus, passes


def _ask_view(mem_pos: np.ndarray, M_pad: int):
    """Static index arrays for the kernel's ask-sorted aggregation view.

    Rows arrive (tracker, pos)-sorted; the stable argsort by ask position
    makes each ask's rows contiguous. Padded rows keep their identity slots
    (each its own segment)."""
    M = mem_pos.shape[0]
    perm = np.arange(M_pad, dtype=np.int64)
    perm[:M] = np.argsort(mem_pos, kind="stable")
    sorted_pos = np.full((M_pad,), -1, np.int64)
    sorted_pos[:M] = mem_pos[perm[:M]]
    # mark pads as distinct pseudo-asks so segments never span the boundary
    if M_pad > M:
        sorted_pos[M:] = -np.arange(2, M_pad - M + 2)
    is_start = np.r_[True, sorted_pos[1:] != sorted_pos[:-1]]
    idx = np.arange(M_pad)
    seg_first = np.maximum.accumulate(np.where(is_start, idx, 0))
    seg_last = np.full((M_pad,), M_pad - 1, np.int64)
    seg_last[:-1] = np.where(is_start[1:], idx[:-1], M_pad - 1)
    np.minimum.accumulate(seg_last[::-1], out=seg_last[::-1])
    inv = np.empty((M_pad,), np.int64)
    inv[perm] = idx
    return (perm.astype(np.int32), inv.astype(np.int32),
            seg_first.astype(np.int32), seg_last.astype(np.int32))


def device_admit(problem: "gate_mod.GateProblem", *, backend=None):
    """Run a GateProblem through the jitted bounded-pass scan.

    Pads every dimension to power-of-two buckets (one compile per bucket
    combination), executes under enable_x64 (exact int64 arithmetic), pulls
    back the [M] int8 row-status vector, and finishes any undecided
    leftovers exactly on the host. Returns (admitted, held, stats) with the
    same contract as core/gate.host_scan; stats["path"] == "device".
    """
    import jax.numpy as jnp
    from jax.experimental import enable_x64

    n, T = problem.n, problem.T
    t_start = time.perf_counter()
    if n == 0:
        return [], 0, {"path": "device", "passes": 0, "trackers": 0}
    base_stats = {
        "path": "device", "trackers": T,
        "rank_ms": (problem.t_rank - problem.t0) * 1000,
    }
    M = problem.mem_tr.size
    if T == 0 or M == 0:
        # pure ranking (no quota/limits near the pending set, or every ask
        # tracker-less): nothing to scan on any backend
        admitted = [problem.asks_ord[pos]
                    for pos in np.flatnonzero(problem.status0 == 1).tolist()]
        return admitted, 0, dict(
            base_stats, passes=0,
            admit_ms=(time.perf_counter() - problem.t_rank) * 1000)

    M_pad = _next_pow2(M, 256)
    K = problem.K
    K_pad = _next_pow2(K, 2)
    max_passes = max_passes_for(n)

    # loop-invariant row tensors, gathered on the host once per cycle
    # (numpy fancy-indexing; the kernel then never touches Rm/B directly)
    rrow = problem.Rm[problem.mem_pos]                    # [M, K]
    wcharge = rrow * problem.mem_w[:, None]
    braw = problem.B[problem.mem_tr] - rrow               # budget minus own

    # narrowest exact dtype: int32 whenever the worst-case prefix sum (the
    # exact per-column charge totals) and every finite budget magnitude
    # provably fit — halves the scan's memory traffic. _INF-backed entries
    # clamp to a sentinel strictly above any reachable prefix.
    _I32CAP = np.int64(1) << 30
    col_sums = wcharge.sum(axis=0, dtype=np.int64)
    finite = np.abs(braw) < (_INF >> 1)
    fits32 = (int(col_sums.max(initial=0)) < _I32CAP
              and (np.abs(braw[finite]).max(initial=0) if finite.any()
                   else 0) < _I32CAP)
    dtype = np.int32 if fits32 else np.int64
    inf_sentinel = _I32CAP if fits32 else _INF

    reqw = np.zeros((M_pad, K_pad), dtype)
    reqw[:M, :K] = wcharge
    bm2 = np.full((M_pad, K_pad), inf_sentinel, dtype)
    bm2[:M, :K] = np.where(finite, braw, inf_sentinel)
    rstatus0 = np.ones((M_pad,), np.int8)                 # pads decided
    rstatus0[:M] = problem.status0[problem.mem_pos]

    # tracker-segment starts (rows arrive tracker-major); pads are solo
    is_start = np.empty((M_pad,), bool)
    is_start[0] = True
    is_start[1:M] = problem.mem_tr[1:] != problem.mem_tr[:-1]
    is_start[M:] = True
    idx = np.arange(M_pad)
    seg_first_t = np.maximum.accumulate(
        np.where(is_start, idx, 0)).astype(np.int32)
    perm_a, inv_perm_a, seg_first_a, seg_last_a = _ask_view(
        problem.mem_pos, M_pad)

    host_arrays = (reqw, bm2, rstatus0, seg_first_t, perm_a, inv_perm_a,
                   seg_first_a, seg_last_a)
    from yunikorn_tpu.aot import runtime as aot_rt

    with enable_x64():
        args = [jnp.asarray(a) for a in host_arrays]
        if backend is not None:
            dev = jax.local_devices(backend=backend)[0]
            args = [jax.device_put(a, dev) for a in args]
        # AOT-store routed (fingerprint includes the x64 mode + the exact
        # int32/int64 bucketed avals): a store hit serves the scan with
        # zero trace+compile in a fresh process. Background mode raises
        # CompilePending out of the supervised gate's device tier — the
        # host-vectorized tier (placement-equivalent) serves the cycle
        # while the compile thread populates the store.
        jrstatus, jpasses = aot_rt.aot_call(
            "gate.scan", _gate_scan, tuple(args),
            {"max_passes": max_passes},
            pending_ok=aot_rt.pending_enabled())
        rstatus = np.asarray(jrstatus)[:M]
        passes = int(jpasses)

    # reassemble the per-ask status: all rows of an ask agree by
    # construction, one numpy scatter instead of any device-side one
    status = problem.status0.copy()
    status[problem.mem_pos] = rstatus
    finish = gate_mod.finish_leftovers(problem, status)
    admitted = [problem.asks_ord[pos]
                for pos in np.flatnonzero(status == 1).tolist()]
    held = int((status == -1).sum())
    return admitted, held, dict(
        base_stats,
        passes=passes,
        max_passes=max_passes,
        finish_loop=finish,
        admit_ms=(time.perf_counter() - problem.t_rank) * 1000,
        device_ms=(time.perf_counter() - t_start) * 1000,
        transfer_bytes=int(sum(a.nbytes for a in host_arrays)),
    )


# --------------------------------------------------------------- encode_rows
# The encoder's request-row quantization as a device program: changed rows
# arrive as RAW resource values (exact int64) plus the per-slot scales, are
# quantized on device with arithmetic bit-identical to the host's
# SnapshotEncoder.quantize_request chain (float64 divide → ceil → float32 →
# int32; the host path stores f32 rows and the solve casts them int32, so
# the device store must reproduce that exact rounding), and scatter into the
# persistent row pool. The batch's req tensor is then a pure device gather —
# a churn cycle's host→device traffic for pod requests is O(changed rows)
# of row data plus an O(n) int32 slot index.


@functools.partial(jax.jit, donate_argnums=(0,))
def encode_rows(pool, raw, scales, slots):
    """Quantize raw rows and scatter them into the row pool.

    pool   [cap, R] int32   persistent quantized rows (donated: updated
                            in place, the old buffer is consumed)
    raw    [C, R]  int64    raw resource values of changed rows (0 pads —
                            their quantized row is 0, and pads point at the
                            reserved all-zero slot 0)
    scales [R]     float64  per-slot device-unit scales
    slots  [C]     int32    target pool slot per row (0 for pads)
    """
    import jax.numpy as jnp

    rows = (jnp.ceil(raw / scales[None, :])
            .astype(jnp.float32).astype(jnp.int32))
    return pool.at[slots].set(rows)


@jax.jit
def gather_rows(pool, idx):
    """[N, R] int32 request tensor for one batch: a pure device gather of
    each ask's pool slot (0 = the reserved zero row for padding)."""
    return pool[idx]


def jit_cache_entries() -> int:
    """Compiled-variant count of the gate scan (CoreScheduler reads this to
    tell a first-bucket compile from a cache hit). -1 when unavailable."""
    from yunikorn_tpu.aot import runtime as aot_rt

    try:
        return _gate_scan._cache_size() + aot_rt.compile_count("gate.scan")
    except Exception:
        return -1


# ---------------------------------------------------------------------------
# Device-resident usage mirror kernels (ops/ledger_mirror.DeviceUsageMirror)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, donate_argnums=(0,))
def usage_apply(dev, shard, t_idx, k_idx, deltas):
    """Scatter one shard's drained confirmed-usage deltas into its row of
    the [S, T, K] int64 mirror: dev[shard, t_idx[b], k_idx[b]] += deltas[b].
    Padded entries carry delta 0 (index (0, 0) — the add is identity), so
    batches bucket to power-of-two sizes (one compile per bucket). Donated:
    the mirror is a persistent device array updated in place."""
    return dev.at[shard, t_idx, k_idx].add(deltas)


@jax.jit
def usage_fold(dev):
    """Fleet usage: fold the per-shard [S, T, K] mirror over the shard
    axis to the [T, K] pre-reduced totals every shard's gate precheck
    reads. On one device this is a jitted sum; under a mesh
    parallel/mesh.usage_fold_sharded runs the same fold as a psum-style
    cross-shard all-reduce."""
    import jax.numpy as jnp

    return jnp.sum(dev, axis=0)
