"""CvxCluster solver arm: ONE jitted full-fleet convex relaxation of the
ask×node assignment, behind `solver.pack=cvx` (and `solver.policy=all`).

The pack solver (ops/pack_solve.py) bounds its dense relaxation state with
POP-style random partitioning: K disjoint subproblems, each solved blind to
the others. CvxCluster (arXiv 2605.01614) is the opposite bet — granular
allocation problems solve fastest AND best as one relaxed convex program
over the whole fleet, because the relaxation is what removes the
combinatorial coupling, not the partition. This module is that arm: a
projected-gradient primal-dual solve over the FULL [N, M] soft assignment,
every trip count compile-time static, rounded through the greedy solver's
own accept machinery so feasibility is greedy feasibility by construction.

The relaxed program is the same packing LP the partitioned arm optimizes —
maximize Σ x_ij·v_i (v = capacity-normalized request mass) subject to
per-node-per-resource capacity, x row-stochastic-or-less — solved here by a
fixed `lax.fori_loop` of primal-dual steps:

  primal      gradient ascent on the priced objective: X += η_p·(v − ⟨req,λ⟩
              + score tiebreak), then projection onto the feasible box —
              per-row simplex cap {x ≥ 0, Σ_m x ≤ 1} by bisection on the
              simplex threshold (a FIXED bisection trip count; the standard
              sort-based projection would cost an [N, M] sort per step).
  gang        all-or-nothing coupling as a projection: a constraint group's
              pods are capped toward the group's minimum placed mass
              (segment-min over the group axis) — a gang member the prices
              squeezed out pulls its siblings' mass down with it, instead of
              the group half-placing. Applied as a soft blend so one
              unplaceable straggler dims its group rather than zeroing it;
              the rounding accept + greedy repair make the final call.
  capacity    per-node downscale to the capacity box (load ≤ free per
              resource), and dual ascent λ += η_d·overload⁺ on the relative
              overload — prices make contended nodes expensive exactly like
              the partitioned LP, but over the whole fleet at once.

The LEARNED-DUAL variant (solver.policy=all wiring, DOPPLER-style) warm
starts λ from the round-17 two-tower scorer: nodes the policy scores BELOW
the demand-weighted fleet mean start with a positive price, so the first
primal steps water-fill the policy's preferred nodes first. An untrained or
garbage-zero checkpoint embeds every pod to the zero vector, the per-node
score is identically 0, and the warm start is exactly the cold λ = 0 — the
untrained-is-inert contract extends to the dual. A BAD warm start can only
cost iterations (the dual ascent re-prices within the fixed budget) and
therefore packed units — the duel then keeps the incumbent; it can never
admit an infeasible plan, because rounding + repair never trust X.

Rounding reuses `pack_solve._round_part` verbatim over the full node set
(Gumbel-max proposals ∝ the relaxation's reduced costs + log soft-assignment
mass, per-node-segment prefix accept, best-fit-decreasing), and leftovers
run the unmodified greedy round loop (`ops.assign._solve_rounds`) — so
every placement clears the exact feasibility arithmetic greedy placements
do, and `free_after >= min(free, 0)` holds structurally. The core still
re-checks before committing (cvx_plans_total{outcome=infeasible}).

Scope gates mirror pack: locality batches and host-port batches raise
CvxUnsupported (greedy keeps the cycle); shapes whose dense [N, M] state
exceeds the cell budget are not cvx-solvable (the partitioned arm exists
precisely for those). Sharded-mesh dispatch lives in
`parallel.mesh.cvx_solve_sharded` (node-dim GSPMD sharding — X, feas and
soft all shard along M, the fleet axis).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from yunikorn_tpu.models.policies import node_base_scores
from yunikorn_tpu.ops.assign import (
    _hoist_group_state,
    _solve_rounds,
    _topo_node_adj,
    prepare_solve_args,
)
from yunikorn_tpu.ops.pack_solve import _LAM_MAX, _round_part

# fixed iteration counts: the compiled program's cost is bounded no matter
# what the trace looks like (the tentpole contract — never data-dependent)
CVX_ITERS = 24         # primal-dual steps over the full fleet
CVX_ROUND_ROUNDS = 4   # seeded rounding accept rounds
CVX_REPAIR_ROUNDS = 8  # greedy rounds for what the rounding stranded
_PROJ_BISECT = 12      # bisection steps of the row-simplex projection
                       # (threshold resolved to 2^-12 of the mass scale)

# step sizes: utilities are O(1) (v is a sum of column-normalized requests,
# base scores ∈ [0, 1]); η_p must move a row to O(1) mass inside CVX_ITERS
_ETA_P = 0.35          # primal step on the priced gradient
_ETA_D = 0.5           # dual step on relative overload (pack's _LP_ETA)
_GANG_W = 0.5          # gang-projection blend: 1 = hard min-coupling
_MASS_W = 0.5          # weight of log(X) in the rounding scores
_MASS_EPS = 1e-4       # floor under the log (zero-mass cells stay finite
                       # but ~unsampleable under the Gumbel temperature)
_DUAL_W = 4.0          # learned warm-start price scale (≤ _LAM_MAX/16:
                       # a wrong prior must stay erasable by the ascent)

# full-fleet cell budget: ONE dense [N, M] f32 buffer per loop temp (X, u,
# feas, soft) — 1<<25 cells = 128 MiB f32. Covers every standard bucket up
# to 4096 pods × 8192 nodes / 2048 pods × 16384 nodes; beyond that the
# partitioned pack arm is the right tool and the core's gate skips cvx.
_CVX_CELL_BUDGET = 1 << 25


class CvxUnsupported(Exception):
    """This batch (or shape) is outside the full-fleet convex model; the
    caller must keep the greedy plan (and the partitioned pack arm, when
    on) for the cycle."""


def cvx_shape_supported(n_pods: int, n_nodes: int) -> bool:
    """Whether a (padded pods, node capacity) shape fits the dense [N, M]
    relaxation state. Deterministic in the shape alone — the core pre-gates
    on this BEFORE the supervised dispatch, like pack's shape gate."""
    if n_pods < 1 or n_nodes < 1:
        return False
    return n_pods * n_nodes <= _CVX_CELL_BUDGET


@dataclasses.dataclass
class CvxResult:
    assigned: jnp.ndarray      # [N] int32 node row, -1 unassigned
    free_after: jnp.ndarray    # [M, R] int32
    # bool scalar: every cell of free_after >= min(initial free, 0)
    feasible: jnp.ndarray
    iters: int
    seed: int
    learned_dual: bool = False

    def block_until_ready(self):
        self.assigned.block_until_ready()
        return self


def _project_rows(x, ok, bisect_iters: int = _PROJ_BISECT):
    """Project each row of x onto {p : p >= 0, sum(p) <= 1, p[~ok] = 0}.

    Euclidean projection onto the capped simplex: p = max(x − τ, 0) with
    τ = 0 when Σ max(x, 0) ≤ 1, else the water level where the thresholded
    mass hits exactly 1. τ lives in [rowmax − 1, rowmax] (at τ = rowmax the
    mass is 0; at rowmax − 1 the max element alone contributes 1), resolved
    by a FIXED bisection trip count — the sort-free form, O(M) per step."""
    x = jnp.where(ok, x, 0.0)
    relu_sum = jnp.sum(jnp.maximum(x, 0.0), axis=1, keepdims=True)  # [N, 1]
    rowmax = jnp.max(jnp.where(ok, x, 0.0), axis=1, keepdims=True)

    def body(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        mass = jnp.sum(jnp.maximum(x - mid, 0.0) * ok, axis=1,
                       keepdims=True)
        return (jnp.where(mass > 1.0, mid, lo),
                jnp.where(mass > 1.0, hi, mid))

    lo, hi = lax.fori_loop(0, bisect_iters, body,
                           (rowmax - 1.0, rowmax))
    tau = jnp.where(relu_sum > 1.0, 0.5 * (lo + hi), 0.0)
    return jnp.maximum(x - tau, 0.0) * ok


def _learned_dual_init(params, req, free, capacity, valid, v,
                       score_cols: int, R: int):
    """DOPPLER-style warm start: λ0 from the two-tower scorer's per-node
    scores. Nodes scoring below the demand-weighted fleet mean start with a
    positive price (the policy says "fill these last"); preferred nodes
    start free. Broadcast over the resource axis — the prior is about node
    desirability, not any one resource. Zero/untrained params → per-node
    score identically 0 → λ0 exactly 0 (the cold start)."""
    from yunikorn_tpu.policy import features as _pf
    from yunikorn_tpu.policy import net as _pnet

    sc = score_cols if score_cols > 0 else R
    inv_sc = _pf.inv_capacity_scale(capacity[:, :sc])
    pod_emb = _pnet.pod_tower(params, _pf.pod_features(req[:, :sc], inv_sc))
    node_emb = _pnet.node_tower(
        params, _pf.node_features(free[:, :sc], capacity[:, :sc], inv_sc))
    w = v * valid.astype(jnp.float32)                           # [N]
    pe = (w @ pod_emb) / jnp.maximum(jnp.sum(w), 1e-6)          # [E]
    s = node_emb @ pe                                           # [M]
    lam0 = _DUAL_W * jnp.maximum(jnp.mean(s) - s, 0.0)
    return jnp.broadcast_to(lam0[:, None], (free.shape[0], R))


@functools.partial(
    jax.jit,
    static_argnames=("iters", "round_rounds", "repair_rounds", "chunk",
                     "policy", "score_cols"),
)
def cvx_solve(
    req, group_id, rank, valid,
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
    g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
    node_labels, node_taints, node_taints_soft, node_ports, node_ok,
    free, capacity, host_group_mask=None, host_group_soft=None, loc=None,
    topo=None,
    seed=0,
    learned=None,
    *,
    iters: int = CVX_ITERS,
    round_rounds: int = CVX_ROUND_ROUNDS,
    repair_rounds: int = CVX_REPAIR_ROUNDS,
    chunk: int = 512,
    policy: str = "binpacking",
    score_cols: int = 0,
):
    """One full-fleet convex solve. Positional args mirror `ops.assign.solve`
    (the prepare_solve_args tuple) so the arms cannot drift on arg prep;
    `seed` is a traced int32 (reseeding never recompiles); `learned` is the
    two-tower params pytree or None (treedef keys the compiled variant, the
    checkpoint hash keys the AOT fingerprint via the caller's extra).
    Returns (assigned [N] i32, free_after [M, R] i32, feasible bool)."""
    if loc is not None:
        raise CvxUnsupported("locality batches take the greedy path")
    N, R = req.shape
    M = free.shape[0]
    sc = score_cols if score_cols > 0 else R

    group_feas, group_soft = _hoist_group_state(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
        node_labels, node_taints, node_taints_soft, node_ports, node_ok,
        host_group_mask, host_group_soft)
    if topo is not None:
        # same node-level contention/empty-domain term as the greedy and
        # pack objectives — the relaxation optimizes what the fleet runs
        group_soft = group_soft + _topo_node_adj(topo)[None, :]
    G = group_feas.shape[0]

    # the ONE place this module materializes [N, M]: the relaxation state
    # (the cell budget exists for exactly these)
    feas = group_feas[group_id]                                 # [N, M]
    soft = group_soft[group_id]                                 # [N, M]
    ok = feas & valid[:, None]

    # column normalization, identical to pack: prices and loads compare
    # per-resource magnitudes spanning orders of magnitude (milliCPU vs
    # bytes) — normalize by the mean node capacity
    inv_scale = 1.0 / jnp.maximum(
        jnp.mean(capacity.astype(jnp.float32), axis=0), 1.0)    # [R]
    req_f = req.astype(jnp.float32) * inv_scale[None, :]        # [N, R]
    free_f = jnp.maximum(free, 0).astype(jnp.float32) \
        * inv_scale[None, :]                                    # [M, R]
    v = jnp.sum(req_f, axis=1)                                  # [N] value
    base = node_base_scores(free[:, :sc], capacity[:, :sc], policy)
    tie = 0.05 * (base[None, :] + soft)

    lam0 = (jnp.zeros((M, R), jnp.float32) if learned is None
            else _learned_dual_init(learned, req, free, capacity, valid, v,
                                    score_cols, R))

    okf = ok.astype(jnp.float32)

    def body(_, state):
        X, lam = state
        u = v[:, None] - req_f @ lam.T + tie                    # [N, M]
        X = _project_rows(X + _ETA_P * u, okf)
        # gang projection: pull every member toward the group's minimum
        # placed mass (invalid pods must not drag the min — they carry no
        # mass by construction, so they are filled past any real mass)
        mass = jnp.sum(X, axis=1)                               # [N]
        gmass = jnp.where(valid, mass, 2.0)
        gmin = jnp.minimum(
            jax.ops.segment_min(gmass, group_id, num_segments=G,
                                indices_are_sorted=False), 1.0)  # [G]
        gang = jnp.minimum(gmin[group_id] / jnp.maximum(mass, 1e-6), 1.0)
        X = X * ((1.0 - _GANG_W) + _GANG_W * gang)[:, None]
        # capacity projection + dual ascent: the PRE-projection load drives
        # the prices (the overload signal), the projection keeps the primal
        # iterate inside the capacity box between steps
        load = X.T @ req_f                                      # [M, R]
        shrink = jnp.min(
            jnp.where(load > free_f,
                      free_f / jnp.maximum(load, 1e-6), 1.0), axis=1)
        X = X * shrink[None, :]
        over = (load - free_f) / jnp.maximum(free_f, 1e-3)
        lam = jnp.clip(lam + _ETA_D * over, 0.0, _LAM_MAX)
        return X, lam

    X, lam = lax.fori_loop(
        0, iters, body, (jnp.zeros((N, M), jnp.float32), lam0))

    # rounding scores: the final reduced costs (pack's proven recipe — the
    # prices are what stay fixed across rounds, base re-scores live) plus
    # the primal mass as a log-bonus — the rounding samples in proportion
    # to where the relaxation actually put assignment mass
    scores = (v[:, None] - req_f @ lam.T + 0.05 * soft
              + _MASS_W * jnp.log(X + _MASS_EPS))
    assigned, free_left = _round_part(
        req, rank, valid, feas, scores, free, capacity, v,
        jax.random.PRNGKey(seed), round_rounds, policy, sc)

    # repair: asks the rounding stranded run the unmodified greedy round
    # loop with the residual capacity — the proof-by-construction that cvx
    # feasibility is exactly greedy feasibility
    leftover = valid & (assigned < 0)
    rep_assigned, _, free_after, _, _ = _solve_rounds(
        req, group_id, rank, leftover, group_feas, group_soft, free_left,
        jnp.zeros((1, 1), jnp.int32), capacity, None, None,
        max_rounds=repair_rounds, chunk=min(chunk, N), policy=policy,
        use_pallas=False, pallas_interpret=False, has_loc_soft=False,
        pallas_soft=False, score_cols=score_cols)
    assigned = jnp.where(assigned >= 0, assigned, rep_assigned)
    feasible = jnp.all(free_after >= jnp.minimum(free, 0))
    return assigned, free_after, feasible


def cvx_solve_batch(batch, node_arrays, *, policy: str = "binpacking",
                    free_delta=None, node_mask=None, ports_delta=None,
                    seed: int = 0, iters: int = CVX_ITERS,
                    round_rounds: int = CVX_ROUND_ROUNDS,
                    repair_rounds: int = CVX_REPAIR_ROUNDS,
                    chunk: int = 512, device_state=None,
                    aot_pending: bool = False, learned=None,
                    aot_extra: tuple = (),
                    compile_only: bool = False) -> "CvxResult | None":
    """Host wrapper: PodBatch + NodeArrays in → async CvxResult out.

    Shares `prepare_solve_args` with the greedy/pack paths (same dtype
    views, same in-flight free/ports overlays, same node masking) so the
    cvx arm can never see different cluster state than the plans it duels.
    learned: the two-tower params pytree for the warm-started dual (pass
    aot_extra=("policy", ckpt_hash) with it — a checkpoint swap must never
    serve a stale compiled executable). Raises CvxUnsupported for batches
    outside the model (locality, host ports, over-budget shapes).
    compile_only=True builds/loads the executable and returns None (the
    prewarm path)."""
    if batch.locality is not None:
        raise CvxUnsupported("locality batches take the greedy path")
    if batch.g_ports.view(np.uint32).any():
        raise CvxUnsupported("host-port batches take the greedy path")
    np_args, static_kwargs = prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        allow_req_device=device_state is not None)
    from yunikorn_tpu.ops.assign import SOLVE_ARG_NAMES

    N = np_args[SOLVE_ARG_NAMES.index("req")].shape[0]
    M = np_args[SOLVE_ARG_NAMES.index("free")].shape[0]
    if not cvx_shape_supported(N, M):
        raise CvxUnsupported(
            f"shape ({N} pods, {M} nodes) exceeds the full-fleet cell "
            "budget (the partitioned pack arm covers it)")
    solve_args = jax.tree_util.tree_map(jnp.asarray, np_args)
    learned_arg = (None if learned is None
                   else jax.tree_util.tree_map(jnp.asarray, learned))
    from yunikorn_tpu.aot import runtime as aot_rt

    call_args = (*solve_args, jnp.int32(seed), learned_arg)
    call_statics = dict(iters=iters, round_rounds=round_rounds,
                        repair_rounds=repair_rounds, chunk=chunk,
                        policy=policy,
                        score_cols=static_kwargs["score_cols"])
    if compile_only:
        aot_rt.aot_compile("cvx.solve", cvx_solve, call_args, call_statics,
                           extra=aot_extra)
        return None
    assigned, free_after, feasible = aot_rt.aot_call(
        "cvx.solve", cvx_solve, call_args, call_statics,
        pending_ok=aot_pending, extra=aot_extra)
    return CvxResult(assigned=assigned, free_after=free_after,
                     feasible=feasible, iters=iters, seed=seed,
                     learned_dual=learned is not None)


def jit_cache_entries() -> int:
    """Compiled-variant count of the cvx entry point (compile-vs-cache-hit
    telemetry, the ops.assign.jit_cache_entries convention)."""
    from yunikorn_tpu.aot import runtime as aot_rt

    try:
        return cvx_solve._cache_size() + aot_rt.compile_count("cvx.")
    except Exception:
        return -1
