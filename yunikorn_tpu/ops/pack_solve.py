"""Globally-optimal device packing: a jitted LP/ADMM relaxation of the
ask×node assignment with POP-style partitioning, behind `solver.policy=optimal`.

The production solve (ops/assign.py) is a rank-ordered greedy argmin: fast,
conflict-free, but myopic — under fragmentation and priority skew it strands
capacity a global view would pack (the first open ROADMAP item). CvxCluster
(arXiv 2605.01614) shows granular allocation problems of exactly this shape
solve orders of magnitude faster as relaxed convex programs; POP (arXiv
2110.11927) shows partitioning a granular allocation problem into fixed-shape
random subproblems keeps quality within a few percent of the full solve while
bounding the problem size. On this codebase the bound is what matters twice
over: it caps the dense [n, m] relaxation state a part materializes AND pins
every compiled XLA program to a standard bucket (docs/PERF.md compile-cost
findings — unbounded shapes mean unbounded compiles).

The solve is three fixed-shape stages inside ONE jitted program:

  partition   seeded `jax.random.permutation` of asks and nodes, reshaped to
              K equal parts (POP's random partitioning). Node parts are
              DISJOINT, so subproblems commit capacity independently — no
              cross-part conflict resolution is ever needed.
  relax       per part, a dual-decomposition LP relaxation (the ADMM/dual
              ascent family): per-node-per-resource prices λ start at zero;
              each of `lp_iters` fixed `lax.fori_loop` steps computes every
              ask's reduced-cost utility  u = score − ⟨req, λ⟩  over the
              part's nodes, relaxes the integral assignment to a softmax
              x ∈ [0,1]^{n×m}, and ascends λ on the aggregate overload
              (Σ_i x_i·req − free)⁺. Prices make contended nodes expensive,
              steering the fractional mass toward a globally packed solution
              instead of the greedy's per-ask argmax.
  round+repair  seeded randomized rounding (deterministic per seed) through
              the greedy solver's OWN accept machinery: each round samples
              every ask a node from its relaxed assignment distribution
              (Gumbel-max over reduced costs — proposals spread across
              nodes in proportion to the LP's fractional mass instead of
              herding onto one argmax node), masks to
              `group_feasibility`-screened nodes that FIT, lexsorts by
              (node, size desc, rank) and accepts the per-node-segment
              prefix that fits (`ops.assign._segment_prefix_accept`),
              best-fit-decreasing inside each segment. Asks the partition
              strands (their part's capacity exhausted) then run through
              the unmodified greedy round loop (`ops.assign._solve_rounds`)
              over the FULL node set with the parts' residual capacity — so
              a bad random cut never costs placements, and every placement
              goes through the exact same feasibility masks and prefix-fit
              arithmetic greedy placements do. Infeasible output is
              impossible by construction; the core still re-checks
              `free_after >= 0` before committing (belt and braces,
              `pack_plans_total{outcome=infeasible}`).

Scope (explicitly gated by the core, not silently mis-handled): batches with
locality constraints or host-port requests fall back to greedy for the
cycle — PackUnsupported names the reason. Mesh-sharded cycles pack too
since round 15 (`parallel.mesh.pack_solve_sharded` + the mesh-aligned
`partitioner="topo"` mode below). The differential contract with greedy is
pinned by tests/test_pack_solve.py and enforced at runtime by the core's
choose_plan comparison: the pack plan commits only when its packed objective
beats the greedy plan's, otherwise the cycle falls back (the
gateVerify/preempt-parity mold).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from yunikorn_tpu.models.policies import node_base_scores
from yunikorn_tpu.ops.assign import (
    NEG_INF,
    _hoist_group_state,
    _segment_prefix_accept,
    _solve_rounds,
    _topo_node_adj,
    prepare_solve_args,
)

# fixed iteration counts: every trip count is static by construction, so the
# compiled program's cost is bounded no matter what the trace looks like
LP_ITERS = 24          # dual-ascent steps per part
ROUND_ROUNDS = 4       # rounding accept rounds per part
REPAIR_ROUNDS = 8      # greedy rounds over the full node set for leftovers

# price-ascent tuning: utilities are O(1) (node_base_scores ∈ [0,1] + small
# soft adjustments), requests/free are normalized per resource column
_LP_ETA = 0.5          # dual step on relative overload
_LP_INV_TAU = 8.0      # softmax sharpness of the relaxed assignment
_LAM_MAX = 64.0        # price clip (keeps reduced costs finite/orderable)
_MASK_FILL = -1.0e9    # finite -inf for masked softmax rows

# partition sizing: smallest power-of-two K whose parts keep the dense
# relaxation state under the cell budget, subject to floors that keep a part
# a meaningful packing problem
_CELL_BUDGET = 1 << 22     # max n*m f32 cells a part may materialize (16 MiB)
_MIN_PART_PODS = 64
_MIN_PART_NODES = 16
MAX_PARTS = 16


class PackUnsupported(Exception):
    """This batch (or runtime mode) is outside the pack solver's model; the
    caller must keep the greedy plan for the cycle."""


def pick_parts(n_pods: int, n_nodes: int, n_shards: int = 1) -> int:
    """Standard partition-count bucket for a (pods, nodes) shape.

    Deterministic in the shape alone, so every compiled program variant is
    keyed by the same standard buckets the encoder already pads to.
    n_shards (the mesh-aligned topology mode): the part count is floored at
    the GSPMD shard count — each device shard then holds a whole number of
    parts, so part boundaries land on shard boundaries and every part's
    relaxation state is chip-local under the static node sharding."""
    k = 1
    while (k < MAX_PARTS
           and n_pods % (2 * k) == 0 and n_nodes % (2 * k) == 0
           and n_pods // (2 * k) >= _MIN_PART_PODS
           and n_nodes // (2 * k) >= _MIN_PART_NODES
           and (n_pods // k) * (n_nodes // k) > _CELL_BUDGET):
        k *= 2
    while (k < n_shards
           and n_pods % (2 * k) == 0 and n_nodes % (2 * k) == 0):
        k *= 2
    return k


def shape_supported(n_pods: int, n_nodes: int, n_shards: int = 1) -> bool:
    """Whether a (padded pods, node capacity) shape is packable: non-empty
    and partitionable within the cell budget (and, for the mesh-aligned
    mode, into at least one whole part per shard). The core pre-gates on
    this BEFORE the supervised dispatch — a deterministic scope gate must
    skip cheaply, not ride the supervisor's transient-retry/breaker
    machinery."""
    if n_pods < 1 or n_nodes < 1:
        return False
    k = pick_parts(n_pods, n_nodes, n_shards)
    if k < n_shards or k % max(n_shards, 1) != 0:
        return False
    return (n_pods // k) * (n_nodes // k) <= 4 * _CELL_BUDGET


@dataclasses.dataclass
class PackResult:
    assigned: jnp.ndarray      # [N] int32 node row, -1 unassigned
    free_after: jnp.ndarray    # [M, R] int32
    # bool scalar: every cell of free_after >= min(initial free, 0) — the
    # plan never over-commits beyond pre-existing overlay negativity
    feasible: jnp.ndarray
    n_parts: int
    seed: int
    partitioner: str = "random"

    def block_until_ready(self):
        self.assigned.block_until_ready()
        return self


def _relax_part(preq_f, feas, pvalid, base, soft, free_f, lp_iters: int):
    """Dual-decomposition LP relaxation for one part.

    The relaxed program is the packing LP itself — maximize the total
    normalized units placed, Σ x_ij·v_i with v_i = Σ_r req_f[i,r], subject
    to per-node-per-resource capacity — solved by dual ascent: prices λ[m,R]
    rise on overloaded (node, resource) pairs, each ask's mass moves by a
    softmax over reduced costs  u = v − ⟨req, λ⟩ (+ a small score tiebreak)
    across its feasible nodes AND an always-feasible null column of utility
    0, so an ask whose value the prices no longer cover drops out instead of
    crowding a constrained node (the knapsack-LP optimality condition).

    preq_f [n, R] and free_f [m, R] are column-normalized f32; returns the
    final reduced-cost score matrix s [n, m] (higher = prefer)."""
    n = preq_f.shape[0]
    m, R = free_f.shape
    ok = feas & pvalid[:, None]
    v = jnp.sum(preq_f, axis=1)                                # [n] value
    tiebreak = 0.05 * (base[None, :] + soft)

    def reduced(lam):
        return v[:, None] - preq_f @ lam.T + tiebreak          # [n, m]

    def body(_, lam):
        u = jnp.where(ok, reduced(lam), _MASK_FILL)
        u_aug = jnp.concatenate([u, jnp.zeros((n, 1), jnp.float32)], axis=1)
        x = jax.nn.softmax(u_aug * _LP_INV_TAU, axis=1)[:, :m]
        x = jnp.where(ok, x, 0.0)
        load = x.T @ preq_f                                    # [m, R]
        over = (load - free_f) / jnp.maximum(free_f, 1e-3)
        return jnp.clip(lam + _LP_ETA * over, 0.0, _LAM_MAX)

    lam = lax.fori_loop(0, lp_iters, body, jnp.zeros((m, R), jnp.float32))
    # the base half of the tiebreak stays OUT of the returned scores: the
    # rounding re-scores base from its CURRENT free capacity each round,
    # and a stale dispatch-time base would keep proposals herding onto
    # already-drained nodes; the node-static soft preferences stay in
    return v[:, None] - preq_f @ lam.T + 0.05 * soft


def _round_part(preq, prank, pvalid, feas, scores, nfree, ncap, size_key,
                key, rounds: int, policy: str, sc_cols: int):
    """Randomized rounding for one part, seeded and deterministic: each
    round samples every ask a node from its relaxed assignment distribution
    (Gumbel-max over the reduced costs — proposals land across nodes in
    proportion to the LP's fractional mass instead of herding onto one
    argmax node; see ops/assign._water_fill_proposals for the herding
    failure), then accepts through the greedy solver's per-node-segment
    prefix-fit — identical feasibility arithmetic. Within a node segment
    acceptance runs LARGEST-FIRST (best-fit-decreasing, rank as the
    tie-break): BFD's packing guarantee needs the big asks placed before
    small ones fill the gaps. The per-round base score is refreshed from
    the CURRENT free capacity (the LP prices are what stay fixed)."""
    n, R = preq.shape
    m = nfree.shape[0]
    init = (nfree, ~pvalid, jnp.full((n,), -1, jnp.int32))

    def body(i, state):
        cur, done, assigned = state
        margin = jnp.full((n, m), jnp.int32(2**30))
        for r in range(R):                       # static unroll, like greedy
            margin = jnp.minimum(margin,
                                 cur[:, r][None, :] - preq[:, r][:, None])
        ok = feas & (margin >= 0)
        base_now = node_base_scores(cur[:, :sc_cols], ncap[:, :sc_cols],
                                    policy)
        u = (scores + 0.05 * base_now[None, :]) * _LP_INV_TAU
        gumbel = jax.random.gumbel(jax.random.fold_in(key, i), (n, m))
        sc = jnp.where(ok, u + gumbel, NEG_INF)
        best = jnp.argmax(sc, axis=1).astype(jnp.int32)
        cand = (~done) & jnp.any(ok, axis=1)
        node_key = jnp.where(cand, best, m)
        order = jnp.lexsort((prank, -size_key, node_key))
        snode = node_key[order]
        sreq = preq[order]
        accept_sorted = _segment_prefix_accept(snode, sreq, cur, m)
        delta = jnp.where(accept_sorted[:, None], sreq, 0)
        cur = cur.at[jnp.clip(snode, 0, m - 1)].add(-delta)
        accepted = jnp.zeros((n,), bool).at[order].set(accept_sorted)
        assigned = jnp.where(accepted, best, assigned)
        return cur, done | accepted, assigned

    free_left, _, assigned = lax.fori_loop(0, rounds, body, init)
    return assigned, free_left


@functools.partial(
    jax.jit,
    static_argnames=("n_parts", "partitioner", "n_shards", "lp_iters",
                     "round_rounds", "repair_rounds", "chunk", "policy",
                     "score_cols"),
)
def pack_solve(
    req, group_id, rank, valid,
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
    g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
    node_labels, node_taints, node_taints_soft, node_ports, node_ok,
    free, capacity, host_group_mask=None, host_group_soft=None, loc=None,
    topo=None,
    seed=0,
    *,
    n_parts: int,
    partitioner: str = "random",
    n_shards: int = 1,
    lp_iters: int = LP_ITERS,
    round_rounds: int = ROUND_ROUNDS,
    repair_rounds: int = REPAIR_ROUNDS,
    chunk: int = 512,
    policy: str = "binpacking",
    score_cols: int = 0,
):
    """One global pack solve. Positional args mirror `ops.assign.solve` (the
    prepare_solve_args tuple, including the topology steering tuple) so the
    two paths cannot drift on arg prep; `seed` is a traced int32 so
    reseeding never recompiles. Returns (assigned [N] i32, free_after
    [M, R] i32, feasible bool).

    partitioner="topo" is the mesh-aligned ICI-domain partitioner: instead
    of POP's random node permutation, nodes are ordered by (GSPMD shard,
    ICI domain, row) and cut into K equal parts — part boundaries land on
    domain boundaries wherever the domain layout allows, and (with
    n_shards > 1) always on shard boundaries, so a sharded mesh solves
    whole parts chip-locally instead of fighting the static node sharding
    (`parallel.mesh.PACK_SHARDED_SUPPORTED`). The node order is a traced
    function of node_dom — deterministic per input, part count still keyed
    only on the bucketed shape, parts still disjoint by construction.
    Unlabeled fleets degrade to (shard, row) order, which is exactly the
    shard-aligned identity cut. Pod partitioning stays the seeded random
    permutation in both modes (POP's ask-side variance reduction)."""
    if loc is not None:
        raise PackUnsupported("locality batches take the greedy path")
    N, R = req.shape
    M = free.shape[0]
    K = n_parts
    n, m = N // K, M // K
    sc = score_cols if score_cols > 0 else R

    group_feas, group_soft = _hoist_group_state(
        g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid,
        g_tol, g_ports, g_pref_req, g_pref_forb, g_pref_weight,
        node_labels, node_taints, node_taints_soft, node_ports, node_ok,
        host_group_mask, host_group_soft)
    if topo is not None:
        # same node-level contention/empty-domain term as the greedy solve
        # — the pack LP then optimizes the same contention-aware objective.
        # Per-gang domain steering stays a greedy-proposal concern: pack's
        # seeded Gumbel rounding has no proposal stage to override, and
        # choose_plan keeps the steered greedy plan as the floor.
        group_soft = group_soft + _topo_node_adj(topo)[None, :]

    # column normalization for the relaxation: prices and loads compare
    # per-resource magnitudes, which span orders of magnitude across vocab
    # columns (milliCPU vs bytes) — normalize by the mean node capacity
    inv_scale = 1.0 / jnp.maximum(
        jnp.mean(capacity.astype(jnp.float32), axis=0), 1.0)       # [R]

    kp, kn, kr = jax.random.split(jax.random.PRNGKey(seed), 3)
    pods_part = jax.random.permutation(kp, N).reshape(K, n)
    if partitioner == "topo":
        node_dom = (topo[0] if topo is not None
                    else jnp.full((M,), -1, jnp.int32))
        idx_m = jnp.arange(M, dtype=jnp.int32)
        shard_id = idx_m // jnp.int32(M // max(n_shards, 1))
        # unlabeled nodes sort after every labeled domain within their shard
        dom_key = jnp.where(node_dom >= 0, node_dom, jnp.int32(2**30))
        order = jnp.lexsort((idx_m, dom_key, shard_id))
        nodes_part = order.astype(jnp.int32).reshape(K, m)
    else:
        nodes_part = jax.random.permutation(kn, M).reshape(K, m)
    part_keys = jax.random.split(kr, K)

    def solve_part(x):
        pod_idx, node_idx, part_key = x
        preq = req[pod_idx]                                        # [n, R]
        pgid = group_id[pod_idx]
        prank = rank[pod_idx]
        pvalid = valid[pod_idx]
        # RAW free through the fit/accept machinery: the in-flight overlay
        # may drive a column negative, and greedy's fit refuses such nodes
        # even for zero-request columns — clamping would let pack place
        # where greedy-side feasibility rejects. Only the LP's price state
        # clamps (prices need non-negative capacity).
        nfree = free[node_idx]                                     # [m, R]
        ncap = capacity[node_idx]
        feas = group_feas[pgid][:, node_idx]                       # [n, m]
        soft = group_soft[pgid][:, node_idx]
        base = node_base_scores(nfree[:, :sc], ncap[:, :sc], policy)
        preq_f = preq.astype(jnp.float32) * inv_scale[None, :]
        free_f = jnp.maximum(nfree, 0).astype(jnp.float32) \
            * inv_scale[None, :]
        scores = _relax_part(preq_f, feas, pvalid, base, soft, free_f,
                             lp_iters)
        local, free_left = _round_part(preq, prank, pvalid, feas, scores,
                                       nfree, ncap,
                                       jnp.sum(preq_f, axis=1), part_key,
                                       round_rounds, policy, sc)
        node_global = jnp.where(
            local >= 0, node_idx[jnp.clip(local, 0, m - 1)], -1)
        return node_global.astype(jnp.int32), free_left

    # lax.map = sequential over parts: peak memory is ONE part's [n, m]
    # relaxation state, the POP bound the partition count was chosen for
    assigned_parts, free_parts = lax.map(solve_part,
                                         (pods_part, nodes_part, part_keys))

    # un-permute via inverse-permutation GATHERS, not scatters: both index
    # vectors are permutations, so vals[argsort(perm)] is exactly the
    # scatter out[perm[i]] = vals[i] — and gathers partition cleanly under
    # GSPMD where the equivalent scatter was observed to drop rows on the
    # sharded CPU mesh (pinned by the round-15 sharded-pack parity test)
    assigned = assigned_parts.reshape(N)[jnp.argsort(pods_part.reshape(N))]
    free_after = free_parts.reshape(M, R)[jnp.argsort(nodes_part.reshape(M))]

    # repair: asks the partition stranded run the unmodified greedy round
    # loop over the FULL node set with the parts' residual capacity — the
    # "per-subproblem fallback" that keeps a bad random cut from costing
    # placements (and the proof-by-construction that pack feasibility is
    # exactly greedy feasibility)
    leftover = valid & (assigned < 0)
    rep_assigned, _, free_after, _, _ = _solve_rounds(
        req, group_id, rank, leftover, group_feas, group_soft, free_after,
        jnp.zeros((1, 1), jnp.int32), capacity, None, None,
        max_rounds=repair_rounds, chunk=min(chunk, N), policy=policy,
        use_pallas=False, pallas_interpret=False, has_loc_soft=False,
        pallas_soft=False, score_cols=score_cols)
    assigned = jnp.where(assigned >= 0, assigned, rep_assigned)
    # structural feasibility: placements only subtract what fits, so every
    # cell must sit at or above min(initial free, 0) — a pre-existing
    # negative column stays untouched, a non-negative one stays
    # non-negative. The core refuses the plan when this is ever False.
    feasible = jnp.all(free_after >= jnp.minimum(free, 0))
    return assigned, free_after, feasible


def pack_solve_batch(batch, node_arrays, *, policy: str = "binpacking",
                     free_delta=None, node_mask=None, ports_delta=None,
                     seed: int = 0, lp_iters: int = LP_ITERS,
                     round_rounds: int = ROUND_ROUNDS,
                     repair_rounds: int = REPAIR_ROUNDS,
                     chunk: int = 512, device_state=None,
                     aot_pending: bool = False,
                     partitioner: Optional[str] = None) -> PackResult:
    """Host wrapper: PodBatch + NodeArrays in → async PackResult out.

    Shares `prepare_solve_args` with the greedy paths (same dtype views,
    same in-flight free/ports overlays, same node masking) so the pack
    solver can never see different cluster state than the greedy solve it
    is compared against. device_state: the persistent device mirror the
    greedy dispatch used this cycle (read-only reuse — node tensors and the
    row-store req gather then transfer O(changed), not O(M)+O(N·R), per
    optimal cycle). Raises PackUnsupported for batches outside the model
    (locality, host ports, non-bucketed shapes).

    partitioner: None resolves to "topo" (the mesh-aligned ICI-domain
    partitioner) when the batch carries topology steering args, else
    "random" (POP's seeded permutation). Sharded-mesh dispatch lives in
    `parallel.mesh.pack_solve_sharded`, which forces "topo"."""
    if batch.locality is not None:
        raise PackUnsupported("locality batches take the greedy path")
    if batch.g_ports.view(np.uint32).any():
        raise PackUnsupported("host-port batches take the greedy path")
    np_args, static_kwargs = prepare_solve_args(
        batch, node_arrays, free_delta=free_delta, node_mask=node_mask,
        ports_delta=ports_delta, device_state=device_state,
        allow_req_device=device_state is not None)
    from yunikorn_tpu.ops.assign import SOLVE_ARG_NAMES

    N = np_args[SOLVE_ARG_NAMES.index("req")].shape[0]
    M = np_args[SOLVE_ARG_NAMES.index("free")].shape[0]
    if not shape_supported(N, M):
        # empty, or a non-bucketed shape the partitioner cannot split
        # (production shapes are power-of-two buckets and always split)
        raise PackUnsupported(
            f"shape ({N} pods, {M} nodes) is not packable within the "
            "partitionable cell budget")
    n_parts = pick_parts(N, M)
    if partitioner is None:
        partitioner = ("topo"
                       if np_args[SOLVE_ARG_NAMES.index("topo")] is not None
                       else "random")
    solve_args = jax.tree_util.tree_map(jnp.asarray, np_args)
    from yunikorn_tpu.aot import runtime as aot_rt

    # seed rides positionally (it is a traced int32, reseeding never
    # recompiles — the AOT fingerprint keys scalar leaves on dtype only);
    # the partitioner mode is static, so it joins the AOT fingerprint with
    # the topology tuple's treedef/shapes
    assigned, free_after, feasible = aot_rt.aot_call(
        "pack.solve", pack_solve, (*solve_args, jnp.int32(seed)),
        dict(n_parts=n_parts, partitioner=partitioner,
             lp_iters=lp_iters, round_rounds=round_rounds,
             repair_rounds=repair_rounds, chunk=chunk, policy=policy,
             score_cols=static_kwargs["score_cols"]),
        pending_ok=aot_pending)
    return PackResult(assigned=assigned, free_after=free_after,
                      feasible=feasible, n_parts=n_parts, seed=seed,
                      partitioner=partitioner)


def packed_utilization(assigned, req_i, valid, free0_i=None,
                       score_cols: int = 0, cap_i=None) -> dict:
    """Exact host-side packing objective of one plan.

    placed      — valid asks the plan assigned
    units       — int64 sum of placed requests over the scoring columns
    units_norm  — the SOLVER's objective: placed requests normalized per
                  column by mean node capacity (cap_i, the same inv_scale
                  pack_solve optimizes) so incommensurable quantized scales
                  (milliCPU vs bytes) cannot dominate the comparison; falls
                  back to raw units when cap_i is not supplied
    util        — units / total free units before the plan (0 when free0_i
                  is not supplied)
    nodes_used  — distinct nodes the plan touches (fewer = denser)
    """
    assigned = np.asarray(assigned)
    n = assigned.shape[0]
    req_i = np.asarray(req_i, dtype=np.int64)[:n]
    sc = score_cols if score_cols > 0 else req_i.shape[1]
    placed = np.asarray(valid, bool)[:n] & (assigned >= 0)
    units = int(req_i[placed, :sc].sum())
    if cap_i is not None:
        inv = 1.0 / np.maximum(
            np.asarray(cap_i, np.float64)[:, :sc].mean(axis=0), 1.0)
        units_norm = float((req_i[placed, :sc].astype(np.float64)
                            * inv[None, :]).sum())
    else:
        units_norm = float(units)
    out = {
        "placed": int(placed.sum()),
        "units": units,
        "units_norm": units_norm,
        "nodes_used": int(np.unique(assigned[placed]).size),
        "util": 0.0,
    }
    if free0_i is not None:
        total_free = int(np.maximum(
            np.asarray(free0_i, dtype=np.int64)[:, :sc], 0).sum())
        out["util"] = round(units / max(total_free, 1), 6)
    return out


def choose_plan_n(plans, req_i, valid, score_cols: int = 0, free0_i=None,
                  cap_i=None, priorities=None):
    """The differential oracle's decision rule as an N-WAY incumbent fold
    (round 17: the duel grew a third, learned arm).

    plans: ordered [(name, assigned)] — plans[0] is the INCUMBENT (the
    greedy floor). Each challenger in order replaces the incumbent only
    when its key compares strictly greater, lexicographically on
    (per-priority-class placed counts highest class first, placed asks,
    capacity-normalized packed units, fewer nodes touched). Ties keep the
    incumbent, so no alternate policy can ever regress default behavior.

    The priority guard is applied PAIRWISE: the class axis of the key is
    built over the one global set of priority classes, so every pairwise
    comparison demands the challenger match the incumbent class by class
    from the highest priority down before packing quality decides — a plan
    that packs more units by displacing a higher-priority ask for bulkier
    low-priority ones LOSES every duel it enters (pinned by the three-plan
    starvation regression in tests/test_policy.py). With one shared class
    axis the pairwise fold is exactly a lexicographic max, so the winner
    is order-independent beyond tie-breaking toward the earlier plan.

    cap_i: [M, R] node capacities — aligns the committed objective with
    the solver's capacity-normalized one (packed_utilization.units_norm).

    Returns (winner_name, stats) with stats[name] = packed_utilization of
    each plan."""
    if not plans:
        raise ValueError("choose_plan_n needs at least the incumbent plan")
    utils = {name: packed_utilization(assigned, req_i, valid, free0_i,
                                      score_cols, cap_i)
             for name, assigned in plans}
    # scale-free integer quantization of the float objective: two plans
    # placing the SAME multiset of requests sum in different row orders,
    # and float addition-order noise (~1e-16 relative) must never break
    # the "ties keep the incumbent" contract
    norm_scale = max(max(u["units_norm"] for u in utils.values()), 1e-12)

    def key(name, assigned):
        u = utils[name]
        units_q = round(u["units_norm"] / norm_scale * 1e9)
        assigned = np.asarray(assigned)
        n = assigned.shape[0]
        pk = ()
        if priorities is not None:
            pr = np.asarray(priorities)[:n]
            placed = np.asarray(valid, bool)[:n] & (assigned >= 0)
            classes = np.unique(pr)[::-1]
            pk = tuple(int((placed & (pr == c)).sum()) for c in classes)
        return pk + (u["placed"], units_q, -u["nodes_used"])

    win_name, win_assigned = plans[0]
    win_key = key(win_name, win_assigned)
    for name, assigned in plans[1:]:
        k = key(name, assigned)
        if k > win_key:
            win_name, win_key = name, k
    return win_name, utils


def choose_plan(greedy_assigned, pack_assigned, req_i, valid,
                score_cols: int = 0, free0_i=None, cap_i=None,
                priorities=None):
    """Two-plan compatibility wrapper over choose_plan_n (the round-12
    surface: greedy incumbent vs the pack challenger).

    Returns (use_pack: bool, stats: dict)."""
    winner, utils = choose_plan_n(
        [("greedy", greedy_assigned), ("pack", pack_assigned)],
        req_i, valid, score_cols, free0_i, cap_i, priorities)
    g, p = utils["greedy"], utils["pack"]
    return winner == "pack", {
        "greedy": g, "pack": p,
        "pack_util": p["util"], "greedy_util": g["util"],
    }


def jit_cache_entries() -> int:
    """Compiled-variant count of the pack entry point (compile-vs-cache-hit
    telemetry, the ops.assign.jit_cache_entries convention)."""
    from yunikorn_tpu.aot import runtime as aot_rt

    try:
        return pack_solve._cache_size() + aot_rt.compile_count("pack.")
    except Exception:
        return -1
