"""Batched predicate evaluation on device.

Replaces the reference's per-(pod,node) predicate upcall hot loop
(pkg/plugin/predicates/predicate_manager.go:130-215 — PreFilter+Filter per probe;
invoked once per pod×node by the core, scheduler_callback.go:196-198). Here the
same checks run for all constraint-groups × all nodes in one XLA program:

  - node selector / required node affinity (In/NotIn/Exists/DoesNotExist, OR of
    terms, AND of expressions, multi-value In via any-of bitsets)
  - taints/tolerations (NoSchedule + NoExecute are hard filters, matching the
    reference's TaintToleration filter)
  - host-port conflicts (NodePorts plugin analog)
  - node schedulable/valid state (NodeUnschedulable plugin analog)

Resource fit (NodeResourcesFit analog) is *not* here: it is per-pod, changes as
capacity updates during assignment rounds, and therefore lives inside the
assignment loop (ops/assign.py). Group feasibility is round-invariant, so it is
evaluated once per solve.

All loops over bitset words/terms are static Python loops — XLA unrolls and
fuses them into a single elementwise kernel over [G, M].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def group_screen(
    g_term_req,    # [G, T, W] uint32
    g_term_forb,   # [G, T, W] uint32
    g_term_valid,  # [G, T] bool
    g_anyof,       # [G, T, E, W] uint32
    g_anyof_valid, # [G, T, E] bool
    g_tol,         # [G, Wt] uint32
    node_labels,   # [M, W] uint32
    node_taints,   # [M, Wt] uint32 (hard effects only)
    node_ok,       # [M] bool (valid & schedulable)
) -> jnp.ndarray:  # [G, M] bool
    """Selector/affinity + taints + schedulable — the port-free subset of
    group_feasibility. This is exactly the preemption planner's candidate
    screen: host ports and capacity are deliberately absent (evicting victims
    can free both, so they are tested against the post-eviction state by the
    victim-subset search, not here — the host planner's screen passes
    "insufficient resources" and "host port conflict" the same way)."""
    G, T, W = g_term_req.shape
    E = g_anyof.shape[2]
    M = node_labels.shape[0]
    Wt = g_tol.shape[1]

    # --- selector / affinity terms ---
    term_ok = jnp.ones((G, T, M), bool)
    for w in range(W):
        nl = node_labels[:, w][None, None, :]                      # [1,1,M]
        term_ok &= (g_term_req[:, :, w][:, :, None] & ~nl) == 0
        term_ok &= (g_term_forb[:, :, w][:, :, None] & nl) == 0
    for e in range(E):
        hit = jnp.zeros((G, T, M), bool)
        for w in range(W):
            hit |= (g_anyof[:, :, e, w][:, :, None] & node_labels[:, w][None, None, :]) != 0
        term_ok &= (~g_anyof_valid[:, :, e][:, :, None]) | hit
    sel_ok = jnp.any(term_ok & g_term_valid[:, :, None], axis=1)   # [G, M]

    # --- taints vs tolerations ---
    taint_bad = jnp.zeros((G, M), bool)
    for w in range(Wt):
        taint_bad |= (node_taints[:, w][None, :] & ~g_tol[:, w][:, None]) != 0

    return sel_ok & ~taint_bad & node_ok[None, :]


def group_feasibility(
    g_term_req,    # [G, T, W] uint32
    g_term_forb,   # [G, T, W] uint32
    g_term_valid,  # [G, T] bool
    g_anyof,       # [G, T, E, W] uint32
    g_anyof_valid, # [G, T, E] bool
    g_tol,         # [G, Wt] uint32
    g_ports,       # [G, Wp] uint32
    node_labels,   # [M, W] uint32
    node_taints,   # [M, Wt] uint32 (hard effects only)
    node_ports,    # [M, Wp] uint32
    node_ok,       # [M] bool (valid & schedulable)
) -> jnp.ndarray:  # [G, M] bool
    G = g_term_req.shape[0]
    M = node_labels.shape[0]
    Wp = g_ports.shape[1]

    base_ok = group_screen(g_term_req, g_term_forb, g_term_valid, g_anyof,
                           g_anyof_valid, g_tol, node_labels, node_taints,
                           node_ok)

    # --- host-port conflicts ---
    port_bad = jnp.zeros((G, M), bool)
    for w in range(Wp):
        port_bad |= (g_ports[:, w][:, None] & node_ports[:, w][None, :]) != 0

    return base_ok & ~port_bad


def group_preferred_bonus(
    g_pref_req,    # [G, P, W] uint32
    g_pref_forb,   # [G, P, W] uint32
    g_pref_weight, # [G, P] float32
    node_labels,   # [M, W] uint32
) -> jnp.ndarray:  # [G, M] float32
    """preferredDuringSchedulingIgnoredDuringExecution scoring: each satisfied
    weighted term adds weight/100 * 0.25 to the node's score for that group
    (kube-scheduler normalizes weights to [0,100])."""
    G, P, W = g_pref_req.shape
    M = node_labels.shape[0]
    bonus = jnp.zeros((G, M), jnp.float32)
    for t in range(P):
        ok = jnp.ones((G, M), bool)
        for w in range(W):
            nl = node_labels[:, w][None, :]
            ok &= (g_pref_req[:, t, w][:, None] & ~nl) == 0
            ok &= (g_pref_forb[:, t, w][:, None] & nl) == 0
        bonus += jnp.where(ok, g_pref_weight[:, t][:, None] / 100.0 * 0.25, 0.0)
    return bonus


def group_soft_penalty(
    g_tol,             # [G, Wt] uint32
    node_taints_soft,  # [M, Wt] uint32 (PreferNoSchedule taints)
) -> jnp.ndarray:      # [G, M] float32
    """Soft-taint penalty: the scoring half of the TaintToleration plugin.

    PreferNoSchedule taints never filter (reference: only NoSchedule/NoExecute
    are hard); nodes carrying untolerated soft taints score lower. The penalty
    is the popcount of untolerated soft-taint bits, scaled small so packing
    dominates and soft taints break ties.
    """
    G, Wt = g_tol.shape
    M = node_taints_soft.shape[0]
    count = jnp.zeros((G, M), jnp.int32)
    for w in range(Wt):
        bad = node_taints_soft[:, w][None, :] & ~g_tol[:, w][:, None]   # [G, M]
        count += jax.lax.population_count(bad).astype(jnp.int32)
    return -0.25 * count.astype(jnp.float32)


group_feasibility_jit = jax.jit(group_feasibility)
