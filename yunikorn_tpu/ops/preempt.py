"""Preemption predicates: ordered victim-subset search + victim-table policy.

Role-equivalent to PredicateManager.PreemptionPredicates (reference
pkg/plugin/predicates/predicate_manager.go:137-188) with the startIndex
contract of scheduler_callback.go:200-209: clone the node's state, remove
victims[0:startIndex) unconditionally, then remove one victim at a time and
return the first index at which the pod fits.

This per-(pod,node) check is exact and host-side. Two batched consumers share
it and the victim-table policy below:

  - core/preemption.py: the host planner (differential-testing oracle and
    fallback) — loops asks × candidate nodes, one victim-subset search each.
  - ops/preempt_solve.py: the device planner — the same victim tables encoded
    into dense [M, V, R] arrays, all asks × all nodes in one jitted dispatch.

`victim_table` is the single source for WHICH pods are eviction candidates on
a node and in what order; both planners consume it, so they cannot drift.
"""
from __future__ import annotations

from typing import Callable, List, Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.common.resource import Resource, get_pod_resource
from yunikorn_tpu.common.si import (
    PreemptionPredicatesArgs,
    PreemptionPredicatesResponse,
)
from yunikorn_tpu.ops.host_predicates import pod_fits_node

# Planner shape limits (shared by the host planner, the victim-table encoder
# and the device kernel — the device victim tables hold MAX_VICTIMS_PER_NODE
# rows per node, so all three must agree on the truncation).
MAX_PREEMPTING_ASKS_PER_CYCLE = 32
MAX_CANDIDATE_NODES = 32
MAX_VICTIMS_PER_NODE = 16

# Per-victim clamp for priority sums: MAX_VICTIMS_PER_NODE x 2^25 = 2^29
# stays clear of int32 wraparound (and of the device kernel's big-sentinel
# keys). Both planners compare clamped sums, so the tie-breaking is identical.
PRIO_SUM_CLAMP = 2**25


def clamped_prio_sum(prios) -> int:
    """Victim priority sum with the device kernel's per-victim clamp."""
    return sum(max(-PRIO_SUM_CLAMP, min(PRIO_SUM_CLAMP, int(p)))
               for p in prios)


def pod_priority(pod: Optional[Pod]) -> int:
    if pod is None or pod.spec.priority is None:
        return 0
    return pod.spec.priority


def is_preemptable(pod: Pod, pc_lookup) -> bool:
    """Victim-side opt-out: PriorityClass carrying the
    yunikorn.apache.org/allow-preemption: "false" annotation (reference
    constants.AnnotationAllowPreemption). PriorityClass-level preemptionPolicy
    Never only blocks the preemptOR side; victims stay eligible (K8s
    semantics)."""
    if pod.spec.priority_class_name:
        pc = pc_lookup(pod.spec.priority_class_name)
        if pc is not None:
            if pc.metadata.annotations.get(constants.ANNOTATION_ALLOW_PREEMPTION) == constants.FALSE:
                return False
    return True


def victim_table(info, pc_lookup, managed: Callable[[str], bool]) -> List[Pod]:
    """The node's eviction-candidate table: yunikorn-managed, preemptable
    pods in cheapest-eviction-first order — (priority asc, newest first) —
    truncated to MAX_VICTIMS_PER_NODE.

    Ask-independent by construction (the ask-priority filter removes a PREFIX
    complement: victims with priority >= the ask's sit at the sorted tail, so
    masking them later never changes which rows the truncation kept). Both
    planners apply per-ask filters (priority fence, already-claimed) on top
    of this shared table.

    Deliberate narrowing vs the pre-round-8 host planner: the already-claimed
    filter applies AFTER truncation, so on a node holding more than
    MAX_VICTIMS_PER_NODE eviction candidates, rows beyond the table are never
    reconsidered when earlier asks claimed part of the prefix. Parity between
    the planners (the device tables physically hold V rows) is worth more
    than that tail: a later ask simply plans another node or retries next
    cycle against re-encoded tables.
    """
    victims = [
        v for v in info.pods.values()
        if managed(v.uid) and is_preemptable(v, pc_lookup)
    ]
    victims.sort(key=lambda v: (pod_priority(v), -v.metadata.creation_timestamp))
    return victims[:MAX_VICTIMS_PER_NODE]


def preemption_victim_search(cache_or_context, args: PreemptionPredicatesArgs,
                             extra_used: Optional[Resource] = None) -> PreemptionPredicatesResponse:
    """extra_used: additional committed-but-unobserved usage on the node (the
    core's in-flight allocations), subtracted from the node's free capacity."""
    cache = getattr(cache_or_context, "schedulers_cache", cache_or_context)
    pod = cache.get_pod(args.allocation_key)
    info = cache.snapshot_node(args.node_id)
    if pod is None or info is None:
        return PreemptionPredicatesResponse(success=False, index=-1)

    victims: List = []
    for key in args.preempt_allocation_keys:
        v = info.pods.get(key) or cache.get_pod(key)
        if v is not None:
            victims.append(v)

    remaining = dict(info.pods)
    free = info.available()
    if extra_used is not None:
        free = free.sub(extra_used)
    # removals up to startIndex are unconditional (the core already decided
    # those victims are going away). The resource credit is guarded on the
    # ACTUAL removal: a key appearing twice in preempt_allocation_keys (or a
    # victim resolved via cache.get_pod that never lived on this node) must
    # not re-add capacity it never freed — double-counting would report a fit
    # the eviction cannot deliver.
    for v in victims[: args.start_index]:
        removed = remaining.pop(v.uid, None)
        if removed is not None:
            free = free.add(get_pod_resource(removed))
    # remove one victim at a time, test after each removal; return the index
    # of the removal that made the pod fit (reference returns i, never testing
    # the zero-extra-removals case)
    for i in range(args.start_index, len(victims)):
        v = victims[i]
        removed = remaining.pop(v.uid, None)
        if removed is not None:
            free = free.add(get_pod_resource(removed))
        err = pod_fits_node(pod, info.node, free, remaining.values())
        if err is None:
            return PreemptionPredicatesResponse(success=True, index=i)
    return PreemptionPredicatesResponse(success=False, index=-1)
