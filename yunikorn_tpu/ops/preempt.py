"""Preemption predicates: ordered victim-subset search.

Role-equivalent to PredicateManager.PreemptionPredicates (reference
pkg/plugin/predicates/predicate_manager.go:137-188) with the startIndex
contract of scheduler_callback.go:200-209: clone the node's state, remove
victims[0:startIndex) unconditionally, then remove one victim at a time and
return the first index at which the pod fits.

This per-(pod,node) check is exact and host-side; the *batched* victim search
across candidate nodes (used by the core's preemption planner) lives in
core/preemption.py and calls this as its per-node kernel.
"""
from __future__ import annotations

from typing import List, Optional

from yunikorn_tpu.common.resource import Resource, get_pod_resource
from yunikorn_tpu.common.si import (
    PreemptionPredicatesArgs,
    PreemptionPredicatesResponse,
)
from yunikorn_tpu.ops.host_predicates import pod_fits_node


def preemption_victim_search(cache_or_context, args: PreemptionPredicatesArgs,
                             extra_used: Optional[Resource] = None) -> PreemptionPredicatesResponse:
    """extra_used: additional committed-but-unobserved usage on the node (the
    core's in-flight allocations), subtracted from the node's free capacity."""
    cache = getattr(cache_or_context, "schedulers_cache", cache_or_context)
    pod = cache.get_pod(args.allocation_key)
    info = cache.snapshot_node(args.node_id)
    if pod is None or info is None:
        return PreemptionPredicatesResponse(success=False, index=-1)

    victims: List = []
    for key in args.preempt_allocation_keys:
        v = info.pods.get(key) or cache.get_pod(key)
        if v is not None:
            victims.append(v)

    remaining = dict(info.pods)
    free = info.available()
    if extra_used is not None:
        free = free.sub(extra_used)
    # removals up to startIndex are unconditional (the core already decided
    # those victims are going away)
    for v in victims[: args.start_index]:
        if v.uid in remaining:
            remaining.pop(v.uid)
            free = free.add(get_pod_resource(v))
    # remove one victim at a time, test after each removal; return the index
    # of the removal that made the pod fit (reference returns i, never testing
    # the zero-extra-removals case)
    for i in range(args.start_index, len(victims)):
        v = victims[i]
        if v.uid in remaining:
            remaining.pop(v.uid)
            free = free.add(get_pod_resource(v))
        err = pod_fits_node(pod, info.node, free, remaining.values())
        if err is None:
            return PreemptionPredicatesResponse(success=True, index=i)
    return PreemptionPredicatesResponse(success=False, index=-1)
