"""Batched preemption victim-selection on device.

Replaces the host planner's triple loop — asks × candidate nodes × victims,
one `preemption_victim_search` per (ask, node) (core/preemption.py) — with ONE
jitted dispatch that plans for every unplaced ask against every node at once.
This is the preemption analog of what ops/assign.py did to the allocation
cycle: the per-entity sequential pattern (CvxCluster / POP, PAPERS.md) turned
into a dense batched solve.

Data model (encoded by snapshot/encoder.py with the same incremental-upload
discipline as free/ports):

  victim_req   [M, V, R] int32  per-node victim freed-resource rows, already
                                in eviction order (priority asc, newest first
                                — ops.preempt.victim_table is the single
                                source; the sort happens at encode, so the
                                device consumes pre-ordered tables)
  victim_prio  [M, V]    int32  victim priorities (pad slots = 2^30)
  victim_valid [M, V]    bool   slot holds a managed, preemptable victim
  victim_app   [M, V]    int32  interned app/gang id (host-side bookkeeping;
                                rides the table for future gang-aware logic)

Per ask (processed in priority order inside one fori_loop, carrying the
cross-ask claimed-victim mask — the device equivalent of the host planner's
`already_victim` set):

  1. eligibility: valid slot, victim priority strictly below the ask's,
     not claimed by an earlier ask this cycle
  2. prefix-scan the eligible victims' freed capacity per node with the
     saturating-add idiom from ops/assign._water_fill_proposals
  3. fit test: free + prefix >= ask request at every resource column — the
     ordered-subset contract of ops/preempt.preemption_victim_search: the
     first eligible slot whose cumulative removal fits is the chosen prefix
     (the zero-removals case is never tested, matching the reference)
  4. candidate screen: the port-free predicate mask (selector/affinity +
     taints + schedulable — ops.predicates.group_screen), nodes with at
     least one eligible victim, capped to the first MAX_CANDIDATE_NODES such
     nodes in cache order (the host planner's search budget, applied
     arithmetically for exact parity)
  5. choose the node minimizing (victim count, victim priority sum, cache
     order) lexicographically — the host planner's strict-< tie-breaking

Topology-aware victim selection (solver.topology, round 15) changes none of
this kernel: the `node_order` ranks BOTH planners consume are produced by
the core, and with topology active they arrive pre-ordered toward freeing
CONTIGUOUS ICI domains (topology/score.preempt_node_order — nodes in the
domains holding the most free capacity rank first, so the budgeted search
and the final tie-break both prefer completing a nearly-open domain over
nibbling a busy one). One shared ordered list in, exact device/host parity
preserved by construction.

Resource arithmetic is int32 in device units: ask requests ceil, freed victim
capacity floor — both conservative, and exact whenever quantities are integral
in device units (the vocab scales are chosen for that). Priority sums clamp
each victim's contribution to ±PRIO_SUM_CLAMP on BOTH planners (shared
helper), so the int32 sum cannot wrap; the comparison stays exact for any
realistic priority band.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from yunikorn_tpu.ops.predicates import group_screen
from yunikorn_tpu.ops.preempt import (
    MAX_CANDIDATE_NODES,
    MAX_PREEMPTING_ASKS_PER_CYCLE,
    PRIO_SUM_CLAMP,
)

# node_order sentinel: rows at/above this are not candidates (padded rows,
# nodes the core excluded). Also the masked-key sentinel for the argmin.
_BIG = jnp.int32(2**30)
NODE_ORDER_EXCLUDED = 2**30


@functools.partial(jax.jit, static_argnames=("max_candidates",))
def preempt_solve(
    a_req,          # [A, R] int32 ask requests (priority-desc order)
    a_gid,          # [A] int32 constraint-group ids
    a_prio,         # [A] int32 ask priorities
    a_valid,        # [A] bool
    g_term_req, g_term_forb, g_term_valid, g_anyof, g_anyof_valid, g_tol,
    node_labels,    # [M, W] uint32
    node_taints,    # [M, Wt] uint32 (hard effects)
    node_ok,        # [M] bool (valid & schedulable)
    node_order,     # [M] int32 position in cache node order; big = excluded
    free,           # [M, R] int32 (available minus in-flight overlay)
    victim_req,     # [M, V, R] int32
    victim_prio,    # [M, V] int32
    victim_valid,   # [M, V] bool
    *,
    max_candidates: int = MAX_CANDIDATE_NODES,
):
    """Returns (node_idx [A] int32 — chosen node row or -1, victim_mask
    [A, V] bool — chosen slots of that node's victim table)."""
    A, R = a_req.shape
    M, V, _ = victim_req.shape
    CAP = jnp.int32(2**30 - 1)
    slot_idx = jnp.arange(V, dtype=jnp.int32)
    row_idx = jnp.arange(M, dtype=jnp.int32)

    # hoisted across asks: the candidate screen and the cache-order ranking
    screen = group_screen(g_term_req, g_term_forb, g_term_valid, g_anyof,
                          g_anyof_valid, g_tol, node_labels, node_taints,
                          node_ok)                                   # [G, M]
    order_perm = jnp.argsort(node_order)                             # [M]
    free_c = jnp.minimum(free, CAP)                                  # [M, R]
    prio_clamped = jnp.clip(victim_prio, -PRIO_SUM_CLAMP, PRIO_SUM_CLAMP)

    sat_add = lambda a, b: jnp.minimum(a + b, CAP)

    def plan_one(i, claimed):
        elig = victim_valid & (victim_prio < a_prio[i]) & ~claimed   # [M, V]
        vreq = jnp.where(elig[:, :, None],
                         jnp.minimum(victim_req, CAP), 0)            # [M, V, R]
        cum = lax.associative_scan(sat_add, vreq, axis=1)            # inclusive
        fits = jnp.all(free_c[:, None, :] + cum >= a_req[i][None, None, :],
                       axis=-1) & elig                               # [M, V]
        # ordered-subset contract: first eligible slot whose cumulative
        # removal fits (ineligible slots free nothing and are never tested —
        # they are simply absent from the host kernel's victim list)
        first = jnp.min(jnp.where(fits, slot_idx[None, :], V), axis=1)  # [M]
        success = first < V
        prefix = elig & (slot_idx[None, :] <= first[:, None])        # [M, V]
        nvic = jnp.sum(prefix.astype(jnp.int32), axis=1)             # [M]
        psum = jnp.sum(jnp.where(prefix, prio_clamped, 0), axis=1)   # [M]
        # candidate screen + the host planner's search budget: only the
        # first max_candidates nodes (cache order) with a non-empty filtered
        # victim list and a passing screen are searched
        searchable = (screen[a_gid[i]] & jnp.any(elig, axis=1)
                      & (node_order < _BIG))
        rank_sorted = jnp.cumsum(searchable[order_perm].astype(jnp.int32)) - 1
        rank = jnp.zeros((M,), jnp.int32).at[order_perm].set(rank_sorted)
        cand = searchable & (rank < max_candidates) & success
        # lexicographic argmin (victims, prio sum, cache order) — the host
        # planner's strict-< keeps the first node in iteration order on
        # ties. Staged min-reductions instead of a lexsort: a full sort
        # network at M inside the ask loop measured ~20x the compile cost
        # on CPU for an argmin three reductions deliver exactly.
        nvic_k = jnp.where(cand, nvic, _BIG)
        tie1 = cand & (nvic_k == jnp.min(nvic_k))
        psum_k = jnp.where(tie1, psum, _BIG)
        tie2 = tie1 & (psum_k == jnp.min(psum_k))
        order_k = jnp.where(tie2, node_order, _BIG)
        best = jnp.argmin(order_k)
        found = jnp.any(cand)
        chosen_mask = jnp.where(found, prefix[best], False)          # [V]
        node = jnp.where(found, best, -1)
        claimed = claimed | (chosen_mask[None, :] & (row_idx == best)[:, None]
                             & found)
        return node.astype(jnp.int32), chosen_mask, claimed

    def body(i, state):
        claimed, out_node, out_mask = state

        def do_plan(operand):
            claimed_in, out_node_in, out_mask_in = operand
            node, mask, claimed_out = plan_one(i, claimed_in)
            return (claimed_out, out_node_in.at[i].set(node),
                    out_mask_in.at[i].set(mask))

        def skip(operand):
            return operand

        # padded ask rows skip the whole [M, V, R] scan, so the fixed A
        # shape costs nothing when few asks preempt
        return lax.cond(a_valid[i], do_plan, skip,
                        (claimed, out_node, out_mask))

    init = (
        jnp.zeros((M, V), bool),
        jnp.full((A,), -1, jnp.int32),
        jnp.zeros((A, V), bool),
    )
    _, out_node, out_mask = lax.fori_loop(0, A, body, init)
    return out_node, out_mask


def prepare_preempt_args(batch, n_asks, prios, node_arrays, node_order, *,
                         free_delta=None, device_state=None):
    """Assemble preempt_solve's positional args.

    batch: a PodBatch encoding the preempting asks (rows 0..n_asks-1, already
    in priority-desc order) — batch.req rows are quantize_request outputs,
    i.e. already ceil'd to integers, so the int32 view below is the exact
    ceil the kernel contract requires; prios: their int priorities. node_order: [M]
    int32 cache-order ranks (big = not a candidate). device_state: the
    persistent device mirror INCLUDING victim fields
    (SnapshotEncoder.victim_arrays) — node-side tensors then transfer
    O(what changed); without it, host numpy views upload per call.
    free_delta: the core's in-flight allocation overlay ([capacity, R] float).
    """
    import numpy as np

    na = node_arrays
    A = MAX_PREEMPTING_ASKS_PER_CYCLE
    R = batch.req.shape[1]
    a_req = np.zeros((A, R), np.int32)
    a_gid = np.zeros((A,), np.int32)
    a_prio = np.zeros((A,), np.int32)
    a_valid = np.zeros((A,), bool)
    n = min(n_asks, A)
    a_req[:n] = batch.req[:n].astype(np.int32)
    a_gid[:n] = batch.group_id[:n]
    a_prio[:n] = np.asarray(list(prios[:n]), np.int32)
    a_valid[:n] = True

    from yunikorn_tpu.ops.assign import apply_free_delta

    if device_state is not None:
        free_i = device_state["free_i"]
        if free_delta is not None:
            free_i = apply_free_delta(free_i, free_delta)
        labels = device_state["labels"]
        taints = device_state["taints_hard"]
        node_ok = device_state["node_ok"]
        victim_req = device_state["victim_req"]
        victim_prio = device_state["victim_prio"]
        victim_valid = device_state["victim_valid"]
    else:
        free_i = np.floor(na.free).astype(np.int32)
        if free_delta is not None:
            free_i = apply_free_delta(free_i, free_delta)
        labels = na.labels.view(np.uint32)
        taints = na.taints_hard.view(np.uint32)
        node_ok = na.valid & na.schedulable
        victim_req = na.victim_req
        victim_prio = na.victim_prio
        victim_valid = na.victim_valid

    return (
        a_req, a_gid, a_prio, a_valid,
        batch.g_term_req.view(np.uint32),
        batch.g_term_forb.view(np.uint32),
        batch.g_term_valid,
        batch.g_anyof.view(np.uint32),
        batch.g_anyof_valid,
        batch.g_tol.view(np.uint32),
        labels, taints, node_ok,
        node_order,
        free_i,
        victim_req, victim_prio, victim_valid,
    )


def preempt_jit_cache_entries() -> int:
    """Compiled-variant count of the preemption kernel (compile-vs-hit
    accounting, same contract as ops.assign.jit_cache_entries)."""
    from yunikorn_tpu.aot import runtime as aot_rt

    try:
        return (preempt_solve._cache_size()
                + aot_rt.compile_count("preempt.", "mesh.preempt"))
    except Exception:
        return -1
