"""Device-resident mirror of the GlobalQuotaLedger's confirmed usage.

The round-16 sharded control plane couples shards through ONE Python
ledger lock: every admitted ask paid a reserve() round-trip under it, so
at N shards the gate's admission tail serialized on the ledger exactly
the way the front end serialized on _mu. This module takes the ledger off
the per-ask hot path without weakening exactness:

  commit-time authority (the invariant)
      The Python GlobalQuotaLedger stays the ONLY authority: reserve at
      admission, confirm at commit, release on release/eviction — all
      plain-int exact under its lock, unchanged. The mirror is a
      read-optimized PROJECTION of the ledger's confirmed usage: the
      ledger journals every _used mutation (one tuple append under the
      lock it already holds), and each shard's gate drains that journal
      once per cycle into a [shards, trackers, resources] int64 device
      array (ops/gate_solve.usage_apply — a jitted scatter-add), then
      re-reduces the fleet totals (ops/gate_solve.usage_fold; under a
      mesh, parallel/mesh.usage_fold_sharded runs the same fold as a
      psum-style ICI all-reduce).

  zero-lock admission precheck
      provably_exceeds(charges) reads the pre-reduced [T, K] fleet-usage
      array (a host numpy view refreshed after each drain) with ZERO lock
      acquisitions: an ask whose charges already exceed a limit on
      CONFIRMED usage alone is held immediately — the ledger would refuse
      it anyway (reservations only add to the left-hand side). Survivors
      then batch through GlobalQuotaLedger.reserve_many — one lock
      acquisition per cycle, not one per ask. Staleness is safe by
      direction: a racing commit makes the mirror UNDERstate (the ledger
      still refuses exactly); a racing release makes it OVERstate, which
      can only hold an ask one extra cycle — the same semantics as a
      ledger contention retry.

  bit-equality (the oracle)
      After a drain, host_usage() must equal ledger.usage_snapshot()
      bit-for-bit (divergence() counts differing cells and pins the
      shard_ledger_mirror_divergence gauge, gated at 0 by
      tests/test_async_front.py across the failover suite). This holds
      because the mirror applies the SAME plain-int deltas the ledger
      applied, in aggregate — int64 end-to-end, no floats anywhere.

Shard attribution note: rows index the shard that DRAINED a delta, not
the shard that committed it (any shard's cycle may drain the shared
journal). The fold — the only consumer — is attribution-invariant; the
per-shard rows exist so drains scatter into disjoint rows and the mesh
fold has a shard axis to reduce over.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from yunikorn_tpu.log.logger import log
from yunikorn_tpu.snapshot.vocab import _next_pow2

logger = log("ops.ledger_mirror")


class DeviceUsageMirror:
    """[shards, trackers, resources] int64 confirmed-usage array on device,
    folded across shards after every drain; the host keeps a numpy view of
    the fleet totals for the zero-lock admission precheck."""

    def __init__(self, n_shards: int, mesh=None, divergence_gauge=None):
        self.n = int(n_shards)
        self._mesh = mesh
        self._gauge = divergence_gauge
        # serializes device updates (drains from different shard cycle
        # threads); NEVER on the precheck read path — provably_exceeds
        # reads the published numpy snapshot lock-free
        self._mu = threading.Lock()
        self._ledger = None
        self._t_vocab: Dict[str, int] = {}
        self._k_vocab: Dict[str, int] = {}
        self._t_names: List[str] = []
        self._k_names: List[str] = []
        self._t_cap = 8
        self._k_cap = 4
        self._dev = None            # jax [S, T_cap, K_cap] int64
        # published fleet view: (fleet [T_cap, K_cap] np.int64, t_vocab,
        # k_vocab) swapped atomically — readers never see a half-update
        self._fleet: Optional[np.ndarray] = None
        # per-shard journal epochs (round 22): quarantine bumps a shard's
        # epoch the way ShardDeliveryQueue.fence() epoch-fences its pump —
        # a zombie cycle's late refresh carries the stale epoch and is
        # refused before its drained deltas can dirty the fold
        self._epochs = [0] * self.n
        self.drains = 0
        self.applied_deltas = 0
        self.folds = 0
        self.fenced_refreshes = 0

    # ----------------------------------------------------------- internals
    def bind_ledger(self, ledger) -> None:
        self._ledger = ledger

    def _ensure_dev_locked(self):
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        with enable_x64():
            if self._dev is None:
                self._dev = jnp.zeros(
                    (self.n, self._t_cap, self._k_cap), jnp.int64)
            return self._dev

    def _grow_locked(self, t_need: int, k_need: int) -> None:
        """Re-pad the device array when a vocab outgrows its capacity
        (rare: tracker/resource vocabularies are config-bounded)."""
        import jax.numpy as jnp
        from jax.experimental import enable_x64

        new_t = _next_pow2(max(t_need, self._t_cap), 8)
        new_k = _next_pow2(max(k_need, self._k_cap), 4)
        if new_t == self._t_cap and new_k == self._k_cap:
            return
        host = (np.asarray(self._dev) if self._dev is not None
                else np.zeros((self.n, self._t_cap, self._k_cap), np.int64))
        grown = np.zeros((self.n, new_t, new_k), np.int64)
        grown[:, :host.shape[1], :host.shape[2]] = host
        self._t_cap, self._k_cap = new_t, new_k
        with enable_x64():
            self._dev = jnp.asarray(grown)

    def _index_locked(self, vocab: Dict[str, int], names: List[str],
                      key: str) -> int:
        idx = vocab.get(key)
        if idx is None:
            idx = len(names)
            vocab[key] = idx
            names.append(key)
        return idx

    # ------------------------------------------------------------------ API
    def epoch_of(self, shard: int) -> int:
        """The shard's current journal epoch (stamped onto each core at
        build/rejoin; a refresh presenting an older stamp is fenced)."""
        with self._mu:
            return self._epochs[shard % self.n]

    def fence_shard(self, shard: int) -> None:
        """Quarantine fence: refreshes stamped with the shard's PREVIOUS
        epoch are refused from here on — a zombie that already drained the
        journal gets its deltas requeued on the ledger instead of folded,
        so nothing is lost and nothing stale lands."""
        with self._mu:
            self._epochs[shard % self.n] += 1

    def refresh(self, shard: int = 0, ledger=None,
                epoch: Optional[int] = None) -> int:
        """Drain the ledger's confirmed-usage journal into this shard's
        device row and re-fold the fleet totals. One short ledger-lock
        swap for the drain; the device work is jitted. Returns the number
        of deltas applied. `epoch` is the caller's journal-epoch stamp
        (None = unfenced caller: divergence checks, tests)."""
        ledger = ledger if ledger is not None else self._ledger
        if ledger is None:
            return 0
        if epoch is not None and epoch != self.epoch_of(shard):
            self.fenced_refreshes += 1
            return 0
        deltas = ledger.drain_deltas()
        if not deltas:
            return 0
        if epoch is not None and epoch != self.epoch_of(shard):
            # fenced BETWEEN the check and the drain: the deltas this
            # zombie swallowed belong to the fleet — put them back
            self.fenced_refreshes += 1
            requeue = getattr(ledger, "requeue_deltas", None)
            if requeue is not None:
                requeue(deltas)
            return 0
        from jax.experimental import enable_x64

        from yunikorn_tpu.ops.gate_solve import usage_apply, usage_fold

        with self._mu:
            rows: List[Tuple[int, int, int]] = []
            t_need = len(self._t_names)
            k_need = len(self._k_names)
            for tid, items, sign in deltas:
                t = self._index_locked(self._t_vocab, self._t_names, tid)
                for rk, v in items:
                    k = self._index_locked(self._k_vocab, self._k_names, rk)
                    rows.append((t, k, sign * int(v)))
            t_need = len(self._t_names)
            k_need = len(self._k_names)
            if t_need > self._t_cap or k_need > self._k_cap:
                self._grow_locked(t_need, k_need)
            dev = self._ensure_dev_locked()
            b = len(rows)
            b_pad = _next_pow2(b, 8)
            t_idx = np.zeros((b_pad,), np.int32)
            k_idx = np.zeros((b_pad,), np.int32)
            vals = np.zeros((b_pad,), np.int64)
            for i, (t, k, v) in enumerate(rows):
                t_idx[i], k_idx[i], vals[i] = t, k, v
            with enable_x64():
                import jax.numpy as jnp

                self._dev = usage_apply(
                    dev, jnp.int32(shard % self.n), jnp.asarray(t_idx),
                    jnp.asarray(k_idx), jnp.asarray(vals))
                if (self._mesh is not None
                        and self.n % self._mesh.devices.size == 0):
                    from yunikorn_tpu.parallel.mesh import usage_fold_sharded

                    fleet = usage_fold_sharded(self._dev, self._mesh)
                else:
                    fleet = usage_fold(self._dev)
                self._fleet = np.asarray(fleet)
            self.drains += 1
            self.applied_deltas += b
            self.folds += 1
        return b

    def provably_exceeds(self, charges) -> bool:
        """True when the fleet's CONFIRMED usage plus this ask's charges
        already breaks some limit — a hold the ledger is guaranteed to
        agree with (its check only ADDS live reservations on top). Reads
        the published fleet snapshot: zero locks, numpy probes only.
        charges: [(tracker_id, limit_items, amount_items)]."""
        fleet = self._fleet
        if fleet is None:
            return False
        t_vocab = self._t_vocab
        k_vocab = self._k_vocab
        for tid, limit, amount in charges:
            t = t_vocab.get(tid)
            if t is None or t >= fleet.shape[0]:
                continue  # tracker never charged: confirmed usage is 0
            amt = dict(amount)
            for rk, lim_v in limit:
                k = k_vocab.get(rk)
                used = int(fleet[t, k]) if (k is not None
                                            and k < fleet.shape[1]) else 0
                if used + amt.get(rk, 0) > lim_v:
                    return True
        return False

    def host_usage(self) -> Dict[str, Dict[str, int]]:
        """The mirror's fleet usage as {tracker: {resource: int}} (zero
        entries filtered) — the side compared bit-for-bit against
        GlobalQuotaLedger.usage_snapshot()."""
        with self._mu:
            fleet = self._fleet
            t_names = list(self._t_names)
            k_names = list(self._k_names)
        out: Dict[str, Dict[str, int]] = {}
        if fleet is None:
            return out
        for t, tid in enumerate(t_names):
            row = {k_names[k]: int(fleet[t, k])
                   for k in range(len(k_names)) if int(fleet[t, k]) != 0}
            if row:
                out[tid] = row
        return out

    def divergence(self, ledger=None) -> int:
        """Cells where the mirror differs from the ledger's confirmed
        usage, after draining any pending journal. The exactness oracle:
        pinned at 0 by test across the failover suite; also published on
        the shard_ledger_mirror_divergence gauge."""
        ledger = ledger if ledger is not None else self._ledger
        if ledger is None:
            return 0
        self.refresh(0, ledger)
        truth = ledger.usage_snapshot()
        mine = self.host_usage()
        diff = 0
        for tid in set(truth) | set(mine):
            a = truth.get(tid, {})
            b = mine.get(tid, {})
            for rk in set(a) | set(b):
                if a.get(rk, 0) != b.get(rk, 0):
                    diff += 1
        if self._gauge is not None:
            self._gauge.set(diff)
        return diff

    def stats(self) -> dict:
        with self._mu:
            return {
                "trackers": len(self._t_names),
                "resources": len(self._k_names),
                "capacity": [self.n, self._t_cap, self._k_cap],
                "drains": self.drains,
                "applied_deltas": self.applied_deltas,
                "folds": self.folds,
                "epochs": list(self._epochs),
                "fenced_refreshes": self.fenced_refreshes,
                "sharded_fold": bool(
                    self._mesh is not None
                    and self.n % self._mesh.devices.size == 0),
            }
