"""Pallas TPU kernel: fused fit + score + argmax for the solver's hot op.

The per-round "best node per pod" computation (ops/assign._best_nodes_chunked)
is the solver's FLOP center: for every active pod, compare its request against
every node's free vector, mask with group feasibility, score, and arg-max over
nodes. The XLA version materializes [chunk, M] score tiles between the mask
and the argmax. This kernel keeps everything in VMEM:

  grid = (pod_tiles, node_tiles)    node tiles innermost
  per (p, n) tile:
    fit[P, Mt]   = AND_r (free[n][:, r] >= req[p][:, r])     (VPU, unrolled R)
    feas[P, Mt]  = onehot(gid[p]) @ group_feas[:, n-tile]    (MXU — the gather
                   of a pod's feasibility row becomes a [P, G] x [G, Mt] matmul)
    soft[P, Mt]  = onehot(gid[p]) @ group_soft[:, n-tile]    (MXU, HIGHEST
                   precision — soft taints / preferred affinity / host terms)
    score[P, Mt] = base_scores[n-tile] + soft, masked by fit & feas
    running packed max accumulates in VMEM scratch across node tiles and is
    written out on the last node tile.

Selection and identification share one int32 max: scores are quantized to
1/128 steps and packed as  q * index_span + (M - column)  with
index_span = smallest power of two > M (min 2^10), so the maximum picks the
best score and, on ties, the LOWEST node index — exactly jnp.argmax
semantics — with all arithmetic exact in int32. The signed score range is
±2^30/index_span/128 (e.g. span 2^16 at 16k<M≤32k nodes → |score| < 128.0).

Exposed through ops.assign.solve(..., use_pallas=True); the default stays the
XLA path (property-tested identical). interpret=True runs the kernel on CPU.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

POD_TILE = 256
NODE_TILE = 512
SCORE_SCALE = 128.0          # score quantization step = 1/128
PACKED_MIN = -(1 << 30)  # plain int: jnp constants cannot be captured by kernels


def _index_span(m: int) -> int:
    """Room for node indices below the score bits: smallest power of two
    STRICTLY greater than m (the packed remainder reaches m), min 2^10.
    Signed score range is ±2^30/span/SCORE_SCALE: e.g. span 2^16 at
    16k<M≤32k nodes still allows |score| < 128.0 exactly."""
    return 1 << max(10, m.bit_length())


def _best_node_kernel(*refs, index_span: int, use_soft: bool):
    """One (pod_tile, node_tile) step; node dimension is grid axis 1.

    The soft input (and its DMA) exists only in the use_soft variant — the
    common no-soft-terms batch pays neither the transfer nor the matmul."""
    if use_soft:
        (req_ref, gid_onehot_ref, feas_ref, soft_ref, free_ref,
         scores_ref, out_ref, acc_ref) = refs
    else:
        (req_ref, gid_onehot_ref, feas_ref, free_ref,
         scores_ref, out_ref, acc_ref) = refs
        soft_ref = None
    n_idx = pl.program_id(1)
    n_tiles = pl.num_programs(1)

    req = req_ref[:]                      # [P, R] int32
    free = free_ref[:]                    # [Mt, R] int32
    P, R = req.shape
    Mt = free.shape[0]

    fit = jnp.ones((P, Mt), jnp.bool_)
    for r in range(R):
        fit &= free[:, r][None, :] >= req[:, r][:, None]

    onehot = gid_onehot_ref[:]            # [P, G] f32
    feas = feas_ref[:]                    # [G, Mt] f32 (0/1)
    feas_rows = jax.lax.dot_general(
        onehot, feas, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) > 0.5          # [P, Mt]

    ok = fit & feas_rows
    base_q = scores_ref[:]                # [Mt] f32 base scores
    if use_soft:
        # per-(pod, node) score: node base + the pod's group soft adjustment
        # (PreferNoSchedule taints, preferred affinity, host-scored terms) —
        # the gather of a pod's soft row is the same onehot matmul (MXU).
        # HIGHEST precision: default MXU bf16 truncation of soft values could
        # round (base+soft)*SCORE_SCALE across a .5 boundary and diverge from
        # the XLA path (the feas matmul tolerates bf16 via its 0.5 threshold).
        soft = soft_ref[:]                # [G, Mt] f32
        soft_rows = jax.lax.dot_general(
            onehot, soft, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)           # [P, Mt]
        q = jnp.round((base_q[None, :] + soft_rows) * SCORE_SCALE).astype(jnp.int32)
    else:
        q = jnp.broadcast_to(
            jnp.round(base_q * SCORE_SCALE).astype(jnp.int32)[None, :], (P, Mt))
    col = jax.lax.broadcasted_iota(jnp.int32, (P, Mt), 1)
    global_col = col + Mt * n_idx
    total_m = Mt * n_tiles
    packed = q * index_span + (total_m - global_col)
    packed = jnp.where(ok, packed, jnp.int32(PACKED_MIN))
    tile_best = jnp.max(packed, axis=1)   # [P]

    @pl.when(n_idx == 0)
    def _init():
        acc_ref[:] = tile_best

    @pl.when(n_idx > 0)
    def _acc():
        acc_ref[:] = jnp.maximum(acc_ref[:], tile_best)

    @pl.when(n_idx == n_tiles - 1)
    def _finish():
        best = acc_ref[:]
        feasible = best > jnp.int32(PACKED_MIN)
        # recover M - column from the packed low bits (floor-div is exact:
        # the remainder term (total_m - col) is always in [1, index_span))
        frac = best - (best // index_span) * index_span
        out_ref[:, 0] = jnp.where(feasible, frac, 0)
        out_ref[:, 1] = jnp.where(feasible, 1, 0)


@functools.partial(jax.jit, static_argnames=("interpret", "has_soft"))
def pallas_best_nodes(req, group_id, group_feas, group_soft, free, base_scores,
                      interpret=False, has_soft=True):
    """Fused best-node computation. Shapes: req [N,R] i32, group_id [N] i32,
    group_feas [G,M] bool, group_soft [G,M] f32 (per-group score adjustment:
    soft taints + preferred affinity + host-scored terms), free [M,R] i32,
    base_scores [M] f32. has_soft=False (static) selects the variant without
    the soft input — no extra DMA or matmul for the common case.

    Returns (best [N] int32, feasible [N] bool). N and M are power-of-two
    padded upstream, so the tile divisibility requirements hold.
    """
    N, R = req.shape
    G, M = group_feas.shape
    pt = min(POD_TILE, N)
    nt = min(NODE_TILE, M)
    assert N % pt == 0 and M % nt == 0
    span = _index_span(M)

    onehot = jax.nn.one_hot(group_id, G, dtype=jnp.float32)            # [N, G]
    feas_f = group_feas.astype(jnp.float32)

    in_specs = [
        pl.BlockSpec((pt, R), lambda p, n: (p, 0)),                    # req
        pl.BlockSpec((pt, G), lambda p, n: (p, 0)),                    # onehot
        pl.BlockSpec((G, nt), lambda p, n: (0, n)),                    # feas
    ]
    args = [req, onehot, feas_f]
    if has_soft:
        in_specs.append(pl.BlockSpec((G, nt), lambda p, n: (0, n)))    # soft
        args.append(group_soft.astype(jnp.float32))
    in_specs += [
        pl.BlockSpec((nt, R), lambda p, n: (n, 0)),                    # free
        pl.BlockSpec((nt,), lambda p, n: (n,)),                        # scores
    ]
    args += [free, base_scores.astype(jnp.float32)]

    out = pl.pallas_call(
        functools.partial(_best_node_kernel, index_span=span, use_soft=has_soft),
        grid=(N // pt, M // nt),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((pt, 2), lambda p, n: (p, 0)),
        out_shape=jax.ShapeDtypeStruct((N, 2), jnp.int32),
        scratch_shapes=[pltpu.VMEM((pt,), jnp.int32)],
        interpret=interpret,
    )(*args)

    feasible = out[:, 1] > 0
    best = jnp.where(feasible, M - out[:, 0], 0).astype(jnp.int32)
    return best, feasible
