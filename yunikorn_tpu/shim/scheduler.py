"""KubernetesShim: the scheduler service.

Role-equivalent to pkg/shim/scheduler.go: struct :46-54, NewShimScheduler
:66-96, Run :191-224 with the startup ordering that matters — dispatcher →
placeholder manager → informers → register RM → initialize state → scheduling
pump — schedule() :175-189 (per tick: drive every app's Schedule(), remove
Failed apps whose tasks all terminated :178-182), registerShimLayer :137-172.

Commit/bind drain vs the pipelined core: the core delivers cycle N's
AllocationResponses (assume → TASK_ALLOCATED → dispatcher → bind pool)
AFTER dispatching cycle N+1's solve, so the drain runs while the device (or
XLA's native thread pool) executes the next solve — off the critical path
without a second Python thread contending for the GIL. The shutdown
ordering that keeps this safe is the one every caller already uses
(cmd/scheduler.py, MockScheduler.stop): stop the CORE first — it drains any
in-flight pipelined cycle — then stop the shim, so no callback ever lands
in a stopped dispatcher.
"""
from __future__ import annotations

import threading
import time
from typing import Optional

from yunikorn_tpu import __version__
from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache.context import Context
from yunikorn_tpu.cache.scheduler_callback import AsyncRMCallback
from yunikorn_tpu.client.interfaces import APIProvider
from yunikorn_tpu.common.si import RegisterResourceManagerRequest, SchedulerAPI
from yunikorn_tpu.conf.schedulerconf import get_holder
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.dispatcher.dispatcher import EventType
from yunikorn_tpu.log.logger import log

logger = log("shim.scheduler")


class KubernetesShim:
    def __init__(self, api_provider: APIProvider, scheduler_api: SchedulerAPI,
                 context: Optional[Context] = None):
        self.api_provider = api_provider
        self.scheduler_api = scheduler_api
        self.context = context or Context(api_provider, scheduler_api)
        self.callback = AsyncRMCallback(self.context)
        self._stop = threading.Event()
        self._pump_thread: Optional[threading.Thread] = None
        self.outstanding_apps_logged = 0

        dispatcher = dispatch_mod.get_dispatcher()
        # shim-side observability joins the core's registry: dispatcher
        # throughput/backlog counters land next to the cycle metrics so one
        # /metrics scrape covers the whole submit→bind path
        obs = getattr(scheduler_api, "obs", None)
        if obs is not None:
            dispatcher.attach_metrics(obs)
            if hasattr(api_provider, "attach_metrics"):
                # reflector restarts + last-sync-age gauges (real provider)
                api_provider.attach_metrics(obs)
            pool = getattr(self.context, "bind_pool", None)
            if pool is not None and hasattr(pool, "attach_metrics"):
                # per-shard bind-pool depth/throughput next to the queue
                # depth gauges: the whole async ingest→bind path scrapes
                # from one registry
                pool.attach_metrics(obs)
        # health sources beyond the core's own (scheduling loop + solver
        # circuits): informer staleness and dispatcher backlog join the
        # /ws/v1/health report when the core carries a monitor
        health = getattr(scheduler_api, "health", None)
        if health is not None:
            from yunikorn_tpu.robustness.health import (
                dispatcher_source,
                informers_source,
            )

            health.register("dispatcher", dispatcher_source(dispatcher))
            if hasattr(api_provider, "sync_ages"):
                health.register("informers", informers_source(api_provider))
        dispatcher.register_event_handler(
            "AppHandler", EventType.APPLICATION, self.context.application_event_handler())
        dispatcher.register_event_handler(
            "TaskHandler", EventType.TASK, self.context.task_event_handler())
        dispatcher.register_event_handler(
            "NodeHandler", EventType.NODE,
            lambda e: logger.debug("node event %s for %s", e.get_event(), e.get_node_id()))

    # ------------------------------------------------------------------- run
    def run(self) -> None:
        """Startup ordering is load-bearing (reference Run :191-224)."""
        # 1. dispatcher
        dispatch_mod.get_dispatcher().start()
        # 2. placeholder manager
        self.context.placeholder_manager.start()
        # 3. informers (no handlers attached yet — recovery reads listings)
        self.api_provider.start()
        self.api_provider.wait_for_sync()
        # 4. register the shim with the core
        self.register_shim_layer()
        # 5. recovery: rebuild state, then attach live handlers
        self.context.initialize_state()
        # 6. scheduling pump
        self._stop.clear()
        self._pump_thread = threading.Thread(target=self._pump, name="shim-pump", daemon=True)
        self._pump_thread.start()
        logger.info("shim is running")

    def register_shim_layer(self) -> None:
        """reference registerShimLayer :137-172."""
        holder = get_holder()
        conf = holder.get()
        request = RegisterResourceManagerRequest(
            rm_id=conf.cluster_id,
            policy_group=conf.policy_group,
            version=__version__,
            build_info={"version": __version__, "arch": "tpu"},
            config=holder.queues_config(),
        )
        self.scheduler_api.register_resource_manager(request, self.callback)

    def _pump(self) -> None:
        interval = self.context.conf.interval
        while not self._stop.is_set():
            try:
                self.schedule()
            except Exception:
                logger.exception("schedule tick failed")
            self._stop.wait(timeout=interval)

    def schedule(self) -> None:
        """One pump tick (reference schedule :175-189)."""
        apps = self.context.applications()
        outstanding = 0
        for app in apps:
            if app.state in (app_mod.NEW, app_mod.ACCEPTED, app_mod.RUNNING,
                             app_mod.RESERVING, app_mod.RESUMING):
                app.schedule()
                outstanding += 1
            elif app.state in (app_mod.FAILED, app_mod.COMPLETED) \
                    and app.are_all_tasks_terminated():
                # garbage-collect terminal apps once every task terminated
                self.context.remove_application(app.application_id)
        self.outstanding_apps_logged = outstanding

    def stop(self) -> None:
        logger.info("stopping shim")
        self._stop.set()
        if self._pump_thread is not None:
            self._pump_thread.join(timeout=5)
            self._pump_thread = None
        self.context.placeholder_manager.stop()
        dispatch_mod.get_dispatcher().stop()
        # after the dispatcher: draining TASK_ALLOCATED events may still
        # submit binds; a closed pool routes them to the failure path
        pool = getattr(self.context, "bind_pool", None)
        if pool is not None:
            pool.shutdown()
        self.api_provider.stop()


def new_shim_scheduler(api_provider: APIProvider, scheduler_api: SchedulerAPI) -> KubernetesShim:
    """reference NewShimScheduler :66-96."""
    return KubernetesShim(api_provider, scheduler_api)
