"""MockScheduler: a full scheduler (real core + real shim) over a fake cluster.

Role-equivalent to the reference's flagship test fake (pkg/shim/
scheduler_mock_test.go:51-370): a *real* core started in-process wired to the
mocked API provider, with assertion helpers that inspect both shim FSM state
and core partition state (waitAndAssertTaskState :165, GetActiveNodeCountInCore
:295). Integration tests and the throughput benchmark run full submit→bind
cycles with zero Kubernetes. Lives in the package (not tests/) because
bench.py builds on it, mirroring scheduler_perf_test.go's use.
"""
from __future__ import annotations

import time
from typing import List, Optional

from yunikorn_tpu.cache.context import Context
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.client.fake import BindStats, FakeCluster
from yunikorn_tpu.common.objects import ConfigMap, Node, ObjectMeta, Pod
from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
from yunikorn_tpu.core.scheduler import CoreScheduler
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.shim.scheduler import KubernetesShim


class MockScheduler:
    def __init__(self):
        self.cluster: Optional[FakeCluster] = None
        self.core: Optional[CoreScheduler] = None
        self.shim: Optional[KubernetesShim] = None
        self.context: Optional[Context] = None

    # ------------------------------------------------------------- lifecycle
    def _boot(self, queues_yaml: str, interval: float, core_interval: float,
              solver_policy: Optional[str], conf_extra: Optional[dict]) -> None:
        """Shared conf/dispatcher/core/shim construction for init + restart
        (self.cluster must already exist). conf_extra's solver.shards (or
        the configmap's) selects the control-plane shard count: "auto"/1
        builds the plain CoreScheduler, N >= 2 the sharded front end
        (core/shard.make_core_scheduler)."""
        reset_for_tests()
        holder = get_holder()
        cm = {"service.schedulingInterval": str(interval),
              "queues.yaml": queues_yaml}
        cm.update(conf_extra or {})
        holder.update_config_maps([cm], initial=True)
        dispatch_mod.reset_dispatcher()
        cache = SchedulerCache()
        from yunikorn_tpu.core.scheduler import SolverOptions
        from yunikorn_tpu.core.shard import make_core_scheduler

        self._solver_policy = solver_policy
        from yunikorn_tpu.obs.flightrec import FlightRecorderOptions
        from yunikorn_tpu.obs.slo import SloOptions
        from yunikorn_tpu.robustness.failover import FailoverOptions
        from yunikorn_tpu.robustness.supervisor import SupervisorOptions

        self.core = make_core_scheduler(
            cache, shards=holder.get().solver_shards,
            interval=core_interval, solver_policy=solver_policy,
            solver_options=SolverOptions.from_conf(holder.get()),
            supervisor_options=SupervisorOptions.from_conf(holder.get()),
            slo_options=SloOptions.from_conf(holder.get()),
            failover_options=FailoverOptions.from_conf(holder.get()),
            journey_capacity=holder.get().obs_journey_capacity,
            flightrec_options=FlightRecorderOptions.from_conf(holder.get()),
            delivery_high_water=holder.get().solver_delivery_high_water)
        self.context = Context(self.cluster, self.core, cache=cache)
        self.shim = KubernetesShim(self.cluster, self.core, context=self.context)

    def init(self, queues_yaml: str = "", interval: float = 0.05,
             core_interval: float = 0.02, solver_policy: Optional[str] = None,
             conf_extra: Optional[dict] = None) -> None:
        self.cluster = FakeCluster()
        self._boot(queues_yaml, interval, core_interval, solver_policy,
                   conf_extra)

    def start(self) -> None:
        self.core.start()
        self.shim.run()

    def restart(self, queues_yaml: str = "", interval: float = 0.05,
                core_interval: float = 0.02, solver_policy: Optional[str] = None,
                conf_extra: Optional[dict] = None) -> None:
        """Simulate a scheduler-pod restart with (possibly changed) config:
        tear down core+shim, keep the CLUSTER (pods/nodes/configmaps persist
        in the API server), then boot a fresh core+shim that must recover the
        existing state (reference e2e restart_changed_config suite: bound
        pods survive recovery, the new config governs new pods).
        solver_policy=None keeps the policy init() was given."""
        self.stop()
        self.cluster.clear_event_handlers()
        self._boot(queues_yaml, interval, core_interval,
                   solver_policy or getattr(self, "_solver_policy", None),
                   conf_extra)
        self.start()

    def stop(self) -> None:
        # core first: its solve thread must not fire callbacks into a stopped
        # dispatcher
        if self.core is not None:
            self.core.stop()
        if self.shim is not None:
            self.shim.stop()

    # --------------------------------------------------------------- actions
    def add_node(self, node: Node) -> None:
        self.cluster.add_node(node)

    def add_nodes(self, nodes: List[Node]) -> None:
        for n in nodes:
            self.cluster.add_node(n)

    def add_pod(self, pod: Pod) -> Pod:
        return self.cluster.add_pod(pod)

    def add_pods(self, pods: List[Pod]) -> None:
        for p in pods:
            self.cluster.add_pod(p)

    def succeed_pod(self, pod: Pod) -> None:
        self.cluster.succeed_pod(pod.uid)

    def delete_pod(self, pod: Pod) -> None:
        self.cluster.delete_pod(pod.uid)

    def update_config(self, queues_yaml: str, namespace: str = "yunikorn") -> None:
        self.cluster.add_configmap(ConfigMap(
            metadata=ObjectMeta(name="yunikorn-configs", namespace=namespace),
            data={"queues.yaml": queues_yaml},
        ))

    # ------------------------------------------------------------ assertions
    def wait_for_task_state(self, app_id: str, task_id: str, expected: str,
                            timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        last = "<no task>"
        while time.time() < deadline:
            app = self.context.get_application(app_id)
            if app is not None:
                task = app.get_task(task_id)
                if task is not None:
                    last = task.state
                    if last == expected:
                        return
            time.sleep(0.02)
        raise AssertionError(
            f"task {task_id} of {app_id}: expected state {expected}, last seen {last}")

    def wait_for_app_state(self, app_id: str, expected: str, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        last = "<no app>"
        while time.time() < deadline:
            app = self.context.get_application(app_id)
            if app is not None:
                last = app.state
                if last == expected:
                    return
            time.sleep(0.02)
        raise AssertionError(f"app {app_id}: expected state {expected}, last seen {last}")

    def wait_for_bound_count(self, count: int, timeout: float = 30.0) -> None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.bind_stats().success_count >= count:
                return
            time.sleep(0.02)
        raise AssertionError(
            f"expected {count} binds, got {self.bind_stats().success_count}")

    def get_active_node_count_in_core(self) -> int:
        return self.core.partition.active_node_count()

    def get_pod_assignment(self, pod: Pod) -> str:
        cur = self.cluster.get_pod(pod.uid)
        return cur.spec.node_name if cur is not None else ""

    def bind_stats(self) -> BindStats:
        return self.cluster.get_client().bind_stats

    def core_allocation_count(self) -> int:
        return self.core.metrics["allocation_attempt_allocated"]
