"""Triggered flight recorder: bounded post-mortem bundles on failure.

When something goes wrong — an SLO objective fires, a shard gets
quarantined, the supervisor's breaker ladder exhausts every tier, the
watchdog abandons a dispatch, or an operator hits `/ws/v1/flightrec/dump`
— the in-memory evidence (cycle rings, journey tails, ledger state) is
exactly what a post-mortem needs and exactly what the next eviction or
rebuild destroys. The recorder dumps it to disk at the moment of the
trigger: a bundle directory of JSON files written atomically
(tmp-dir + rename: a reader never sees a half-written bundle), kept in a
capped ring (oldest bundle deleted past `max_recordings` — bounded disk,
always), debounced per trigger (a violation storm yields ONE bundle per
debounce window, not one per tick).

Sources are pluggable callables registered by the owning scheduler
(merged fleet trace window, metrics snapshot, ledger `audit()`, cycle
entry tail, journey tail, duel stats); a failing source records its error
string in the manifest instead of killing the dump. `stage()` lets a
caller attach evidence ahead of the trigger — the quarantine path stages
the dying shard's frozen rings BEFORE the engine detaches, so the bundle
the quarantine trigger writes moments later still contains the dead
shard's final cycle spans.
"""
from __future__ import annotations

import json
import logging
import os
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# every trigger gets a stable zero series (dashboards rate() them)
TRIGGERS = ("slo_violation", "quarantine", "breaker_exhausted",
            "watchdog_abandoned", "manual")


@dataclass(frozen=True)
class FlightRecorderOptions:
    """`observability.flightRecorder*` keys (see conf/schedulerconf.py)."""
    dir: str = ""             # empty → recorder disabled (no disk writes)
    max_recordings: int = 8   # capped ring of bundle directories
    window_s: float = 30.0    # merged-trace export window per bundle
    cycle_tail: int = 32      # last-K cycle entries per bundle
    journey_tail: int = 64    # journey records per bundle
    debounce_s: float = 30.0  # per-trigger minimum spacing

    @property
    def enabled(self) -> bool:
        return bool(self.dir)

    @classmethod
    def from_conf(cls, conf) -> "FlightRecorderOptions":
        return cls(
            dir=getattr(conf, "obs_flightrec_dir", ""),
            max_recordings=getattr(conf, "obs_flightrec_max", 8),
            window_s=getattr(conf, "obs_flightrec_window_s", 30.0),
            debounce_s=getattr(conf, "obs_flightrec_debounce_s", 30.0),
        )


class FlightRecorder:
    """Thread-safe; `record()` serializes dumps under one lock. Trigger
    callers (SLO tick, supervisor execute(), quarantine transaction) MUST
    invoke it outside their own engine locks — sources re-enter the
    metrics registry, the ledger, and the fleet tracer."""

    def __init__(self, options: FlightRecorderOptions, registry=None):
        self.options = options
        # RLock + _dumping: a source can re-enter record() on the dumping
        # thread (metrics snapshot -> collect hooks -> SLO tick -> a fresh
        # violation edge); the reentrant call must no-op, not deadlock
        self._mu = threading.RLock()
        self._dumping = False
        self._seq = 0
        self._last: Dict[str, float] = {}      # trigger -> last dump wall time
        self._staged: Dict[str, object] = {}   # pre-trigger evidence
        self._sources: Dict[str, Callable[[], object]] = {}
        self.recordings_total = 0
        self.debounced_total = 0
        self._by_trigger: Dict[str, int] = {t: 0 for t in TRIGGERS}
        self._m_recordings = None
        if registry is not None:
            self.attach_metrics(registry)

    def attach_metrics(self, registry) -> None:
        self._m_recordings = registry.counter(
            "flight_recordings_total",
            "post-mortem flight-recorder bundles written, by trigger "
            "(slo_violation, quarantine, breaker_exhausted, "
            "watchdog_abandoned, manual); debounced/disabled triggers "
            "are not counted", labelnames=("trigger",))
        for t in TRIGGERS:
            self._m_recordings.inc(0, trigger=t)

    # ------------------------------------------------------------- sources
    def add_source(self, name: str, fn: Callable[[], object]) -> None:
        """Register a bundle source: fn() -> JSON-able payload, written to
        `<bundle>/<name>.json`. Errors are caught per-source."""
        with self._mu:
            self._sources[name] = fn

    def stage(self, name: str, payload: object) -> None:
        """Attach evidence to the NEXT bundle (consumed on dump). The
        quarantine path stages the dying shard's frozen rings before the
        engine detaches; the trigger fires after the transaction."""
        with self._mu:
            self._staged[name] = payload

    # --------------------------------------------------------------- dumps
    def record(self, trigger: str, reason: str = "",
               force: bool = False) -> Optional[str]:
        """Write one bundle; returns its path, or None when disabled or
        debounced. `force` (manual / REST) bypasses the debounce."""
        if not self.options.enabled:
            return None
        now = time.time()
        with self._mu:
            if self._dumping:
                return None  # reentrant trigger from a source — drop it
            last = self._last.get(trigger, 0.0)
            if not force and now - last < self.options.debounce_s:
                self.debounced_total += 1
                return None
            self._last[trigger] = now
            self._seq += 1
            seq = self._seq
            sources = dict(self._sources)
            staged, self._staged = self._staged, {}
            self._dumping = True
            try:
                path = self._write_locked(seq, trigger, reason, now,
                                          sources, staged)
            finally:
                self._dumping = False
            if path is None:
                return None
            self.recordings_total += 1
            self._by_trigger[trigger] = self._by_trigger.get(trigger, 0) + 1
        if self._m_recordings is not None:
            self._m_recordings.inc(
                trigger=trigger if trigger in TRIGGERS else "manual")
        logger.warning("flight recorder: %s bundle -> %s (%s)",
                       trigger, path, reason or "no reason given")
        return path

    def _write_locked(self, seq: int, trigger: str, reason: str, now: float,
                      sources: Dict[str, Callable[[], object]],
                      staged: Dict[str, object]) -> Optional[str]:
        """Atomic bundle write: everything lands in a dot-prefixed tmp dir,
        then ONE rename publishes it (list_recordings skips dot dirs, so a
        concurrent reader never sees a partial bundle)."""
        base = self.options.dir
        final = os.path.join(base, f"rec-{seq:04d}-{trigger}")
        tmp = os.path.join(base, f".tmp-{seq:04d}")
        try:
            os.makedirs(tmp, exist_ok=True)
            manifest = {
                "seq": seq,
                "trigger": trigger,
                "reason": reason,
                "wall_time": now,
                "window_s": self.options.window_s,
                "files": [],
                "source_errors": {},
            }
            payloads = dict(staged)
            for name, fn in sources.items():
                try:
                    payloads[name] = fn()
                except Exception as exc:  # evidence > completeness
                    manifest["source_errors"][name] = repr(exc)
            for name, payload in payloads.items():
                fname = f"{name}.json"
                try:
                    with open(os.path.join(tmp, fname), "w") as f:
                        json.dump(payload, f, default=str)
                    manifest["files"].append(fname)
                except Exception as exc:
                    manifest["source_errors"][name] = repr(exc)
            with open(os.path.join(tmp, "manifest.json"), "w") as f:
                json.dump(manifest, f, indent=2, default=str)
            os.rename(tmp, final)
        except OSError:
            logger.exception("flight recorder: bundle write failed")
            shutil.rmtree(tmp, ignore_errors=True)
            return None
        self._prune(base)
        return final

    def _prune(self, base: str) -> None:
        """Bounded-disk contract: keep the newest `max_recordings` bundles
        (sequence numbers sort lexically at %04d), delete the rest."""
        try:
            recs = sorted(d for d in os.listdir(base)
                          if d.startswith("rec-"))
        except OSError:
            return
        for d in recs[: max(len(recs) - self.options.max_recordings, 0)]:
            shutil.rmtree(os.path.join(base, d), ignore_errors=True)

    # --------------------------------------------------------------- reads
    def list_recordings(self) -> List[dict]:
        """Manifests of the bundles currently on disk, oldest first."""
        if not self.options.enabled:
            return []
        try:
            recs = sorted(d for d in os.listdir(self.options.dir)
                          if d.startswith("rec-"))
        except OSError:
            return []
        out = []
        for d in recs:
            try:
                with open(os.path.join(self.options.dir, d,
                                       "manifest.json")) as f:
                    m = json.load(f)
            except (OSError, ValueError):
                m = {}
            m["path"] = os.path.join(self.options.dir, d)
            out.append(m)
        return out

    def stats(self) -> dict:
        """The `trace` block's recorder summary (bench + trace_replay)."""
        with self._mu:
            return {
                "enabled": self.options.enabled,
                "recordings": self.recordings_total,
                "debounced": self.debounced_total,
                "by_trigger": {t: n for t, n in self._by_trigger.items()
                               if n},
            }
