"""Mini Prometheus text-exposition parser + validator.

Shared by the test suite and `make obs-smoke`: parses the 0.0.4 text format
the registry emits and checks the invariants a real Prometheus scrape relies
on — every sample belongs to a `# TYPE`-declared family ("unregistered
emission" fails the smoke), histogram `_bucket` series are cumulative and
monotone with a `+Inf` bucket equal to `_count`, counters never go negative,
and label values parse under the escaping rules. Intentionally small: it
accepts exactly the subset the registry produces (no timestamps, no exemplar
syntax).
"""
from __future__ import annotations

import dataclasses
import math
import re
from typing import Dict, List, Optional, Tuple

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r" (?P<value>[^ ]+)$")
_TYPE_RE = re.compile(
    r"^# TYPE (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r" (?P<kind>counter|gauge|histogram|summary|untyped)$")
_HELP_RE = re.compile(r"^# HELP (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*) (?P<help>.*)$")
_LABEL_NAME_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_]*$")


@dataclasses.dataclass
class Sample:
    name: str
    labels: Dict[str, str]
    value: float
    line_no: int


@dataclasses.dataclass
class Family:
    name: str
    kind: str
    help: str = ""
    samples: List[Sample] = dataclasses.field(default_factory=list)


class ParseError(ValueError):
    pass


def _parse_labels(text: str, line_no: int) -> Dict[str, str]:
    """Parse `k="v",k2="v2"` handling \\\\, \\" and \\n escapes."""
    labels: Dict[str, str] = {}
    i, n = 0, len(text)
    while i < n:
        eq = text.find("=", i)
        if eq < 0:
            raise ParseError(f"line {line_no}: malformed label block {text!r}")
        name = text[i:eq]
        if not _LABEL_NAME_RE.match(name):
            raise ParseError(f"line {line_no}: bad label name {name!r}")
        if eq + 1 >= n or text[eq + 1] != '"':
            raise ParseError(f"line {line_no}: unquoted label value for {name}")
        j = eq + 2
        out = []
        while j < n:
            c = text[j]
            if c == "\\":
                if j + 1 >= n:
                    raise ParseError(f"line {line_no}: dangling escape")
                nxt = text[j + 1]
                if nxt == "n":
                    out.append("\n")
                elif nxt in ('"', "\\"):
                    out.append(nxt)
                else:
                    raise ParseError(
                        f"line {line_no}: invalid escape \\{nxt}")
                j += 2
                continue
            if c == '"':
                break
            out.append(c)
            j += 1
        else:
            raise ParseError(f"line {line_no}: unterminated label value")
        if name in labels:
            raise ParseError(f"line {line_no}: duplicate label {name!r}")
        labels[name] = "".join(out)
        i = j + 1
        if i < n:
            if text[i] != ",":
                raise ParseError(
                    f"line {line_no}: expected ',' after label, got "
                    f"{text[i]!r}")
            i += 1
    return labels


def _parse_value(raw: str, line_no: int) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    try:
        return float(raw)
    except ValueError:
        raise ParseError(f"line {line_no}: bad sample value {raw!r}")


def parse_exposition(text: str) -> Dict[str, Family]:
    """Parse the full exposition; raises ParseError on any malformed line.

    Histogram `_bucket`/`_sum`/`_count` samples are attached to their base
    family. A sample whose family has no preceding `# TYPE` raises — the
    registry always declares before emitting, so an unregistered emission is
    a bug, not a style choice.
    """
    families: Dict[str, Family] = {}
    for line_no, line in enumerate(text.splitlines(), start=1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            if m:
                name = m.group("name")
                if name in families and families[name].samples:
                    raise ParseError(
                        f"line {line_no}: TYPE for {name} after samples")
                fam = families.setdefault(name, Family(name, m.group("kind")))
                fam.kind = m.group("kind")
                continue
            m = _HELP_RE.match(line)
            if m:
                fam = families.get(m.group("name"))
                if fam is None:
                    fam = families[m.group("name")] = Family(
                        m.group("name"), "")
                fam.help = m.group("help")
                continue
            raise ParseError(f"line {line_no}: unparseable comment {line!r}")
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ParseError(f"line {line_no}: unparseable sample {line!r}")
        sname = m.group("name")
        labels = _parse_labels(m.group("labels") or "", line_no)
        value = _parse_value(m.group("value"), line_no)
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            cand = sname[: -len(suffix)] if sname.endswith(suffix) else None
            if cand and cand in families and families[cand].kind == "histogram":
                base = cand
                break
        fam = families.get(base)
        if fam is None or not fam.kind:
            raise ParseError(
                f"line {line_no}: sample {sname!r} emitted without a "
                f"# TYPE declaration (unregistered metric)")
        fam.samples.append(Sample(sname, labels, value, line_no))
    return families


def validate_exposition(text: str,
                        required: Tuple[str, ...] = ()) -> List[str]:
    """Full-surface validation; returns a list of error strings (empty =
    valid). `required` names families that must be present with samples."""
    errors: List[str] = []
    try:
        families = parse_exposition(text)
    except ParseError as e:
        return [str(e)]

    for name in required:
        fam = families.get(name)
        if fam is None:
            errors.append(f"required family {name!r} missing")
        elif not fam.samples:
            errors.append(f"required family {name!r} has no samples")

    for fam in families.values():
        if fam.kind == "counter":
            for s in fam.samples:
                if s.name != fam.name:
                    errors.append(
                        f"{fam.name}: counter sample named {s.name!r}")
                if s.value < 0:
                    errors.append(
                        f"{fam.name}: negative counter value {s.value}")
        elif fam.kind == "gauge":
            for s in fam.samples:
                if s.name != fam.name:
                    errors.append(f"{fam.name}: gauge sample named {s.name!r}")
        elif fam.kind == "histogram":
            errors.extend(_validate_histogram(fam))
    return errors


def quantile_from_buckets(q: float,
                          buckets: List[Tuple[float, float]]) -> Optional[float]:
    """Prometheus-style histogram_quantile over cumulative (le, count)
    pairs: linear interpolation inside the bucket holding the q-rank, with
    the conventional edge rules — rank in the first bucket interpolates
    from 0, rank in the +Inf bucket clamps to the highest finite edge.

    This is the EXPOSITION-side estimator (error = the bucket's full
    width): the SLO engine's streaming sketch exists precisely because this
    interpolation cannot tell a 1.1 s p99 from a 2.4 s one on the default
    LATENCY_BUCKETS_S ladder. Use this helper for dashboards/tests over
    scraped text; use the sketch for objectives.
    """
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    bl = sorted(buckets)
    if not bl or bl[-1][0] != math.inf:
        return None
    total = bl[-1][1]
    if total <= 0:
        return None
    rank = q * total
    prev_le, prev_count = 0.0, 0.0
    for le, count in bl:
        if count >= rank:
            if le == math.inf:
                # conventional clamp: the estimate cannot exceed the
                # highest finite bucket edge
                return prev_le if len(bl) > 1 else None
            width = le - prev_le
            in_bucket = count - prev_count
            if in_bucket <= 0 or width <= 0:
                return le
            return prev_le + width * (rank - prev_count) / in_bucket
        prev_le, prev_count = le, count
    return prev_le


def histogram_quantile(q: float, fam: Family,
                       labels: Optional[Dict[str, str]] = None) -> Optional[float]:
    """histogram_quantile over a parsed exposition Family: collects the
    `_bucket` samples of the child matching `labels` (ignoring `le`) and
    interpolates. None when the family has no matching buckets."""
    if fam.kind != "histogram":
        raise ValueError(f"{fam.name}: not a histogram family")
    want = dict(labels or {})
    pairs: List[Tuple[float, float]] = []
    for s in fam.samples:
        if s.name != fam.name + "_bucket" or "le" not in s.labels:
            continue
        rest = {k: v for k, v in s.labels.items() if k != "le"}
        if rest != want:
            continue
        le = math.inf if s.labels["le"] == "+Inf" else float(s.labels["le"])
        pairs.append((le, s.value))
    if not pairs:
        return None
    return quantile_from_buckets(q, pairs)


def _validate_histogram(fam: Family) -> List[str]:
    errors: List[str] = []
    # group the samples per child (labelset minus `le`)
    children: Dict[Tuple[Tuple[str, str], ...], dict] = {}
    for s in fam.samples:
        base_labels = tuple(sorted(
            (k, v) for k, v in s.labels.items() if k != "le"))
        child = children.setdefault(
            base_labels, {"buckets": [], "sum": None, "count": None})
        if s.name == fam.name + "_bucket":
            if "le" not in s.labels:
                errors.append(f"{fam.name}: _bucket without le label")
                continue
            le = math.inf if s.labels["le"] == "+Inf" else float(s.labels["le"])
            child["buckets"].append((le, s.value, s.line_no))
        elif s.name == fam.name + "_sum":
            child["sum"] = s.value
        elif s.name == fam.name + "_count":
            child["count"] = s.value
        else:
            errors.append(f"{fam.name}: unexpected sample {s.name!r}")
    if not children:
        errors.append(f"{fam.name}: histogram with no samples")
    for base_labels, child in children.items():
        tag = fam.name + (str(dict(base_labels)) if base_labels else "")
        if not child["buckets"]:
            errors.append(f"{tag}: no _bucket series")
            continue
        bl = sorted(child["buckets"])
        les = [b[0] for b in bl]
        if les[-1] != math.inf:
            errors.append(f"{tag}: missing le=\"+Inf\" bucket")
        if len(set(les)) != len(les):
            errors.append(f"{tag}: duplicate le values")
        counts = [b[1] for b in bl]
        if any(b > a for a, b in zip(counts[1:], counts)):
            errors.append(f"{tag}: bucket counts not monotone: {counts}")
        if child["count"] is None:
            errors.append(f"{tag}: missing _count")
        elif les[-1] == math.inf and counts[-1] != child["count"]:
            errors.append(
                f"{tag}: +Inf bucket {counts[-1]} != _count {child['count']}")
        if child["sum"] is None:
            errors.append(f"{tag}: missing _sum")
    return errors
