"""Structured cycle tracer: ring-buffered stage spans + Chrome trace export.

Subsumes the old `CoreScheduler._pipeline_trace` deque (a tuple log readable
only from tests): every scheduling-cycle stage — gate / encode / dispatch /
solve / materialize / commit / publish — records a span with its cycle id and
stage-specific args (device-transfer bytes, compile-cache outcome, batch
size), and per-pod bind spans ride in a separate ring so a 50k-pod bind storm
cannot evict the cycle skeleton. Export is Chrome trace-event JSON
(`chrome_trace()`): complete events ("ph":"X", microsecond ts/dur) on named
lanes, loadable in Perfetto / chrome://tracing — the pipelined cycle's
overlap (encode of cycle N+1 under solve N) is directly visible as
overlapping spans on the prepare and device lanes.

Lock-cheap: one mutex guarding two bounded deques; a span append is a tuple
build + deque.append.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple


class Span(NamedTuple):
    name: str
    cycle_id: int
    t0: float
    t1: float
    args: tuple  # ((key, value), ...) — hashable, built once


# stage → (lane title, tid). Lanes separate the pipeline's concurrent actors
# so overlap renders as parallel tracks, not stacked self-overlap.
LANES: Dict[str, Tuple[str, int]] = {
    "gate": ("host: gate+encode", 1),
    "encode": ("host: gate+encode", 1),
    "dispatch": ("host: gate+encode", 1),
    "solve": ("device: solve", 2),
    "materialize": ("host: commit+publish", 3),
    "commit": ("host: commit+publish", 3),
    "housekeeping": ("host: commit+publish", 3),
    "publish": ("host: commit+publish", 3),
    "bind": ("shim: bind", 4),
    # front-end lanes (the sharded front's own spans, pid FRONT_PID)
    "route": ("front: route", 6),
    "repair": ("front: repair", 7),
    "ledger_confirm": ("front: ledger", 8),
    "quarantine": ("front: failover", 9),
    "rehome": ("front: failover", 9),
}
_DEFAULT_LANE = ("host: other", 5)


class CycleTracer:
    def __init__(self, capacity: int = 4096, pod_capacity: int = 2048):
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        self._pod_spans: collections.deque = collections.deque(
            maxlen=pod_capacity)

    def add(self, name: str, cycle_id: int, t0: float, t1: float,
            **args) -> None:
        span = Span(name, cycle_id, t0, t1, tuple(sorted(args.items())))
        with self._lock:
            self._spans.append(span)

    def add_pod(self, name: str, cycle_id: int, t0: float, t1: float,
                **args) -> None:
        """Per-pod span (own ring: bind storms must not evict cycle spans)."""
        span = Span(name, cycle_id, t0, t1, tuple(sorted(args.items())))
        with self._lock:
            self._pod_spans.append(span)

    def spans(self, pods: bool = False) -> List[Span]:
        with self._lock:
            out = list(self._spans)
            if pods:
                out.extend(self._pod_spans)
        return out

    def rings(self) -> Tuple[List[Span], List[Span]]:
        """Atomic (cycle_spans, pod_spans) snapshot — the quarantine
        freeze and the flight recorder read both rings in one lock trip.
        Lock-cheap (two list copies): safe to call on a WEDGED core, whose
        core/pipeline locks may be held forever — this mutex never is."""
        with self._lock:
            return list(self._spans), list(self._pod_spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._pod_spans.clear()

    # --------------------------------------------------------------- export
    def chrome_events(self, pid: int = 1,
                      process_name: str = "yunikorn-tpu scheduler",
                      epoch: Optional[float] = None,
                      since: Optional[float] = None
                      ) -> Tuple[List[dict], List[dict]]:
        """(meta_events, data_events) for one pid lane group.

        pid/process_name parameterized so two tracers' exports concatenate
        without lane collisions (pre-round-20 both were hardcoded, so a
        fleet merge interleaved unrelated shards on the same tracks).
        epoch: shared zero timestamp for cross-tracer correlation — every
        tracer in a merged export must subtract the SAME epoch or the
        timelines skew by their first-span offsets. since: drop spans that
        ended before this wall time (the flight recorder's bounded window).
        """
        spans = self.spans(pods=True)
        if since is not None:
            spans = [s for s in spans if s.t1 >= since]
        if not spans:
            return [], []
        if epoch is None:
            epoch = min(s.t0 for s in spans)
        seen_lanes = {}
        events: List[dict] = []
        for s in spans:
            title, tid = LANES.get(s.name, _DEFAULT_LANE)
            seen_lanes[tid] = title
            args = {"cycle": s.cycle_id}
            args.update(dict(s.args))
            # dur from the ROUNDED endpoints: rounding ts and dur
            # independently lets ts+dur exceed the next span's ts by a
            # ulp, breaking contiguity checks on back-to-back spans
            ts = round((s.t0 - epoch) * 1e6, 3)
            te = round((s.t1 - epoch) * 1e6, 3)
            events.append({
                "name": s.name,
                "cat": "scheduler",
                "ph": "X",
                "pid": pid,
                "tid": tid,
                "ts": ts,
                "dur": round(max(te - ts, 0.0), 3),
                "args": args,
            })
        meta = [{"name": "process_name", "ph": "M", "pid": pid,
                 "args": {"name": process_name}}]
        for tid in sorted(seen_lanes):
            meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                         "tid": tid, "args": {"name": seen_lanes[tid]}})
        meta.append({"name": "thread_sort_index", "ph": "M", "pid": pid,
                     "tid": 1, "args": {"sort_index": 1}})
        return meta, events

    def chrome_trace(self, pid: int = 1,
                     process_name: str = "yunikorn-tpu scheduler") -> dict:
        """Chrome trace-event JSON (the `traceEvents` array format)."""
        meta, events = self.chrome_events(pid=pid, process_name=process_name)
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


class _FrozenTracer:
    """Immutable span snapshot standing in for a dead shard's live tracer
    (same read surface: spans()/chrome_events()). The quarantine path
    captures the dying core's rings into one of these BEFORE the engine
    detaches — the evidence survives the core's rebuild."""

    def __init__(self, spans: List[Span], pod_spans: List[Span]):
        self._frozen = list(spans)
        self._frozen_pods = list(pod_spans)

    def spans(self, pods: bool = False) -> List[Span]:
        out = list(self._frozen)
        if pods:
            out.extend(self._frozen_pods)
        return out

    def rings(self) -> Tuple[List[Span], List[Span]]:
        return list(self._frozen), list(self._frozen_pods)

    chrome_events = CycleTracer.chrome_events
    chrome_trace = CycleTracer.chrome_trace

    def add(self, *a, **kw) -> None:   # a zombie writing post-freeze is noise
        pass

    add_pod = add

    def clear(self) -> None:
        pass


# the front end's pid in a merged fleet export; shard k renders as pid
# FRONT_PID + 1 + k (one process lane per shard, stable across rejoins)
FRONT_PID = 1


class FleetTracer:
    """Cross-shard trace correlation: every registered source (one
    CycleTracer per shard, plus this tracer's own front-end ring for
    routing / repair / ledger / quarantine spans) merges into ONE Chrome
    trace on a SHARED epoch — one pid per shard plus a front-end lane, so
    pipelined overlap AND cross-shard repair hops render on one timeline.

    add()/add_pod() record front-end spans. freeze(idx) swaps a dying
    shard's live tracer for an immutable snapshot (quarantine evidence);
    replace(idx, tracer) re-points the lane at a rebuilt core's tracer on
    rejoin (same pid: the shard's lane is stable across its lifetimes)."""

    def __init__(self, front_name: str = "yunikorn-tpu front end"):
        self._mu = threading.Lock()
        self._front = CycleTracer()
        self._names: Dict[int, str] = {}      # pid -> process name
        self._sources: Dict[int, object] = {FRONT_PID: self._front}
        self._names[FRONT_PID] = front_name

    # ------------------------------------------------------------ sources
    def register(self, idx: int, tracer, name: Optional[str] = None) -> int:
        """Register shard `idx`'s tracer; returns its pid."""
        pid = FRONT_PID + 1 + idx
        with self._mu:
            self._sources[pid] = tracer
            self._names[pid] = name or f"shard {idx}"
        return pid

    def freeze(self, idx: int):
        """Snapshot shard `idx`'s rings into an immutable source (returns
        it). Called by the quarantine transaction BEFORE the engine
        detaches — the dead shard's final cycle spans stay exportable."""
        pid = FRONT_PID + 1 + idx
        with self._mu:
            src = self._sources.get(pid)
            if src is None:
                return None
            if isinstance(src, _FrozenTracer):
                return src
            spans, pod_spans = src.rings()
            frozen = _FrozenTracer(spans, pod_spans)
            self._sources[pid] = frozen
            return frozen

    def replace(self, idx: int, tracer) -> None:
        """Re-point shard `idx`'s lane at a rebuilt core's tracer."""
        with self._mu:
            self._sources[FRONT_PID + 1 + idx] = tracer

    # ------------------------------------------------- front-end span API
    def add(self, name: str, cycle_id: int, t0: float, t1: float,
            **args) -> None:
        self._front.add(name, cycle_id, t0, t1, **args)

    def add_pod(self, name: str, cycle_id: int, t0: float, t1: float,
                **args) -> None:
        self._front.add_pod(name, cycle_id, t0, t1, **args)

    # --------------------------------------------------------------- reads
    def _snapshot(self) -> List[Tuple[int, str, object]]:
        with self._mu:
            return [(pid, self._names[pid], src)
                    for pid, src in sorted(self._sources.items())]

    def spans(self, pods: bool = False) -> List[Span]:
        out: List[Span] = []
        for _pid, _name, src in self._snapshot():
            out.extend(src.spans(pods=pods))
        out.sort(key=lambda s: s.t0)
        return out

    def clear(self) -> None:
        for _pid, _name, src in self._snapshot():
            src.clear()

    def chrome_trace(self, window_s: Optional[float] = None) -> dict:
        """One merged Chrome trace: meta events (process/thread names)
        first, then every source's data events on its own pid, all against
        ONE shared epoch. window_s bounds the export to spans ending in
        the trailing window (flight-recorder bundles stay small)."""
        import time as _time

        sources = self._snapshot()
        since = (_time.time() - window_s) if window_s else None
        epoch = None
        for _pid, _name, src in sources:
            for s in src.spans(pods=True):
                if since is not None and s.t1 < since:
                    continue
                if epoch is None or s.t0 < epoch:
                    epoch = s.t0
        meta_all: List[dict] = []
        data_all: List[dict] = []
        for pid, name, src in sources:
            meta, events = src.chrome_events(pid=pid, process_name=name,
                                             epoch=epoch, since=since)
            if not meta:
                # a registered-but-idle shard still gets its process lane:
                # the merged trace describes the fleet shape, and "shard 2
                # did nothing this window" is itself evidence
                meta = [{"name": "process_name", "ph": "M", "pid": pid,
                         "args": {"name": name}}]
            meta_all.extend(meta)
            data_all.extend(events)
        data_all.sort(key=lambda e: e["ts"])
        return {"traceEvents": meta_all + data_all, "displayTimeUnit": "ms"}
