"""Structured cycle tracer: ring-buffered stage spans + Chrome trace export.

Subsumes the old `CoreScheduler._pipeline_trace` deque (a tuple log readable
only from tests): every scheduling-cycle stage — gate / encode / dispatch /
solve / materialize / commit / publish — records a span with its cycle id and
stage-specific args (device-transfer bytes, compile-cache outcome, batch
size), and per-pod bind spans ride in a separate ring so a 50k-pod bind storm
cannot evict the cycle skeleton. Export is Chrome trace-event JSON
(`chrome_trace()`): complete events ("ph":"X", microsecond ts/dur) on named
lanes, loadable in Perfetto / chrome://tracing — the pipelined cycle's
overlap (encode of cycle N+1 under solve N) is directly visible as
overlapping spans on the prepare and device lanes.

Lock-cheap: one mutex guarding two bounded deques; a span append is a tuple
build + deque.append.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, List, NamedTuple, Optional, Tuple


class Span(NamedTuple):
    name: str
    cycle_id: int
    t0: float
    t1: float
    args: tuple  # ((key, value), ...) — hashable, built once


# stage → (lane title, tid). Lanes separate the pipeline's concurrent actors
# so overlap renders as parallel tracks, not stacked self-overlap.
LANES: Dict[str, Tuple[str, int]] = {
    "gate": ("host: gate+encode", 1),
    "encode": ("host: gate+encode", 1),
    "dispatch": ("host: gate+encode", 1),
    "solve": ("device: solve", 2),
    "materialize": ("host: commit+publish", 3),
    "commit": ("host: commit+publish", 3),
    "housekeeping": ("host: commit+publish", 3),
    "publish": ("host: commit+publish", 3),
    "bind": ("shim: bind", 4),
}
_DEFAULT_LANE = ("host: other", 5)


class CycleTracer:
    def __init__(self, capacity: int = 4096, pod_capacity: int = 2048):
        self._lock = threading.Lock()
        self._spans: collections.deque = collections.deque(maxlen=capacity)
        self._pod_spans: collections.deque = collections.deque(
            maxlen=pod_capacity)

    def add(self, name: str, cycle_id: int, t0: float, t1: float,
            **args) -> None:
        span = Span(name, cycle_id, t0, t1, tuple(sorted(args.items())))
        with self._lock:
            self._spans.append(span)

    def add_pod(self, name: str, cycle_id: int, t0: float, t1: float,
                **args) -> None:
        """Per-pod span (own ring: bind storms must not evict cycle spans)."""
        span = Span(name, cycle_id, t0, t1, tuple(sorted(args.items())))
        with self._lock:
            self._pod_spans.append(span)

    def spans(self, pods: bool = False) -> List[Span]:
        with self._lock:
            out = list(self._spans)
            if pods:
                out.extend(self._pod_spans)
        return out

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()
            self._pod_spans.clear()

    # --------------------------------------------------------------- export
    def chrome_trace(self) -> dict:
        """Chrome trace-event JSON (the `traceEvents` array format)."""
        spans = self.spans(pods=True)
        events: List[dict] = []
        if spans:
            epoch = min(s.t0 for s in spans)
            seen_lanes = {}
            for s in spans:
                title, tid = LANES.get(s.name, _DEFAULT_LANE)
                seen_lanes[tid] = title
                args = {"cycle": s.cycle_id}
                args.update(dict(s.args))
                # dur from the ROUNDED endpoints: rounding ts and dur
                # independently lets ts+dur exceed the next span's ts by a
                # ulp, breaking contiguity checks on back-to-back spans
                ts = round((s.t0 - epoch) * 1e6, 3)
                te = round((s.t1 - epoch) * 1e6, 3)
                events.append({
                    "name": s.name,
                    "cat": "scheduler",
                    "ph": "X",
                    "pid": 1,
                    "tid": tid,
                    "ts": ts,
                    "dur": round(max(te - ts, 0.0), 3),
                    "args": args,
                })
            meta = [{"name": "process_name", "ph": "M", "pid": 1,
                     "args": {"name": "yunikorn-tpu scheduler"}}]
            for tid in sorted(seen_lanes):
                meta.append({"name": "thread_name", "ph": "M", "pid": 1,
                             "tid": tid, "args": {"name": seen_lanes[tid]}})
            meta.append({"name": "thread_sort_index", "ph": "M", "pid": 1,
                         "tid": 1, "args": {"sort_index": 1}})
            events = meta + events
        return {"traceEvents": events, "displayTimeUnit": "ms"}
