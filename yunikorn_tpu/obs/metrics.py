"""Metrics registry: declared counters, gauges, and fixed-bucket histograms.

Role-equivalent to the reference core's metrics package (yunikorn-core
pkg/metrics, scraped by deployments/grafana-dashboard): every metric is
DECLARED with a type and optional label names, so the exposition emits
correct `# TYPE` lines instead of guessing counter-vs-gauge from name
suffixes (the pre-round-7 `webapp/rest._prometheus_text` heuristic), and
histograms emit spec-compliant `_bucket`/`_sum`/`_count` series.

Lock discipline: the registry lock guards only declaration (get-or-create);
each metric child carries its own small mutex, so a hot-path increment costs
one uncontended lock round-trip and a float add. Batch observation
(`Histogram.observe_batch`) amortizes that to one round-trip per commit wave
— the 50k-pod bind storm records latencies without measurable drag.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

_NAME_OK = None  # compiled lazily (module import must stay cheap)


def _check_name(name: str, what: str = "metric") -> None:
    global _NAME_OK
    if _NAME_OK is None:
        import re

        _NAME_OK = re.compile(r"[a-zA-Z_:][a-zA-Z0-9_:]*$")
    if not _NAME_OK.match(name):
        raise ValueError(f"invalid {what} name {name!r}")


def escape_label_value(value: str) -> str:
    return (str(value).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def format_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


# Default bucket ladders. Latencies are seconds (Prometheus convention);
# cycle-stage timings keep the ms unit the rest of the cycle accounting uses.
LATENCY_BUCKETS_S = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
MS_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
              250.0, 500.0, 1000.0, 2500.0, 10000.0)
COUNT_BUCKETS = (1.0, 2.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                 5000.0, 10000.0, 50000.0)


class _Metric:
    """Base: one metric family; children keyed by label-value tuples."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        _check_name(name)
        for ln in labelnames:
            _check_name(ln, "label")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # unlabeled families expose a zero sample immediately: scrape
            # targets see a stable series set from the first scrape on
            self._children[()] = self._new_child()

    def _new_child(self):
        return 0  # int-preserving: integer increments expose as integers

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[ln]) for ln in self.labelnames)

    # ---------------------------------------------------------- collection
    def collect(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...], float]]:
        """[(suffix, ((label, value), ...), sample_value)] snapshot.

        Samples are extracted UNDER the metric lock: histogram children are
        mutated in place by observe_batch, and reading counts/sum/count
        outside the lock could tear mid-wave (a finite bucket exceeding
        +Inf — exactly the monotonicity violation the validator flags)."""
        out = []
        with self._lock:
            for key, child in sorted(self._children.items()):
                out.extend(self._child_samples(
                    tuple(zip(self.labelnames, key)), child))
        return out

    def _child_samples(self, labels, child):
        return [("", labels, child)]

    def sum_over(self, **labels) -> float:
        """Sum of scalar children matching a PARTIAL label set (counters/
        gauges only) — the reading analog of a PromQL sum by(): callers that
        don't care about one dimension (e.g. the policy label on
        supervised_dispatch_total) aggregate over it instead of guessing
        every value."""
        if self.kind == "histogram":
            raise TypeError(
                f"{self.name}: sum_over aggregates scalar children only "
                "(counters/gauges); histogram children are bucket records")
        unknown = set(labels) - set(self.labelnames)
        if unknown:
            raise ValueError(f"{self.name}: unknown labels {sorted(unknown)}")
        idx = [self.labelnames.index(k) for k in labels]
        want = [str(v) for v in labels.values()]
        total = 0
        with self._lock:
            for key, child in self._children.items():
                if all(key[i] == w for i, w in zip(idx, want)):
                    total += child
        return total


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"{self.name}: counter decrease ({amount})")
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._children[key] = self._children.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._children.get(self._key(labels), 0.0)


class _HistChild:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * (n_buckets + 1)  # per-bucket, +Inf last
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = LATENCY_BUCKETS_S):
        bl = [float(b) for b in buckets]
        if not bl or sorted(bl) != bl or len(set(bl)) != len(bl):
            raise ValueError(f"{name}: buckets must be sorted and unique")
        self.buckets = tuple(bl)
        if "le" in labelnames:
            raise ValueError(f"{name}: 'le' is a reserved label")
        super().__init__(name, help, labelnames)

    def _new_child(self):
        return _HistChild(len(self.buckets))

    def add_observer(self, fn) -> None:
        """Tee raw observations to `fn(values)` (called OUTSIDE the metric
        lock with the same batch observe_batch recorded). The SLO engine's
        streaming quantile sketch consumes the exact values this way —
        exposition-bucket interpolation would cap its precision at the
        coarse LATENCY_BUCKETS_S ladder."""
        with self._lock:
            self._observers = getattr(self, "_observers", []) + [fn]

    def remove_observer(self, fn) -> None:
        """Detach a previously-added observer (equality match, so bound
        methods work). A rebuilt shard's SLO engine detaches its
        predecessor's tee — without this, every core rebuild would leave
        one more dead engine consuming each observation batch."""
        with self._lock:
            self._observers = [o for o in getattr(self, "_observers", [])
                               if o != fn]

    def observe(self, value: float, **labels) -> None:
        self.observe_batch((value,), **labels)

    def observe_batch(self, values: Iterable[float], **labels) -> None:
        """One lock round-trip for a whole wave of observations."""
        key = self._key(labels)
        values = [float(v) for v in values]
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._new_child()
            counts, buckets = child.counts, self.buckets
            for v in values:
                counts[bisect.bisect_left(buckets, v)] += 1
                child.sum += v
                child.count += 1
            observers = getattr(self, "_observers", ())
        for fn in observers:
            try:
                fn(values)
            except Exception:
                pass  # a sketch feeder must never fail the hot path

    def _child_samples(self, labels, child: _HistChild):
        out = []
        cum = 0
        for b, c in zip(self.buckets, child.counts):
            cum += c
            out.append(("_bucket", labels + (("le", format_value(b)),), cum))
        out.append(("_bucket", labels + (("le", "+Inf"),), child.count))
        out.append(("_sum", labels, child.sum))
        out.append(("_count", labels, child.count))
        return out

    def child_state(self, **labels) -> Tuple[int, float, Tuple[int, ...]]:
        """(count, sum, per-bucket counts) — test/snapshot helper."""
        with self._lock:
            child = self._children.get(self._key(labels))
            if child is None:
                return 0, 0.0, tuple(0 for _ in range(len(self.buckets) + 1))
            return child.count, child.sum, tuple(child.counts)


class MetricsRegistry:
    """Holds declared metric families; single source for BOTH exposition
    surfaces (`/metrics` Prometheus text via expose(), `/ws/v1/metrics` JSON
    via snapshot()). Declaration is get-or-create so late subsystems (the
    dispatcher, lazily-named per-stage gauges) attach to an already-running
    registry; re-declaring with a different kind or label set is an error —
    that is the 'unregistered emission' the obs smoke guards against."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}
        # refreshers run at the top of every exposition (expose/snapshot):
        # age- and depth-style gauges are push-model, so without this a
        # scrape-only deployment (no health-probe traffic) would read the
        # value from whenever the owner last happened to push — e.g. a
        # wedged informer's last-sync age frozen at 0 during the exact
        # staleness incident the gauge exists to catch
        self._collect_hooks: List = []

    def on_collect(self, fn) -> None:
        """Register a zero-arg callback run before each exposition."""
        with self._lock:
            self._collect_hooks.append(fn)

    def remove_collect_hook(self, fn) -> None:
        """Drop a collect hook (equality match — bound methods compare by
        (instance, function), so an engine can remove its own maybe_tick)."""
        with self._lock:
            self._collect_hooks = [h for h in self._collect_hooks if h != fn]

    def _run_collect_hooks(self) -> None:
        with self._lock:
            hooks = list(self._collect_hooks)
        for fn in hooks:
            try:
                fn()
            except Exception:
                pass  # a scrape must never fail on a refresher

    def _get_or_create(self, cls, name, help, labelnames, **kwargs):
        with self._lock:
            cur = self._metrics.get(name)
            if cur is not None:
                if (type(cur) is not cls
                        or cur.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name!r} re-declared as {cls.kind}"
                        f"{tuple(labelnames)} (was {cur.kind}"
                        f"{cur.labelnames})")
                return cur
            m = cls(name, help, labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = LATENCY_BUCKETS_S) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def families(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # ------------------------------------------------------------- renderers
    def expose(self, prefix: str = "yunikorn_") -> str:
        """Prometheus text exposition (format 0.0.4)."""
        self._run_collect_hooks()
        lines: List[str] = []
        for m in self.families():
            full = prefix + m.name
            if m.help:
                lines.append(f"# HELP {full} {_escape_help(m.help)}")
            lines.append(f"# TYPE {full} {m.kind}")
            for suffix, labels, value in m.collect():
                if labels:
                    lab = ",".join(
                        f'{k}="{escape_label_value(v)}"' for k, v in labels)
                    lines.append(f"{full}{suffix}{{{lab}}} "
                                 f"{format_value(value)}")
                else:
                    lines.append(f"{full}{suffix} {format_value(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """JSON-friendly snapshot: unlabeled counters/gauges flatten to bare
        numbers (the legacy `/ws/v1/metrics` keys, e.g.
        `allocation_attempt_allocated`); labeled families nest by label
        values; histograms report count/sum/per-bucket cumulative counts."""
        self._run_collect_hooks()
        out: dict = {}
        for m in self.families():
            if isinstance(m, Histogram):
                per_child: dict = {}
                with m._lock:  # children mutate in place; read under lock
                    for key, child in sorted(m._children.items()):
                        cum, cum_counts = 0, []
                        for c in child.counts[:-1]:
                            cum += c
                            cum_counts.append(cum)
                        per_child["|".join(key) or "_"] = {
                            "count": child.count,
                            "sum": child.sum,
                            "buckets": {format_value(b): c for b, c in
                                        zip(m.buckets, cum_counts)},
                        }
                out[m.name] = (per_child["_"] if list(per_child) == ["_"]
                               else per_child)
                continue
            samples = m.collect()
            if not m.labelnames:
                out[m.name] = samples[0][2] if samples else 0
            else:
                out[m.name] = {
                    ",".join(f"{k}={v}" for k, v in labels): value
                    for _, labels, value in samples}
        return out
