"""Observability: metrics registry, cycle tracer, exposition tooling.

The reference ships first-class scheduler observability (Prometheus metrics
via yunikorn-core's metrics package, K8s events, pprof) — SURVEY.md lists it
on the capability bar. This package is the TPU port's equivalent grown into a
real subsystem instead of the ad-hoc flat dict it started as:

  metrics.py   — declared counters / gauges / fixed-bucket histograms with
                 labels; correct Prometheus text exposition
  trace.py     — ring-buffered cycle/stage spans + Chrome trace-event export
                 (loads in Perfetto / chrome://tracing)
  promtext.py  — mini exposition parser/validator (tests + `make obs-smoke`)
"""
