"""Per-pod journey ledger: the hop timeline of every ask through the fleet.

Each pod's record accumulates absolute stage marks — admitted (ask arrival)
→ gated (admission-gate pass: path, quota holds) → solved (winning duel arm,
solve ms, AOT outcome) → committed → bound — plus terminal outcomes
(skipped_fleetwide, preempted, released) and cross-shard hops
(repaired-to-shard-k, failover re-admission). Because every stage duration
is the difference of two marks on the SAME clock as the e2e latency
histogram (the bind upcall stamps both), the stage sum tiles the measured
end-to-end latency exactly — millisecond blame attribution per pod, not a
sampled approximation.

Bounded: one OrderedDict capped at `capacity`; inserting past the cap
evicts the oldest record (completed or not). A 10k-pod storm costs dict
ops only — no per-stage allocation beyond the record itself.

Surfaces: `/ws/v1/journey/<uid>` (REST), the `journey_stage_ms{stage}`
histogram family, `journey_completed_total` / `journey_terminal_total`
counters, and the flight recorder's journey tail.
"""
from __future__ import annotations

import collections
import threading
from typing import Dict, Iterable, List, Optional

from yunikorn_tpu.obs.metrics import MS_BUCKETS, MetricsRegistry

# stage label = the hop being COMPLETED by that mark: `gated` spans
# admitted->gated, `solved` spans gated->solved, and so on. Four durations,
# five marks; their sum is exactly bound - admitted.
STAGES = ("gated", "solved", "committed", "bound")

# terminal outcomes get stable zero series (dashboards rate() them)
OUTCOMES = ("bound", "skipped_fleetwide", "preempted", "released")

_ORDER = {"admitted": 0, "gated": 1, "solved": 2, "committed": 3, "bound": 4}


class JourneyLedger:
    """Thread-safe bounded map: pod uid (allocation key) -> journey record.

    Lock discipline: one leaf mutex; every call is dict ops + at most one
    batched histogram observation — safe from the core lock, bind worker
    threads and the sharded front end alike."""

    def __init__(self, capacity: int = 8192,
                 registry: Optional[MetricsRegistry] = None):
        self._mu = threading.Lock()
        self._cap = max(int(capacity), 64)
        self._j: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()
        self.admitted_total = 0
        self.completed_total = 0
        self.evicted_total = 0
        self._m_stage = self._m_completed = self._m_terminal = None
        if registry is not None:
            self.attach_metrics(registry)

    def attach_metrics(self, registry: MetricsRegistry) -> None:
        self._m_stage = registry.histogram(
            "journey_stage_ms",
            "per-pod journey stage durations (ms): each stage spans from "
            "the previous mark to its own — gated = ask arrival to gate "
            "pass, solved = gate to solve, committed = solve to commit, "
            "bound = commit to shim bind; the four sum to the pod's exact "
            "end-to-end latency", labelnames=("stage",),
            buckets=MS_BUCKETS)
        for stage in STAGES:
            # stable zero child per stage: the exposition surface carries
            # the family before the first bind (and validates against it)
            self._m_stage.observe_batch((), stage=stage)
        self._m_completed = registry.counter(
            "journey_completed_total",
            "pod journeys that reached bound with a full hop timeline")
        self._m_terminal = registry.counter(
            "journey_terminal_total",
            "pod journeys by terminal outcome (bound, skipped_fleetwide = "
            "every shard tried and refused, preempted = victim released, "
            "released = ask withdrawn before bind)",
            labelnames=("outcome",))
        for out in OUTCOMES:
            self._m_terminal.inc(0, outcome=out)

    # ------------------------------------------------------------- writers
    def admit(self, keys: Iterable[str], t: float,
              shard: Optional[str] = None) -> None:
        """Open (or re-open) journeys at ask arrival. A key re-admitted
        after a discard (shard repair migration, failover re-routing)
        RESETS its admitted mark and clears the stale gate/solve marks —
        the measured e2e span restarts at re-submission, and the journey
        must tile THAT window, not the original one; the hop is kept in
        `hops` so the detour stays attributable."""
        with self._mu:
            for k in keys:
                rec = self._j.get(k)
                if rec is None:
                    self.admitted_total += 1
                    rec = {"t": {"admitted": t}, "attrs": {}, "hops": [],
                           "outcome": None}
                    if shard is not None:
                        rec["attrs"]["shard"] = shard
                    self._j[k] = rec
                    while len(self._j) > self._cap:
                        self._j.popitem(last=False)
                        self.evicted_total += 1
                elif (rec["t"].get("committed") is None
                      and rec["outcome"] != "bound"):
                    # committed/bound journeys are settled history — a
                    # re-sent ask for a placed pod must not rewrite them
                    marks = rec["t"]
                    if marks.get("admitted") is not None:
                        rec["hops"].append(
                            f"readmitted@s{shard}" if shard is not None
                            else "readmitted")
                    marks["admitted"] = t
                    marks.pop("gated", None)
                    marks.pop("solved", None)
                    if shard is not None:
                        rec["attrs"]["shard"] = shard
                    # a re-admitted journey is live again: an earlier
                    # non-bind outcome (skipped_fleetwide cooldown, a
                    # failover detour) no longer describes it
                    if rec["outcome"] not in (None, "bound"):
                        rec["outcome"] = None

    def mark(self, keys: Iterable[str], stage: str, t: float,
             **attrs) -> None:
        """Stamp one stage mark on a batch of journeys (one lock trip).
        Later cycles overwrite earlier marks for a still-unplaced ask —
        the journey reflects the cycle that finally committed it."""
        with self._mu:
            for k in keys:
                rec = self._j.get(k)
                if rec is None or rec["t"].get("committed") is not None:
                    continue
                rec["t"][stage] = t
                if attrs:
                    rec["attrs"].update(attrs)

    def annotate(self, key: str, hop: Optional[str] = None, **attrs) -> None:
        with self._mu:
            rec = self._j.get(key)
            if rec is None:
                return
            if hop is not None:
                rec["hops"].append(hop)
            if attrs:
                rec["attrs"].update(attrs)

    def bound(self, key: str, t: float) -> None:
        """Close a journey at shim bind: compute the stage durations and
        feed the exact histogram family. Idempotent — the sharded front
        fans the bind upcall to every shard, only the first closes it."""
        stages = None
        with self._mu:
            rec = self._j.get(key)
            if rec is None or rec["outcome"] == "bound":
                return
            if rec["outcome"] is not None:
                # bind is definitive: a skipped-fleetwide ask that later
                # placed after the cooldown DID complete its journey
                rec["hops"].append("recovered:" + rec["outcome"])
            rec["t"]["bound"] = t
            rec["outcome"] = "bound"
            self.completed_total += 1
            stages = self._stages_locked(rec)
            rec["stages_ms"] = stages
        if self._m_stage is not None and stages:
            for stage, ms in stages.items():
                self._m_stage.observe(ms, stage=stage)
        if self._m_completed is not None:
            self._m_completed.inc()
        if self._m_terminal is not None:
            self._m_terminal.inc(outcome="bound")

    def terminal(self, key: str, outcome: str, **attrs) -> None:
        """Record a non-bind terminal outcome. A journey that already
        bound keeps `bound` as its outcome (a preempted VICTIM's eviction
        rides `hops`, not the outcome — its journey completed)."""
        with self._mu:
            rec = self._j.get(key)
            if rec is None:
                return
            if rec["outcome"] is not None:
                rec["hops"].append(outcome)
                if attrs:
                    rec["attrs"].update(attrs)
                return
            rec["outcome"] = outcome
            if attrs:
                rec["attrs"].update(attrs)
        if self._m_terminal is not None:
            if outcome in OUTCOMES:
                self._m_terminal.inc(outcome=outcome)
            else:
                self._m_terminal.inc(outcome="released")

    # ------------------------------------------------------------- readers
    @staticmethod
    def _stages_locked(rec: dict) -> Dict[str, float]:
        """Stage durations from the present marks. Absent intermediate
        marks (pinned asks bypass gate+solve) fold into the next present
        stage, so the sum ALWAYS equals bound - admitted exactly."""
        marks = rec["t"]
        t0 = marks.get("admitted")
        if t0 is None:
            return {}
        out: Dict[str, float] = {}
        prev = t0
        for stage in STAGES:
            tm = marks.get(stage)
            if tm is None:
                continue
            # clamp: a mark recorded before its predecessor (pipelined
            # cycle boundaries) contributes 0, never negative
            tm = max(tm, prev)
            out[stage] = round((tm - prev) * 1000.0, 6)
            prev = tm
        return out

    def get(self, key: str) -> Optional[dict]:
        """One pod's journey (the /ws/v1/journey/<uid> payload)."""
        with self._mu:
            rec = self._j.get(key)
            if rec is None:
                return None
            marks = dict(rec["t"])
            out = {
                "uid": key,
                "marks": {k: round(v, 6) for k, v in marks.items()},
                "stages_ms": dict(rec.get("stages_ms")
                                  or self._stages_locked(rec)),
                "attrs": dict(rec["attrs"]),
                "hops": list(rec["hops"]),
                "outcome": rec["outcome"],
            }
        t0, t1 = marks.get("admitted"), marks.get("bound")
        if t0 is not None and t1 is not None:
            out["e2e_ms"] = round((t1 - t0) * 1000.0, 6)
        return out

    def tail(self, n: int = 64) -> List[dict]:
        """Most recent n journeys (flight-recorder bundle payload)."""
        with self._mu:
            keys = list(self._j.keys())[-n:]
        return [j for j in (self.get(k) for k in keys) if j is not None]

    def stats(self) -> dict:
        """The `trace` block's journey summary (bench + trace_replay)."""
        with self._mu:
            outcomes: Dict[str, int] = {}
            open_n = 0
            for rec in self._j.values():
                o = rec["outcome"]
                if o is None:
                    open_n += 1
                else:
                    outcomes[o] = outcomes.get(o, 0) + 1
            admitted = self.admitted_total
            completed = self.completed_total
            evicted = self.evicted_total
        return {
            "admitted": admitted,
            "completed": completed,
            "open": open_n,
            "evicted": evicted,
            "outcomes": outcomes,
            "complete_ratio": round(completed / admitted, 4) if admitted
            else 1.0,
        }
