"""Streaming SLO engine: rolling-window objectives over the live telemetry.

Every number the repo produced before round 14 was a single-shape microbench
or a short soak; this module turns the observability layer's raw signals —
per-pod e2e latency observations, cycle completions, supervisor degradation
state, the preemption confirm path, the AOT cold-start measurement — into
machine-checkable objectives with SRE-style multi-window burn-rate evaluation
and a three-state verdict API (``ok | burning | violated``). The trace-replay
proving ground (scripts/trace_replay.py) and bench.py gate on these verdicts;
`/ws/v1/slo` and `/metrics` (`slo_burn_rate{objective,window}`,
`slo_violations_total{objective}`) expose them to operators, and the health
monitor flips `/ws/v1/health` readiness when an availability-class objective
is violated.

Objectives (fixed set, targets from `observability.slo*` conf):

  pod_e2e_p99      p99 pod end-to-end latency (submit -> bound), measured by
                   a STREAMING quantile sketch over the raw
                   pod_e2e_latency_seconds observations — not Prometheus
                   bucket interpolation, whose error is the full width of the
                   exposition ladder's coarse buckets. Good event: latency
                   <= target. Error budget 1% (that is what "p99" means).
  cycle_staleness  age since the last successfully completed scheduling
                   cycle per partition. A wedged/failing loop stops stamping
                   completions, so staleness grows monotonically — the
                   objective the chaos "hang" fault must trip.
  degraded_dwell   fraction of time any supervised path sat off its primary
                   tier (solver_degradation_state != primary). Budgeted:
                   brief degradations are the ladder doing its job; chronic
                   dwell is capacity silently lost.
  mis_evictions    victims evicted by preemption whose beneficiary ask still
                   had not placed when its cooldown expired (the preemption
                   confirm path's wasted-eviction residue). Zero-tolerance.
  aot_cold_start   wall time of the process's first scheduling cycle with
                   admitted pods vs the cold-start budget (the round-13 AOT
                   store's contract: a prebuilt store makes this artifact
                   load + execute, not an XLA compile stall).

Burn rate (SRE workbook semantics): bad_fraction(window) / error_budget. A
burn rate of 1.0 consumes exactly the window's budget; `burning` fires when
the FAST window burns several times too fast (the page-worthy signal),
`violated` when the objective itself is out of SLO over its evaluation
window (budget exhausted / hard threshold crossed). Verdict logic per kind
is documented on `_evaluate_*` below.

Memory is bounded by construction: the sketch is a ring of per-sub-window
log-spaced bucket arrays (~5% relative error), the event windows are rings
of (good, bad) pairs; both advance by wall time and never grow with traffic.
"""
from __future__ import annotations

import dataclasses
import logging
import math
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

VERDICT_OK = "ok"
VERDICT_BURNING = "burning"
VERDICT_VIOLATED = "violated"
# gauge encoding for slo_verdict{objective}
VERDICT_GAUGE = {VERDICT_OK: 0, VERDICT_BURNING: 1, VERDICT_VIOLATED: 2}


class _EpochRing:
    """Shared sub-window ring: cells keyed by epoch index (now // sub_s),
    pruned as the window advances. QuantileSketch cells are bucket-count
    arrays; BurnWindow cells are [good, bad] pairs — the ring bookkeeping
    (epoch derivation, sizing, pruning, window-filtered iteration) is one
    implementation so a pruning fix can never reach only one of them."""

    def __init__(self, window_s: float, sub_s: float):
        self.window_s = float(window_s)
        self.sub_s = max(float(sub_s), 0.05)
        self.n_sub = max(2, int(math.ceil(self.window_s / self.sub_s)))
        self._subs: Dict[int, list] = {}

    def _new_cell(self) -> list:
        raise NotImplementedError

    def _cell(self, now: float) -> list:
        epoch = int(now // self.sub_s)
        cell = self._subs.get(epoch)
        if cell is None:
            cell = self._subs[epoch] = self._new_cell()
            if len(self._subs) > self.n_sub + 1:
                floor = epoch - self.n_sub
                for e in [e for e in self._subs if e <= floor]:
                    del self._subs[e]
        return cell

    def _window_cells(self, now: float, window_s: Optional[float]):
        floor = int((now - (window_s or self.window_s)) // self.sub_s)
        cur = int(now // self.sub_s)
        for e, cell in self._subs.items():
            if floor < e <= cur:
                yield cell

    def reset(self) -> None:
        self._subs.clear()


# ---------------------------------------------------------------------------
# Streaming quantile sketch
# ---------------------------------------------------------------------------
class QuantileSketch(_EpochRing):
    """Mergeable log-bucket quantile sketch over a rolling time window.

    Observations land in the current sub-window's bucket array (log-spaced
    value buckets, GROWTH relative resolution); a quantile query merges the
    sub-windows inside the asked window. Deterministic, bounded memory
    (n_sub x n_buckets ints), O(1) observe, O(buckets) query — the streaming
    analog of an HDR histogram, precise enough that "p99 over target" means
    the delivered latency, not a bucket-interpolation artifact.
    """

    LO = 1e-4          # 0.1 ms: everything at or below lands in bucket 0
    HI = 7.2e3         # 2 h: everything above clamps into the last bucket
    GROWTH = 1.05      # ~5% relative error per bucket

    def __init__(self, window_s: float, sub_s: float):
        super().__init__(window_s, sub_s)
        self._log_growth = math.log(self.GROWTH)
        self.n_buckets = (
            int(math.log(self.HI / self.LO) / self._log_growth) + 2)

    def _new_cell(self) -> List[int]:
        return [0] * self.n_buckets

    def _bucket_of(self, v: float) -> int:
        if v <= self.LO:
            return 0
        b = int(math.log(v / self.LO) / self._log_growth) + 1
        return min(b, self.n_buckets - 1)

    def bucket_upper(self, b: int) -> float:
        """Upper edge of bucket b (value such that everything in the bucket
        is <= it, modulo the GROWTH relative error)."""
        if b <= 0:
            return self.LO
        return self.LO * (self.GROWTH ** b)

    def observe(self, value: float, now: float) -> None:
        self._cell(now)[self._bucket_of(float(value))] += 1

    def _merged(self, now: float, window_s: float) -> Tuple[List[int], int]:
        merged = [0] * self.n_buckets
        total = 0
        for counts in self._window_cells(now, window_s):
            for i, c in enumerate(counts):
                merged[i] += c
                total += c
        return merged, total

    def count(self, now: float, window_s: Optional[float] = None) -> int:
        _, total = self._merged(now, window_s or self.window_s)
        return total

    def count_over(self, threshold: float, now: float,
                   window_s: Optional[float] = None) -> Tuple[int, int]:
        """(observations, observations above threshold) in the window. The
        threshold is resolved to the bucket whose lower edge is the first at
        or above it, so 'over' is exact modulo the sketch's ~5% bucket
        width — conservative in neither direction systematically."""
        merged, total = self._merged(now, window_s or self.window_s)
        tb = self._bucket_of(float(threshold))
        bad = sum(merged[tb + 1:])
        return total, bad

    def quantile(self, q: float, now: float,
                 window_s: Optional[float] = None) -> Optional[float]:
        """q-quantile of the window's observations (None when empty)."""
        merged, total = self._merged(now, window_s or self.window_s)
        if total == 0:
            return None
        rank = q * (total - 1)
        cum = 0
        for b, c in enumerate(merged):
            cum += c
            if cum > rank:
                return self.bucket_upper(b)
        return self.bucket_upper(self.n_buckets - 1)


# ---------------------------------------------------------------------------
# Good/bad event window (sampled + counted objectives)
# ---------------------------------------------------------------------------
class BurnWindow(_EpochRing):
    """Ring of per-sub-window (good, bad) event counts."""

    def _new_cell(self) -> List[int]:
        return [0, 0]

    def record(self, good: bool, now: float, n: int = 1) -> None:
        self._cell(now)[0 if good else 1] += n

    def counts(self, now: float,
               window_s: Optional[float] = None) -> Tuple[int, int]:
        good = bad = 0
        for g, b in self._window_cells(now, window_s):
            good += g
            bad += b
        return good, bad

    def bad_fraction(self, now: float,
                     window_s: Optional[float] = None) -> Optional[float]:
        good, bad = self.counts(now, window_s)
        total = good + bad
        return (bad / total) if total else None


# ---------------------------------------------------------------------------
# Options
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class SloOptions:
    """Targets + windows (conf: observability.slo*). Defaults are the
    production shape — hour-scale slow window, 5-minute fast window; the
    trace-replay driver compresses both to seconds via the same keys."""

    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    # pod e2e: 99% of pods bound within this many seconds of ask submit
    pod_e2e_p99_s: float = 30.0
    # scheduling loop: a cycle must complete at least this often
    cycle_staleness_s: float = 60.0
    # supervised paths may dwell off their primary tier at most this
    # fraction of the time (slow window)
    degraded_dwell_budget: float = 0.05
    # first cycle with admitted pods must land within this budget
    cold_start_budget_ms: float = 15000.0
    # fast-window burn rate at/above which an objective reports `burning`
    burn_fast_threshold: float = 6.0
    # latency error budget: p99 target == 1% of observations may exceed it
    error_budget: float = 0.01

    @classmethod
    def from_conf(cls, conf) -> "SloOptions":
        return cls(
            fast_window_s=conf.obs_slo_fast_window_s,
            slow_window_s=conf.obs_slo_slow_window_s,
            pod_e2e_p99_s=conf.obs_slo_pod_e2e_p99_s,
            cycle_staleness_s=conf.obs_slo_cycle_staleness_s,
            degraded_dwell_budget=conf.obs_slo_degraded_dwell_budget,
            cold_start_budget_ms=conf.obs_slo_cold_start_budget_ms,
            burn_fast_threshold=conf.obs_slo_burn_fast_threshold,
        )


# objective name -> (availability class, unit). Availability-class verdicts
# flip /ws/v1/health readiness when violated; the rest are informational.
OBJECTIVES: Dict[str, Tuple[bool, str]] = {
    "pod_e2e_p99": (True, "s"),
    "cycle_staleness": (True, "s"),
    "degraded_dwell": (False, "ratio"),
    "mis_evictions": (True, "victims"),
    "aot_cold_start": (False, "ms"),
}


class SloEngine:
    """Consumes the registry's raw observations + the core's state probes,
    maintains the rolling windows, and serves verdicts.

    Thread-safety: one engine lock; feeders (histogram observer on bind
    worker threads), the run loop's tick, scrape-time ticks (registry
    on_collect) and report() all take it. Everything inside is O(buckets).
    """

    # ticks closer together than this are coalesced (scrape storms must not
    # multiply the sampling weight of the sampled objectives)
    MIN_TICK_S = 0.2

    def __init__(self, options: Optional[SloOptions] = None, registry=None,
                 now_fn: Callable[[], float] = time.time):
        self.opts = options or SloOptions()
        self._now = now_fn
        self._mu = threading.RLock()
        o = self.opts
        sub = max(o.fast_window_s / 30.0, 0.1)
        self._sketch = QuantileSketch(o.slow_window_s, sub)
        self._windows: Dict[str, BurnWindow] = {
            name: BurnWindow(o.slow_window_s, sub)
            for name in ("cycle_staleness", "degraded_dwell", "mis_evictions")
        }
        # providers wired by attach_core (None = objective not applicable)
        self._staleness_fn: Optional[Callable[[], Optional[Dict[str, float]]]] = None
        self._degraded_fn: Optional[Callable[[], Dict[str, str]]] = None
        self._misevict_fn: Optional[Callable[[], float]] = None
        self._coldstart_fn: Optional[Callable[[], Optional[float]]] = None
        self._misevict_seen = 0.0
        self._last_tick = 0.0
        self._verdicts: Dict[str, str] = {n: VERDICT_OK for n in OBJECTIVES}
        self._violations: Dict[str, int] = {n: 0 for n in OBJECTIVES}
        self._last_eval: Dict[str, dict] = {}
        self._g_burn = self._m_violations = self._g_verdict = None
        self._g_value = None
        # called as on_violation([objective, ...]) when objectives EDGE
        # into the violated verdict, after tick() releases _mu — the
        # flight recorder's slo_violation trigger lives here, and its
        # sources re-enter report()/violations(), so firing under the
        # lock would deadlock. Edge-triggered like slo_violations_total:
        # one call per violation episode, not one per tick.
        self.on_violation: Optional[Callable[[List[str]], None]] = None
        if registry is not None:
            self.attach_metrics(registry)

    # ------------------------------------------------------------ wiring
    def attach_metrics(self, registry) -> None:
        self._g_burn = registry.gauge(
            "slo_burn_rate",
            "error-budget burn rate per objective and evaluation window "
            "(bad fraction / error budget; 1.0 = consuming exactly the "
            "window's budget)",
            labelnames=("objective", "window"))
        self._m_violations = registry.counter(
            "slo_violations_total",
            "objective transitions into the `violated` verdict "
            "(edge-triggered: one count per violation episode)",
            labelnames=("objective",))
        self._g_verdict = registry.gauge(
            "slo_verdict",
            "current verdict per objective (0=ok, 1=burning, 2=violated)",
            labelnames=("objective",))
        self._g_value = registry.gauge(
            "slo_objective_value",
            "current measured value per objective (pod_e2e_p99: fast-window "
            "p99 seconds; cycle_staleness: seconds since last completed "
            "cycle; degraded_dwell: fast-window dwell ratio; mis_evictions: "
            "slow-window victim count; aot_cold_start: first-cycle ms)",
            labelnames=("objective",))
        # scrape-driven evaluation: a scrape-only deployment (no run loop
        # calling tick) still gets fresh verdicts at exposition time
        registry.on_collect(self.maybe_tick)

    def attach_core(self, core) -> None:
        """Wire the engine to a CoreScheduler: tee the e2e histogram's raw
        observations into the sketch, hook the staleness / degradation /
        mis-eviction / cold-start probes, and register the health source."""
        hist = core.obs.get("pod_e2e_latency_seconds")
        if hist is not None and hasattr(hist, "add_observer"):
            hist.add_observer(self.observe_e2e)
        self._staleness_fn = core._slo_staleness
        self._degraded_fn = lambda: core.supervisor.degraded_paths()
        mis = core.obs.get("preemption_mis_evictions_total")
        if mis is not None:
            self._misevict_fn = mis.value
        self._coldstart_fn = lambda: core._first_cycle_ms
        core.health.register("slo", self.health_source)

    def detach_core(self, core) -> None:
        """Undo attach_core: drop the histogram tee, the scrape hook and
        the probes. Shard failover rebuilds a quarantined shard's core
        against the SHARED registry — the dead core's engine must stop
        consuming the fleet's e2e stream and ticking at scrape time, or
        every rebuild leaks one more live engine."""
        hist = core.obs.get("pod_e2e_latency_seconds")
        if hist is not None and hasattr(hist, "remove_observer"):
            hist.remove_observer(self.observe_e2e)
        if hasattr(core.obs, "remove_collect_hook"):
            core.obs.remove_collect_hook(self.maybe_tick)
        core.health.unregister("slo")
        with self._mu:
            self._staleness_fn = None
            self._degraded_fn = None
            self._misevict_fn = None
            self._coldstart_fn = None

    # ------------------------------------------------------------ feeders
    def observe_e2e(self, values: Sequence[float]) -> None:
        now = self._now()
        with self._mu:
            for v in values:
                self._sketch.observe(v, now)

    # ------------------------------------------------------------ evaluation
    def maybe_tick(self) -> None:
        now = self._now()
        with self._mu:
            if now - self._last_tick < self.MIN_TICK_S:
                return
            # claim the slot INSIDE the check: two scrapers racing past an
            # unlocked check would both tick and double-sample the windows
            self._last_tick = now
        self.tick(now)

    def tick(self, now: Optional[float] = None) -> Dict[str, dict]:
        """One evaluation pass: sample the probes, recompute every
        objective, publish gauges, edge-count violations."""
        if now is None:
            now = self._now()
        fired: List[str] = []
        with self._mu:
            self._last_tick = now
            self._sample_probes(now)
            out: Dict[str, dict] = {}
            out["pod_e2e_p99"] = self._evaluate_latency(now)
            out["cycle_staleness"] = self._evaluate_staleness(now)
            out["degraded_dwell"] = self._evaluate_dwell(now)
            out["mis_evictions"] = self._evaluate_misevict(now)
            out["aot_cold_start"] = self._evaluate_coldstart(now)
            for name, ev in out.items():
                if self._publish(name, ev):
                    fired.append(name)
            self._last_eval = out
        hook = self.on_violation
        if fired and hook is not None:
            try:
                hook(fired)
            except Exception:
                logger.exception("on_violation hook failed for %s", fired)
        return out

    def _sample_probes(self, now: float) -> None:
        if self._staleness_fn is not None:
            ages = self._staleness_fn()
            if ages:
                worst = max(ages.values())
                self._windows["cycle_staleness"].record(
                    worst <= self.opts.cycle_staleness_s, now)
                self._staleness_now: Optional[float] = worst
                self._staleness_detail = {
                    p: round(a, 3) for p, a in ages.items()}
            else:
                self._staleness_now = None
                self._staleness_detail = {}
        if self._degraded_fn is not None:
            try:
                degraded = self._degraded_fn() or {}
            except Exception:
                degraded = {}
            self._windows["degraded_dwell"].record(not degraded, now)
            self._degraded_now = dict(degraded)
        if self._misevict_fn is not None:
            cur = float(self._misevict_fn())
            delta = cur - self._misevict_seen
            if delta > 0:
                self._windows["mis_evictions"].record(False, now,
                                                      n=int(delta))
            self._misevict_seen = cur

    def _burns(self, total_bad_fast, total_bad_slow,
               budget: float) -> Tuple[Optional[float], Optional[float]]:
        def burn(pair):
            total, bad = pair
            if not total:
                return None
            return (bad / total) / budget

        return burn(total_bad_fast), burn(total_bad_slow)

    @staticmethod
    def _round(v: Optional[float]) -> Optional[float]:
        return None if v is None else round(v, 4)

    def _evaluate_latency(self, now: float) -> dict:
        """violated: the error budget is exhausted over the SLOW window
        (delivered p99 over the window is out of target — burn >= 1);
        burning: the FAST window burns >= burn_fast_threshold while the
        slow window still holds. No observations -> ok (n/a)."""
        o = self.opts
        fast = self._sketch.count_over(o.pod_e2e_p99_s, now, o.fast_window_s)
        slow = self._sketch.count_over(o.pod_e2e_p99_s, now, o.slow_window_s)
        burn_f, burn_s = self._burns(fast, slow, o.error_budget)
        p99 = self._sketch.quantile(0.99, now, o.fast_window_s)
        if burn_s is not None and burn_s >= 1.0:
            verdict = VERDICT_VIOLATED
        elif burn_f is not None and burn_f >= o.burn_fast_threshold:
            verdict = VERDICT_BURNING
        else:
            verdict = VERDICT_OK
        return {
            "verdict": verdict, "value": self._round(p99), "unit": "s",
            "target": o.pod_e2e_p99_s,
            "burn_rate": {"fast": self._round(burn_f),
                          "slow": self._round(burn_s)},
            "observations": {"fast": fast[0], "slow": slow[0]},
        }

    def _evaluate_staleness(self, now: float) -> dict:
        """violated: the CURRENT staleness exceeds the target — no cycle
        has completed within the allowed age, which is by construction a
        sustained condition (the age grows monotonically until a cycle
        lands); burning: recent bad samples burn the fast window's budget
        even though the loop has since recovered. Not running -> ok."""
        o = self.opts
        cur = getattr(self, "_staleness_now", None)
        win = self._windows["cycle_staleness"]
        burn_f, burn_s = self._burns(win.counts(now, o.fast_window_s),
                                     win.counts(now, o.slow_window_s),
                                     o.error_budget)
        if cur is not None and cur > o.cycle_staleness_s:
            verdict = VERDICT_VIOLATED
        elif burn_f is not None and burn_f >= o.burn_fast_threshold:
            verdict = VERDICT_BURNING
        else:
            verdict = VERDICT_OK
        out = {
            "verdict": verdict, "value": self._round(cur), "unit": "s",
            "target": o.cycle_staleness_s,
            "burn_rate": {"fast": self._round(burn_f),
                          "slow": self._round(burn_s)},
        }
        detail = getattr(self, "_staleness_detail", None)
        if detail:
            out["partitions"] = detail
        return out

    # sampled ratio objectives refuse to escalate to `violated` before the
    # window holds this many samples: three degraded ticks right after an
    # engine reset are a 100% ratio with no evidentiary weight
    MIN_RATIO_SAMPLES = 20

    def _evaluate_dwell(self, now: float) -> dict:
        """violated: degraded-dwell ratio over the SLOW window exceeds the
        dwell budget (once the window has MIN_RATIO_SAMPLES of coverage);
        burning: the fast window's ratio does. Value is the fast-window
        ratio (the operator's 'how degraded are we right now')."""
        o = self.opts
        win = self._windows["degraded_dwell"]
        ratio_f = win.bad_fraction(now, o.fast_window_s)
        ratio_s = win.bad_fraction(now, o.slow_window_s)
        n_slow = sum(win.counts(now, o.slow_window_s))
        budget = max(o.degraded_dwell_budget, 1e-9)
        burn_f = None if ratio_f is None else ratio_f / budget
        burn_s = None if ratio_s is None else ratio_s / budget
        if (burn_s is not None and burn_s >= 1.0
                and n_slow >= self.MIN_RATIO_SAMPLES):
            verdict = VERDICT_VIOLATED
        elif burn_f is not None and burn_f >= 1.0:
            verdict = VERDICT_BURNING
        else:
            verdict = VERDICT_OK
        out = {
            "verdict": verdict, "value": self._round(ratio_f),
            "unit": "ratio", "target": o.degraded_dwell_budget,
            "burn_rate": {"fast": self._round(burn_f),
                          "slow": self._round(burn_s)},
        }
        degraded = getattr(self, "_degraded_now", None)
        if degraded:
            out["degraded"] = degraded
        return out

    def _evaluate_misevict(self, now: float) -> dict:
        """Zero-tolerance: ANY mis-eviction inside the slow window is a
        violation (there is no acceptable rate of evicting victims for an
        ask that never places). Burn rate reports the raw window counts."""
        o = self.opts
        win = self._windows["mis_evictions"]
        _, bad_f = win.counts(now, o.fast_window_s)
        _, bad_s = win.counts(now, o.slow_window_s)
        verdict = VERDICT_VIOLATED if bad_s > 0 else VERDICT_OK
        return {
            "verdict": verdict, "value": bad_s, "unit": "victims",
            "target": 0,
            "burn_rate": {"fast": float(bad_f), "slow": float(bad_s)},
        }

    def _evaluate_coldstart(self, now: float) -> dict:
        """One-shot budget objective: the first admitted cycle's wall vs
        the cold-start budget. Burn rate = value/budget on both windows
        (there is no window; the ratio is the useful number). Unrecorded
        (no cycle yet) -> ok."""
        o = self.opts
        ms = self._coldstart_fn() if self._coldstart_fn is not None else None
        if ms is None:
            return {"verdict": VERDICT_OK, "value": None, "unit": "ms",
                    "target": o.cold_start_budget_ms,
                    "burn_rate": {"fast": None, "slow": None}}
        burn = ms / max(o.cold_start_budget_ms, 1e-9)
        verdict = (VERDICT_VIOLATED if ms > o.cold_start_budget_ms
                   else VERDICT_OK)
        return {"verdict": verdict, "value": round(ms, 1), "unit": "ms",
                "target": o.cold_start_budget_ms,
                "burn_rate": {"fast": self._round(burn),
                              "slow": self._round(burn)}}

    def _publish(self, name: str, ev: dict) -> bool:
        """Publish one objective's evaluation; True iff it EDGED into
        violated this pass (tick() fans those to on_violation)."""
        prev = self._verdicts.get(name, VERDICT_OK)
        cur = ev["verdict"]
        self._verdicts[name] = cur
        edged = cur == VERDICT_VIOLATED and prev != VERDICT_VIOLATED
        if edged:
            self._violations[name] += 1
            if self._m_violations is not None:
                self._m_violations.inc(objective=name)
        if self._g_verdict is not None:
            self._g_verdict.set(VERDICT_GAUGE[cur], objective=name)
        if self._g_burn is not None:
            for wname in ("fast", "slow"):
                self._g_burn.set(ev["burn_rate"][wname] or 0.0,
                                 objective=name, window=wname)
        if self._g_value is not None:
            # a None value (objective n/a: loop stopped, window empty)
            # must CLEAR the gauge — freezing the last reading would show
            # e.g. a 45s staleness on the dashboard long after the loop
            # was intentionally stopped
            v = ev.get("value")
            self._g_value.set(float(v) if v is not None else 0.0,
                              objective=name)
        # violations counter must expose a stable zero series per objective
        # from the first scrape (dashboards rate() it)
        if self._m_violations is not None and self._violations[name] == 0:
            self._m_violations.inc(0, objective=name)
        return edged

    # ------------------------------------------------------------ read API
    def verdicts(self) -> Dict[str, str]:
        with self._mu:
            return dict(self._verdicts)

    def verdict(self, objective: str) -> str:
        with self._mu:
            return self._verdicts[objective]

    def violations(self) -> Dict[str, int]:
        """Violation episodes per objective since start (or last reset)."""
        with self._mu:
            return dict(self._violations)

    def worst_burn(self, objective: str) -> float:
        with self._mu:
            ev = self._last_eval.get(objective) or {}
        burns = [b for b in (ev.get("burn_rate") or {}).values()
                 if b is not None]
        return max(burns) if burns else 0.0

    def report(self) -> dict:
        """The /ws/v1/slo payload (also the replay report's `slo` block):
        per-objective verdict/value/target/burn rates + the engine's windows
        and violation episodes. Evaluates fresh (rate-limited)."""
        self.maybe_tick()
        with self._mu:
            objectives = {}
            for name, (availability, unit) in OBJECTIVES.items():
                ev = dict(self._last_eval.get(name) or
                          {"verdict": VERDICT_OK, "value": None,
                           "unit": unit, "target": None,
                           "burn_rate": {"fast": None, "slow": None}})
                ev["availability"] = availability
                ev["violations"] = self._violations[name]
                objectives[name] = ev
            violated = [n for n, v in self._verdicts.items()
                        if v == VERDICT_VIOLATED]
            return {
                "at": round(self._now(), 3),
                "windows": {"fast_s": self.opts.fast_window_s,
                            "slow_s": self.opts.slow_window_s},
                "objectives": objectives,
                "violated": violated,
                "healthy": not any(
                    OBJECTIVES[n][0] for n in violated),
            }

    def health_source(self) -> dict:
        """HealthMonitor source: a VIOLATED availability-class objective
        fails readiness (degraded — the scheduler keeps serving, /ws/v1/
        health stays 200 with the objective named); liveness is never an
        SLO question, so `live` is not touched."""
        self.maybe_tick()
        with self._mu:
            violated_avail = [
                n for n, v in self._verdicts.items()
                if v == VERDICT_VIOLATED and OBJECTIVES[n][0]]
            out: dict = {
                "healthy": not violated_avail,
                "verdicts": dict(self._verdicts),
            }
            if violated_avail:
                out["violated"] = violated_avail
            return out

    def reset(self) -> None:
        """Drop every window, sketch and verdict (the trace-replay driver
        resets after its warm-up phase so compile stalls and recovery
        noise never count against the measured window)."""
        with self._mu:
            self._sketch.reset()
            for w in self._windows.values():
                w.reset()
            self._verdicts = {n: VERDICT_OK for n in OBJECTIVES}
            self._violations = {n: 0 for n in OBJECTIVES}
            self._last_eval = {}
            self._staleness_now = None
            self._degraded_now = {}
            if self._misevict_fn is not None:
                self._misevict_seen = float(self._misevict_fn())
