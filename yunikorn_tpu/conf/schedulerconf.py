"""Scheduler configuration.

Role-equivalent to pkg/conf/schedulerconf.go: a `SchedulerConf` holder (:114-135)
populated from two ConfigMaps — `yunikorn-defaults` overlaid by `yunikorn-configs`
(FlattenConfigMaps, :508-523) — keyed `service.*` / `kubernetes.*` / `log.*`
(:344-448), with gzip-compressed values supported (Decompress, :482-507), defaults
(:83-97), hot-reload via an atomic holder swap, and warnings for non-reloadable
keys (:210-265). The solver-specific knobs (`solver.*`) are new: they size the
device-array buckets and the assignment loop.
"""
from __future__ import annotations

import base64
import dataclasses
import gzip
from typing import Dict, List, Optional, Tuple

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants
from yunikorn_tpu.log.logger import log, update_logging_config

logger = log("shim.config")

PREFIX_SERVICE = "service."
PREFIX_KUBERNETES = "kubernetes."
PREFIX_LOG = "log."
PREFIX_SOLVER = "solver."
PREFIX_OBS = "observability."

# service.* keys
CM_SVC_CLUSTER_ID = PREFIX_SERVICE + "clusterId"
CM_SVC_POLICY_GROUP = PREFIX_SERVICE + "policyGroup"
CM_SVC_SCHEDULING_INTERVAL = PREFIX_SERVICE + "schedulingInterval"
CM_SVC_VOLUME_BIND_TIMEOUT = PREFIX_SERVICE + "volumeBindTimeout"
CM_SVC_EVENT_CHANNEL_CAPACITY = PREFIX_SERVICE + "eventChannelCapacity"
CM_SVC_DISPATCH_TIMEOUT = PREFIX_SERVICE + "dispatchTimeout"
CM_SVC_DISABLE_GANG = PREFIX_SERVICE + "disableGangScheduling"
CM_SVC_ENABLE_HOT_REFRESH = PREFIX_SERVICE + "enableConfigHotRefresh"
CM_SVC_ENABLE_DRA = PREFIX_SERVICE + "enableDRA"
CM_SVC_PLACEHOLDER_IMAGE = PREFIX_SERVICE + "placeholderImage"
CM_SVC_PLACEHOLDER_RUN_AS_USER = PREFIX_SERVICE + "placeholderRunAsUser"
CM_SVC_PLACEHOLDER_RUN_AS_GROUP = PREFIX_SERVICE + "placeholderRunAsGroup"
CM_SVC_PLACEHOLDER_FS_GROUP = PREFIX_SERVICE + "placeholderFsGroup"
CM_SVC_INSTANCE_TYPE_LABEL = PREFIX_SERVICE + "nodeInstanceTypeNodeLabelKey"
CM_SVC_OPERATOR_PLUGINS = PREFIX_SERVICE + "operatorPlugins"
# per-shard bind worker count (cache/context ShardedBindPool); 0 = auto
# (total stays 32 up to 4 shards). Pool structure: NOT hot-reloadable.
CM_SVC_BIND_POOL_WORKERS = PREFIX_SERVICE + "bindPoolWorkers"

# kubernetes.* keys
CM_KUBE_QPS = PREFIX_KUBERNETES + "qps"
CM_KUBE_BURST = PREFIX_KUBERNETES + "burst"

# solver.* keys (TPU-native additions)
CM_SOLVER_MAX_ROUNDS = PREFIX_SOLVER + "maxAssignRounds"
CM_SOLVER_POD_CHUNK = PREFIX_SOLVER + "podChunk"
CM_SOLVER_MAX_BATCH = PREFIX_SOLVER + "maxBatch"
CM_SOLVER_SCORING_POLICY = PREFIX_SOLVER + "scoringPolicy"
CM_SOLVER_DEVICE_PLATFORM = PREFIX_SOLVER + "platform"
CM_SOLVER_USE_PALLAS = PREFIX_SOLVER + "usePallas"     # auto | true | false
CM_SOLVER_SHARD = PREFIX_SOLVER + "shardSolve"         # auto | true | false
CM_SOLVER_FALLBACK_ROUNDS = PREFIX_SOLVER + "localityFallbackRounds"
CM_SOLVER_PIPELINE = PREFIX_SOLVER + "pipeline"         # auto | true | false
CM_SOLVER_PREEMPT_DEVICE = PREFIX_SOLVER + "preemptDevice"  # auto | true | false
CM_SOLVER_GATE = PREFIX_SOLVER + "gateVectorized"       # auto | true | false
CM_SOLVER_GATE_DEVICE = PREFIX_SOLVER + "gateDevice"    # auto | true | false
CM_SOLVER_GATE_VERIFY = PREFIX_SOLVER + "gateVerify"    # true | false
CM_SOLVER_POLICY = PREFIX_SOLVER + "policy"             # auto | greedy | optimal | learned | all
CM_SOLVER_PACK = PREFIX_SOLVER + "pack"                 # auto | pop | cvx
# learned-policy checkpoint prefix (policy/net.save_checkpoint's
# <prefix>.npz + <prefix>.json pair); "" = no checkpoint, the learned arm
# skips. A checkpoint failing validation REJECTS at load with the previous
# policy retained (core.set_policy_checkpoint).
CM_SOLVER_POLICY_CHECKPOINT = PREFIX_SOLVER + "policyCheckpoint"
CM_SOLVER_AOT_STORE = PREFIX_SOLVER + "aotStore"        # dir path; "" = off
CM_SOLVER_AOT_BACKGROUND = PREFIX_SOLVER + "aotBackground"  # auto | true | false
CM_SOLVER_TOPOLOGY = PREFIX_SOLVER + "topology"         # auto | true | false
CM_SOLVER_SHARDS = PREFIX_SOLVER + "shards"             # auto | 1..64
# sharded front end: per-shard delivery-queue high-water mark — past it
# new unpinned asks shed to the least-loaded survivor (core/delivery.py).
# Queue structure like the shard count: NOT hot-reloadable.
CM_SOLVER_DELIVERY_HIGH_WATER = PREFIX_SOLVER + "deliveryHighWater"

# the tri-state device-path gates share one value domain; solver.policy and
# solver.gateVerify have their own. All parse through _parse_choice: an
# unknown value REJECTS the configmap update (ValueError) instead of
# silently keeping a default the operator did not ask for.
TRI_STATE = ("auto", "true", "false")
SOLVER_POLICIES = ("auto", "greedy", "optimal", "learned", "all")
# pack-arm flavor under solver.policy=optimal: "pop" = the partitioned
# LP/ADMM solve (ops/pack_solve.py), "cvx" = the full-fleet convex
# relaxation (ops/cvx_solve.py), "auto" = pop. solver.policy=all always
# duels BOTH pack flavors next to greedy and learned.
SOLVER_PACK_ARMS = ("auto", "pop", "cvx")

# observability.* keys (the obs/ registry + tracer + SLO engine)
CM_OBS_TRACE_SPANS = PREFIX_OBS + "traceBufferSpans"
CM_OBS_SLO_FAST_WINDOW = PREFIX_OBS + "sloFastWindowSeconds"
CM_OBS_SLO_SLOW_WINDOW = PREFIX_OBS + "sloSlowWindowSeconds"
CM_OBS_SLO_POD_E2E_P99 = PREFIX_OBS + "sloPodE2eP99Seconds"
CM_OBS_SLO_STALENESS = PREFIX_OBS + "sloCycleStalenessSeconds"
CM_OBS_SLO_DWELL_BUDGET = PREFIX_OBS + "sloDegradedDwellBudget"
CM_OBS_SLO_COLD_BUDGET = PREFIX_OBS + "sloColdStartBudgetMs"
CM_OBS_SLO_BURN_FAST = PREFIX_OBS + "sloBurnFastThreshold"
# journey ledger + flight recorder (round 20; obs/journey.py, obs/flightrec.py)
CM_OBS_JOURNEY_CAPACITY = PREFIX_OBS + "journeyCapacity"
CM_OBS_FLIGHTREC_DIR = PREFIX_OBS + "flightRecorderDir"
CM_OBS_FLIGHTREC_MAX = PREFIX_OBS + "flightRecorderMaxRecordings"
CM_OBS_FLIGHTREC_WINDOW = PREFIX_OBS + "flightRecorderWindowSeconds"
CM_OBS_FLIGHTREC_DEBOUNCE = PREFIX_OBS + "flightRecorderDebounceSeconds"

# robustness.* keys (supervised device dispatches, robustness/supervisor.py)
PREFIX_ROBUSTNESS = "robustness."
CM_ROBUST_DEADLINE = PREFIX_ROBUSTNESS + "dispatchDeadlineSeconds"
CM_ROBUST_MAX_RETRIES = PREFIX_ROBUSTNESS + "maxRetries"
CM_ROBUST_BREAKER_THRESHOLD = PREFIX_ROBUSTNESS + "breakerThreshold"
CM_ROBUST_PROBE_INTERVAL = PREFIX_ROBUSTNESS + "probeIntervalSeconds"
CM_ROBUST_PROBE_DEADLINE = PREFIX_ROBUSTNESS + "probeDeadlineSeconds"
# shard failover (robustness/failover.py; active only when solver.shards>=2):
# a shard whose run loop has not completed a cycle within the stale budget
# (or whose loop thread died, or whose every supervised circuit is open) is
# QUARANTINED — its node domains re-home onto surviving shards — and
# rebuilt + re-admitted at the next partition epoch after the rejoin delay.
CM_ROBUST_FAILOVER_STALE = PREFIX_ROBUSTNESS + "failoverStaleSeconds"
CM_ROBUST_FAILOVER_PROBE = PREFIX_ROBUSTNESS + "failoverProbeSeconds"
CM_ROBUST_FAILOVER_REJOIN = PREFIX_ROBUSTNESS + "failoverRejoinSeconds"
CM_ROBUST_FAILOVER_ENABLED = PREFIX_ROBUSTNESS + "failoverEnabled"  # true | false
# ledger as a service (round 22; core/ledger_service.py, active only when
# the sharded front end couples through the RPC boundary):
# ledgerEndpoint "host:port" connects to an authority in ANOTHER process
# (empty = serve in-process when --ledger-serve is set); NOT hot-reloadable
# (process structure, like the shard count). failClosed: true = a shard
# that loses the ledger past its breaker budget REJECTS admissions instead
# of degraded local admission (quota exactness over availability).
CM_SOLVER_LEDGER_ENDPOINT = PREFIX_SOLVER + "ledgerEndpoint"
CM_ROBUST_LEDGER_FAIL_CLOSED = PREFIX_ROBUSTNESS + "ledgerFailClosed"  # true | false
CM_ROBUST_LEDGER_DEADLINE = PREFIX_ROBUSTNESS + "ledgerDeadlineSeconds"
CM_ROBUST_LEDGER_LEASE_TTL = PREFIX_ROBUSTNESS + "ledgerLeaseTtlSeconds"

# The queues.yaml payload key inside the configmap (opaque to the shim).
POLICY_GROUP_DEFAULT = "queues"


@dataclasses.dataclass
class PlaceholderConfig:
    image: str = constants.PLACEHOLDER_CONTAINER_IMAGE
    run_as_user: int = -1
    run_as_group: int = -1
    fs_group: int = -1


@dataclasses.dataclass
class SchedulerConf:
    cluster_id: str = "mycluster"
    cluster_version: str = "latest"
    policy_group: str = POLICY_GROUP_DEFAULT
    interval: float = 1.0                      # scheduling pump cadence, seconds
    volume_bind_timeout: float = 600.0
    event_channel_capacity: int = 1024 * 1024
    dispatch_timeout: float = 300.0
    kube_qps: int = 1000
    kube_burst: int = 1000
    enable_config_hot_refresh: bool = True
    disable_gang_scheduling: bool = False
    # DynamicResourceAllocation gate (reference context.go:116-130)
    enable_dra: bool = False
    user_label_key: str = constants.DEFAULT_USER_LABEL
    instance_type_node_label_key: str = constants.NODE_INSTANCE_TYPE_LABEL
    generate_unique_app_ids: bool = False
    namespace: str = "yunikorn"
    operator_plugins: str = "general"
    placeholder: PlaceholderConfig = dataclasses.field(default_factory=PlaceholderConfig)
    # --- solver knobs --- (defaults match ops.assign.solve_batch so the
    # prewarm buckets and the production cycle share compiled variants)
    solver_max_rounds: int = 16
    solver_pod_chunk: int = 512
    # canonical pod-bucket cap: batches above this run as chained fixed-shape
    # chunk solves so only one shape ever compiles (ops.assign.MAX_SOLVE_PODS).
    # Default = the north-star bucket: the monolithic program is the fastest
    # warm path; lower it only when large-shape compiles are expensive in your
    # environment (e.g. a remote_compile relay) — the chained path is a single
    # lax.scan program, so the cost of lowering it is mild.
    solver_max_batch: int = 65536
    solver_scoring_policy: str = "binpacking"  # binpacking | fair | spread
    solver_platform: str = ""                  # "" = jax default; "cpu" forces host
    # tri-state device-path gates: "auto" resolves against the live backend
    # at first solve (pallas: TPU only; shard: >1 visible device)
    solver_use_pallas: str = "auto"
    solver_shard: str = "auto"
    # intra-cycle drain rounds for locality groups that overflow the tensor
    # encoding (0 disables: one pod per group per cycle, round-2 behavior)
    solver_fallback_rounds: int = 16
    # two-stage pipelined cycle: overlap host encode/commit/publish with the
    # async device solve ("auto" = on; single-partition mode only)
    solver_pipeline: str = "auto"
    # batched device preemption planner ("auto" = on): one jitted
    # victim-selection solve per pressure cycle, host planner as oracle/
    # fallback
    solver_preempt_device: str = "auto"
    # array-form admission gate ("auto" = on): quota + user/group-limit
    # admission as grouped prefix-scan arithmetic (core/gate.py), legacy
    # per-ask loop as fallback
    solver_gate: str = "auto"
    # device-resident gate+encode ("auto" = on): the bounded-pass jitted
    # admission scan (ops/gate_solve.py) as the gate's primary tier, with
    # the host-vectorized scan and the legacy loop as the supervised
    # degradation ladder, plus the DeviceRowStore req tensor for the solve
    solver_gate_device: str = "auto"
    # differential gate oracle: run the legacy loop after every vectorized
    # gate and pin the results identical (doubles gate host cost; the
    # gate-equivalence test tier runs with this on)
    solver_gate_verify: str = "false"
    # assignment policy: "optimal" runs the jitted LP/ADMM pack solver
    # (ops/pack_solve.py) next to the greedy solve and commits whichever
    # plan packs better (greedy is the floor — the cycle falls back when the
    # pack plan does not beat it); "learned" runs the two-tower learned
    # scorer (policy/) behind the same differential oracle; "all" runs both
    # (the three-way duel); "auto" = greedy for now (flips when the
    # hardware A/B lands, like PALLAS_TPU_DEFAULT)
    solver_policy: str = "auto"
    # pack-arm flavor (solver.pack): which global-packing challenger the
    # optimal policy fields — "pop" partitions (POP), "cvx" solves the
    # whole fleet as one convex program (CvxCluster); "auto" = pop.
    # Under solver.policy=all both flavors enter the duel regardless.
    solver_pack: str = "auto"
    # learned-policy checkpoint prefix (solver.policyCheckpoint): the
    # .npz+manifest pair a policy_train run emits; "" = none
    solver_policy_checkpoint: str = ""
    # AOT executable store (aot/): directory holding serialized compiled
    # solver executables per fingerprint; "" = disabled. A fresh process
    # with a prebuilt store serves its first cycle without XLA compiles.
    solver_aot_store: str = ""
    # on a store miss in a supervised device dispatch: "auto"/"true" =
    # raise CompilePending and compile in the background (the ladder serves
    # from cpu/host until the half-open probe reclaims the tier); "false" =
    # compile inline (the legacy first-cycle stall)
    solver_aot_background: str = "auto"
    # topology-aware placement (topology/): ICI-domain contention penalty +
    # gang-contiguous steering in the batched score, topology-ordered
    # preemption candidates, mesh-aligned pack partitioning. "auto" = on
    # when the fleet carries topology labels (a no-op otherwise); "false"
    # keeps every solver path bit-identical to the pre-topology programs.
    solver_topology: str = "auto"
    # control-plane sharding (core/shard.py): N pipelined CoreScheduler
    # shards over disjoint topology-aligned node partitions, coupled only
    # through the exact global quota ledger + the stranded-ask repair
    # pass. "auto" and "1" build the plain single scheduler (bit-identical
    # to the pre-shard core); sharding is opt-in until the parity bench
    # has hardware numbers. NOT hot-reloadable (shards are process
    # structure, like the scheduling interval).
    solver_shards: str = "auto"
    # async front end (core/delivery.py): shed-to-repair high-water mark
    # per shard delivery queue
    solver_delivery_high_water: int = 1024
    # per-shard bind workers (utils/workers.ShardedBindPool); 0 = auto
    bind_pool_workers: int = 0
    # ring capacity of the cycle tracer (spans kept for /debug/traces and
    # bench --trace-out; per-pod bind spans ride a separate fixed ring)
    obs_trace_spans: int = 4096
    # --- SLO engine knobs (obs/slo.py) --- windows + per-objective targets
    # for the streaming multi-window burn-rate evaluation; the trace-replay
    # proving ground compresses the windows to seconds through these same
    # keys (scripts/trace_replay.py)
    obs_slo_fast_window_s: float = 300.0
    obs_slo_slow_window_s: float = 3600.0
    obs_slo_pod_e2e_p99_s: float = 30.0
    obs_slo_cycle_staleness_s: float = 60.0
    obs_slo_degraded_dwell_budget: float = 0.05
    obs_slo_cold_start_budget_ms: float = 15000.0
    obs_slo_burn_fast_threshold: float = 6.0
    # --- journey ledger + flight recorder (round 20) --- the journey cap
    # bounds the per-pod hop-timeline map; an empty flight-recorder dir
    # DISABLES post-mortem bundles (no disk writes without an operator
    # opting into a location — the bounded-disk contract starts there)
    obs_journey_capacity: int = 8192
    obs_flightrec_dir: str = ""
    obs_flightrec_max: int = 8
    obs_flightrec_window_s: float = 30.0
    obs_flightrec_debounce_s: float = 30.0
    # --- robustness knobs --- (SupervisedExecutor: every device dispatch
    # gets a deadline, classified bounded retry, and a per-path circuit
    # breaker degrading device → cpu → host; see robustness/supervisor.py)
    # deadline is generous: a first-touch compile at a big bucket can
    # legitimately take minutes — the deadline catches WEDGED dispatches
    robustness_dispatch_deadline_s: float = 300.0
    robustness_max_retries: int = 2
    robustness_breaker_threshold: int = 3
    robustness_probe_interval_s: float = 30.0
    robustness_probe_deadline_s: float = 20.0
    # --- shard failover (robustness/failover.py, sharded control plane
    # only) --- stale: a shard with no completed cycle for this long is
    # quarantined (generous: a first-touch big-bucket compile is tens of
    # seconds on CPU); probe: detector cadence; rejoin: quarantine dwell
    # before the shard is rebuilt and re-admitted at the next epoch.
    robustness_failover_stale_s: float = 120.0
    robustness_failover_probe_s: float = 2.0
    robustness_failover_rejoin_s: float = 60.0
    # false = the failover supervisor never starts (an external
    # orchestrator owns shard health, or failover is being ruled out
    # while debugging); the quarantine mechanics stay callable directly
    robustness_failover_enabled: str = "true"
    # --- ledger service (round 22; core/ledger_service.py) --- endpoint
    # of an out-of-process quota authority ("" = in-process; NOT
    # hot-reloadable); per-RPC deadline; degraded-mode admission policy;
    # host lease TTL on the ledger liveness authority
    solver_ledger_endpoint: str = ""
    robustness_ledger_deadline_s: float = 2.0
    robustness_ledger_fail_closed: str = "false"
    robustness_ledger_lease_ttl_s: float = 15.0

    def clone(self) -> "SchedulerConf":
        c = dataclasses.replace(self)
        c.placeholder = dataclasses.replace(self.placeholder)
        return c


# Keys that cannot change across a hot reload (reference :212-226).
_NON_RELOADABLE = [
    CM_SVC_CLUSTER_ID,
    CM_SVC_POLICY_GROUP,
    CM_SVC_SCHEDULING_INTERVAL,
    CM_SVC_VOLUME_BIND_TIMEOUT,
    CM_SVC_EVENT_CHANNEL_CAPACITY,
    CM_SVC_DISPATCH_TIMEOUT,
    CM_KUBE_QPS,
    CM_KUBE_BURST,
    CM_SVC_DISABLE_GANG,
    CM_SVC_INSTANCE_TYPE_LABEL,
    CM_SVC_PLACEHOLDER_IMAGE,
    CM_SVC_PLACEHOLDER_RUN_AS_USER,
    CM_SVC_PLACEHOLDER_RUN_AS_GROUP,
    CM_SVC_PLACEHOLDER_FS_GROUP,
    CM_SOLVER_SHARDS,
    CM_SOLVER_DELIVERY_HIGH_WATER,
    CM_SVC_BIND_POOL_WORKERS,
]


def _parse_bool(v: str, default: bool) -> bool:
    s = v.strip().lower()
    if s in ("1", "true", "yes", "on"):
        return True
    if s in ("0", "false", "no", "off"):
        return False
    logger.warning("invalid bool value %r, keeping %s", v, default)
    return default


def _parse_duration(v: str, default: float) -> float:
    """Parse Go-style durations ("10s", "5m", "1h30m", "300ms") or bare seconds."""
    s = v.strip()
    try:
        return float(s)
    except ValueError:
        pass
    import re

    total = 0.0
    matched = False
    for num, unit in re.findall(r"([0-9.]+)(ns|us|µs|ms|s|m|h)", s):
        matched = True
        mult = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}[unit]
        total += float(num) * mult
    if not matched:
        logger.warning("invalid duration %r, keeping %s", v, default)
        return default
    return total


def _parse_int(v: str, default: int) -> int:
    try:
        return int(v.strip())
    except ValueError:
        logger.warning("invalid int value %r, keeping %s", v, default)
        return default


def _parse_float(v: str, default: float) -> float:
    try:
        return float(v.strip())
    except ValueError:
        logger.warning("invalid float value %r, keeping %s", v, default)
        return default


def _parse_choice(key: str, v: str, allowed: Tuple[str, ...]) -> str:
    """Validated enumerated option (the tri-state device-path gates,
    solver.gateVerify, solver.policy). Unknown values raise — the whole
    configmap update is rejected loudly (ConfHolder keeps the previous
    config) instead of silently running with a default the operator did not
    configure."""
    s = v.strip().lower()
    if s not in allowed:
        raise ValueError(
            f"invalid value {v!r} for {key}: expected one of {allowed}")
    return s


def parse_config_map(data: Dict[str, str], base: Optional[SchedulerConf] = None) -> SchedulerConf:
    """Parse a flattened configmap into a SchedulerConf (reference :344-448)."""
    conf = (base or SchedulerConf()).clone()

    def s(key: str, cur: str) -> str:
        return data.get(key, cur)

    conf.cluster_id = s(CM_SVC_CLUSTER_ID, conf.cluster_id)
    conf.policy_group = s(CM_SVC_POLICY_GROUP, conf.policy_group)
    conf.operator_plugins = s(CM_SVC_OPERATOR_PLUGINS, conf.operator_plugins)
    if CM_SVC_BIND_POOL_WORKERS in data:
        conf.bind_pool_workers = _parse_int(
            data[CM_SVC_BIND_POOL_WORKERS], conf.bind_pool_workers)
    conf.placeholder.image = s(CM_SVC_PLACEHOLDER_IMAGE, conf.placeholder.image)
    conf.instance_type_node_label_key = s(CM_SVC_INSTANCE_TYPE_LABEL, conf.instance_type_node_label_key)
    conf.solver_scoring_policy = s(CM_SOLVER_SCORING_POLICY, conf.solver_scoring_policy)
    conf.solver_platform = s(CM_SOLVER_DEVICE_PLATFORM, conf.solver_platform)
    conf.solver_aot_store = s(CM_SOLVER_AOT_STORE, conf.solver_aot_store)
    conf.solver_policy_checkpoint = s(CM_SOLVER_POLICY_CHECKPOINT,
                                      conf.solver_policy_checkpoint)
    if CM_SVC_SCHEDULING_INTERVAL in data:
        conf.interval = _parse_duration(data[CM_SVC_SCHEDULING_INTERVAL], conf.interval)
    if CM_SVC_VOLUME_BIND_TIMEOUT in data:
        conf.volume_bind_timeout = _parse_duration(data[CM_SVC_VOLUME_BIND_TIMEOUT], conf.volume_bind_timeout)
    if CM_SVC_DISPATCH_TIMEOUT in data:
        conf.dispatch_timeout = _parse_duration(data[CM_SVC_DISPATCH_TIMEOUT], conf.dispatch_timeout)
    if CM_SVC_EVENT_CHANNEL_CAPACITY in data:
        conf.event_channel_capacity = _parse_int(data[CM_SVC_EVENT_CHANNEL_CAPACITY], conf.event_channel_capacity)
    if CM_KUBE_QPS in data:
        conf.kube_qps = _parse_int(data[CM_KUBE_QPS], conf.kube_qps)
    if CM_KUBE_BURST in data:
        conf.kube_burst = _parse_int(data[CM_KUBE_BURST], conf.kube_burst)
    if CM_SVC_DISABLE_GANG in data:
        conf.disable_gang_scheduling = _parse_bool(data[CM_SVC_DISABLE_GANG], conf.disable_gang_scheduling)
    if CM_SVC_ENABLE_HOT_REFRESH in data:
        conf.enable_config_hot_refresh = _parse_bool(data[CM_SVC_ENABLE_HOT_REFRESH], conf.enable_config_hot_refresh)
    if CM_SVC_ENABLE_DRA in data:
        conf.enable_dra = _parse_bool(data[CM_SVC_ENABLE_DRA], conf.enable_dra)
    if CM_SVC_PLACEHOLDER_RUN_AS_USER in data:
        conf.placeholder.run_as_user = _parse_int(data[CM_SVC_PLACEHOLDER_RUN_AS_USER], conf.placeholder.run_as_user)
    if CM_SVC_PLACEHOLDER_RUN_AS_GROUP in data:
        conf.placeholder.run_as_group = _parse_int(data[CM_SVC_PLACEHOLDER_RUN_AS_GROUP], conf.placeholder.run_as_group)
    if CM_SVC_PLACEHOLDER_FS_GROUP in data:
        conf.placeholder.fs_group = _parse_int(data[CM_SVC_PLACEHOLDER_FS_GROUP], conf.placeholder.fs_group)
    if CM_SOLVER_MAX_ROUNDS in data:
        conf.solver_max_rounds = _parse_int(data[CM_SOLVER_MAX_ROUNDS], conf.solver_max_rounds)
    if CM_SOLVER_POD_CHUNK in data:
        conf.solver_pod_chunk = _parse_int(data[CM_SOLVER_POD_CHUNK], conf.solver_pod_chunk)
    if CM_SOLVER_MAX_BATCH in data:
        conf.solver_max_batch = _parse_int(data[CM_SOLVER_MAX_BATCH], conf.solver_max_batch)
    if CM_SOLVER_FALLBACK_ROUNDS in data:
        conf.solver_fallback_rounds = _parse_int(
            data[CM_SOLVER_FALLBACK_ROUNDS], conf.solver_fallback_rounds)
    if CM_OBS_TRACE_SPANS in data:
        conf.obs_trace_spans = _parse_int(
            data[CM_OBS_TRACE_SPANS], conf.obs_trace_spans)
    for key, attr in ((CM_OBS_SLO_FAST_WINDOW, "obs_slo_fast_window_s"),
                      (CM_OBS_SLO_SLOW_WINDOW, "obs_slo_slow_window_s"),
                      (CM_OBS_SLO_POD_E2E_P99, "obs_slo_pod_e2e_p99_s"),
                      (CM_OBS_SLO_STALENESS, "obs_slo_cycle_staleness_s")):
        if key in data:
            setattr(conf, attr,
                    _parse_duration(data[key], getattr(conf, attr)))
    for key, attr in ((CM_OBS_SLO_DWELL_BUDGET,
                       "obs_slo_degraded_dwell_budget"),
                      (CM_OBS_SLO_COLD_BUDGET, "obs_slo_cold_start_budget_ms"),
                      (CM_OBS_SLO_BURN_FAST, "obs_slo_burn_fast_threshold")):
        if key in data:
            setattr(conf, attr, _parse_float(data[key], getattr(conf, attr)))
    if CM_OBS_JOURNEY_CAPACITY in data:
        conf.obs_journey_capacity = _parse_int(
            data[CM_OBS_JOURNEY_CAPACITY], conf.obs_journey_capacity)
    if CM_OBS_FLIGHTREC_DIR in data:
        conf.obs_flightrec_dir = str(data[CM_OBS_FLIGHTREC_DIR]).strip()
    if CM_OBS_FLIGHTREC_MAX in data:
        conf.obs_flightrec_max = _parse_int(
            data[CM_OBS_FLIGHTREC_MAX], conf.obs_flightrec_max)
    if CM_OBS_FLIGHTREC_WINDOW in data:
        conf.obs_flightrec_window_s = _parse_duration(
            data[CM_OBS_FLIGHTREC_WINDOW], conf.obs_flightrec_window_s)
    if CM_OBS_FLIGHTREC_DEBOUNCE in data:
        conf.obs_flightrec_debounce_s = _parse_duration(
            data[CM_OBS_FLIGHTREC_DEBOUNCE], conf.obs_flightrec_debounce_s)
    if CM_ROBUST_DEADLINE in data:
        conf.robustness_dispatch_deadline_s = _parse_duration(
            data[CM_ROBUST_DEADLINE], conf.robustness_dispatch_deadline_s)
    if CM_ROBUST_MAX_RETRIES in data:
        conf.robustness_max_retries = _parse_int(
            data[CM_ROBUST_MAX_RETRIES], conf.robustness_max_retries)
    if CM_ROBUST_BREAKER_THRESHOLD in data:
        conf.robustness_breaker_threshold = _parse_int(
            data[CM_ROBUST_BREAKER_THRESHOLD], conf.robustness_breaker_threshold)
    if CM_ROBUST_PROBE_INTERVAL in data:
        conf.robustness_probe_interval_s = _parse_duration(
            data[CM_ROBUST_PROBE_INTERVAL], conf.robustness_probe_interval_s)
    if CM_ROBUST_PROBE_DEADLINE in data:
        conf.robustness_probe_deadline_s = _parse_duration(
            data[CM_ROBUST_PROBE_DEADLINE], conf.robustness_probe_deadline_s)
    for key, attr in ((CM_ROBUST_FAILOVER_STALE, "robustness_failover_stale_s"),
                      (CM_ROBUST_FAILOVER_PROBE, "robustness_failover_probe_s"),
                      (CM_ROBUST_FAILOVER_REJOIN,
                       "robustness_failover_rejoin_s")):
        if key in data:
            setattr(conf, attr,
                    _parse_duration(data[key], getattr(conf, attr)))
    if CM_ROBUST_FAILOVER_ENABLED in data:
        conf.robustness_failover_enabled = _parse_choice(
            CM_ROBUST_FAILOVER_ENABLED, data[CM_ROBUST_FAILOVER_ENABLED],
            ("true", "false"))
    for key, attr, allowed in (
            (CM_SOLVER_USE_PALLAS, "solver_use_pallas", TRI_STATE),
            (CM_SOLVER_SHARD, "solver_shard", TRI_STATE),
            (CM_SOLVER_PIPELINE, "solver_pipeline", TRI_STATE),
            (CM_SOLVER_PREEMPT_DEVICE, "solver_preempt_device", TRI_STATE),
            (CM_SOLVER_GATE, "solver_gate", TRI_STATE),
            (CM_SOLVER_GATE_DEVICE, "solver_gate_device", TRI_STATE),
            (CM_SOLVER_GATE_VERIFY, "solver_gate_verify", ("true", "false")),
            (CM_SOLVER_AOT_BACKGROUND, "solver_aot_background", TRI_STATE),
            (CM_SOLVER_TOPOLOGY, "solver_topology", TRI_STATE),
            (CM_SOLVER_POLICY, "solver_policy", SOLVER_POLICIES),
            (CM_SOLVER_PACK, "solver_pack", SOLVER_PACK_ARMS)):
        if key in data:
            setattr(conf, attr, _parse_choice(key, data[key], allowed))
    if CM_SOLVER_SHARDS in data:
        conf.solver_shards = _parse_shards(data[CM_SOLVER_SHARDS])
    if CM_SOLVER_DELIVERY_HIGH_WATER in data:
        conf.solver_delivery_high_water = _parse_int(
            data[CM_SOLVER_DELIVERY_HIGH_WATER],
            conf.solver_delivery_high_water)
    if CM_SOLVER_LEDGER_ENDPOINT in data:
        conf.solver_ledger_endpoint = str(
            data[CM_SOLVER_LEDGER_ENDPOINT]).strip()
    if CM_ROBUST_LEDGER_FAIL_CLOSED in data:
        conf.robustness_ledger_fail_closed = _parse_choice(
            CM_ROBUST_LEDGER_FAIL_CLOSED,
            data[CM_ROBUST_LEDGER_FAIL_CLOSED], ("true", "false"))
    if CM_ROBUST_LEDGER_DEADLINE in data:
        conf.robustness_ledger_deadline_s = _parse_duration(
            data[CM_ROBUST_LEDGER_DEADLINE],
            conf.robustness_ledger_deadline_s)
    if CM_ROBUST_LEDGER_LEASE_TTL in data:
        conf.robustness_ledger_lease_ttl_s = _parse_duration(
            data[CM_ROBUST_LEDGER_LEASE_TTL],
            conf.robustness_ledger_lease_ttl_s)
    return conf


def _parse_shards(v: str) -> str:
    """solver.shards: "auto" or an integer shard count in [1, 64]. Unknown
    values REJECT the configmap update like the other enumerated keys
    (core/shard.resolve_shards maps the validated string to a count)."""
    s = v.strip().lower()
    if s == "auto":
        return s
    try:
        n = int(s)
    except ValueError:
        raise ValueError(
            f"invalid value {v!r} for {CM_SOLVER_SHARDS}: expected "
            "'auto' or an integer in [1, 64]")
    if not 1 <= n <= 64:
        raise ValueError(
            f"invalid value {v!r} for {CM_SOLVER_SHARDS}: shard count "
            "must be in [1, 64]")
    return str(n)


def decompress(key: str, value: bytes) -> Tuple[str, str]:
    """Decompress a gzip-compressed binaryData configmap entry.

    The key convention is ``<real-key>.gz`` (reference Decompress, :482-507).
    """
    real_key = key[:-3] if key.endswith(".gz") else key
    try:
        raw = gzip.decompress(value)
    except OSError:
        try:
            raw = gzip.decompress(base64.b64decode(value))
        except Exception:
            logger.error("failed to decompress configmap value for key %s", key)
            return real_key, ""
    return real_key, raw.decode("utf-8")


def flatten_config_maps(config_maps: List[Optional[Dict]], binary_maps: Optional[List[Dict[str, bytes]]] = None) -> Dict[str, str]:
    """Overlay configmaps in order: later maps win (reference FlattenConfigMaps).

    Index 0 is yunikorn-defaults, index 1 is yunikorn-configs.
    """
    out: Dict[str, str] = {}
    for i, cm in enumerate(config_maps):
        if not cm:
            continue
        out.update({k: str(v) for k, v in cm.items()})
        if binary_maps and i < len(binary_maps) and binary_maps[i]:
            for k, v in binary_maps[i].items():
                rk, rv = decompress(k, v)
                out[rk] = rv
    return out


def check_non_reloadable(old: SchedulerConf, new: SchedulerConf) -> List[str]:
    """Return the list of non-reloadable keys whose values changed (warn-only)."""
    changed = []
    pairs = {
        CM_SVC_CLUSTER_ID: (old.cluster_id, new.cluster_id),
        CM_SVC_POLICY_GROUP: (old.policy_group, new.policy_group),
        CM_SVC_SCHEDULING_INTERVAL: (old.interval, new.interval),
        CM_SVC_VOLUME_BIND_TIMEOUT: (old.volume_bind_timeout, new.volume_bind_timeout),
        CM_SVC_EVENT_CHANNEL_CAPACITY: (old.event_channel_capacity, new.event_channel_capacity),
        CM_SVC_DISPATCH_TIMEOUT: (old.dispatch_timeout, new.dispatch_timeout),
        CM_KUBE_QPS: (old.kube_qps, new.kube_qps),
        CM_KUBE_BURST: (old.kube_burst, new.kube_burst),
        CM_SVC_DISABLE_GANG: (old.disable_gang_scheduling, new.disable_gang_scheduling),
        CM_SVC_INSTANCE_TYPE_LABEL: (old.instance_type_node_label_key, new.instance_type_node_label_key),
        CM_SVC_PLACEHOLDER_IMAGE: (old.placeholder.image, new.placeholder.image),
        CM_SVC_PLACEHOLDER_RUN_AS_USER: (old.placeholder.run_as_user, new.placeholder.run_as_user),
        CM_SVC_PLACEHOLDER_RUN_AS_GROUP: (old.placeholder.run_as_group, new.placeholder.run_as_group),
        CM_SVC_PLACEHOLDER_FS_GROUP: (old.placeholder.fs_group, new.placeholder.fs_group),
        CM_SOLVER_SHARDS: (old.solver_shards, new.solver_shards),
        CM_SOLVER_DELIVERY_HIGH_WATER: (old.solver_delivery_high_water,
                                        new.solver_delivery_high_water),
        CM_SOLVER_LEDGER_ENDPOINT: (old.solver_ledger_endpoint,
                                    new.solver_ledger_endpoint),
        CM_SVC_BIND_POOL_WORKERS: (old.bind_pool_workers,
                                   new.bind_pool_workers),
    }
    for key, (a, b) in pairs.items():
        if a != b:
            changed.append(key)
            logger.warning("ignoring non-reloadable configmap key change: %s (%r -> %r)", key, a, b)
    return changed


class ConfHolder:
    """Atomic config holder with hot-reload semantics (reference confHolder)."""

    def __init__(self):
        self._lock = locking.Mutex()
        self._conf = SchedulerConf()
        self._queues_config: str = ""
        self._extra: Dict[str, str] = {}

    def get(self) -> SchedulerConf:
        with self._lock:
            return self._conf

    def queues_config(self) -> str:
        with self._lock:
            return self._queues_config

    def update_config_maps(self, config_maps: List[Optional[Dict]], initial: bool = False,
                           binary_maps: Optional[List[Dict[str, bytes]]] = None) -> SchedulerConf:
        flat = flatten_config_maps(config_maps, binary_maps)
        with self._lock:
            try:
                new_conf = parse_config_map(flat, SchedulerConf())
            except ValueError as e:
                if initial:
                    # at startup there is no previous config to keep —
                    # swallowing the error would silently run the whole
                    # deployment on defaults; fail the boot loudly instead
                    # (deploy-time validation, the operator sees it)
                    logger.error("invalid initial configmap: %s", e)
                    raise
                # hot reload with an unknown enumerated value: reject the
                # whole update (keep serving the previous config) instead
                # of silently running with defaults the operator didn't set
                logger.error("rejecting configmap update: %s", e)
                return self._conf
            if not initial:
                check_non_reloadable(self._conf, new_conf)
                # keep old values for non-reloadable fields
                keep = self._conf
                new_conf.cluster_id = keep.cluster_id
                new_conf.policy_group = keep.policy_group
                new_conf.interval = keep.interval
                new_conf.volume_bind_timeout = keep.volume_bind_timeout
                new_conf.event_channel_capacity = keep.event_channel_capacity
                new_conf.dispatch_timeout = keep.dispatch_timeout
                new_conf.kube_qps = keep.kube_qps
                new_conf.kube_burst = keep.kube_burst
                new_conf.disable_gang_scheduling = keep.disable_gang_scheduling
                new_conf.instance_type_node_label_key = keep.instance_type_node_label_key
                new_conf.solver_shards = keep.solver_shards
                new_conf.solver_delivery_high_water = \
                    keep.solver_delivery_high_water
                new_conf.solver_ledger_endpoint = \
                    keep.solver_ledger_endpoint
                new_conf.bind_pool_workers = keep.bind_pool_workers
                new_conf.placeholder = dataclasses.replace(keep.placeholder)
            self._conf = new_conf
            # queues.yaml payload keyed by "<policyGroup>.yaml" or the bare policy group
            self._queues_config = flat.get(
                f"{new_conf.policy_group}.yaml", flat.get(new_conf.policy_group, "")
            )
            self._extra = {k: v for k, v in flat.items() if k.startswith(PREFIX_LOG)}
        update_logging_config(self._extra)
        return new_conf


_holder = ConfHolder()


def get_scheduler_conf() -> SchedulerConf:
    return _holder.get()


def get_holder() -> ConfHolder:
    return _holder


def reset_for_tests() -> None:
    global _holder
    _holder = ConfHolder()
