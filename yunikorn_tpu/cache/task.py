"""Task: one pod's scheduling lifecycle on the shim side.

Role-equivalent to pkg/cache/task.go (struct :42-64, submit :288-337,
postTaskAllocated async bind :348-394, release protocol :454-516, pod-condition
dedup :577-597) + task_state.go (FSM New/Pending/Scheduling/Allocated/Rejected/
Bound/Killing/Killed/Failed/Completed, transitions :322-376) +
task_sched_state.go (the autoscaler-facing TaskSchedulingState, separate from
the FSM).
"""
from __future__ import annotations

import enum
import threading
import time
from typing import Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.events import AppEventRecord, TaskEventRecord, get_recorder
from yunikorn_tpu.common.objects import Pod, PodCondition
from yunikorn_tpu.common.resource import Resource, get_pod_resource
from yunikorn_tpu.common.si import (
    AllocationAsk,
    AllocationRelease,
    AllocationRequest,
    TerminationType,
)
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.utils.fsm import FSM, Transition

logger = log("shim.cache.task")

# FSM states (reference task_state.go TaskStates)
NEW = "New"
PENDING = "Pending"
SCHEDULING = "Scheduling"
ALLOCATED = "Allocated"
REJECTED = "Rejected"
BOUND = "Bound"
KILLING = "Killing"
KILLED = "Killed"
FAILED = "Failed"
COMPLETED = "Completed"
ANY = [NEW, PENDING, SCHEDULING, ALLOCATED, REJECTED, BOUND, KILLING, KILLED, FAILED, COMPLETED]
TERMINATED = [REJECTED, KILLED, FAILED, COMPLETED]

# events (reference task_state.go TaskEventType)
INIT_TASK = "InitTask"
SUBMIT_TASK = "SubmitTask"
TASK_ALLOCATED = "TaskAllocated"
TASK_BOUND = "TaskBound"
COMPLETE_TASK = "CompleteTask"
KILL_TASK = "KillTask"
TASK_KILLED = "TaskKilled"
TASK_REJECTED = "TaskRejected"
TASK_FAIL = "TaskFail"
TASK_RETRY = "TaskRetry"

# bind attempts per task before the failure is treated as terminal: a bind
# can race cluster state (the target node deleted between the core's commit
# and the API bind — the node-remove-with-pods-in-flight scenario), and the
# pod is still Pending and unassigned, so terminal-failing it strands a
# schedulable pod forever. The cap keeps a persistently failing bind (API
# rejecting the pod itself) from looping.
BIND_RETRY_MAX = 5

_TRANSITIONS = [
    Transition(INIT_TASK, [NEW], PENDING),
    Transition(SUBMIT_TASK, [PENDING], SCHEDULING),
    Transition(TASK_ALLOCATED, [SCHEDULING], ALLOCATED),
    Transition(TASK_ALLOCATED, [COMPLETED], COMPLETED),
    Transition(TASK_BOUND, [ALLOCATED], BOUND),
    Transition(COMPLETE_TASK, ANY, COMPLETED),
    Transition(KILL_TASK, [PENDING, SCHEDULING, ALLOCATED, BOUND], KILLING),
    Transition(TASK_KILLED, [KILLING], KILLED),
    Transition(TASK_REJECTED, [NEW, PENDING, SCHEDULING], REJECTED),
    Transition(TASK_FAIL, [NEW, PENDING, SCHEDULING, REJECTED, ALLOCATED], FAILED),
    # bind failed against live cluster state (allocation already released):
    # back to Pending, which re-submits a fresh ask on the next dispatch
    Transition(TASK_RETRY, [ALLOCATED], PENDING),
]


class TaskSchedulingState(enum.Enum):
    """Autoscaler-facing state, distinct from the FSM (task_sched_state.go:27-40)."""

    PENDING = "Pending"
    SKIPPED = "Skipped"
    FAILED = "Failed"
    ALLOCATED = "Allocated"


class Task:
    def __init__(self, app, pod: Pod, context, placeholder: bool = False,
                 task_group_name: str = "", originator: bool = False):
        self.application = app
        self.task_id = pod.uid
        self.alias = pod.key()
        self.pod = pod
        self.context = context
        self.placeholder = placeholder
        self.task_group_name = task_group_name or ""
        self.originator = originator
        self.resource: Resource = get_pod_resource(pod)
        self.allocation_key: str = ""
        self.node_name: str = ""
        self.created_time = pod.metadata.creation_timestamp
        self.scheduling_state = TaskSchedulingState.PENDING
        self.terminated_reason = ""
        self.bind_retries = 0
        self._lock = locking.RMutex()
        self.fsm = FSM(NEW, _TRANSITIONS, {
            "enter_state": self._log_transition,
            "enter_" + PENDING: lambda e: self._post_pending(),
            "after_" + SUBMIT_TASK: lambda e: self._handle_submit(),
            "before_" + TASK_ALLOCATED: lambda e: self._before_allocated(*e.args),
            "enter_" + ALLOCATED: lambda e: self._post_allocated(),
            "enter_" + BOUND: lambda e: self._post_bound(),
            "enter_" + REJECTED: lambda e: self._post_rejected(*e.args),
            "before_" + COMPLETE_TASK: lambda e: self._before_completed(),
            "after_" + COMPLETE_TASK: lambda e: self._after_completed(),
            "before_" + TASK_FAIL: lambda e: self._before_fail(*e.args),
            "before_" + TASK_RETRY: lambda e: self._before_retry(*e.args),
        })

    # ------------------------------------------------------------------ state
    @property
    def state(self) -> str:
        return self.fsm.current

    def is_terminated(self) -> bool:
        return self.fsm.current in TERMINATED

    def sanity_check_before_scheduling(self) -> Optional[str]:
        """PVC checks before submitting the ask (reference task.go:552-575)."""
        for vol in self.pod.spec.volumes:
            if vol.pvc_claim_name:
                pvc = self.context.get_pvc(self.pod.namespace, vol.pvc_claim_name)
                if pvc is None:
                    return f"pvc {vol.pvc_claim_name} not found"
                if getattr(pvc, "deleted", False):
                    return f"pvc {vol.pvc_claim_name} is being deleted"
        return None

    # ------------------------------------------------------------- FSM hooks
    def _log_transition(self, e) -> None:
        logger.info("task state transition app=%s task=%s %s -> %s (%s)",
                    self.application.application_id, self.alias, e.src, e.dst, e.event)

    def _post_pending(self) -> None:
        dispatch_mod.dispatch(TaskEventRecord(
            self.application.application_id, self.task_id, SUBMIT_TASK))

    def _handle_submit(self) -> None:
        """Submit the allocation ask to the core (reference task.go:288-337)."""
        err = self.sanity_check_before_scheduling()
        if err is not None:
            dispatch_mod.dispatch(TaskEventRecord(
                self.application.application_id, self.task_id, TASK_FAIL, (err,)))
            return
        ask = AllocationAsk(
            allocation_key=self.task_id,
            application_id=self.application.application_id,
            resource=self.resource,
            priority=self.pod.spec.priority or 0,
            placeholder=self.placeholder,
            task_group_name=self.task_group_name,
            originator=self.originator,
            tags={"kubernetes.io/meta/namespace": self.pod.namespace,
                  "kubernetes.io/meta/podName": self.pod.name},
            pod=self.pod,
        )
        self.context.scheduler_api.update_allocation(AllocationRequest(asks=[ask]))
        get_recorder().eventf("Pod", self.alias, "Normal", "Scheduling",
                              "%s is queued and waiting for allocation", self.alias)

    def _before_allocated(self, allocation_key: str = "", node_name: str = "") -> None:
        self.allocation_key = allocation_key or self.task_id
        self.node_name = node_name
        self.scheduling_state = TaskSchedulingState.ALLOCATED

    def _bind_shard(self):
        """Which scheduler shard owns this task's node (duck-typed against
        ShardedCoreScheduler.fanout; None for the plain core — the pool
        maps it to group 0). Attributes the bind to the shard that placed
        it so per-shard bind groups drain independently."""
        api = getattr(self.context, "scheduler_api", None)
        fan = getattr(api, "fanout", None)
        if fan is not None and self.node_name:
            try:
                return fan.owner_of(self.node_name)
            except Exception:
                return None
        return None

    def _post_allocated(self) -> None:
        """Bind volumes + pod asynchronously (reference task.go:348-394)."""

        def bind():
            try:
                self.context.bind_pod_volumes(self.pod, self.node_name)
                self.context.api_provider.get_client().bind(self.pod, self.node_name)
                # close the pod's end-to-end latency span in the core's
                # observability registry (submit→…→commit happened core-side;
                # the bind completes the span) — duck-typed so minimal test
                # scheduler_api fakes need no observability surface
                observe = getattr(self.context.scheduler_api,
                                  "observe_pod_bound", None)
                if observe is not None:
                    try:
                        observe(self.task_id)
                    except Exception:
                        logger.exception("pod-bound span observation failed")
                get_recorder().eventf("Pod", self.alias, "Normal", "PodBindSuccessful",
                                      "Pod %s is successfully bound to node %s",
                                      self.alias, self.node_name)
                dispatch_mod.dispatch(TaskEventRecord(
                    self.application.application_id, self.task_id, TASK_BOUND))
            except Exception as e:  # bind failure → release + retry or fail
                logger.exception("bind failed for %s", self.alias)
                get_recorder().eventf("Pod", self.alias, "Warning", "PodBindFailure",
                                      "binding pod %s failed: %s", self.alias, e)
                self.release_allocation(TerminationType.STOPPED_BY_RM, f"bind failure: {e}")
                try:
                    dispatch_mod.dispatch(TaskEventRecord(
                        self.application.application_id, self.task_id,
                        self._bind_failure_event(), (str(e),)))
                except Exception:
                    pass

        pool = getattr(self.context, "bind_pool", None)
        if pool is None:  # minimal contexts in tests
            threading.Thread(target=bind, name=f"bind-{self.task_id}",
                             daemon=True).start()
        elif not pool.submit(bind, key=self.task_id,
                             shard=self._bind_shard()):
            # pool already shut down (shim stopping): run the failure path so
            # the allocation is not leaked as forever-ALLOCATED
            logger.warning("bind pool shut down; failing task %s", self.alias)
            self.release_allocation(TerminationType.STOPPED_BY_RM,
                                    "shim stopping before bind")

    def _post_bound(self) -> None:
        if self.placeholder:
            from yunikorn_tpu.cache import application as app_mod

            dispatch_mod.dispatch(TaskEventRecord(
                self.application.application_id, "", app_mod.UPDATE_RESERVATION))
        cond = PodCondition(
            type="PodScheduled", status="True", reason="Scheduled",
            message=f"bound to {self.node_name}")
        # the condition patch is an API write with an informer fan-out; run
        # it on the bind pool so the single dispatcher consumer (which runs
        # this hook) is not serialized behind 50k of them in a bind storm
        pool = getattr(self.context, "bind_pool", None)
        if pool is None or not pool.submit(
                lambda: self.update_pod_condition(cond),
                key=self.task_id, shard=self._bind_shard()):
            self.update_pod_condition(cond)

    def _post_rejected(self, reason: str = "") -> None:
        self.terminated_reason = reason
        get_recorder().eventf("Pod", self.alias, "Warning", "TaskRejected",
                              "task %s is rejected: %s", self.alias, reason)
        dispatch_mod.dispatch(TaskEventRecord(
            self.application.application_id, self.task_id, TASK_FAIL,
            (f"task rejected: {reason}",)))

    def _before_completed(self) -> None:
        self.release_allocation(TerminationType.STOPPED_BY_RM, "task completed")

    def _after_completed(self) -> None:
        # a Resuming app waits for its placeholder tasks to finish
        # (reference AppTaskCompleted event, application_state.go)
        from yunikorn_tpu.cache import application as app_mod

        if self.application.state == app_mod.RESUMING:
            dispatch_mod.dispatch(AppEventRecord(
                self.application.application_id, app_mod.APP_TASK_COMPLETED))

    def _bind_failure_event(self) -> str:
        """Outcome of a failed bind: retry while the pod is still a live,
        unassigned API object and the retry budget holds — the failure then
        raced cluster state (node deleted mid-flight) rather than being
        inherent to the pod — else terminal TASK_FAIL (the reference
        behavior). The allocation was already released either way; a retry
        walks Allocated → Pending, and Pending's entry hook re-submits a
        fresh ask, so the next cycle re-places the pod on surviving nodes."""
        self.bind_retries += 1
        if self.bind_retries > BIND_RETRY_MAX:
            return TASK_FAIL
        # NOT guarded on is_assigned: the shim cache assumes the pod onto
        # the target node before the bind (update_pod stamps node_name on
        # the cached object), so the pod we just failed to bind always
        # looks assigned here; the release above un-assumes it
        pod = self.context.schedulers_cache.get_pod(self.task_id)
        if pod is None or pod.is_terminated():
            return TASK_FAIL
        return TASK_RETRY

    def _before_retry(self, reason: str = "") -> None:
        logger.info("task %s: bind attempt %d failed (%s); re-queueing",
                    self.alias, self.bind_retries, reason)
        self.allocation_key = ""
        self.node_name = ""
        self.scheduling_state = TaskSchedulingState.PENDING

    def _before_fail(self, reason: str = "") -> None:
        self.terminated_reason = reason
        get_recorder().eventf("Pod", self.alias, "Warning", "TaskFailed",
                              "task %s failed: %s", self.alias, reason)
        self.release_allocation(TerminationType.STOPPED_BY_RM, reason)

    # -------------------------------------------------------------- releases
    def release_allocation(self, termination: TerminationType, message: str = "") -> None:
        """Release ask/allocation in the core (reference task.go:454-516)."""
        self.context.scheduler_api.update_allocation(AllocationRequest(releases=[
            AllocationRelease(
                application_id=self.application.application_id,
                allocation_key=self.task_id,
                termination_type=termination,
                message=message,
            )
        ]))

    # ------------------------------------------------------------- recovery
    def mark_previously_allocated(self, node_name: str) -> None:
        """Recovery fast-forward: pod already bound in the cluster
        (reference task.go:266-281 MarkPreviouslyAllocated + context fast-path
        context.go:1087-1109): skip Pending/Scheduling, land in Bound."""
        self.allocation_key = self.task_id
        self.node_name = node_name
        self.scheduling_state = TaskSchedulingState.ALLOCATED
        self.fsm.set_current(BOUND)

    # ----------------------------------------------------------- conditions
    def update_pod_condition(self, condition: PodCondition) -> bool:
        """Set a pod condition with dedup (reference task.go:577-597)."""
        client = self.context.api_provider.get_client()
        return client.update_pod_condition(self.pod, condition)

    def set_task_scheduling_state(self, state: TaskSchedulingState, reason: str = "") -> None:
        """Autoscaler integration: SKIPPED/FAILED → PodScheduled=False condition
        (reference context.go:1222-1261)."""
        with self._lock:
            if self.scheduling_state == TaskSchedulingState.ALLOCATED:
                return  # never downgrade an allocated task
            self.scheduling_state = state
        if state in (TaskSchedulingState.SKIPPED, TaskSchedulingState.FAILED):
            self.update_pod_condition(PodCondition(
                type="PodScheduled", status="False", reason="Unschedulable",
                message=reason or "Pod is pending scheduling"))

    def handle_event(self, event: str, *args) -> None:
        """Dispatcher entry: drive the FSM, tolerate invalid events with a log."""
        from yunikorn_tpu.utils.fsm import FSMError

        try:
            self.fsm.event(event, *args)
        except FSMError as e:
            logger.warning("task %s: event %s ignored: %s", self.alias, event, e)

    def time_since_creation(self) -> float:
        return time.time() - self.created_time
