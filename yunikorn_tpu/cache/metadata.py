"""Pod → application/task metadata extraction.

Role-equivalent to pkg/cache/metadata.go (pod → TaskMetadata :120-143, pod →
ApplicationMetadata :145-231) and the utils resolution helpers
(pkg/common/utils/utils.go: appID order canonical label → annotation → legacy
label → spark-app-selector → generated :141-188; queue resolution :102-118).
"""
from __future__ import annotations

import dataclasses
import json
from typing import Dict, List, Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.common.resource import Resource, get_pod_resource
from yunikorn_tpu.common.si import TaskGroup, UserGroupInfo
from yunikorn_tpu.log.logger import log

logger = log("shim.utils")


@dataclasses.dataclass
class TaskMetadata:
    application_id: str
    task_id: str
    pod: Pod
    placeholder: bool
    task_group_name: str


@dataclasses.dataclass
class ApplicationMetadata:
    application_id: str
    queue_name: str
    user: UserGroupInfo
    tags: Dict[str, str]
    task_groups: List[TaskGroup]
    owner_references: List[dict]
    scheduling_policy_params: Dict[str, str]
    creation_time: float
    placeholder_timeout: Optional[float] = None
    gang_scheduling_style: str = constants.GANG_STYLE_SOFT
    partition: str = "default"


def get_application_id(pod: Pod, generate_unique: bool = False) -> str:
    """AppID resolution order (reference utils.go:141-188)."""
    for source in (
        pod.metadata.labels.get(constants.CANONICAL_LABEL_APP_ID),
        pod.metadata.annotations.get(constants.ANNOTATION_APP_ID),
        pod.metadata.labels.get(constants.LABEL_APPLICATION_ID),
        pod.metadata.labels.get(constants.LABEL_SPARK_APP_ID),
    ):
        if source:
            return source
    # autogenerate: one app per namespace unless unique ids requested
    if generate_unique:
        return f"yunikorn-{pod.namespace}-{pod.uid}"
    return f"yunikorn-{pod.namespace}-autogen"


def has_app_id(pod: Pod) -> bool:
    return any(
        (
            pod.metadata.labels.get(constants.CANONICAL_LABEL_APP_ID),
            pod.metadata.annotations.get(constants.ANNOTATION_APP_ID),
            pod.metadata.labels.get(constants.LABEL_APPLICATION_ID),
            pod.metadata.labels.get(constants.LABEL_SPARK_APP_ID),
        )
    )


def get_queue_name(pod: Pod) -> str:
    """Queue resolution (reference utils.go:102-118)."""
    for source in (
        pod.metadata.labels.get(constants.CANONICAL_LABEL_QUEUE_NAME),
        pod.metadata.annotations.get(constants.ANNOTATION_QUEUE_NAME),
        pod.metadata.labels.get(constants.LABEL_QUEUE_NAME),
    ):
        if source:
            return source
    return ""  # empty → core placement decides (root.<namespace> default rule)


def is_placeholder(pod: Pod) -> bool:
    return pod.metadata.annotations.get(constants.ANNOTATION_PLACEHOLDER_FLAG) == constants.TRUE


def get_task_group_name(pod: Pod) -> str:
    return pod.metadata.annotations.get(constants.ANNOTATION_TASK_GROUP_NAME, "")


def parse_task_groups(pod: Pod) -> List[TaskGroup]:
    """Parse the task-groups annotation JSON (reference metadata.go + gang docs)."""
    raw = pod.metadata.annotations.get(constants.ANNOTATION_TASK_GROUPS)
    if not raw:
        return []
    try:
        items = json.loads(raw)
    except json.JSONDecodeError as e:
        logger.error("invalid %s annotation on %s: %s", constants.ANNOTATION_TASK_GROUPS, pod.key(), e)
        return []
    out: List[TaskGroup] = []
    for item in items:
        try:
            out.append(
                TaskGroup(
                    name=item["name"],
                    min_member=int(item["minMember"]),
                    min_resource=dict(item.get("minResource", {})),
                    node_selector=dict(item.get("nodeSelector", {})),
                    tolerations=list(item.get("tolerations", [])),
                    affinity=item.get("affinity"),
                    topology_spread_constraints=list(item.get("topologySpreadConstraints", [])),
                    labels=dict(item.get("labels", {})),
                    annotations=dict(item.get("annotations", {})),
                )
            )
        except (KeyError, TypeError, ValueError) as e:
            logger.error("invalid task group entry on %s: %s", pod.key(), e)
            return []
    return out


def parse_scheduling_policy_params(pod: Pod) -> Dict[str, str]:
    raw = pod.metadata.annotations.get(constants.ANNOTATION_SCHED_POLICY_PARAM, "")
    out: Dict[str, str] = {}
    for part in raw.split(constants.SCHED_POLICY_PARAM_DELIMITER):
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def get_user_groups(pod: Pod, user_label_key: str = constants.DEFAULT_USER_LABEL) -> UserGroupInfo:
    """User info: admission-injected annotation wins, then the user label."""
    raw = pod.metadata.annotations.get(constants.ANNOTATION_USER_INFO)
    if raw:
        try:
            data = json.loads(raw)
            return UserGroupInfo(user=data.get("user", constants.DEFAULT_USER),
                                 groups=list(data.get("groups", [])))
        except json.JSONDecodeError:
            logger.warning("invalid user.info annotation on %s", pod.key())
    user = pod.metadata.labels.get(user_label_key, constants.DEFAULT_USER)
    return UserGroupInfo(user=user, groups=[])


def get_task_metadata(pod: Pod, generate_unique: bool = False) -> Optional[TaskMetadata]:
    if not has_app_id(pod) and pod.spec.scheduler_name != constants.SCHEDULER_NAME:
        return None
    return TaskMetadata(
        application_id=get_application_id(pod, generate_unique),
        task_id=pod.uid,
        pod=pod,
        placeholder=is_placeholder(pod),
        task_group_name=get_task_group_name(pod),
    )


def get_app_metadata(pod: Pod, generate_unique: bool = False) -> Optional[ApplicationMetadata]:
    if not has_app_id(pod) and pod.spec.scheduler_name != constants.SCHEDULER_NAME:
        return None
    params = parse_scheduling_policy_params(pod)
    timeout = None
    if constants.SCHED_POLICY_TIMEOUT_PARAM in params:
        try:
            timeout = float(params[constants.SCHED_POLICY_TIMEOUT_PARAM])
        except ValueError:
            logger.warning("invalid placeholder timeout on %s", pod.key())
    style = params.get(constants.SCHED_POLICY_STYLE_PARAM, constants.GANG_STYLE_SOFT)
    if style not in constants.GANG_STYLES:
        style = constants.GANG_STYLE_SOFT
    tags = {
        constants.APP_TAG_NAMESPACE: pod.namespace,
        "application.stateaware.disable": "true",
    }
    parent_queue = pod.metadata.annotations.get(constants.ANNOTATION_PARENT_QUEUE)
    if parent_queue:
        tags[constants.APP_TAG_NAMESPACE_PARENT_QUEUE] = parent_queue
    # multi-partition: annotation routes the app (extension; the reference
    # shim is single-partition)
    partition = (pod.metadata.annotations.get(constants.ANNOTATION_PARTITION)
                 or pod.metadata.labels.get(constants.LABEL_NODE_PARTITION)
                 or "default")
    return ApplicationMetadata(
        application_id=get_application_id(pod, generate_unique),
        queue_name=get_queue_name(pod),  # empty → the core's placement rules decide
        user=get_user_groups(pod),
        tags=tags,
        task_groups=parse_task_groups(pod),
        owner_references=list(pod.metadata.owner_references) or [
            {"kind": "Pod", "name": pod.name, "uid": pod.uid}
        ],
        scheduling_policy_params=params,
        creation_time=pod.metadata.creation_timestamp,
        placeholder_timeout=timeout,
        gang_scheduling_style=style,
        partition=partition,
    )


def task_group_resource(tg: TaskGroup) -> Resource:
    return Resource.from_requests(tg.min_resource)
