"""External scheduler cache: the kube-scheduler-style view of cluster state.

Role-equivalent to pkg/cache/external/scheduler_cache.go:43-60 — nodesMap /
podsMap / assignedPods / **assumedPods** (value = volumes-all-bound) /
**orphanedPods** (pod referencing an unknown node) / pvcRefCounts, with
AssumePod/ForgetPod (:428-470), UpdatePod assign/unassign/orphan-adoption
(:288-374), and updatePVCRefCounts (:559-578).

Two framework-specific additions:
  - a monotonically increasing **generation** plus per-node dirty tracking, which
    the snapshot encoder uses for incremental device-array updates;
  - NodeInfo keeps an exact aggregated `requested` Resource so encoding a node's
    free capacity is O(1).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Set, Tuple

from yunikorn_tpu.common.objects import Node, Pod
from yunikorn_tpu.common.resource import Resource, get_node_resource, get_pod_resource
from yunikorn_tpu.locking.locking import RWMutex
from yunikorn_tpu.log.logger import log

logger = log("shim.cache.external")


@dataclasses.dataclass
class NodeInfo:
    """Per-node aggregate (analog of framework.NodeInfo the reference borrows)."""

    node: Node
    pods: Dict[str, Pod] = dataclasses.field(default_factory=dict)
    requested: Resource = dataclasses.field(default_factory=Resource)
    allocatable: Resource = dataclasses.field(default_factory=Resource)
    # attach-limit occupancy from VolumeAttachments whose PV no cache pod on
    # this node mounts (out-of-scheduler attachers)
    foreign_attach: int = 0

    def add_pod(self, pod: Pod) -> None:
        key = pod.uid
        if key in self.pods:
            return
        self.pods[key] = pod
        self.requested = self.requested.add(get_pod_resource(pod))

    def remove_pod(self, pod: Pod) -> bool:
        key = pod.uid
        if key not in self.pods:
            return False
        old = self.pods.pop(key)
        self.requested = self.requested.sub(get_pod_resource(old))
        return True

    def set_node(self, node: Node) -> None:
        self.node = node
        self.allocatable = get_node_resource(node.status.allocatable)

    def available(self) -> Resource:
        out = self.allocatable.sub(self.requested)
        if self.foreign_attach:
            from yunikorn_tpu.common.resource import VOLUME_ATTACH

            out = out.sub(Resource({VOLUME_ATTACH: self.foreign_attach}))
        return out


class SchedulerCache:
    def __init__(self):
        self._lock = RWMutex()
        self.nodes_map: Dict[str, NodeInfo] = {}
        self.pods_map: Dict[str, Pod] = {}
        self.pc_map: Dict[str, object] = {}
        self.assigned_pods: Dict[str, str] = {}   # pod uid -> node name
        self.assumed_pods: Dict[str, bool] = {}   # pod uid -> volumes all bound
        self.orphaned_pods: Dict[str, Pod] = {}
        self.pvc_ref_counts: Dict[str, int] = {}  # "ns/claim" -> count
        # DRA state (reference context.go:116-130 gates a DRA manager;
        # informers feed these maps): "ns/name" -> ResourceClaim and
        # "node/class" -> ResourceSlice
        self.resource_claims: Dict[str, object] = {}
        self.resource_slices: Dict[str, object] = {}
        # volume objects ("ns/name" PVCs, PV/StorageClass by name): fed by
        # informers, read by the VolumeBinder and the encoder's volume mask
        self.pvcs_map: Dict[str, object] = {}
        self.pvs_map: Dict[str, object] = {}
        self.storage_classes_map: Dict[str, object] = {}
        self.csi_drivers_map: Dict[str, object] = {}
        self.csi_capacities_map: Dict[str, object] = {}
        self.volume_attachments_map: Dict[str, object] = {}
        # node name -> {va name -> va}: per-node recompute without scanning
        # every attachment (VA nodeName is immutable upstream)
        self._vas_by_node: Dict[str, Dict[str, object]] = {}
        # generation tracking for incremental snapshot encoding
        self._generation = 0
        # bumped only when node allocatable capacity changes (add/remove/update
        # of the node object itself, not pod churn) — cheap memo key for
        # cluster-capacity reductions
        self._capacity_version = 0
        # pod churn only moves a node's FREE capacity; node-object changes
        # (labels/taints/allocatable) need a full row re-encode. Tracked
        # separately so the encoder can take the cheap path for the common case.
        self._dirty_nodes: Set[str] = set()
        self._dirty_node_objects: Set[str] = set()
        # bumped only when a pod carrying required anti-affinity terms enters
        # or leaves the cache — keys the symmetric-anti-affinity term memo so
        # per-pod group signatures stay cached for ordinary workloads
        self._anti_version = 0
        self._listeners: List[Callable[[str], None]] = []

    # ------------------------------------------------------------------ nodes
    def update_node(self, node: Node) -> List[Pod]:
        """Add or update a node. Returns orphaned pods adopted by this node."""
        with self._lock:
            info = self.nodes_map.get(node.name)
            adopted: List[Pod] = []
            if info is None:
                info = NodeInfo(node=node)
                info.set_node(node)
                self.nodes_map[node.name] = info
                # adopt orphans that were waiting for this node (reference :296-374)
                for key, pod in list(self.orphaned_pods.items()):
                    if pod.spec.node_name == node.name:
                        del self.orphaned_pods[key]
                        info.add_pod(pod)
                        self.assigned_pods[key] = node.name
                        self._update_pvc_refs(pod, add=True)
                        adopted.append(pod)
                        logger.info("adopted orphan pod %s onto node %s", pod.key(), node.name)
            else:
                info.set_node(node)
            self._capacity_version += 1
            self._mark_dirty(node.name)
            self._dirty_node_objects.add(node.name)
            return adopted

    def remove_node(self, node_name: str) -> List[Pod]:
        """Remove a node; its pods become orphans. Returns the orphaned pods."""
        with self._lock:
            info = self.nodes_map.pop(node_name, None)
            if info is None:
                return []
            orphans = []
            for key, pod in info.pods.items():
                self.assigned_pods.pop(key, None)
                self.orphaned_pods[key] = pod
                self._update_pvc_refs(pod, add=False)
                orphans.append(pod)
            self._capacity_version += 1
            self._mark_dirty(node_name)
            self._dirty_node_objects.add(node_name)
            return orphans

    def get_node(self, name: str) -> Optional[NodeInfo]:
        with self._lock.reader():
            return self.nodes_map.get(name)

    def snapshot_node(self, name: str) -> Optional[NodeInfo]:
        """Shallow-copied NodeInfo safe to iterate off-thread (the live pods
        dict mutates under informer events)."""
        with self._lock.reader():
            info = self.nodes_map.get(name)
            if info is None:
                return None
            return NodeInfo(node=info.node, pods=dict(info.pods),
                            requested=info.requested, allocatable=info.allocatable,
                            foreign_attach=info.foreign_attach)

    def node_names(self) -> List[str]:
        with self._lock.reader():
            return list(self.nodes_map)

    def node_count(self) -> int:
        with self._lock.reader():
            return len(self.nodes_map)

    # ------------------------------------------------------------------- pods
    def update_pod(self, pod: Pod) -> bool:
        """Insert/refresh a pod; handles assignment and orphaning.

        Returns False when the pod is orphaned (node not in cache), True
        otherwise — reference updatePod (:295-374).
        """
        with self._lock:
            return self._update_pod_locked(pod)

    @staticmethod
    def _has_anti_terms(pod: Optional[Pod]) -> bool:
        return bool(pod is not None and pod.spec.affinity is not None
                    and pod.spec.affinity.pod_anti_affinity_required)

    def _update_pod_locked(self, pod: Pod) -> bool:
        key = pod.uid
        result = True
        if self._has_anti_terms(pod) or self._has_anti_terms(self.pods_map.get(key)):
            self._anti_version += 1
        cur = self.pods_map.get(key)
        # fast path for status-only refires (bind conditions, heartbeat
        # updates): same assignment, same resources, still live → swap the
        # stored object without the remove/add accounting cycle (two resource
        # extractions + node dirty marks per informer event otherwise)
        if cur is not None and not pod.is_terminated():
            node_name = self.assigned_pods.get(key)
            if (node_name is not None
                    and (pod.spec.node_name or node_name) == node_name):
                r_new = get_pod_resource(pod)
                if r_new.resources == get_pod_resource(cur).resources:
                    if not pod.spec.node_name:
                        pod.spec.node_name = node_name
                    info = self.nodes_map.get(node_name)
                    if info is not None and key in info.pods:
                        info.pods[key] = pod
                    self.pods_map[key] = pod
                    if pod.status.phase == "Running":
                        # terminated phases never reach this fast path
                        # (is_terminated() guard above)
                        self.assumed_pods.pop(key, None)
                    return True
        if cur is not None:
            self.pods_map.pop(key, None)
            self.orphaned_pods.pop(key, None)
            node_name = self.assigned_pods.pop(key, None)
            if node_name is not None:
                info = self.nodes_map.get(node_name)
                if info is not None:
                    if not info.remove_pod(cur):
                        logger.warning("BUG: failed to remove pod %s from node %s", cur.key(), node_name)
                    self._update_pvc_refs(cur, add=False)
                    self._mark_dirty(node_name)
                if not pod.spec.node_name:
                    # new version not assigned: keep existing assignment
                    pod.spec.node_name = node_name

        if pod.status.phase in ("Running", "Succeeded", "Failed"):
            # pod has been bound (or finished): assumed state is obsolete
            self.assumed_pods.pop(key, None)

        if pod.is_assigned() and not pod.is_terminated():
            info = self.nodes_map.get(pod.spec.node_name)
            if info is None:
                logger.info("marking pod %s as orphan (node %s not present)", pod.key(), pod.spec.node_name)
                self.orphaned_pods[key] = pod
                result = False
            else:
                info.add_pod(pod)
                self.assigned_pods[key] = pod.spec.node_name
                self._update_pvc_refs(pod, add=True)
                self._mark_dirty(pod.spec.node_name)

        if not pod.is_terminated():
            self.pods_map[key] = pod
        else:
            self.pods_map.pop(key, None)
            self.assigned_pods.pop(key, None)
            self.assumed_pods.pop(key, None)
            self.orphaned_pods.pop(key, None)
        return result

    def remove_pod(self, pod: Pod) -> None:
        with self._lock:
            key = pod.uid
            if self._has_anti_terms(pod) or self._has_anti_terms(self.pods_map.get(key)):
                self._anti_version += 1
            node_name = self.assigned_pods.pop(key, None)
            cur = self.pods_map.pop(key, None)
            if node_name is not None and cur is not None:
                info = self.nodes_map.get(node_name)
                if info is not None:
                    info.remove_pod(cur)
                    self._update_pvc_refs(cur, add=False)
                    self._mark_dirty(node_name)
            self.assumed_pods.pop(key, None)
            self.orphaned_pods.pop(key, None)
            if cur is not None:
                self._dra_release_locked(cur)

    def get_pod(self, uid: str) -> Optional[Pod]:
        with self._lock.reader():
            return self.pods_map.get(uid)

    def get_pod_node_name(self, uid: str) -> Optional[str]:
        with self._lock.reader():
            return self.assigned_pods.get(uid)

    def is_pod_orphaned(self, uid: str) -> bool:
        with self._lock.reader():
            return uid in self.orphaned_pods

    # ------------------------------------------------------------ assume/forget
    def assume_pod(self, pod: Pod, all_volumes_bound: bool) -> None:
        """Optimistically place a pod on its chosen node before the bind lands.

        Reference AssumePod (:428-452): the pod object must already carry
        spec.node_name. A later informer update with phase Running clears the
        assumed flag.
        """
        with self._lock:
            key = pod.uid
            self._update_pod_locked(pod)
            self.assumed_pods[key] = all_volumes_bound
            self._dra_reserve_locked(pod, pod.spec.node_name)

    # --------------------------------------------------------------- volumes
    # PVC/PV/StorageClass object stores: single source for the VolumeBinder
    # (find/assume/bind) and the encoder's vectorized volume-feasibility mask
    # (reference keeps these in informer listers the volumebinding plugin
    # reads, apifactory.go:39-59).
    def update_pvc_obj(self, pvc) -> None:
        with self._lock:
            key = f"{pvc.metadata.namespace}/{pvc.metadata.name}"
            old = self.pvcs_map.get(key)
            self.pvcs_map[key] = pvc
            self._refresh_va_nodes_locked(
                {getattr(old, "volume_name", ""), pvc.volume_name})

    def remove_pvc_obj(self, pvc) -> None:
        with self._lock:
            old = self.pvcs_map.pop(
                f"{pvc.metadata.namespace}/{pvc.metadata.name}", None)
            self._refresh_va_nodes_locked(
                {getattr(old, "volume_name", ""), pvc.volume_name})

    def _refresh_va_nodes_locked(self, pv_names) -> None:
        """A PVC binding change shifts which attachments of those PVs count
        as foreign; refresh only nodes holding an attachment of an affected
        volume (the common PVC event touches no VA at all)."""
        pv_names.discard("")
        if not pv_names or not self._vas_by_node:
            return
        for node, vas in self._vas_by_node.items():
            if any(va.pv_name in pv_names for va in vas.values()):
                self._recompute_foreign_attach_locked(node)

    def get_pvc_obj(self, namespace: str, name: str):
        with self._lock.reader():
            return self.pvcs_map.get(f"{namespace}/{name}")

    def update_pv_obj(self, pv) -> None:
        with self._lock:
            self.pvs_map[pv.metadata.name] = pv

    def remove_pv_obj(self, pv) -> None:
        with self._lock:
            self.pvs_map.pop(pv.metadata.name, None)

    def get_pv_obj(self, name: str):
        with self._lock.reader():
            return self.pvs_map.get(name)

    def list_pv_objs(self) -> list:
        with self._lock.reader():
            return list(self.pvs_map.values())

    def update_storage_class_obj(self, sc) -> None:
        with self._lock:
            self.storage_classes_map[sc.metadata.name] = sc

    def remove_storage_class_obj(self, sc) -> None:
        with self._lock:
            self.storage_classes_map.pop(sc.metadata.name, None)

    def get_storage_class_obj(self, name: str):
        with self._lock.reader():
            return self.storage_classes_map.get(name)

    # CSIDriver flags + CSIStorageCapacity segments: capacity-aware dynamic
    # provisioning (reference: the volumebinding plugin's CSIStorageCapacity
    # checks behind the driver's storageCapacity flag)
    def update_csi_driver_obj(self, drv) -> None:
        with self._lock:
            self.csi_drivers_map[drv.metadata.name] = drv

    def remove_csi_driver_obj(self, drv) -> None:
        with self._lock:
            self.csi_drivers_map.pop(drv.metadata.name, None)

    def get_csi_driver_obj(self, name: str):
        with self._lock.reader():
            return self.csi_drivers_map.get(name)

    def update_csi_capacity_obj(self, cap) -> None:
        with self._lock:
            key = f"{cap.metadata.namespace}/{cap.metadata.name}"
            self.csi_capacities_map[key] = cap

    def remove_csi_capacity_obj(self, cap) -> None:
        with self._lock:
            self.csi_capacities_map.pop(
                f"{cap.metadata.namespace}/{cap.metadata.name}", None)

    def csi_fitting_segments(self, storage_class, requested: int):
        """None = the class's driver does not track capacity (provisionable
        anywhere); else the list of CSIStorageCapacity segments of this class
        that fit `requested` — callers check covers_node() lock-free per node
        (one locked pass instead of M lock round-trips per snapshot build)."""
        with self._lock.reader():
            drv = self.csi_drivers_map.get(storage_class.provisioner)
            if drv is None or not drv.storage_capacity:
                return None
            return [cap for cap in self.csi_capacities_map.values()
                    if cap.storage_class == storage_class.metadata.name
                    and cap.fits(requested)]

    def csi_capacity_feasible(self, storage_class, node, requested: int) -> bool:
        """Can `requested` bytes of `storage_class` be provisioned reachable
        from `node`? True unless the class's driver opted into capacity
        tracking (storageCapacity: true) and no covering segment fits."""
        segments = self.csi_fitting_segments(storage_class, requested)
        if segments is None:
            return True
        return any(node is None or cap.covers_node(node) for cap in segments)

    # VolumeAttachment objects: attachments not backed by a cache pod on the
    # node count as foreign occupancy against the attach limit
    def update_volume_attachment_obj(self, va) -> None:
        with self._lock:
            self.volume_attachments_map[va.metadata.name] = va
            if va.node_name:
                self._vas_by_node.setdefault(va.node_name, {})[va.metadata.name] = va
                self._recompute_foreign_attach_locked(va.node_name)

    def remove_volume_attachment_obj(self, va) -> None:
        with self._lock:
            old = self.volume_attachments_map.pop(va.metadata.name, None)
            node = (old.node_name if old is not None else "") or va.node_name
            if node:
                per = self._vas_by_node.get(node)
                if per is not None:
                    per.pop(va.metadata.name, None)
                    if not per:
                        del self._vas_by_node[node]
                self._recompute_foreign_attach_locked(node)

    def _recompute_foreign_attach_locked(self, node_name: str) -> None:
        info = self.nodes_map.get(node_name)
        if info is None:
            return
        # PVs mounted by pods the cache already counts on this node
        counted_pvs = set()
        for pod in info.pods.values():
            for v in pod.spec.volumes:
                if v.pvc_claim_name:
                    pvc = self.pvcs_map.get(
                        f"{pod.namespace}/{v.pvc_claim_name}")
                    if pvc is not None and pvc.volume_name:
                        counted_pvs.add(pvc.volume_name)
        foreign = sum(
            1 for va in self._vas_by_node.get(node_name, {}).values()
            if va.pv_name not in counted_pvs)
        if foreign != info.foreign_attach:
            info.foreign_attach = foreign
            self._mark_dirty(node_name)

    # ------------------------------------------------------------------- DRA
    def update_resource_claim(self, claim) -> None:
        with self._lock:
            cur = self.resource_claims.get(claim.key)
            if cur is not None and not claim.allocated_node and cur.allocated_node:
                # assume-time reservations live only here; an informer echo
                # without allocation state must not free an in-use device
                claim.allocated_node = cur.allocated_node
                claim.reserved_for = list(cur.reserved_for)
            self.resource_claims[claim.key] = claim

    def remove_resource_claim(self, claim) -> None:
        with self._lock:
            self.resource_claims.pop(claim.key, None)

    def update_resource_slice(self, sl) -> None:
        with self._lock:
            self.resource_slices[sl.key] = sl

    def remove_resource_slice(self, sl) -> None:
        with self._lock:
            self.resource_slices.pop(sl.key, None)

    def _dra_reserve_locked(self, pod: Pod, node_name: str) -> None:
        """Pin the pod's claims to its node at assume time (the structured-
        parameters allocation the in-tree DRA plugin performs at Reserve)."""
        for cname in pod.spec.resource_claims:
            claim = self.resource_claims.get(f"{pod.namespace}/{cname}")
            if claim is None:
                continue
            if claim.allocated_node and claim.allocated_node != node_name:
                # the claim's device lives elsewhere; never record a
                # reservation the node cannot satisfy
                logger.error("DRA: pod %s assumed on %s but claim %s is "
                             "allocated to %s", pod.uid, node_name,
                             claim.key, claim.allocated_node)
                continue
            if not claim.allocated_node:
                claim.allocated_node = node_name
            if pod.uid not in claim.reserved_for:
                claim.reserved_for.append(pod.uid)

    def dra_release(self, pod: Pod) -> None:
        """Drop the pod's reservations; a claim with no reservations left
        deallocates (devices return to the node's free inventory)."""
        with self._lock:
            self._dra_release_locked(pod)

    def _dra_release_locked(self, pod: Pod) -> None:
        for cname in pod.spec.resource_claims:
            claim = self.resource_claims.get(f"{pod.namespace}/{cname}")
            if claim is None:
                continue
            if pod.uid in claim.reserved_for:
                claim.reserved_for.remove(pod.uid)
            if not claim.reserved_for:
                claim.allocated_node = ""

    def dra_feasible_nodes(self, namespace: str, claim_names) -> Optional[set]:
        """Node names where every named claim can be satisfied, or None when
        the pod has no claims. An unknown claim yields the empty set (the pod
        stays pending until the claim object appears). Demand-aware: a node
        must have as many free devices of a class as the claim set demands
        unallocated (one pod with two gpu claims needs two free devices)."""
        if not claim_names:
            return None
        with self._lock.reader():
            # allocations per (node, class), one scan
            used: Dict[Tuple[str, str], int] = {}
            for other in self.resource_claims.values():
                if other.allocated_node:
                    k = (other.allocated_node, other.device_class)
                    used[k] = used.get(k, 0) + 1
            result: Optional[set] = None
            demand: Dict[str, int] = {}  # unallocated demand per class
            unalloc_classes: List[str] = []
            for cname in claim_names:
                claim = self.resource_claims.get(f"{namespace}/{cname}")
                if claim is None:
                    return set()
                if claim.allocated_node:
                    nodes = {claim.allocated_node}
                    result = nodes if result is None else (result & nodes)
                else:
                    demand[claim.device_class] = demand.get(claim.device_class, 0) + 1
                    unalloc_classes.append(claim.device_class)
            for cls in set(unalloc_classes):
                nodes = {
                    sl.node_name for sl in self.resource_slices.values()
                    if sl.device_class == cls
                    and sl.count - used.get((sl.node_name, cls), 0) >= demand[cls]
                }
                result = nodes if result is None else (result & nodes)
            return result or set()

    def dra_unallocated_classes(self, namespace: str, claim_names):
        """frozenset of device classes with at least one unallocated claim in
        the set (empty when all are pinned); unknown claims count as
        unallocated of class '<unknown>'. Locked accessor for the encoder's
        serialization decision."""
        with self._lock.reader():
            out = set()
            for cname in claim_names:
                claim = self.resource_claims.get(f"{namespace}/{cname}")
                if claim is None:
                    out.add("<unknown>")
                elif not claim.allocated_node:
                    out.add(claim.device_class)
            return frozenset(out)

    def forget_pod(self, pod: Pod) -> None:
        """Undo an assume (bind failed / rejected) — reference ForgetPod (:455-470)."""
        with self._lock:
            key = pod.uid
            node_name = self.assigned_pods.pop(key, None)
            cur = self.pods_map.get(key)
            if node_name is not None and cur is not None:
                info = self.nodes_map.get(node_name)
                if info is not None:
                    info.remove_pod(cur)
                    self._update_pvc_refs(cur, add=False)
                    self._mark_dirty(node_name)
                self._dra_release_locked(cur)
                # keep the pod in pods_map but unassigned
                cur.spec.node_name = ""
            self.assumed_pods.pop(key, None)

    def is_assumed_pod(self, uid: str) -> bool:
        with self._lock.reader():
            return uid in self.assumed_pods

    def are_pod_volumes_all_bound(self, uid: str) -> bool:
        with self._lock.reader():
            return self.assumed_pods.get(uid, False)

    # --------------------------------------------------------- priority classes
    def update_priority_class(self, pc) -> None:
        with self._lock:
            self.pc_map[pc.name] = pc

    def remove_priority_class(self, name: str) -> None:
        with self._lock:
            self.pc_map.pop(name, None)

    def get_priority_class(self, name: str):
        with self._lock.reader():
            return self.pc_map.get(name)

    # ----------------------------------------------------------------- PVC refs
    def _update_pvc_refs(self, pod: Pod, add: bool) -> None:
        for vol in pod.spec.volumes:
            if vol.pvc_claim_name:
                key = f"{pod.namespace}/{vol.pvc_claim_name}"
                n = self.pvc_ref_counts.get(key, 0) + (1 if add else -1)
                if n <= 0:
                    self.pvc_ref_counts.pop(key, None)
                else:
                    self.pvc_ref_counts[key] = n

    def is_pvc_used_by_pods(self, key: str) -> bool:
        with self._lock.reader():
            return key in self.pvc_ref_counts

    # ------------------------------------------------------------- generations
    def _mark_dirty(self, node_name: str) -> None:
        self._generation += 1
        self._dirty_nodes.add(node_name)
        # pod membership on the node shifted: a VolumeAttachment previously
        # counted foreign may now be backed by a cache pod (or vice versa).
        # No-op without VAs; self-terminating (the nested recompute only
        # re-enters when the count CHANGED, and then finds it unchanged).
        if self.volume_attachments_map:
            self._recompute_foreign_attach_locked(node_name)

    def generation(self) -> int:
        with self._lock.reader():
            return self._generation

    def capacity_version(self) -> int:
        with self._lock.reader():
            return self._capacity_version

    def anti_version(self) -> int:
        with self._lock.reader():
            return self._anti_version

    def take_dirty_nodes(self) -> Tuple[Set[str], Set[str]]:
        """Return and clear (all dirty nodes, subset whose node OBJECT changed).

        Nodes only in the first set need just a free-capacity row refresh;
        nodes in the second need a full symbol re-encode.
        """
        with self._lock:
            dirty = self._dirty_nodes
            objects = self._dirty_node_objects
            self._dirty_nodes = set()
            self._dirty_node_objects = set()
            return dirty, objects

    # ---------------------------------------------------------------- snapshot
    def snapshot_nodes(self) -> List[NodeInfo]:
        """Stable-ordered node list for the encoder."""
        with self._lock.reader():
            return [self.nodes_map[name] for name in sorted(self.nodes_map)]

    def dao(self) -> dict:
        """Diagnostic state dump (reference scheduler_cache_dao.go:28-117)."""
        with self._lock.reader():
            return {
                "nodes": {
                    name: {
                        "allocatable": dict(info.allocatable.resources),
                        "requested": dict(info.requested.resources),
                        "podCount": len(info.pods),
                    }
                    for name, info in self.nodes_map.items()
                },
                "podCount": len(self.pods_map),
                "assignedPods": dict(self.assigned_pods),
                "assumedPods": dict(self.assumed_pods),
                "orphanedPods": sorted(p.key() for p in self.orphaned_pods.values()),
                "pvcRefCounts": dict(self.pvc_ref_counts),
            }
