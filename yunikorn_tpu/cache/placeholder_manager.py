"""PlaceholderManager: creates and cleans up gang placeholder pods.

Role-equivalent to pkg/cache/placeholder_manager.go: createAppPlaceholders
creates minMember - existing pause pods per task group (:72-102); cleanUp
deletes all of an app's placeholders, parking failed deletes in an orphan map
retried every 5 seconds (:105-160).
"""
from __future__ import annotations

import threading
import time
from typing import Dict

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common.events import AppEventRecord, get_recorder
from yunikorn_tpu.common.objects import Pod
from yunikorn_tpu.cache.placeholder import gen_placeholder_name, new_placeholder
from yunikorn_tpu.log.logger import log

logger = log("shim.cache.placeholder")

ORPHAN_RETRY_INTERVAL = 5.0


class PlaceholderManager:
    def __init__(self, api_provider):
        self.api_provider = api_provider
        self._orphans: Dict[str, Pod] = {}
        self._lock = locking.Mutex()
        self._running = threading.Event()
        self._thread = None

    # ------------------------------------------------------------- creation
    def create_app_placeholders(self, app) -> None:
        """Create pause pods up to minMember per task group (reference :72-102)."""
        from yunikorn_tpu.cache import application as app_mod

        origin = app.get_task(app.origin_task_id) if app.origin_task_id else None
        origin_pod = origin.pod if origin is not None else None
        client = self.api_provider.get_client()
        for tg in app.metadata.task_groups:
            existing = sum(
                1 for t in app.task_list()
                if t.placeholder and t.task_group_name == tg.name
            )
            for _ in range(tg.min_member - existing):
                name = gen_placeholder_name(app.application_id, tg.name)
                pod = new_placeholder(name, app, tg, origin_pod)
                try:
                    client.create(pod)
                except Exception as e:
                    logger.error("failed to create placeholder %s: %s", name, e)
                    get_recorder().eventf(
                        "Pod", app.application_id, "Warning", "GangScheduling",
                        "placeholder creation failed: %s", e)
                    # Soft fallback: clean what we made and run normally
                    self.clean_up(app)
                    from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod

                    dispatch_mod.dispatch(AppEventRecord(app.application_id, app_mod.RUN_APPLICATION))
                    return
        get_recorder().eventf("Pod", app.application_id, "Normal", "GangScheduling",
                              "app %s placeholders created", app.application_id)

    # -------------------------------------------------------------- cleanup
    def clean_up(self, app) -> None:
        """Delete all placeholders of an app (reference :105-160)."""
        client = self.api_provider.get_client()
        for t in app.task_list():
            if not t.placeholder:
                continue
            if t.pod.is_terminated():
                continue
            try:
                client.delete(t.pod)
            except Exception as e:
                logger.warning("placeholder delete failed (%s), orphaned: %s", t.alias, e)
                with self._lock:
                    self._orphans[t.pod.uid] = t.pod

    def orphan_count(self) -> int:
        with self._lock:
            return len(self._orphans)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if self._running.is_set():
            return
        self._running.set()
        self._thread = threading.Thread(target=self._retry_loop, name="placeholder-orphans",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._running.clear()

    def _retry_loop(self) -> None:
        while self._running.is_set():
            time.sleep(ORPHAN_RETRY_INTERVAL)
            with self._lock:
                orphans = dict(self._orphans)
            if not orphans:
                continue
            client = self.api_provider.get_client()
            for uid, pod in orphans.items():
                try:
                    client.delete(pod)
                    with self._lock:
                        self._orphans.pop(uid, None)
                except Exception:
                    logger.debug("orphan placeholder delete retry failed: %s", pod.key())
