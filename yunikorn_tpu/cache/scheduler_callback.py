"""AsyncRMCallback: the core→shim half of the SI boundary.

Role-equivalent to pkg/cache/scheduler_callback.go:38-47: new allocations →
AssumePod (reference retries 30×, :58-72) → dispatch TaskAllocated; rejections
→ TaskRejected; releases → ForgetPod / ReleaseAppAllocation; application
accept/reject/status updates; node accept; the per-pair Predicates API is kept
for protocol parity (and preemption), evaluated through the same snapshot
encoder the batched path uses.
"""
from __future__ import annotations

import time
from typing import List, Optional

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.cache.context import Context
from yunikorn_tpu.common.events import AppEventRecord, TaskEventRecord, get_recorder
from yunikorn_tpu.common.si import (
    AllocationResponse,
    ApplicationResponse,
    EventRecord,
    NodeResponse,
    PredicatesArgs,
    PreemptionPredicatesArgs,
    PreemptionPredicatesResponse,
    ResourceManagerCallback,
    TerminationType,
    UpdateContainerSchedulingStateRequest,
)
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.log.logger import log

logger = log("rmproxy")

ASSUME_RETRY_STEPS = 5
ASSUME_RETRY_INTERVAL = 0.05


class AsyncRMCallback(ResourceManagerCallback):
    def __init__(self, context: Context):
        self.context = context

    # ------------------------------------------------------------ allocations
    def update_allocation(self, response: AllocationResponse) -> None:
        for alloc in response.new:
            if alloc.foreign:
                continue
            # assume with a short bounded retry (this runs on the core's solve
            # thread — the reference's 30×backoff would stall scheduling when a
            # pod vanished mid-solve). On failure the task fails and the core
            # allocation is released; the pod re-enters via the informer if it
            # still exists.
            ok, reason = False, ""
            for _ in range(ASSUME_RETRY_STEPS):
                ok, reason, retryable = self.context.assume_pod(
                    alloc.allocation_key, alloc.node_id)
                if ok or not retryable:
                    break
                time.sleep(ASSUME_RETRY_INTERVAL)
            if not ok:
                logger.error("failed to assume pod %s on %s (%s); failing task",
                             alloc.allocation_key, alloc.node_id, reason)
                dispatch_mod.dispatch(TaskEventRecord(
                    alloc.application_id, alloc.allocation_key, task_mod.TASK_FAIL,
                    (f"failed to assume pod ({reason})",)))
                continue
            dispatch_mod.dispatch(TaskEventRecord(
                alloc.application_id, alloc.allocation_key, task_mod.TASK_ALLOCATED,
                (alloc.allocation_key, alloc.node_id)))
        for rejected in response.rejected:
            dispatch_mod.dispatch(TaskEventRecord(
                rejected.application_id, rejected.allocation_key, task_mod.TASK_REJECTED,
                (rejected.reason,)))
        for release in response.released:
            self.context.forget_pod(release.allocation_key)
            if release.termination_type != TerminationType.STOPPED_BY_RM:
                # core-initiated (replaced/timeout/preempted): the app deletes
                # the task's pod (reference :139-166 + handleReleaseAppAllocation)
                dispatch_mod.dispatch(AppEventRecord(
                    release.application_id, app_mod.RELEASE_APP_ALLOCATION,
                    (release.allocation_key, release.termination_type.value)))

    # ------------------------------------------------------------ applications
    def update_application(self, response: ApplicationResponse) -> None:
        for acc in response.accepted:
            dispatch_mod.dispatch(AppEventRecord(acc.application_id, app_mod.ACCEPT_APPLICATION))
        for rej in response.rejected:
            dispatch_mod.dispatch(AppEventRecord(
                rej.application_id, app_mod.REJECT_APPLICATION, (rej.reason,)))
        for upd in response.updated:
            app = self.context.get_application(upd.application_id)
            if app is None:
                continue
            if upd.state == "Resuming" and app.state == app_mod.RESERVING:
                dispatch_mod.dispatch(AppEventRecord(
                    upd.application_id, app_mod.RESUMING_APPLICATION))
            elif upd.state == "Failing":
                dispatch_mod.dispatch(AppEventRecord(
                    upd.application_id, app_mod.FAIL_APPLICATION, (upd.message,)))
            elif upd.state == "Completed":
                # the core's Completed notice is one-shot; drive the shim FSM
                # to Running first when needed so the completion always lands
                if app.state in (app_mod.ACCEPTED, app_mod.RESERVING, app_mod.RESUMING):
                    dispatch_mod.dispatch(AppEventRecord(
                        upd.application_id, app_mod.RUN_APPLICATION))
                if app.state in (app_mod.RUNNING, app_mod.ACCEPTED,
                                 app_mod.RESERVING, app_mod.RESUMING):
                    dispatch_mod.dispatch(AppEventRecord(
                        upd.application_id, app_mod.COMPLETE_APPLICATION))

    # ------------------------------------------------------------------ nodes
    def update_node(self, response: NodeResponse) -> None:
        from yunikorn_tpu.common.events import NodeEventRecord

        for acc in response.accepted:
            get_recorder().eventf("Node", acc.node_id, "Normal", "NodeAccepted",
                                  "node %s is accepted by the scheduler", acc.node_id)
            dispatch_mod.dispatch(NodeEventRecord(acc.node_id, "NodeAccepted"))
        for rej in response.rejected:
            get_recorder().eventf("Node", rej.node_id, "Warning", "NodeRejected",
                                  "node %s is rejected: %s", rej.node_id, rej.reason)

    # ------------------------------------------------------------- predicates
    def predicates(self, args: PredicatesArgs) -> Optional[str]:
        """Single-pair feasibility probe, kept for SI parity (reference :196-198).

        The batched solver subsumes this in the hot path; preemption and tests
        use it. Evaluated with the same encoder + device kernels on a 1-pod
        batch.
        """
        return self.context_predicate_check(args.allocation_key, args.node_id)

    def context_predicate_check(self, pod_uid: str, node_name: str) -> Optional[str]:
        import numpy as np

        from yunikorn_tpu.common.si import AllocationAsk
        from yunikorn_tpu.common.resource import get_pod_resource
        from yunikorn_tpu.ops.assign import solve_batch

        pod = self.context.schedulers_cache.get_pod(pod_uid)
        if pod is None:
            return f"pod {pod_uid} not found"
        # one-pod batch, restricted to the single target node via host mask
        core = getattr(self.context.scheduler_api, "encoder", None)
        from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

        encoder = core if isinstance(core, SnapshotEncoder) else SnapshotEncoder(
            self.context.schedulers_cache)
        encoder.sync_nodes(full=True)
        idx = encoder.nodes.index_of(node_name)
        if idx is None:
            return f"node {node_name} not found"
        ask = AllocationAsk(pod_uid, "", get_pod_resource(pod), pod=pod)
        batch = encoder.build_batch([ask])
        mask = np.zeros((batch.g_term_req.shape[0], encoder.nodes.capacity), bool)
        mask[:, idx] = True
        batch.g_host_mask = mask if batch.g_host_mask is None else (batch.g_host_mask & mask)
        result = solve_batch(batch, encoder.nodes)
        assigned = int(np.asarray(result.assigned)[0])
        if assigned == idx:
            return None
        return "pod does not fit node"

    def preemption_predicates(self, args: PreemptionPredicatesArgs) -> PreemptionPredicatesResponse:
        from yunikorn_tpu.ops.preempt import preemption_victim_search

        return preemption_victim_search(self.context, args)

    # ------------------------------------------------------------------ misc
    def send_event(self, events: List[EventRecord]) -> None:
        """Publish core events onto cluster objects (reference PublishEvents,
        context.go:1157-1200: request events attach to the pod, node events
        are filtered to add/decommission reasons :1362-1372)."""
        from yunikorn_tpu.common.si import EventRecordType

        for ev in events:
            if ev.type == EventRecordType.REQUEST:
                pod = self.context.schedulers_cache.get_pod(ev.object_id)
                key = pod.key() if pod is not None else ev.object_id
                get_recorder().eventf("Pod", key, "Normal", ev.reason, ev.message)
            elif ev.type == EventRecordType.NODE:
                if ev.reason not in ("NodeAdded", "NodeRemoved", "Decommission"):
                    continue  # reference filters node events to lifecycle ones
                get_recorder().eventf("Node", ev.object_id, "Normal", ev.reason, ev.message)
            else:
                get_recorder().eventf(ev.type.value, ev.object_id, "Normal",
                                      ev.reason, ev.message)

    def update_container_scheduling_state(
        self, request: UpdateContainerSchedulingStateRequest
    ) -> None:
        self.context.handle_container_state_update(request)

    def get_state_dump(self) -> str:
        import json

        return json.dumps(self.context.state_dump(), default=str)
