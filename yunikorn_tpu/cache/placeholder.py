"""Placeholder pod construction for gang scheduling.

Role-equivalent to pkg/cache/placeholder.go:41-163 (pause-pod spec copying
NodeSelector/Tolerations/Affinity/TopologySpreadConstraints + priority class
from the task group and originator pod) and pkg/cache/gang_utils.go:61-80
(placeholder name generator tg-<app28>-<taskgroup20>-<nonce10>).
"""
from __future__ import annotations

import random
import string
from typing import Optional

from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import (
    Affinity,
    Container,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    ObjectMeta,
    Pod,
    PodSpec,
    PodStatus,
    Toleration,
    TopologySpreadConstraint,
)
from yunikorn_tpu.common.si import TaskGroup

_NONCE_CHARS = string.ascii_lowercase + string.digits


def gen_placeholder_name(app_id: str, task_group: str, rng: Optional[random.Random] = None) -> str:
    """tg-<app(≤28)>-<taskgroup(≤20)>-<nonce(10)> (reference gang_utils.go:61-80)."""
    rng = rng or random.Random()
    nonce = "".join(rng.choice(_NONCE_CHARS) for _ in range(10))
    return f"tg-{app_id[:28]}-{task_group[:20]}-{nonce}"


def _tg_affinity(raw) -> Optional[Affinity]:
    """Decode a task-group affinity dict (annotation JSON shape) into Affinity."""
    if raw is None:
        return None
    if isinstance(raw, Affinity):
        return raw
    aff = Affinity()
    node_aff = (raw.get("nodeAffinity") or {}).get(
        "requiredDuringSchedulingIgnoredDuringExecution") or {}
    for term in node_aff.get("nodeSelectorTerms", []):
        aff.node_required_terms.append(NodeSelectorTerm(
            match_expressions=[
                NodeSelectorRequirement(e["key"], e["operator"], list(e.get("values", [])))
                for e in term.get("matchExpressions", [])
            ]
        ))
    return aff


def _tg_tolerations(raw_list) -> list:
    out = []
    for t in raw_list or []:
        if isinstance(t, Toleration):
            out.append(t)
        else:
            out.append(Toleration(
                key=t.get("key", ""), operator=t.get("operator", "Equal"),
                value=t.get("value", ""), effect=t.get("effect", ""),
            ))
    return out


def new_placeholder(name: str, app, task_group: TaskGroup, origin_pod: Optional[Pod],
                    placeholder_image: str = constants.PLACEHOLDER_CONTAINER_IMAGE) -> Pod:
    """Build the pause pod for one gang member (reference placeholder.go:41-163)."""
    namespace = origin_pod.namespace if origin_pod else constants.DEFAULT_APP_NAMESPACE
    labels = {
        constants.LABEL_APPLICATION_ID: app.application_id,
        constants.LABEL_QUEUE_NAME: app.queue_name,
        "placeholder": constants.TRUE,
    }
    labels.update(task_group.labels)
    annotations = {
        constants.ANNOTATION_PLACEHOLDER_FLAG: constants.TRUE,
        constants.ANNOTATION_TASK_GROUP_NAME: task_group.name,
    }
    annotations.update(task_group.annotations)

    spread = [
        tsc if isinstance(tsc, TopologySpreadConstraint) else TopologySpreadConstraint(
            max_skew=int(tsc.get("maxSkew", 1)),
            topology_key=tsc.get("topologyKey", ""),
            when_unsatisfiable=tsc.get("whenUnsatisfiable", "DoNotSchedule"),
            label_selector=tsc.get("labelSelector"),
        )
        for tsc in task_group.topology_spread_constraints
    ]

    requests = dict(task_group.min_resource)
    spec = PodSpec(
        scheduler_name=constants.SCHEDULER_NAME,
        restart_policy=constants.PLACEHOLDER_POD_RESTART_POLICY,
        containers=[Container(
            name=constants.PLACEHOLDER_CONTAINER_NAME,
            resources_requests=requests,
        )],
        node_selector=dict(task_group.node_selector),
        tolerations=_tg_tolerations(task_group.tolerations),
        affinity=_tg_affinity(task_group.affinity),
        topology_spread_constraints=spread,
    )
    if origin_pod is not None:
        spec.priority = origin_pod.spec.priority
        spec.priority_class_name = origin_pod.spec.priority_class_name

    owner_refs = list(app.metadata.owner_references)
    return Pod(
        metadata=ObjectMeta(
            name=name,
            namespace=namespace,
            labels=labels,
            annotations=annotations,
            owner_references=owner_refs,
        ),
        spec=spec,
        status=PodStatus(phase="Pending"),
    )
