"""Context: the shim's brain — informer event handling, app/task bookkeeping,
assume/forget, config hot-reload, recovery.

Role-equivalent to pkg/cache/context.go (struct :72-84): informer registration
:134-178, node handlers :180-315, pod handlers with the YuniKorn/foreign split
:316-535, configmap hot reload :536-601,648-677, priorityClass :602-647,
volume binding :747-827, AssumePod/ForgetPod :828-899, app/task CRUD :976-1144,
PublishEvents :1157-1200, HandleContainerStateUpdate :1222-1261, recovery
InitializeState :1380-1455.

The reference wraps all of this in one big context lock because its predicates
read cache state concurrently with informer writes. Here the predicate path is
a device-array snapshot (the encoder reads the cache once per solve under the
cache's own lock), so the Context only needs a lock around its app/task maps —
the serialization point the TPU design removes (SURVEY.md L2 note).
"""
from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.cache.application import Application
from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.cache.metadata import (
    get_app_metadata,
    get_task_metadata,
)
from yunikorn_tpu.cache.placeholder_manager import PlaceholderManager
from yunikorn_tpu.cache.task import Task, TaskSchedulingState
from yunikorn_tpu.client.interfaces import APIProvider, InformerType, ResourceEventHandlers
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.events import (
    AppEventRecord,
    NodeEventRecord,
    TaskEventRecord,
    get_recorder,
)
from yunikorn_tpu.common.objects import Node, Pod, PriorityClass
from yunikorn_tpu.common.resource import Resource, get_node_resource, get_pod_resource
from yunikorn_tpu.common.si import (
    Allocation,
    AllocationRelease,
    AllocationRequest,
    ContainerSchedulingState,
    NodeAction,
    NodeInfo,
    NodeRequest,
    SchedulerAPI,
    TerminationType,
)
from yunikorn_tpu.conf.schedulerconf import SchedulerConf, get_holder
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.log.logger import log

logger = log("shim.context")


class VolumeBinder:
    """Provider-agnostic volume binder (reference volumebinding.NewVolumeBinder
    with the 10-minute bind timeout, apifactory.go:92-165; FindPodVolumes/
    AssumePodVolumes/bindPodVolumes semantics in context.go:747-827).

    State is informer-fed — Context routes PVC/PV/StorageClass events here —
    and writes go through the KubeClient volume-update methods, so the same
    binder drives the in-memory FakeCluster and the real HTTP adapter.

    - find_pod_volumes(pod, node): feasibility at assume time — every claim
      is known and either bound (its PV's node affinity matching the node),
      statically matchable to an Available PV, or dynamically provisionable
      through its StorageClass.
    - assume_pod_volumes: reserve the static PV picks in-memory so parallel
      assumes cannot double-commit one PV.
    - bind_pod_volumes: static picks get PV.claimRef + PVC.volumeName written
      through the API; WaitForFirstConsumer claims get the
      volume.kubernetes.io/selected-node annotation and wait for the external
      provisioner; everything then waits (bounded by bind_timeout) until the
      informer stream reports the claim Bound.
    """

    def __init__(self, api_provider: APIProvider, cache: SchedulerCache,
                 bind_timeout: float = 600.0):
        self.api = api_provider
        self.cache = cache                      # PVC/PV/SC single source
        self.bind_timeout = bind_timeout
        self._lock = locking.Mutex()
        self._reserved: Dict[str, str] = {}     # pv name -> claim key

    # ------------------------------------------------------------- internals
    def _claims(self, pod: Pod):
        for v in pod.spec.volumes:
            if v.pvc_claim_name:
                yield f"{pod.namespace}/{v.pvc_claim_name}"

    def _get_pvc(self, key: str):
        ns, name = key.split("/", 1)
        pvc = self.cache.get_pvc_obj(ns, name)
        if pvc is not None:
            return pvc
        # informer may not have synced yet: fall through to the provider
        get = getattr(self.api, "get_pvc", None)
        return get(ns, name) if get is not None else None

    def _match_pv(self, pvc, node, claim_key: str, reserve: bool = False):
        """Smallest Available PV satisfying the claim on this node.

        reserve=True records the pick in _reserved under the same lock as the
        candidate scan — check-then-reserve must be atomic or two bind-pool
        threads (or parallel assumes) can hand one PV to two claims."""
        from yunikorn_tpu.common.volumes import pv_matches_claim

        with self._lock:
            candidates = [pv for pv in self.cache.list_pv_objs()
                          if pv_matches_claim(pv, pvc, node, claim_key,
                                              reserved=self._reserved.get)]
            if not candidates:
                return None
            pv = min(candidates, key=lambda pv: (pv.capacity, pv.metadata.name))
            if reserve:
                self._reserved[pv.metadata.name] = claim_key
            return pv

    # ------------------------------------------------------------ public API
    def all_bound(self, pod: Pod) -> bool:
        for key in self._claims(pod):
            pvc = self._get_pvc(key)
            if pvc is None or not pvc.bound:
                return False
        return True

    def find_pod_volumes(self, pod: Pod, node) -> bool:
        """FindPodVolumes: can every claim be satisfied on this node?"""
        for key in self._claims(pod):
            pvc = self._get_pvc(key)
            if pvc is None:
                return False                    # unknown claim: unschedulable
            if pvc.bound:
                from yunikorn_tpu.common.volumes import node_matches_pv_affinity

                pv = self.cache.get_pv_obj(pvc.volume_name)
                if pv is not None and not node_matches_pv_affinity(pv, node):
                    return False                # volume not reachable here
                continue
            if self._match_pv(pvc, node, key) is not None:
                continue                        # static binding possible
            sc = self.cache.get_storage_class_obj(pvc.storage_class)
            if sc is not None and not sc.provisioner:
                return False                    # class exists, cannot provision
            if sc is not None and not self.cache.csi_capacity_feasible(
                    sc, node, pvc.requested_storage):
                return False                    # capacity-tracked driver: no
                                                # segment covering this node fits
            # class unknown (informer lag / legacy provider): optimistic —
            # dynamic provisioning is attempted and the 10-min bind timeout
            # is the enforcement, mirroring the reference's bind-time failure
            # handling rather than its PreFilter rejection
        return True

    def assume_pod_volumes(self, pod: Pod, node) -> None:
        """Reserve static PV picks so parallel assumes can't share a PV."""
        for key in self._claims(pod):
            pvc = self._get_pvc(key)
            if pvc is None or pvc.bound:
                continue
            self._match_pv(pvc, node, key, reserve=True)

    def release_pod_volumes(self, pod: Pod) -> None:
        """Drop assume-time PV reservations held for this pod's claims
        (forget path, and cleanup after a completed bind)."""
        keys = set(self._claims(pod))
        if not keys:
            return
        with self._lock:
            for pv_name, holder in list(self._reserved.items()):
                if holder in keys:
                    del self._reserved[pv_name]

    def bind_pod_volumes(self, pod: Pod, node_name: str = "") -> None:
        """Bind every unbound claim, then wait until the API reports Bound.

        Writes go through the API on COPIES — the informer echo of a
        successful write is what updates the caches, so a failed PUT leaves
        no phantom "Bound" state behind (real-adapter transient errors)."""
        import dataclasses as _dc

        client = self.api.get_client()
        info = self.cache.get_node(node_name) if node_name else None
        node = info.node if info is not None else None
        waiting = []
        for key in self._claims(pod):
            pvc = self._get_pvc(key)
            if pvc is None:
                raise RuntimeError(f"pvc {key} disappeared before bind")
            if pvc.bound:
                continue
            # prefer the PV reserved for this claim at assume time
            pv = None
            with self._lock:
                for pv_name, holder in self._reserved.items():
                    if holder == key:
                        pv = self.cache.get_pv_obj(pv_name)
                        break
            if pv is None:
                # no assume-time reservation (PV appeared late / optimistic
                # find): reserve here so a concurrent bind can't take it too
                pv = self._match_pv(pvc, node, key, reserve=True)
            update_pvc = getattr(client, "update_pvc", None)
            update_pv = getattr(client, "update_pv", None)
            if pv is not None and update_pv is not None and update_pvc is not None:
                update_pv(_dc.replace(pv, claim_ref=key, phase="Bound"))
                update_pvc(_dc.replace(
                    pvc, volume_name=pv.metadata.name, bound=True,
                    metadata=_dc.replace(
                        pvc.metadata,
                        annotations=dict(pvc.metadata.annotations))))
                waiting.append(key)
                continue
            if update_pvc is not None and node_name:
                # dynamic provisioning: hand the claim to the provisioner
                # with the node decision (WaitForFirstConsumer semantics;
                # harmless for Immediate classes — provisioners key on the
                # annotation's presence)
                anns = dict(pvc.metadata.annotations)
                anns["volume.kubernetes.io/selected-node"] = node_name
                update_pvc(_dc.replace(
                    pvc, metadata=_dc.replace(pvc.metadata, annotations=anns)))
            elif update_pvc is None:
                # legacy provider (no volume update API): best-effort direct
                # bind — still joins the waiting list below so the bind
                # timeout is enforced (an async/failed bind_pvc must not let
                # the pod proceed with unbound volumes)
                bind_pvc = getattr(self.api, "bind_pvc", None)
                if bind_pvc is not None:
                    ns, name = key.split("/", 1)
                    bind_pvc(ns, name)
            waiting.append(key)
        deadline = time.time() + self.bind_timeout
        for key in waiting:
            while time.time() < deadline:
                pvc = self._get_pvc(key)
                if pvc is not None and pvc.bound:
                    break
                time.sleep(0.05)
            else:
                raise TimeoutError(f"volume bind timeout for pvc {key}")
        # every claim bound: assume-time reservations served their purpose
        self.release_pod_volumes(pod)


class Context:
    def __init__(self, api_provider: APIProvider, scheduler_api: SchedulerAPI,
                 conf: Optional[SchedulerConf] = None,
                 cache: Optional[SchedulerCache] = None):
        self.api_provider = api_provider
        self.scheduler_api = scheduler_api
        self.conf = conf or get_holder().get()
        # the cache is shared with the in-process core (its encoder reads it)
        self.schedulers_cache = cache if cache is not None else SchedulerCache()
        self.placeholder_manager = PlaceholderManager(api_provider)
        self.volume_binder = VolumeBinder(
            api_provider, self.schedulers_cache,
            bind_timeout=self.conf.volume_bind_timeout)
        self._apps: Dict[str, Application] = {}
        # CSINode attach limits seen so far: applied to nodes on arrival in
        # EITHER order (the CSINode and Node informers are independent watch
        # streams; a limit landing first must not be dropped)
        self._csinode_limits: Dict[str, int] = {}
        self._namespaces: Dict[str, Dict[str, str]] = {}
        # foreign pods already reported to the core: uid -> (node, resource)
        self._foreign_sent: Dict[str, tuple] = {}
        # uid-keyed fast-path memos: a pod's YuniKorn adoption and its
        # (app, task) identity are immutable per uid, but informers refire
        # update_pod for every status change — at 50k binds that is 3-4 full
        # metadata extractions per pod without these. Evicted on delete.
        self._pod_kind_memo: Dict[str, bool] = {}
        self._task_ref_memo: Dict[str, tuple] = {}
        self._lock = locking.RMutex()
        self._initialized = False
        # bounded bind workers: the reference spawns a goroutine per bind
        # (task.go:348-394, cheap in Go); a Python thread per task would spike
        # to tens of thousands at the 50k bucket. Daemon workers: a bind hung
        # on an unresponsive API server must not block interpreter exit.
        # One worker group per scheduler shard (ShardedCoreScheduler.n,
        # duck-typed — 1 for the plain core) so binds fan out with the
        # shards instead of re-serializing behind one FIFO; ordering is
        # preserved per task_id. service.bindPoolWorkers overrides the
        # per-shard size (0 = auto: total stays 32 up to 4 shards).
        from yunikorn_tpu.utils.workers import ShardedBindPool

        n_shards = max(1, int(getattr(scheduler_api, "n", 1) or 1))
        per_shard = int(getattr(self.conf, "bind_pool_workers", 0) or 0)
        if per_shard <= 0:
            per_shard = max(8, 32 // n_shards)
        self.bind_pool = ShardedBindPool(
            n_shards=n_shards, workers_per_shard=per_shard, name="bind")

    # convenience alias matching the reference naming
    @property
    def scheduler_cache(self) -> SchedulerCache:
        return self.schedulers_cache

    # ------------------------------------------------------------- informers
    def add_scheduling_event_handlers(self) -> None:
        """Register informer handlers (reference context.go:134-178)."""
        self.api_provider.add_event_handler(InformerType.POD, ResourceEventHandlers(
            add_fn=self.add_pod, update_fn=self.update_pod, delete_fn=self.delete_pod))
        self.api_provider.add_event_handler(InformerType.NODE, ResourceEventHandlers(
            add_fn=self.add_node, update_fn=self.update_node, delete_fn=self.delete_node))
        self.api_provider.add_event_handler(InformerType.CONFIGMAP, ResourceEventHandlers(
            filter_fn=self._is_yunikorn_configmap,
            add_fn=self._on_configmap, update_fn=lambda old, new: self._on_configmap(new),
            delete_fn=self._on_configmap))
        self.api_provider.add_event_handler(InformerType.PRIORITY_CLASS, ResourceEventHandlers(
            add_fn=self.add_priority_class,
            update_fn=lambda old, new: self.add_priority_class(new),
            delete_fn=self.delete_priority_class))
        self.api_provider.add_event_handler(InformerType.PVC, ResourceEventHandlers(
            add_fn=self._on_pvc, update_fn=lambda old, new: self._on_pvc(new),
            delete_fn=self._on_pvc_deleted))
        # volume state: PV / StorageClass / CSINode (reference
        # apifactory.go:39-59 informer set; CSINode drives per-node
        # attachable-volume limits like the K8s volume-limits plugin). The
        # cache is the single store — binder and encoder both read it.
        cache = self.schedulers_cache
        self.api_provider.add_event_handler(InformerType.PV, ResourceEventHandlers(
            add_fn=cache.update_pv_obj,
            update_fn=lambda old, new: cache.update_pv_obj(new),
            delete_fn=cache.remove_pv_obj))
        self.api_provider.add_event_handler(InformerType.STORAGE_CLASS, ResourceEventHandlers(
            add_fn=cache.update_storage_class_obj,
            update_fn=lambda old, new: cache.update_storage_class_obj(new),
            delete_fn=cache.remove_storage_class_obj))
        self.api_provider.add_event_handler(InformerType.CSINODE, ResourceEventHandlers(
            add_fn=self._on_csinode,
            update_fn=lambda old, new: self._on_csinode(new),
            delete_fn=self._on_csinode_deleted))
        # CSIDriver flags + CSIStorageCapacity segments (capacity-aware
        # provisioning) + VolumeAttachment foreign occupancy (reference
        # apifactory.go:39-59 informer set)
        self.api_provider.add_event_handler(InformerType.CSI_DRIVER, ResourceEventHandlers(
            add_fn=cache.update_csi_driver_obj,
            update_fn=lambda old, new: cache.update_csi_driver_obj(new),
            delete_fn=cache.remove_csi_driver_obj))
        self.api_provider.add_event_handler(
            InformerType.CSI_STORAGE_CAPACITY, ResourceEventHandlers(
                add_fn=cache.update_csi_capacity_obj,
                update_fn=lambda old, new: cache.update_csi_capacity_obj(new),
                delete_fn=cache.remove_csi_capacity_obj))
        self.api_provider.add_event_handler(
            InformerType.VOLUME_ATTACHMENT, ResourceEventHandlers(
                add_fn=cache.update_volume_attachment_obj,
                update_fn=lambda old, new: cache.update_volume_attachment_obj(new),
                delete_fn=cache.remove_volume_attachment_obj))
        self.api_provider.add_event_handler(InformerType.NAMESPACE, ResourceEventHandlers(
            add_fn=self._on_namespace,
            update_fn=lambda old, new: self._on_namespace(new),
            delete_fn=self._on_namespace_deleted))
        # DRA informers, gated exactly like the reference's DRA manager
        # (context.go:116-130, apifactory.go:39-59)
        from yunikorn_tpu.conf import schedulerconf as conf_mod

        if conf_mod.get_scheduler_conf().enable_dra:
            self.api_provider.add_event_handler(
                InformerType.RESOURCE_CLAIM, ResourceEventHandlers(
                    add_fn=self.schedulers_cache.update_resource_claim,
                    update_fn=lambda old, new: self.schedulers_cache.update_resource_claim(new),
                    delete_fn=self.schedulers_cache.remove_resource_claim))
            self.api_provider.add_event_handler(
                InformerType.RESOURCE_SLICE, ResourceEventHandlers(
                    add_fn=self.schedulers_cache.update_resource_slice,
                    update_fn=lambda old, new: self.schedulers_cache.update_resource_slice(new),
                    delete_fn=self.schedulers_cache.remove_resource_slice))

    # ----------------------------------------------------------------- nodes
    def add_node(self, node: Node) -> None:
        from yunikorn_tpu.common.resource import VOLUME_ATTACH

        with self._lock:
            csi_limit = self._csinode_limits.get(node.name)
        if csi_limit is not None:
            # CSINode arrived first: apply its attach limit on node arrival
            node.status.allocatable[VOLUME_ATTACH] = csi_limit
        adopted = self.schedulers_cache.update_node(node)
        capacity = get_node_resource(node.status.allocatable)
        attributes = {
            constants.NODE_ATTRIBUTE_HOSTNAME: node.name,
            constants.NODE_ATTRIBUTE_RACKNAME: constants.DEFAULT_RACK,
            "instance-type": node.metadata.labels.get(self.conf.instance_type_node_label_key, ""),
        }
        # multi-partition routing: the node-partition label (an extension
        # beyond the reference shim, which is single-partition) becomes the
        # SI attribute the core's partition router reads
        part = node.metadata.labels.get(constants.LABEL_NODE_PARTITION, "")
        if part:
            attributes[constants.SI_NODE_PARTITION] = part
        self.scheduler_api.update_node(NodeRequest(nodes=[NodeInfo(
            node_id=node.name,
            action=NodeAction.CREATE if self._initialized else NodeAction.CREATE_DRAIN,
            attributes=attributes,
            schedulable_resource=capacity,
            node=node,
        )]))
        for pod in adopted:
            self.update_pod(None, pod)

    def update_node(self, old: Optional[Node], node: Node) -> None:
        from yunikorn_tpu.common.resource import VOLUME_ATTACH

        with self._lock:
            csi_limit = self._csinode_limits.get(node.name)
        if csi_limit is not None:
            # routine node updates (kubelet heartbeats) carry no attach limit;
            # without re-applying it every update would silently revert the
            # CSI driver's cap to the default until the next CSINode event
            node.status.allocatable[VOLUME_ATTACH] = csi_limit
        self.schedulers_cache.update_node(node)
        capacity = get_node_resource(node.status.allocatable)
        infos = [NodeInfo(node_id=node.name, action=NodeAction.UPDATE,
                          schedulable_resource=capacity, node=node)]
        # only toggle drain state when schedulability actually changed
        if old is None or old.spec.unschedulable != node.spec.unschedulable:
            infos.append(NodeInfo(
                node_id=node.name,
                action=(NodeAction.DRAIN_NODE if node.spec.unschedulable
                        else NodeAction.DRAIN_TO_SCHEDULABLE)))
        self.scheduler_api.update_node(NodeRequest(nodes=infos))

    def delete_node(self, node: Node) -> None:
        self.schedulers_cache.remove_node(node.name)
        self.scheduler_api.update_node(NodeRequest(nodes=[NodeInfo(
            node_id=node.name, action=NodeAction.DECOMISSION)]))
        get_recorder().eventf("Node", node.name, "Normal", "NodeDeleted",
                              "node %s is deleted from the scheduler", node.name)

    # ------------------------------------------------------------------ pods
    def add_pod(self, pod: Pod) -> None:
        self.update_pod(None, pod)

    def update_pod(self, _old: Optional[Pod], pod: Pod) -> None:
        """Pod add/update with YuniKorn/foreign split (reference :316-351)."""
        # memoize only the YuniKorn classification: app identity is immutable
        # once adopted, but a FOREIGN pod can become YuniKorn-managed by a
        # later label/annotation edit (metadata.py's label-based adoption),
        # so the foreign verdict must be recomputed per delivery
        is_yk = self._pod_kind_memo.get(pod.uid)
        if is_yk is None:
            is_yk = get_task_metadata(
                pod, self.conf.generate_unique_app_ids) is not None
            if is_yk:
                self._pod_kind_memo[pod.uid] = True
        if is_yk:
            self._update_yunikorn_pod(pod)
        else:
            self._update_foreign_pod(pod)

    def _update_yunikorn_pod(self, pod: Pod) -> None:
        # scheduling gates hold pods out of scheduling (reference :372-386)
        if pod.spec.scheduling_gates:
            logger.debug("pod %s is gated, ignoring", pod.key())
            return
        if pod.is_terminated():
            self.schedulers_cache.update_pod(pod)
            self._notify_task_complete(pod, self._task_ref_memo.get(pod.uid))
            return
        self.schedulers_cache.update_pod(pod)
        self._ensure_app_and_task(pod)

    def _update_foreign_pod(self, pod: Pod) -> None:
        """Non-YuniKorn pods become occupied resource (reference :422-486).

        Routine status updates re-fire this handler; only changes in
        (node, resource) are forwarded to the core so occupied accounting
        stays exact.
        """
        key = pod.uid
        if pod.is_assigned() and not pod.is_terminated():
            in_cache = self.schedulers_cache.update_pod(pod)
            if in_cache:
                resource = get_pod_resource(pod)
                sig = (pod.spec.node_name, tuple(sorted(resource.resources.items())))
                if self._foreign_sent.get(key) == sig:
                    return
                self._foreign_sent[key] = sig
                self.scheduler_api.update_allocation(AllocationRequest(allocations=[
                    Allocation(
                        allocation_key=key,
                        application_id="",
                        node_id=pod.spec.node_name,
                        resource=resource,
                        foreign=True,
                        tags={"kubernetes.io/meta/podType": "foreign"},
                    )
                ]))
        elif pod.is_terminated():
            self.schedulers_cache.remove_pod(pod)
            if self._foreign_sent.pop(key, None) is not None:
                self.scheduler_api.update_allocation(AllocationRequest(releases=[
                    AllocationRelease(application_id="", allocation_key=key,
                                      termination_type=TerminationType.STOPPED_BY_RM)
                ]))

    def delete_pod(self, pod: Pod) -> None:
        # the memo, not a fresh extraction, decides the branch AND supplies
        # the task identity: a label edit after adoption must not flip a
        # scheduled pod to the foreign path on delete, and the completion
        # notification must not depend on re-extracting the (possibly
        # stripped) labels — either way the task would never see
        # COMPLETE_TASK and the allocation would leak
        was_yk = self._pod_kind_memo.pop(pod.uid, None)
        ref = self._task_ref_memo.pop(pod.uid, None)
        if was_yk or (was_yk is None and get_task_metadata(
                pod, self.conf.generate_unique_app_ids) is not None):
            self.schedulers_cache.remove_pod(pod)
            self._notify_task_complete(pod, ref)
        else:
            self.schedulers_cache.remove_pod(pod)
            if self._foreign_sent.pop(pod.uid, None) is not None:
                self.scheduler_api.update_allocation(AllocationRequest(releases=[
                    AllocationRelease(application_id="", allocation_key=pod.uid,
                                      termination_type=TerminationType.STOPPED_BY_RM)
                ]))

    def _notify_task_complete(self, pod: Pod, ref: Optional[tuple] = None) -> None:
        if ref is not None:
            app_id, task_id = ref
        else:
            meta = get_task_metadata(pod, self.conf.generate_unique_app_ids)
            if meta is None:
                return
            app_id, task_id = meta.application_id, meta.task_id
        app = self.get_application(app_id)
        if app is None:
            return
        task = app.get_task(task_id)
        if task is not None and not task.is_terminated():
            dispatch_mod.dispatch(TaskEventRecord(
                app_id, task_id, task_mod.COMPLETE_TASK))

    # ------------------------------------------------------------- app/task
    def _ensure_app_and_task(self, pod: Pod) -> None:
        """reference ensureAppAndTaskCreated (:976-1144)."""
        ref = self._task_ref_memo.get(pod.uid)
        if ref is not None:
            # fast path: this uid's task already exists (informers refire on
            # every status update; app/task identity is immutable per uid)
            app = self._apps.get(ref[0])
            if app is not None and app.get_task(ref[1]) is not None:
                return
        app_meta = get_app_metadata(pod, self.conf.generate_unique_app_ids)
        if app_meta is None:
            return
        ns_anns = self.namespace_annotations(pod.namespace)
        if ns_anns:
            for key in (constants.NAMESPACE_QUOTA, constants.NAMESPACE_GUARANTEED,
                        constants.NAMESPACE_MAX_APPS):
                if key in ns_anns:
                    app_meta.tags[key] = ns_anns[key]
            parent = ns_anns.get(constants.ANNOTATION_PARENT_QUEUE)
            if parent and constants.APP_TAG_NAMESPACE_PARENT_QUEUE not in app_meta.tags:
                app_meta.tags[constants.APP_TAG_NAMESPACE_PARENT_QUEUE] = parent
        with self._lock:
            app = self._apps.get(app_meta.application_id)
            if app is None:
                app = Application(app_meta, self)
                self._apps[app_meta.application_id] = app
                logger.info("app %s added to context (queue=%s)",
                            app.application_id, app.queue_name)
        task_meta = get_task_metadata(pod, self.conf.generate_unique_app_ids)
        task = app.get_task(task_meta.task_id)
        if task is None:
            # first non-placeholder task is the originator; has_tasks avoids
            # copying the (possibly 50k-entry) task dict per new pod
            originator = not app.has_tasks() and not task_meta.placeholder
            task = Task(app, pod, self, placeholder=task_meta.placeholder,
                        task_group_name=task_meta.task_group_name, originator=originator)
            app.add_task(task)
            # recovery fast-path: already-bound pods skip scheduling
            # (reference context.go:1071-1114)
            if pod.is_assigned() and not pod.is_terminated():
                task.mark_previously_allocated(pod.spec.node_name)
        self._task_ref_memo[pod.uid] = (app_meta.application_id,
                                        task_meta.task_id)

    def get_application(self, app_id: str) -> Optional[Application]:
        with self._lock:
            return self._apps.get(app_id)

    def applications(self) -> List[Application]:
        with self._lock:
            return list(self._apps.values())

    def remove_application(self, app_id: str) -> None:
        with self._lock:
            app = self._apps.pop(app_id, None)
        if app is not None:
            app.remove_from_core()

    # ------------------------------------------------------ assume / forget
    def assume_pod(self, pod_uid: str, node_name: str):
        """Optimistically place the pod in the cache (reference :828-888):
        FindPodVolumes feasibility, AssumePodVolumes reservation, then the
        cache assume — a volume-infeasible node fails the assume so the core
        re-schedules the task elsewhere.

        Returns (ok, reason, retryable): reason/retryable drive the
        callback's bounded retry — a pod missing from the cache is informer
        lag worth a short retry; volume infeasibility is not (volume state
        will not change within the retry window) and must be reported as
        what it is."""
        pod = self.schedulers_cache.get_pod(pod_uid)
        if pod is None:
            logger.warning("assume: pod %s not in cache", pod_uid)
            return False, "pod missing from cache", True
        info = self.schedulers_cache.get_node(node_name)
        node = info.node if info is not None else None
        for key in self.volume_binder._claims(pod):
            if self.volume_binder._get_pvc(key) is None:
                # unknown claim is informer lag, not infeasibility — the
                # retry window exists exactly for this case
                logger.warning("assume: pod %s claim %s not yet in cache",
                               pod_uid, key)
                return False, f"pvc {key} not yet in cache", True
        if not self.volume_binder.find_pod_volumes(pod, node):
            logger.warning("assume: pod %s volumes unsatisfiable on node %s",
                           pod_uid, node_name)
            return False, f"volumes unsatisfiable on node {node_name}", False
        self.volume_binder.assume_pod_volumes(pod, node)
        all_bound = self.volume_binder.all_bound(pod)
        assumed = pod.deepcopy()
        assumed.spec.node_name = node_name
        self.schedulers_cache.assume_pod(assumed, all_bound)
        return True, "", False

    def forget_pod(self, pod_uid: str) -> None:
        pod = self.schedulers_cache.get_pod(pod_uid)
        if pod is not None:
            self.volume_binder.release_pod_volumes(pod)
            self.schedulers_cache.forget_pod(pod)

    def bind_pod_volumes(self, pod: Pod, node_name: str = "") -> None:
        if not self.schedulers_cache.are_pod_volumes_all_bound(pod.uid):
            self.volume_binder.bind_pod_volumes(pod, node_name)

    def _on_namespace(self, ns) -> None:
        with self._lock:
            self._namespaces[ns.metadata.name] = dict(ns.metadata.annotations)

    def _on_namespace_deleted(self, ns) -> None:
        with self._lock:
            self._namespaces.pop(ns.metadata.name, None)

    def namespace_annotations(self, name: str) -> Dict[str, str]:
        with self._lock:
            anns = self._namespaces.get(name)
        if anns is not None:
            return anns
        get = getattr(self.api_provider, "get_namespace", None)
        if get is not None:
            ns = get(name)
            if ns is not None:
                return dict(ns.metadata.annotations)
        return {}

    def _on_pvc(self, pvc) -> None:
        self.schedulers_cache.update_pvc_obj(pvc)

    def _on_pvc_deleted(self, pvc) -> None:
        pvc.deleted = True
        self.schedulers_cache.remove_pvc_obj(pvc)

    def _on_csinode(self, csinode) -> None:
        """CSINode attach limits → node attachable-volumes capacity: patch
        the node's allocatable and replay it through the normal node-update
        path so the cache, encoder and core all see the new limit. The limit
        is remembered so a Node arriving AFTER its CSINode still gets it
        (applied in add_node)."""
        limit = csinode.total_limit()
        if limit is None:
            # CSINode still exists but reports no driver limits (driver
            # uninstalled): forget the cap, or update_node's re-apply would
            # pin the stale limit forever
            self._on_csinode_deleted(csinode)
            return
        with self._lock:
            self._csinode_limits[csinode.name] = limit
        info = self.schedulers_cache.get_node(csinode.name)
        if info is None:
            return                      # applied when the node arrives
        from yunikorn_tpu.common.resource import VOLUME_ATTACH

        node = info.node
        if node.status.allocatable.get(VOLUME_ATTACH) == limit:
            return
        node.status.allocatable[VOLUME_ATTACH] = limit
        self.update_node(node, node)

    def _on_csinode_deleted(self, csinode) -> None:
        from yunikorn_tpu.common.resource import VOLUME_ATTACH

        with self._lock:
            self._csinode_limits.pop(csinode.name, None)
        info = self.schedulers_cache.get_node(csinode.name)
        if info is None:
            return
        node = info.node
        if VOLUME_ATTACH in node.status.allocatable:
            node.status.allocatable.pop(VOLUME_ATTACH, None)
            self.update_node(node, node)

    def get_pvc(self, namespace: str, name: str):
        pvc = self.schedulers_cache.get_pvc_obj(namespace, name)
        if pvc is not None:
            return pvc
        # fall through to the cluster store (informer may not have synced yet)
        get = getattr(self.api_provider, "get_pvc", None)
        return get(namespace, name) if get is not None else None

    # ------------------------------------------------------ priority classes
    def add_priority_class(self, pc: PriorityClass) -> None:
        self.schedulers_cache.update_priority_class(pc)

    def delete_priority_class(self, pc: PriorityClass) -> None:
        self.schedulers_cache.remove_priority_class(pc.name)

    def is_preempt_self_allowed(self, pc_name: str) -> bool:
        pc = self.schedulers_cache.get_priority_class(pc_name)
        if pc is None:
            return True
        val = pc.metadata.annotations.get(constants.ANNOTATION_ALLOW_PREEMPTION)
        return val != constants.FALSE

    # ---------------------------------------------------------- config maps
    def _is_yunikorn_configmap(self, cm) -> bool:
        return (cm.metadata.namespace == self.conf.namespace
                and cm.metadata.name in (constants.CONFIGMAP_NAME, constants.DEFAULT_CONFIGMAP_NAME))

    def _on_configmap(self, cm) -> None:
        """Config hot reload (reference triggerReloadConfig :648-677)."""
        if not self.conf.enable_config_hot_refresh:
            logger.info("config hot refresh disabled, ignoring configmap change")
            return
        defaults = self.api_provider.get_client().get_configmap(
            self.conf.namespace, constants.DEFAULT_CONFIGMAP_NAME)
        overrides = self.api_provider.get_client().get_configmap(
            self.conf.namespace, constants.CONFIGMAP_NAME)
        holder = get_holder()
        holder.update_config_maps(
            [defaults.data if defaults else None, overrides.data if overrides else None],
            binary_maps=[defaults.binary_data if defaults else {},
                         overrides.binary_data if overrides else {}],
        )
        self.conf = holder.get()
        self.scheduler_api.update_configuration(holder.queues_config(), {})

    # ---------------------------------------------------------- autoscaler
    def handle_container_state_update(self, request) -> None:
        """Core 'skipped/failed' container states → pod conditions
        (reference HandleContainerStateUpdate :1222-1261)."""
        app = self.get_application(request.application_id)
        if app is None:
            return
        task = app.get_task(request.allocation_key)
        if task is None:
            return
        if request.state == ContainerSchedulingState.SKIPPED:
            task.set_task_scheduling_state(TaskSchedulingState.SKIPPED, request.reason)
        elif request.state == ContainerSchedulingState.FAILED:
            task.set_task_scheduling_state(TaskSchedulingState.FAILED, request.reason)

    # -------------------------------------------------------------- recovery
    def initialize_state(self) -> None:
        """Cold-start recovery (reference InitializeState :1380-1455):
        priority classes → nodes registered draining → pods replayed in
        creation order (assigned ones become existing Allocations in the core)
        → nodes enabled → handlers attached."""
        logger.info("initializing state (recovery)")
        # 1. priority classes
        for pc in self.api_provider.list_priority_classes():
            self.add_priority_class(pc)
        # 2. nodes, registered draining
        nodes = self.api_provider.list_nodes()
        infos = []
        for node in nodes:
            self.schedulers_cache.update_node(node)
            infos.append(NodeInfo(
                node_id=node.name, action=NodeAction.CREATE_DRAIN,
                attributes={constants.NODE_ATTRIBUTE_HOSTNAME: node.name},
                schedulable_resource=get_node_resource(node.status.allocatable),
                node=node,
            ))
        if infos:
            self.scheduler_api.update_node(NodeRequest(nodes=infos))
        # 3. pods in creation order; existing assignments become allocations
        pods = sorted(self.api_provider.list_pods(), key=lambda p: p.metadata.creation_timestamp)
        existing: List[Allocation] = []
        for pod in pods:
            self.update_pod(None, pod)
            alloc = self._existing_allocation(pod)
            if alloc is not None:
                existing.append(alloc)
        if existing:
            self.scheduler_api.update_allocation(AllocationRequest(allocations=existing))
        # 4. enable nodes
        if infos:
            self.scheduler_api.update_node(NodeRequest(nodes=[
                NodeInfo(node_id=i.node_id, action=NodeAction.DRAIN_TO_SCHEDULABLE)
                for i in infos
            ]))
        # 5. attach live handlers
        self.add_scheduling_event_handlers()
        self._initialized = True
        logger.info("state initialization done: %d nodes, %d pods", len(nodes), len(pods))

    def _existing_allocation(self, pod: Pod) -> Optional[Allocation]:
        """reference getExistingAllocation (:1758-1787)."""
        meta = get_task_metadata(pod, self.conf.generate_unique_app_ids)
        if meta is None or not pod.is_assigned() or pod.is_terminated():
            return None
        return Allocation(
            allocation_key=pod.uid,
            application_id=meta.application_id,
            node_id=pod.spec.node_name,
            resource=get_pod_resource(pod),
            placeholder=meta.placeholder,
            task_group_name=meta.task_group_name,
        )

    # -------------------------------------------------- dispatcher handlers
    def application_event_handler(self) -> Callable:
        def handle(event):
            if isinstance(event, AppEventRecord):
                app = self.get_application(event.application_id)
                if app is None:
                    logger.warning("app event %s for unknown app %s",
                                   event.event, event.application_id)
                    return
                app.handle_event(event.event, *event.args)

        return handle

    def task_event_handler(self) -> Callable:
        def handle(event):
            if isinstance(event, TaskEventRecord):
                app = self.get_application(event.application_id)
                if app is None:
                    return
                if event.event == app_mod.UPDATE_RESERVATION:
                    app.handle_event(app_mod.UPDATE_RESERVATION)
                    return
                task = app.get_task(event.task_id)
                if task is None:
                    return
                task.handle_event(event.event, *event.args)

        return handle

    # ------------------------------------------------------------ inspection
    def state_dump(self) -> dict:
        with self._lock:
            return {
                "cache": self.schedulers_cache.dao(),
                "applications": {a.application_id: a.dao() for a in self._apps.values()},
            }
