"""Application: the shim-side app lifecycle + task scheduling pump.

Role-equivalent to pkg/cache/application.go (struct :43-64, Schedule() state
pump :353-395, task filter :397-424, submit :425-456, gang reservation
:457-584, failure handling :586-661) + application_state.go (states :329-360,
transition table :364-470).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from yunikorn_tpu.locking import locking
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.events import AppEventRecord, get_recorder
from yunikorn_tpu.common.si import (
    AddApplicationRequest,
    ApplicationRequest,
    RemoveApplicationRequest,
)
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.cache.metadata import ApplicationMetadata, task_group_resource
from yunikorn_tpu.cache.task import Task
from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
from yunikorn_tpu.log.logger import log
from yunikorn_tpu.utils.fsm import FSM, FSMError, Transition

logger = log("shim.cache.application")

# states (reference application_state.go:329-360)
NEW = "New"
SUBMITTED = "Submitted"
ACCEPTED = "Accepted"
RESERVING = "Reserving"
RUNNING = "Running"
REJECTED = "Rejected"
COMPLETED = "Completed"
KILLING = "Killing"
KILLED = "Killed"
FAILING = "Failing"
FAILED = "Failed"
TERMINAL = [REJECTED, COMPLETED, KILLED, FAILED]
RESUMING = "Resuming"

# events
SUBMIT_APPLICATION = "SubmitApplication"
ACCEPT_APPLICATION = "AcceptApplication"
TRY_RESERVE = "TryReserve"
UPDATE_RESERVATION = "UpdateReservation"
RESUMING_APPLICATION = "ResumingApplication"
APP_TASK_COMPLETED = "AppTaskCompleted"
RUN_APPLICATION = "RunApplication"
RELEASE_APP_ALLOCATION = "ReleaseAppAllocation"
COMPLETE_APPLICATION = "CompleteApplication"
REJECT_APPLICATION = "RejectApplication"
FAIL_APPLICATION = "FailApplication"
KILL_APPLICATION = "KillApplication"
KILLED_APPLICATION = "KilledApplication"

_TRANSITIONS = [
    Transition(SUBMIT_APPLICATION, [NEW], SUBMITTED),
    Transition(ACCEPT_APPLICATION, [SUBMITTED], ACCEPTED),
    Transition(TRY_RESERVE, [ACCEPTED], RESERVING),
    Transition(UPDATE_RESERVATION, [RESERVING], RESERVING),
    Transition(RESUMING_APPLICATION, [RESERVING], RESUMING),
    Transition(APP_TASK_COMPLETED, [RESUMING], RESUMING),
    Transition(RUN_APPLICATION, [ACCEPTED, RESERVING, RESUMING, RUNNING], RUNNING),
    Transition(RELEASE_APP_ALLOCATION, [RUNNING, ACCEPTED, RESERVING], RUNNING),
    Transition(RELEASE_APP_ALLOCATION, [FAILING], FAILING),
    Transition(RELEASE_APP_ALLOCATION, [RESUMING], RESUMING),
    Transition(COMPLETE_APPLICATION, [RUNNING], COMPLETED),
    Transition(REJECT_APPLICATION, [SUBMITTED], REJECTED),
    Transition(FAIL_APPLICATION, [SUBMITTED, ACCEPTED, RUNNING, RESERVING], FAILING),
    Transition(FAIL_APPLICATION, [FAILING, REJECTED], FAILED),
    Transition(KILL_APPLICATION, [ACCEPTED, RUNNING, RESERVING], KILLING),
    Transition(KILLED_APPLICATION, [KILLING], KILLED),
]


class Application:
    def __init__(self, metadata: ApplicationMetadata, context):
        self.application_id = metadata.application_id
        self.queue_name = metadata.queue_name
        self.metadata = metadata
        self.context = context
        self.tasks: Dict[str, Task] = {}
        # lazily-evicted indexes: tasks still in NEW / not yet terminated.
        # NEW and terminal are one-way states, so eviction on read is exact —
        # the pump's per-tick scans stay O(pending), not O(all tasks)
        # (profiled: the full-scan pending_tasks dominated the pump at 10k
        # tasks per app).
        self._new_tasks: Dict[str, Task] = {}
        self._live_tasks: Dict[str, Task] = {}
        self.submit_time = time.time()
        self.placeholder_asks_sent = False
        self.origin_task_id: Optional[str] = None
        self._lock = locking.RMutex()
        self.fsm = FSM(NEW, _TRANSITIONS, {
            "enter_state": self._log_transition,
            "after_" + SUBMIT_APPLICATION: lambda e: self._handle_submit(),
            "enter_" + RESERVING: lambda e: self._on_reserving(),
            "enter_" + RESUMING: lambda e: self._on_resuming(),
            "after_" + UPDATE_RESERVATION: lambda e: self._on_reservation_state_change(),
            "after_" + REJECT_APPLICATION: lambda e: self._on_rejected(*e.args),
            "enter_" + FAILING: lambda e: self._on_failing(*e.args),
            "after_" + APP_TASK_COMPLETED: lambda e: self._on_resuming_task_completed(),
            "after_" + RELEASE_APP_ALLOCATION: lambda e: self._handle_release_allocation(*e.args),
        })

    # ------------------------------------------------------------------ state
    @property
    def state(self) -> str:
        return self.fsm.current

    def _log_transition(self, e) -> None:
        logger.info("app state transition app=%s %s -> %s (%s)",
                    self.application_id, e.src, e.dst, e.event)

    # ------------------------------------------------------------------ tasks
    def add_task(self, task: Task) -> Task:
        with self._lock:
            existing = self.tasks.get(task.task_id)
            if existing is not None:
                return existing
            self.tasks[task.task_id] = task
            if task.state == task_mod.NEW:
                self._new_tasks[task.task_id] = task
            if not task.is_terminated():
                self._live_tasks[task.task_id] = task
            if task.originator and self.origin_task_id is None:
                self.origin_task_id = task.task_id
            return task

    def get_task(self, task_id: str) -> Optional[Task]:
        with self._lock:
            return self.tasks.get(task_id)

    def remove_task(self, task_id: str) -> None:
        with self._lock:
            self.tasks.pop(task_id, None)
            self._new_tasks.pop(task_id, None)
            self._live_tasks.pop(task_id, None)

    def task_list(self) -> List[Task]:
        with self._lock:
            return list(self.tasks.values())

    def has_tasks(self) -> bool:
        with self._lock:
            return bool(self.tasks)

    def pending_tasks(self) -> List[Task]:
        with self._lock:
            stale = [tid for tid, t in self._new_tasks.items()
                     if t.state != task_mod.NEW]
            for tid in stale:
                del self._new_tasks[tid]
            return list(self._new_tasks.values())

    def are_all_tasks_terminated(self) -> bool:
        with self._lock:
            stale = [tid for tid, t in self._live_tasks.items()
                     if t.is_terminated()]
            for tid in stale:
                del self._live_tasks[tid]
            return not self._live_tasks

    # ----------------------------------------------------------------- pump
    def schedule(self) -> None:
        """The per-tick state pump (reference application.go:353-395)."""
        state = self.state
        try:
            if state == NEW:
                self.fsm.event(SUBMIT_APPLICATION)
            elif state == ACCEPTED:
                self._post_accepted()
            elif state in (RUNNING, RESERVING, RESUMING):
                self._schedule_tasks()
        except FSMError as e:
            logger.warning("app %s: schedule skipped: %s", self.application_id, e)

    def _post_accepted(self) -> None:
        """Run directly, or reserve first when gang placeholders are needed
        (reference application.go:482-505)."""
        if (self.metadata.task_groups
                and not self.placeholder_asks_sent
                and not self.context.conf.disable_gang_scheduling):
            self.fsm.event(TRY_RESERVE)
        else:
            self.fsm.event(RUN_APPLICATION)
            self._schedule_tasks()

    def _schedule_tasks(self) -> None:
        """Drive New tasks to Pending, filtered by app state
        (reference application.go:397-424): placeholders-only while Reserving,
        non-placeholders while Running/Resuming."""
        state = self.state
        for task in self.pending_tasks():
            if state == RESERVING and not task.placeholder:
                continue
            if state in (RUNNING, RESUMING) and task.placeholder:
                # placeholders are not scheduled outside Reserving
                continue
            task.handle_event(task_mod.INIT_TASK)

    # ---------------------------------------------------------------- submit
    def _handle_submit(self) -> None:
        """Submit to the core (reference application.go:425-456)."""
        placeholder_ask = None
        if self.metadata.task_groups:
            total = None
            for tg in self.metadata.task_groups:
                r = task_group_resource(tg)
                for _ in range(tg.min_member):
                    total = r if total is None else total.add(r)
            placeholder_ask = total
        request = ApplicationRequest(new=[AddApplicationRequest(
            application_id=self.application_id,
            queue_name=self.queue_name,
            user=self.metadata.user,
            tags=dict(self.metadata.tags),
            placeholder_ask=placeholder_ask,
            task_groups=list(self.metadata.task_groups),
            gang_scheduling_style=self.metadata.gang_scheduling_style,
            execution_timeout_seconds=self.metadata.placeholder_timeout,
            partition=self.metadata.partition,
        )])
        self.context.scheduler_api.update_application(request)

    # ------------------------------------------------------------------ gang
    def _on_reserving(self) -> None:
        """Create placeholder pods (reference application.go:516-545)."""
        if not self.placeholder_asks_sent:
            self.placeholder_asks_sent = True
            threading.Thread(
                target=self.context.placeholder_manager.create_app_placeholders,
                args=(self,),
                name=f"placeholders-{self.application_id}",
                daemon=True,
            ).start()

    def _on_reservation_state_change(self) -> None:
        """Count Bound placeholders per task group vs minMember
        (reference application.go:547-584)."""
        counts: Dict[str, int] = {}
        for t in self.task_list():
            if t.placeholder and t.state == task_mod.BOUND:
                counts[t.task_group_name] = counts.get(t.task_group_name, 0) + 1
        for tg in self.metadata.task_groups:
            if counts.get(tg.name, 0) < tg.min_member:
                return
        dispatch_mod.dispatch(AppEventRecord(self.application_id, RUN_APPLICATION))

    def _on_resuming(self) -> None:
        """Soft gang fallback: placeholders timed out; clean them up and run
        normal tasks once placeholder tasks finish (reference onResuming)."""
        self.context.placeholder_manager.clean_up(self)
        self._check_resuming_done()

    def _on_resuming_task_completed(self) -> None:
        self._check_resuming_done()

    def _check_resuming_done(self) -> None:
        if all(t.is_terminated() for t in self.task_list() if t.placeholder):
            dispatch_mod.dispatch(AppEventRecord(self.application_id, RUN_APPLICATION))

    def _handle_release_allocation(self, task_id: str = "", termination_type: str = "") -> None:
        """Core-initiated release: delete the task's pod (reference
        handleReleaseAppAllocationEvent, application.go:643-661). The pod
        deletion flows back through the informer and completes the task."""
        task = self.get_task(task_id)
        if task is None:
            logger.warning("release for unknown task %s of app %s", task_id, self.application_id)
            return
        task.terminated_reason = termination_type
        if task.placeholder:
            get_recorder().eventf("Pod", task.alias, "Normal", "GangScheduling",
                                  "placeholder %s released: %s", task.alias, termination_type)
        try:
            self.context.api_provider.get_client().delete(task.pod)
        except Exception as e:
            logger.error("failed to delete released pod %s: %s", task.alias, e)

    # --------------------------------------------------------------- failure
    def _on_rejected(self, reason: str = "") -> None:
        logger.warning("app %s rejected: %s", self.application_id, reason)
        get_recorder().eventf("Pod", self.application_id, "Warning", "ApplicationRejected",
                              "application %s is rejected: %s", self.application_id, reason)
        # rejected apps fail their non-terminated tasks then move to Failed
        for t in self.task_list():
            if not t.is_terminated():
                t.handle_event(task_mod.TASK_FAIL, constants.APP_FAIL_REJECTED)
        dispatch_mod.dispatch(AppEventRecord(self.application_id, FAIL_APPLICATION,
                                             (constants.APP_FAIL_REJECTED,)))

    def _on_failing(self, reason: str = "") -> None:
        """Hard gang failure / core Failing: fail tasks, clean placeholders,
        then Failed (reference application.go:586-661)."""
        logger.warning("app %s failing: %s", self.application_id, reason)
        get_recorder().eventf("Pod", self.application_id, "Warning", "ApplicationFailed",
                              "application %s failed: %s", self.application_id, reason)
        self.context.placeholder_manager.clean_up(self)
        for t in self.task_list():
            if not t.is_terminated() and t.fsm.can(task_mod.TASK_FAIL):
                t.handle_event(task_mod.TASK_FAIL, reason or "application failed")
        dispatch_mod.dispatch(AppEventRecord(self.application_id, FAIL_APPLICATION, (reason,)))

    # ------------------------------------------------------------- lifecycle
    def handle_event(self, event: str, *args) -> None:
        try:
            self.fsm.event(event, *args)
        except FSMError as e:
            logger.warning("app %s: event %s ignored: %s", self.application_id, event, e)

    def remove_from_core(self) -> None:
        self.context.scheduler_api.update_application(ApplicationRequest(remove=[
            RemoveApplicationRequest(application_id=self.application_id)
        ]))

    def dao(self) -> dict:
        return {
            "applicationID": self.application_id,
            "queue": self.queue_name,
            "state": self.state,
            "taskCount": len(self.tasks),
            "tasks": {
                t.task_id: {"alias": t.alias, "state": t.state,
                            "nodeName": t.node_name, "placeholder": t.placeholder}
                for t in self.task_list()
            },
        }
