"""Fault containment around device dispatches + the health subsystem.

The JAX port added a fault domain the reference shim never had: the device
runtime. A wedged or persistently failing XLA dispatch must degrade the
solver — device backend → CPU-backend re-jitted solve → exact host path —
never stop placement (POP, arXiv:2110.11927: granular allocation solvers
stay serviceable under degradation; Priority-Matters, arXiv:2511.08373:
the production packing solver may get slower or coarser, never stop
answering).

    supervisor  — SupervisedExecutor: per-dispatch deadlines (watchdog
                  worker), error classification, bounded jittered retry,
                  per-path circuit breakers with half-open probe recovery
    faults      — injectable fault plane the chaos suite drives (incl. the
                  crash() loop-killer the failover suite uses)
    health      — component health state machine behind /ws/v1/health
    host_solve  — the exact host-path assignment tier (last resort)
    failover    — shard failure domains: detect a dead/wedged control-plane
                  shard, quarantine + re-home its domains, rebuild + rejoin
"""
from yunikorn_tpu.robustness.failover import FailoverOptions, ShardSupervisor
from yunikorn_tpu.robustness.faults import (
    FaultPlane,
    InjectedCrash,
    InjectedFault,
)
from yunikorn_tpu.robustness.health import HealthMonitor
from yunikorn_tpu.robustness.supervisor import (
    AllTiersFailed,
    DeadlineExceeded,
    SupervisedExecutor,
    SupervisorOptions,
    classify_error,
)

__all__ = [
    "AllTiersFailed",
    "DeadlineExceeded",
    "FailoverOptions",
    "FaultPlane",
    "HealthMonitor",
    "InjectedCrash",
    "InjectedFault",
    "ShardSupervisor",
    "SupervisedExecutor",
    "SupervisorOptions",
    "classify_error",
]
