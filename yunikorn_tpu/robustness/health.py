"""Health state machine behind /ws/v1/health.

The reference core's healthChecker aggregates component checks into one
HealthCheckInfo DAO; the pre-round-9 port hardcoded `{"Healthy": True}`.
This monitor aggregates real sources — supervisor circuit states, the
scheduling loop's last-successful-cycle age and last failure, informer
staleness, dispatcher backlog — into a liveness/readiness report with
per-component detail.

Semantics:
  live    — the scheduler answers: the run loop (when started) is alive and
            some tier of every supervised path still dispatches. A path
            degraded to the CPU or host tier is LIVE (slower, still
            placing) — degradation is readable in the component detail,
            not a liveness failure.
  ready   — live AND every component healthy (no stale informers, no
            failing cycle streak, dispatcher under its backlog limit).

Each source is a callable returning {"healthy": bool, ...detail}; optional
"live": False marks a liveness failure. Sources must be cheap — the report
is built per probe, and kubelet probes are frequent.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional


class HealthMonitor:
    def __init__(self):
        self._mu = threading.Lock()
        self._sources: Dict[str, Callable[[], dict]] = {}

    def register(self, name: str, fn: Callable[[], dict]) -> None:
        with self._mu:
            self._sources[name] = fn

    def unregister(self, name: str) -> None:
        with self._mu:
            self._sources.pop(name, None)

    def report(self) -> dict:
        with self._mu:
            sources = dict(self._sources)
        components: Dict[str, dict] = {}
        live = True
        ready = True
        for name, fn in sources.items():
            try:
                comp = dict(fn())
            except Exception as e:  # a broken probe is itself a finding
                comp = {"healthy": False,
                        "error": f"{type(e).__name__}: {e}"[:200]}
            healthy = bool(comp.get("healthy", True))
            comp["healthy"] = healthy
            ready = ready and healthy
            live = live and bool(comp.pop("live", True))
            components[name] = comp
        # kept key: the reference REST contract (and every existing probe/
        # test) reads "Healthy"; it reports LIVENESS — a degraded-but-
        # serving scheduler must not be restarted by its liveness probe
        return {
            "Healthy": live,
            "live": live,
            "ready": live and ready,
            "components": components,
            "at": round(time.time(), 3),
        }


# ---------------------------------------------------------------------------
# Canonical sources
# ---------------------------------------------------------------------------

def solver_source(supervisor) -> Callable[[], dict]:
    """Supervised-path health from circuit states. Degraded paths stay
    healthy=True (they are serving) with the degradation spelled out; a
    path whose ENTIRE ladder is open is a liveness failure ONLY when it has
    no fallback outside the supervisor (tier != FALLBACK_TIER) — an open
    mesh/upload/preempt circuit means the cycle takes its documented
    fallback (single-device solve / per-cycle transfer / host planner),
    and restarting a serving scheduler for that would be self-inflicted
    downtime."""
    from yunikorn_tpu.robustness.supervisor import FALLBACK_TIER

    def probe() -> dict:
        snap = supervisor.snapshot()
        paths = {p: s for p, s in snap.items() if isinstance(s, dict)}
        degraded = {p: s["tier"] for p, s in paths.items()
                    if s["ladder"][0] != s["tier"]}
        dead = [p for p, s in paths.items()
                if s["tier"] != FALLBACK_TIER
                and all(c["state"] == "open" for c in s["circuits"].values())]
        out = {
            "healthy": not dead,
            "paths": snap,
            "state": ("unserviceable" if dead
                      else "degraded" if degraded else "ok"),
        }
        if degraded:
            out["degraded"] = degraded
        if dead:
            out["live"] = False
            out["unserviceable"] = dead
        return out

    return probe


def informers_source(provider, stale_after_s: float = 90.0) -> Callable[[], dict]:
    """Reflector staleness from the API provider's per-informer last-sync
    ages (client/kube.py). Stale informers fail readiness: scheduling
    decisions against an old cluster view should stop admitting traffic."""
    def probe() -> dict:
        ages = provider.sync_ages()
        stale = {k: round(v, 1) for k, v in ages.items()
                 if v is not None and v > stale_after_s}
        never = [k for k, v in ages.items() if v is None]
        out: dict = {
            "healthy": not stale,
            "ages_s": {k: (round(v, 1) if v is not None else None)
                       for k, v in ages.items()},
        }
        if stale:
            out["stale"] = stale
        if never:
            # informers that never synced: normal during startup, so they
            # are reported but do not fail readiness by themselves
            out["never_synced"] = never
        restarts = getattr(provider, "restart_count", None)
        if restarts is not None:
            out["restarts"] = restarts()
        return out

    return probe


def dispatcher_source(dispatcher) -> Callable[[], dict]:
    """Event-plane backlog: overflow depth approaching the async limit means
    handlers cannot keep up and events are about to be dropped."""
    def probe() -> dict:
        buffered, overflow = dispatcher.backlog()
        limit = getattr(dispatcher, "_async_limit", 0) or 1
        return {
            "healthy": overflow < limit * 0.9,
            "buffered": buffered,
            "overflow": overflow,
            "overflow_limit": limit,
        }

    return probe
