"""Shard failure domains: detect a dead or wedged control-plane shard and
drive it through quarantine -> re-home -> rejoin.

The round-16 sharded control plane (core/shard.py) made the scheduling loop
horizontally scalable but left it with no failure story: a shard whose run
loop wedges (a dispatch no deadline catches, a lock-ordering bug, a crashed
thread) strands its node domains and its pending asks forever — the fleet
is only as available as its worst shard. This module is the failure-domain
half of that design:

  detection   The ShardSupervisor probes every serving shard on a cadence:
                crashed   — the run-loop thread died while supposed to run
                            (the faults.InjectedCrash chaos shape, or any
                            unhandled BaseException unwinding the loop)
                breakers  — some supervised path's ENTIRE circuit ladder is
                            open with no external fallback (the health
                            monitor's "unserviceable" state: nothing on
                            that shard answers dispatches anymore)
                stale     — no successfully completed cycle within the
                            stale budget while the loop claims to run (the
                            wedge the per-dispatch deadlines cannot see:
                            stuck outside a supervised call)
  quarantine  The owner (core/shard.ShardedCoreScheduler.quarantine_shard)
              stops routing to the shard, re-homes its whole ICI domains
              onto surviving shards through the same DECOMISSION->CREATE
              migration contract epoch re-seeding uses (bound pods stay
              bound: node occupancy lives in the shared cache, confirmed
              usage in the global ledger), releases the quarantined shard's
              ledger RESERVATIONS (confirmed usage is untouched, so
              audit() stays zero-violation throughout), restores its
              committed allocations into each app's new home shard, and
              re-admits its parked pending asks there.
  rejoin      After the rejoin delay the shard is REBUILT from scratch — a
              fresh CoreScheduler, exactly like a crashed scheduler process
              restarting — re-admitted to the partitioner, and node domains
              flow back at the next epoch re-seed. The supervisor marks it
              serving again only once the rebuilt loop completes a cycle
              (the healthy probe).

Never quarantines the LAST serving shard: a fully-degraded fleet must keep
limping on whatever still answers, not amputate itself to death.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

from yunikorn_tpu.log.logger import log

logger = log("robustness.failover")

# shard_state{shard} gauge encoding
SERVING, QUARANTINED, REJOINING = "serving", "quarantined", "rejoining"
STATE_GAUGE = {SERVING: 0, QUARANTINED: 1, REJOINING: 2}

REASON_CRASHED = "crashed"
REASON_BREAKERS = "breakers"
REASON_STALE = "stale"


@dataclasses.dataclass
class FailoverOptions:
    """Shard-failover knobs (conf robustness.failover* keys).

    stale_budget_s is deliberately generous by default: a first-touch
    big-bucket program materialization is tens of seconds on CPU even as a
    cache hit, and a legitimately slow cycle must not read as a dead shard.
    The replay/chaos suites compress it to seconds via the same keys."""
    stale_budget_s: float = 120.0
    probe_interval_s: float = 2.0
    rejoin_after_s: float = 60.0
    enabled: bool = True

    @classmethod
    def from_conf(cls, conf) -> "FailoverOptions":
        return cls(
            stale_budget_s=max(float(getattr(
                conf, "robustness_failover_stale_s", 120.0)), 0.5),
            probe_interval_s=max(float(getattr(
                conf, "robustness_failover_probe_s", 2.0)), 0.05),
            rejoin_after_s=max(float(getattr(
                conf, "robustness_failover_rejoin_s", 60.0)), 0.5),
            enabled=(str(getattr(conf, "robustness_failover_enabled",
                                 "true")) != "false"),
        )


def diagnose(core, now: float, serving_since: float,
             stale_budget_s: float) -> Optional[str]:
    """One shard's health verdict, cheapest signal first. Reads only
    lock-free core attributes plus the supervisor snapshot (its own short
    mutex) — safe to call against a wedged shard whose core lock and
    pipeline mutex are held forever by the stuck cycle."""
    running = core._running.is_set()
    thread = core._thread
    if running and (thread is None or not thread.is_alive()):
        return REASON_CRASHED
    try:
        snap = core.supervisor.snapshot()
    except Exception:
        snap = {}
    from yunikorn_tpu.robustness.supervisor import FALLBACK_TIER

    for path, s in snap.items():
        if not isinstance(s, dict) or "circuits" not in s:
            continue
        if (s.get("tier") != FALLBACK_TIER and s["circuits"]
                and all(c["state"] == "open"
                        for c in s["circuits"].values())):
            return REASON_BREAKERS
    if running:
        age = now - max(core._last_cycle_success_at, serving_since)
        if age > stale_budget_s:
            return REASON_STALE
    return None


class ShardSupervisor:
    """Failure-domain state machine + detection loop over N shards.

    The owner (ShardedCoreScheduler) supplies the mechanics through two
    callables: quarantine_fn(idx, reason) -> bool performs the full
    quarantine/re-home transaction, rejoin_fn(idx) -> bool rebuilds and
    re-admits. State transitions, per-shard timestamps and the failover
    metrics live here; routing decisions consult is_active()."""

    def __init__(self, n_shards: int, options: Optional[FailoverOptions],
                 quarantine_fn: Callable[[int, str], bool],
                 rejoin_fn: Callable[[int], bool],
                 registry=None):
        self.n = n_shards
        self.options = options or FailoverOptions()
        self._quarantine_fn = quarantine_fn
        self._rejoin_fn = rejoin_fn
        self._mu = threading.Lock()
        self._state: List[str] = [SERVING] * n_shards
        self._since: List[float] = [time.time()] * n_shards
        self._reasons: List[Optional[str]] = [None] * n_shards
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.quarantines = 0
        self.rejoins = 0
        self.last_event: Optional[dict] = None
        self._m_quarantines = self._h_rehome = self._g_state = None
        if registry is not None:
            self.attach_metrics(registry)

    def attach_metrics(self, registry) -> None:
        self._m_quarantines = registry.counter(
            "shard_quarantines_total",
            "shards quarantined by the failure-domain supervisor, by "
            "detection reason (crashed = run-loop thread died, breakers = "
            "every supervised circuit open with no fallback, stale = no "
            "completed cycle within the stale budget)",
            labelnames=("reason",))
        self._h_rehome = registry.histogram(
            "shard_rehome_seconds",
            "wall time of one quarantine transaction: detection to every "
            "ICI domain re-homed, reservations released, allocations "
            "re-attributed and parked asks re-admitted",
            buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                     60.0, 120.0))
        self._g_state = registry.gauge(
            "shard_state",
            "failure-domain state per shard "
            "(0=serving, 1=quarantined, 2=rejoining)",
            labelnames=("shard",))
        for k in range(self.n):
            self._g_state.set(STATE_GAUGE[SERVING], shard=str(k))
        # stable zero series per reason (dashboards rate() these)
        for reason in (REASON_CRASHED, REASON_BREAKERS, REASON_STALE):
            self._m_quarantines.inc(0, reason=reason)

    # ------------------------------------------------------------ state API
    def state(self, idx: int) -> str:
        with self._mu:
            return self._state[idx]

    def states(self) -> Dict[int, str]:
        with self._mu:
            return {k: s for k, s in enumerate(self._state)}

    def is_active(self, idx: int) -> bool:
        """Whether routing may target this shard (serving or rejoining —
        a rejoining shard is healthy and owns whatever domains the epoch
        re-seed already gave back)."""
        with self._mu:
            return self._state[idx] != QUARANTINED

    def active_shards(self) -> List[int]:
        with self._mu:
            return [k for k, s in enumerate(self._state) if s != QUARANTINED]

    def note_rehome_seconds(self, seconds: float) -> None:
        if self._h_rehome is not None:
            self._h_rehome.observe(seconds)

    def note_quarantined(self, idx: int, reason: str,
                         rehome_s: float = 0.0) -> None:
        """Record a quarantine performed OUTSIDE the detection loop — the
        host-lease monitor drives the owner's quarantine transaction
        directly on lease expiry, and the failure-domain states, counters
        and report must still reflect it (the cross-host drill's
        assertion surface reads them). A shard already quarantined is a
        no-op; the rejoin ladder picks the shard up from here exactly as
        if the probe loop had diagnosed it."""
        now = time.time()
        with self._mu:
            if not (0 <= idx < self.n) or self._state[idx] == QUARANTINED:
                return
            self._state[idx] = QUARANTINED
            self._since[idx] = now
            self._reasons[idx] = reason
            self.quarantines += 1
            self.last_event = {"shard": idx, "event": "quarantine",
                               "reason": reason, "at": round(now, 3),
                               "rehome_s": round(rehome_s, 3)}
        if self._m_quarantines is not None:
            self._m_quarantines.inc(reason=reason)
        if self._g_state is not None:
            self._g_state.set(STATE_GAUGE[QUARANTINED], shard=str(idx))
        self.note_rehome_seconds(rehome_s)

    def report(self) -> dict:
        with self._mu:
            return {
                "states": {str(k): s for k, s in enumerate(self._state)},
                "quarantines": self.quarantines,
                "rejoins": self.rejoins,
                "last_event": dict(self.last_event) if self.last_event else None,
            }

    # ------------------------------------------------------------ lifecycle
    def start(self) -> None:
        if not self.options.enabled or self._thread is not None:
            return
        now = time.time()
        with self._mu:
            self._since = [now] * self.n
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="shard-failover", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.options.probe_interval_s):
            try:
                self.probe_once()
            except Exception:
                logger.exception("shard failover probe failed; "
                                 "states unchanged this round")

    # ------------------------------------------------------------ the probe
    def probe_once(self, cores: Optional[list] = None,
                   now: Optional[float] = None) -> List[dict]:
        """One detection pass. cores defaults to the owner's live shard
        list read lazily through quarantine_fn's owner — the caller (the
        probe thread or a test) passes the list explicitly instead; the
        ShardedCoreScheduler binds it via set_cores()."""
        if now is None:
            now = time.time()
        cores = cores if cores is not None else self._cores()
        events: List[dict] = []
        for k, core in enumerate(cores):
            with self._mu:
                state = self._state[k]
                since = self._since[k]
            if state == SERVING:
                reason = diagnose(core, now, since,
                                  self.options.stale_budget_s)
                if reason is None:
                    continue
                with self._mu:
                    # never amputate the last serving shard
                    active = [i for i, s in enumerate(self._state)
                              if s == SERVING]
                    if len(active) <= 1:
                        continue
                logger.warning("shard %d diagnosed %s; quarantining",
                               k, reason)
                t0 = time.time()
                if not self._quarantine_fn(k, reason):
                    continue
                took = time.time() - t0
                with self._mu:
                    self._state[k] = QUARANTINED
                    self._since[k] = now
                    self._reasons[k] = reason
                    self.quarantines += 1
                    self.last_event = {"shard": k, "event": "quarantine",
                                       "reason": reason, "at": round(now, 3),
                                       "rehome_s": round(took, 3)}
                if self._m_quarantines is not None:
                    self._m_quarantines.inc(reason=reason)
                if self._g_state is not None:
                    self._g_state.set(STATE_GAUGE[QUARANTINED], shard=str(k))
                self.note_rehome_seconds(took)
                events.append(dict(self.last_event))
            elif state == QUARANTINED:
                if now - since < self.options.rejoin_after_s:
                    continue
                if not self._rejoin_fn(k):
                    continue
                with self._mu:
                    self._state[k] = REJOINING
                    # stamped AFTER the rebuild so the serving check below
                    # requires a cycle completed by the NEW loop, not the
                    # constructor's baseline success stamp
                    self._since[k] = time.time()
                    self.rejoins += 1
                    self.last_event = {"shard": k, "event": "rejoin",
                                       "at": round(now, 3)}
                if self._g_state is not None:
                    self._g_state.set(STATE_GAUGE[REJOINING], shard=str(k))
                events.append(dict(self.last_event))
                logger.info("shard %d rebuilt; rejoining at the next epoch",
                            k)
            else:  # REJOINING: the healthy probe — a completed cycle on the
                # rebuilt loop re-admits the shard as serving
                core = cores[k]
                if (core._running.is_set()
                        and core._thread is not None
                        and core._thread.is_alive()
                        and core._last_cycle_success_at > since):
                    with self._mu:
                        self._state[k] = SERVING
                        self._since[k] = now
                        self._reasons[k] = None
                    if self._g_state is not None:
                        self._g_state.set(STATE_GAUGE[SERVING], shard=str(k))
                    events.append({"shard": k, "event": "serving",
                                   "at": round(now, 3)})
                    logger.info("shard %d healthy again; serving", k)
        return events

    # bound by the owner after construction (the owner's shard list is
    # mutable: rejoin REPLACES the quarantined core object in place)
    _cores_fn: Optional[Callable[[], list]] = None

    def set_cores(self, fn: Callable[[], list]) -> None:
        self._cores_fn = fn

    def _cores(self) -> list:
        if self._cores_fn is None:
            return []
        return self._cores_fn()


class HostLeaseMonitor:
    """Cross-HOST failover (round 22, ROADMAP (e)): the ledger service as
    liveness authority.

    Each shard host registers the shard indices it owns and heartbeats its
    lease over the same ledger connection its quota ops ride — liveness
    and quota coupling share fate on purpose: a host that cannot reach the
    ledger cannot ADMIT anything fleet-visible either, so an expired lease
    really means the host's shards are out of the admission plane. Every
    poll, the monitor heartbeats its OWN lease and asks the ledger for
    expired PEER leases; a dead peer's shards are driven through the
    round-18 quarantine/evacuate/re-home machinery on THIS (surviving)
    host's supervisor — bound pods preserved, audit clean, exactly the
    in-process quarantine contract.

    Degraded note: while the ledger is unreachable the client's breaker
    answers expired_hosts() with the empty default — a partitioned
    SURVIVOR never mass-quarantines the fleet on its own blindness (the
    ledger side sees the survivor's lease expire instead)."""

    def __init__(self, ledger, host_id: str, self_shards: List[int],
                 quarantine_fn: Callable[[int, str], bool],
                 ttl_s: float = 15.0, interval_s: float = 2.0,
                 registry=None):
        self.ledger = ledger
        self.host_id = host_id
        self.self_shards = list(self_shards)
        self.quarantine_fn = quarantine_fn
        self.ttl_s = float(ttl_s)
        self.interval_s = float(interval_s)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._registered = False
        self.heartbeats = 0
        self.expiries_seen = 0
        self._m_expiries = None
        if registry is not None:
            self._m_expiries = registry.counter(
                "ledger_lease_expiries_total",
                "peer host leases this supervisor observed expiring on the "
                "ledger liveness authority (each drives the dead host's "
                "shards through quarantine/re-home)")
            self._m_expiries.inc(0)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="host-lease", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.poll_once()
            except Exception:
                logger.exception("host lease poll failed")

    def poll_once(self) -> List[str]:
        """One heartbeat + expiry sweep; returns the hosts whose leases
        were found expired (the chaos drill's assertion surface)."""
        if not self._registered:
            self.ledger.register_host_shards(self.host_id, self.self_shards)
            self._registered = True
        self.ledger.heartbeat_host(self.host_id)
        self.heartbeats += 1
        dead: List[str] = []
        for host, shards in self.ledger.expired_hosts(self.ttl_s):
            if host == self.host_id:
                # our own lease lapsed (we were the partitioned side):
                # re-register rather than amputate ourselves
                self._registered = False
                continue
            dead.append(host)
            self.expiries_seen += 1
            if self._m_expiries is not None:
                self._m_expiries.inc()
            logger.warning("host lease expired: %s (shards %s); "
                           "quarantining", host, shards)
            for idx in shards:
                try:
                    self.quarantine_fn(int(idx), f"lease:{host}")
                except Exception:
                    logger.exception("lease-driven quarantine of shard "
                                     "%s failed", idx)
        return dead


def failover_source(shard_supervisor: ShardSupervisor) -> Callable[[], dict]:
    """HealthMonitor source: a quarantined shard degrades readiness (the
    fleet is serving on reduced capacity — operators should know) while
    liveness stays untouched (the surviving shards ARE answering)."""
    def probe() -> dict:
        rep = shard_supervisor.report()
        quarantined = [k for k, s in rep["states"].items()
                       if s == QUARANTINED]
        out = {
            "healthy": not quarantined,
            "states": rep["states"],
            "quarantines": rep["quarantines"],
            "rejoins": rep["rejoins"],
        }
        if rep["last_event"]:
            out["last_event"] = rep["last_event"]
        if quarantined:
            out["quarantined"] = quarantined
        return out

    return probe
