"""Injectable fault plane: the seams the chaos suite drives.

Reference pattern: NewMockedAPIProvider(showError) + the mockable
Bind/Create/Delete seams (apifactory_mock.go:137-165) let the reference
inject client-plane faults; the JAX port's new fault domain is the device
runtime, so the injection point sits inside every SUPERVISED dispatch
attempt (SupervisedExecutor runs `on_attempt` on the watchdog worker right
before the wrapped call — a scripted `slow` therefore really trips the
dispatch deadline, exactly like a wedged XLA dispatch would).

Rules match (path, tier): `fail("assign", tier="device")` poisons only the
device tier, so the chaos suite can prove the CPU/host tiers keep answering
while the primary is down.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """Raised by a scripted fail rule (classified transient by default)."""


class InjectedPersistentFault(InjectedFault):
    """Scripted fault classified persistent (compile/shape-error analog)."""


class InjectedCrash(BaseException):
    """Scripted crash of the scheduling loop itself.

    Deliberately NOT an Exception subclass: the supervisor's tier ladder,
    the cycle's failure accounting and the run loop all contain `except
    Exception` — an InjectedCrash passes through every one of them and
    unwinds the scheduler thread, which then dies exactly like a thread
    hitting a segfault-adjacent interpreter bug would. The shard-failover
    chaos suite uses it to kill ONE shard's loop in-process."""


class _Rule:
    __slots__ = ("kind", "tier", "times", "after", "delay_s", "exc")

    def __init__(self, kind: str, tier: Optional[str], times: float,
                 after: int, delay_s: float, exc: Optional[Exception]):
        self.kind = kind            # "fail" | "slow"
        self.tier = tier            # None matches every tier
        self.times = times          # remaining firings (inf = forever)
        self.after = after          # attempts to let through first
        self.delay_s = delay_s
        self.exc = exc


class FaultPlane:
    """Per-path scripted faults, consumed attempt by attempt."""

    def __init__(self):
        self._mu = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        # attempts seen per (path, tier) — lets tests assert retry counts
        self.attempts: Dict[str, int] = {}

    # -- scripting ---------------------------------------------------------
    def fail(self, path: str, times: int = 1, tier: Optional[str] = None,
             after: int = 0, exc: Optional[Exception] = None,
             persistent: bool = False) -> None:
        """Raise on the next `times` matching attempts (after `after`)."""
        if exc is None:
            cls = InjectedPersistentFault if persistent else InjectedFault
            exc = cls(f"injected fault on {path}"
                      + (f"/{tier}" if tier else ""))
        with self._mu:
            self._rules.setdefault(path, []).append(
                _Rule("fail", tier, times, after, 0.0, exc))

    def fail_forever(self, path: str, tier: Optional[str] = None,
                     exc: Optional[Exception] = None) -> None:
        self.fail(path, times=float("inf"), tier=tier, exc=exc)

    def crash(self, path: str, tier: Optional[str] = None,
              after: int = 0) -> None:
        """Kill the scheduling loop on the next matching attempt: raises
        InjectedCrash (a BaseException), which no supervised handler
        contains — the run-loop thread that dispatched the attempt dies.
        The failover suite's injected shard death."""
        self.fail(path, times=1, tier=tier, after=after,
                  exc=InjectedCrash(f"injected crash on {path}"))

    def slow(self, path: str, seconds: float, times: int = 1,
             tier: Optional[str] = None, after: int = 0) -> None:
        """Sleep before the next `times` matching attempts (deadline test)."""
        with self._mu:
            self._rules.setdefault(path, []).append(
                _Rule("slow", tier, times, after, float(seconds), None))

    def clear(self, path: Optional[str] = None) -> None:
        with self._mu:
            if path is None:
                self._rules.clear()
            else:
                self._rules.pop(path, None)

    def pending(self, path: str) -> int:
        """Matching rules still armed (diagnostics)."""
        with self._mu:
            return sum(1 for r in self._rules.get(path, ())
                       if r.times > 0)

    # -- the seam ----------------------------------------------------------
    def on_attempt(self, path: str, tier: str) -> None:
        """Called by the supervisor inside every supervised attempt.

        May sleep (slow rules) and then raise (fail rules). Rules are
        consumed in script order; a rule's `after` budget is decremented by
        matching attempts that pass through it.
        """
        delay = 0.0
        exc: Optional[Exception] = None
        with self._mu:
            key = f"{path}/{tier}"
            self.attempts[key] = self.attempts.get(key, 0) + 1
            for rule in self._rules.get(path, ()):  # script order
                if rule.tier is not None and rule.tier != tier:
                    continue
                if rule.times <= 0:
                    continue
                if rule.after > 0:
                    rule.after -= 1
                    continue
                rule.times -= 1
                if rule.kind == "slow":
                    delay += rule.delay_s
                else:
                    exc = rule.exc
                    break
        if delay > 0:
            time.sleep(delay)
        if exc is not None:
            raise exc


class NetPartitioned(ConnectionError):
    """The ledger transport is partitioned/down for this frame.

    A ConnectionError subclass on purpose: the LedgerClient's retry path
    and `classify_error` both already treat ConnectionError as transient,
    so injected partitions exercise the EXACT production error path."""


class NetFaultPlane:
    """Network fault family for the ledger transport (round 22).

    Where FaultPlane scripts faults per supervised (path, tier) attempt,
    NetFaultPlane scripts them per transport FRAME: both ends of the
    ledger socket call `on_frame(op)` before touching the wire, which may
    sleep (delay rules), raise NetPartitioned (drop/partition/flap), or
    ask the caller to send the frame more than once (duplicate) — the
    exact abuse the idempotency layer must absorb. Driven from
    `trace_replay --fault netsplit|ledger-lag` and the chaos suites."""

    def __init__(self):
        self._mu = threading.Lock()
        self._drop = 0              # frames to drop (inf = until heal)
        self._delay_s = 0.0
        self._delay_times = 0
        self._dup = 0               # frames to duplicate
        self._partition_until = 0.0  # inf = until heal()
        self._flap_period_s = 0.0
        self._flap_down = 0.0
        self._flap_anchor = 0.0
        self.frames = 0
        self.dropped = 0
        self.delayed = 0
        self.duplicated = 0

    # -- scripting ---------------------------------------------------------
    def drop(self, times: float = 1) -> None:
        """Drop the next `times` frames (each surfaces as a transient
        connection error the client retries under its deadline)."""
        with self._mu:
            self._drop += times

    def delay(self, seconds: float, times: float = float("inf")) -> None:
        """Stall the next `times` frames by `seconds` (ledger-lag shape:
        frames arrive, late — deadlines and backoff do the work)."""
        with self._mu:
            self._delay_s = float(seconds)
            self._delay_times = times

    def duplicate(self, times: float = 1) -> None:
        """Send the next `times` frames twice (idempotency-cache abuse)."""
        with self._mu:
            self._dup += times

    def partition(self, seconds: Optional[float] = None) -> None:
        """Hard partition: every frame fails until `seconds` elapse (or
        until heal() when None) — the netsplit shape that must open the
        breaker and push the client into degraded mode."""
        with self._mu:
            self._partition_until = (float("inf") if seconds is None
                                     else time.time() + float(seconds))

    def flap(self, period_s: float, down_fraction: float = 0.5) -> None:
        """Periodic partition: down for `down_fraction` of every period.
        The wedge/leak storm shape — repeated open/half-open/close breaker
        cycles with journal replay on every heal."""
        with self._mu:
            self._flap_period_s = max(float(period_s), 1e-6)
            self._flap_down = min(max(float(down_fraction), 0.0), 1.0)
            self._flap_anchor = time.time()

    def heal(self) -> None:
        """Clear partition/flap/delay/drop state (the network comes back)."""
        with self._mu:
            self._drop = 0
            self._delay_s = 0.0
            self._delay_times = 0
            self._partition_until = 0.0
            self._flap_period_s = 0.0

    # -- the seam ----------------------------------------------------------
    def on_frame(self, op: str) -> int:
        """Called before each frame exchange. Returns the send count
        (1, or 2+ for duplicated frames); may sleep; raises NetPartitioned
        while the transport is down."""
        now = time.time()
        delay = 0.0
        sends = 1
        with self._mu:
            self.frames += 1
            if now < self._partition_until:
                self.dropped += 1
                raise NetPartitioned(f"ledger transport partitioned ({op})")
            if self._flap_period_s > 0.0:
                phase = ((now - self._flap_anchor) % self._flap_period_s)
                if phase < self._flap_period_s * self._flap_down:
                    self.dropped += 1
                    raise NetPartitioned(
                        f"ledger transport flapped down ({op})")
            if self._drop > 0:
                self._drop -= 1
                self.dropped += 1
                raise NetPartitioned(f"ledger frame dropped ({op})")
            if self._delay_times > 0 and self._delay_s > 0.0:
                self._delay_times -= 1
                self.delayed += 1
                delay = self._delay_s
            if self._dup > 0:
                self._dup -= 1
                self.duplicated += 1
                sends = 2
        if delay > 0:
            time.sleep(delay)
        return sends
