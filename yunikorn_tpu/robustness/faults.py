"""Injectable fault plane: the seams the chaos suite drives.

Reference pattern: NewMockedAPIProvider(showError) + the mockable
Bind/Create/Delete seams (apifactory_mock.go:137-165) let the reference
inject client-plane faults; the JAX port's new fault domain is the device
runtime, so the injection point sits inside every SUPERVISED dispatch
attempt (SupervisedExecutor runs `on_attempt` on the watchdog worker right
before the wrapped call — a scripted `slow` therefore really trips the
dispatch deadline, exactly like a wedged XLA dispatch would).

Rules match (path, tier): `fail("assign", tier="device")` poisons only the
device tier, so the chaos suite can prove the CPU/host tiers keep answering
while the primary is down.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """Raised by a scripted fail rule (classified transient by default)."""


class InjectedPersistentFault(InjectedFault):
    """Scripted fault classified persistent (compile/shape-error analog)."""


class InjectedCrash(BaseException):
    """Scripted crash of the scheduling loop itself.

    Deliberately NOT an Exception subclass: the supervisor's tier ladder,
    the cycle's failure accounting and the run loop all contain `except
    Exception` — an InjectedCrash passes through every one of them and
    unwinds the scheduler thread, which then dies exactly like a thread
    hitting a segfault-adjacent interpreter bug would. The shard-failover
    chaos suite uses it to kill ONE shard's loop in-process."""


class _Rule:
    __slots__ = ("kind", "tier", "times", "after", "delay_s", "exc")

    def __init__(self, kind: str, tier: Optional[str], times: float,
                 after: int, delay_s: float, exc: Optional[Exception]):
        self.kind = kind            # "fail" | "slow"
        self.tier = tier            # None matches every tier
        self.times = times          # remaining firings (inf = forever)
        self.after = after          # attempts to let through first
        self.delay_s = delay_s
        self.exc = exc


class FaultPlane:
    """Per-path scripted faults, consumed attempt by attempt."""

    def __init__(self):
        self._mu = threading.Lock()
        self._rules: Dict[str, List[_Rule]] = {}
        # attempts seen per (path, tier) — lets tests assert retry counts
        self.attempts: Dict[str, int] = {}

    # -- scripting ---------------------------------------------------------
    def fail(self, path: str, times: int = 1, tier: Optional[str] = None,
             after: int = 0, exc: Optional[Exception] = None,
             persistent: bool = False) -> None:
        """Raise on the next `times` matching attempts (after `after`)."""
        if exc is None:
            cls = InjectedPersistentFault if persistent else InjectedFault
            exc = cls(f"injected fault on {path}"
                      + (f"/{tier}" if tier else ""))
        with self._mu:
            self._rules.setdefault(path, []).append(
                _Rule("fail", tier, times, after, 0.0, exc))

    def fail_forever(self, path: str, tier: Optional[str] = None,
                     exc: Optional[Exception] = None) -> None:
        self.fail(path, times=float("inf"), tier=tier, exc=exc)

    def crash(self, path: str, tier: Optional[str] = None,
              after: int = 0) -> None:
        """Kill the scheduling loop on the next matching attempt: raises
        InjectedCrash (a BaseException), which no supervised handler
        contains — the run-loop thread that dispatched the attempt dies.
        The failover suite's injected shard death."""
        self.fail(path, times=1, tier=tier, after=after,
                  exc=InjectedCrash(f"injected crash on {path}"))

    def slow(self, path: str, seconds: float, times: int = 1,
             tier: Optional[str] = None, after: int = 0) -> None:
        """Sleep before the next `times` matching attempts (deadline test)."""
        with self._mu:
            self._rules.setdefault(path, []).append(
                _Rule("slow", tier, times, after, float(seconds), None))

    def clear(self, path: Optional[str] = None) -> None:
        with self._mu:
            if path is None:
                self._rules.clear()
            else:
                self._rules.pop(path, None)

    def pending(self, path: str) -> int:
        """Matching rules still armed (diagnostics)."""
        with self._mu:
            return sum(1 for r in self._rules.get(path, ())
                       if r.times > 0)

    # -- the seam ----------------------------------------------------------
    def on_attempt(self, path: str, tier: str) -> None:
        """Called by the supervisor inside every supervised attempt.

        May sleep (slow rules) and then raise (fail rules). Rules are
        consumed in script order; a rule's `after` budget is decremented by
        matching attempts that pass through it.
        """
        delay = 0.0
        exc: Optional[Exception] = None
        with self._mu:
            key = f"{path}/{tier}"
            self.attempts[key] = self.attempts.get(key, 0) + 1
            for rule in self._rules.get(path, ()):  # script order
                if rule.tier is not None and rule.tier != tier:
                    continue
                if rule.times <= 0:
                    continue
                if rule.after > 0:
                    rule.after -= 1
                    continue
                rule.times -= 1
                if rule.kind == "slow":
                    delay += rule.delay_s
                else:
                    exc = rule.exc
                    break
        if delay > 0:
            time.sleep(delay)
        if exc is not None:
            raise exc
