"""SupervisedExecutor: fault containment around every device dispatch.

The scheduler's device paths (assign solve, preemption solve, sharded-mesh
dispatch, device-mirror upload) all funnel through here. Each supervised
attempt gets:

  deadline       — the wrapped call runs on a watchdog worker thread; a call
                   that outlives its deadline is abandoned (the worker is
                   poisoned and replaced; a late result is discarded) and
                   surfaces as DeadlineExceeded instead of wedging the
                   scheduling loop — the r01–r05 TPU dial wedge (rc=124) was
                   exactly a dispatch with no deadline.
  classification — transient XLA/transfer errors retry (bounded, jittered
                   backoff); persistent compile/shape errors skip straight
                   to degradation (identical args cannot start succeeding).
  circuit breaker— per (path, tier): consecutive failures past the threshold
                   open the circuit; an open circuit half-opens after the
                   probe interval and the next dispatch probes it — success
                   re-closes, so a recovered TPU is reclaimed without a
                   restart.
  degradation    — a path with a tier ladder (assign: device → cpu → host)
                   falls to the first tier whose circuit admits traffic; the
                   host tier is the exact host path (the same differential
                   oracle the preemption planner and locality fallback use),
                   so the scheduler gets slower under faults, never stops
                   answering (POP, arXiv:2110.11927; Priority-Matters,
                   arXiv:2511.08373).

Observability: every transition is visible — `solver_degradation_state{path}`
gauge (tier index), `supervised_dispatch_total{path,outcome,policy}`,
`circuit_transitions_total{path,tier,state}`, and a `degrade`/`recover`
tracer span on the cycle timeline.
"""
from __future__ import annotations

import collections
import random
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from yunikorn_tpu.log.logger import log
from yunikorn_tpu.robustness.faults import (
    FaultPlane,
    InjectedFault,
    InjectedPersistentFault,
)

logger = log("robustness.supervisor")

# canonical ladder for the assignment path; single-tier paths (upload, mesh,
# preempt) use ("device",)
ASSIGN_LADDER = ("device", "cpu", "host")

# pseudo-tier reported for a path whose every circuit is open but whose
# caller degrades OUTSIDE the ladder (mesh → single-device solve, upload →
# per-cycle transfer, preempt → host planner). A ladder ending in "host"
# has no external fallback — all-open there really means nothing answers.
FALLBACK_TIER = "fallback"

# solver_degradation_state encoding: fixed per tier NAME so a value means
# the same thing on every path — a single-tier path degrading to its
# external fallback must not report the assign ladder's cpu slot
TIER_GAUGE = {"device": 0, "cpu": 1, "host": 2, FALLBACK_TIER: 3}

TRANSIENT = "transient"
PERSISTENT = "persistent"
DEADLINE = "deadline"


class DeadlineExceeded(RuntimeError):
    """A supervised call outlived its dispatch deadline and was abandoned."""


class AbandonedDispatch(RuntimeError):
    """Raised inside a watchdog thread whose supervised call was already
    abandoned: nested supervised work (the upload inside the assign
    dispatch) must neither run nor pollute the LIVE circuit state."""


class AllTiersFailed(RuntimeError):
    """Every tier of a supervised path failed for this operation."""


def _call_abandoned() -> bool:
    """Whether the CURRENT thread is a watchdog whose waiter gave up on it
    (the flag is stamped on the thread object at abandonment)."""
    return getattr(threading.current_thread(), "_yk_abandoned", False)


def classify_error(exc: BaseException) -> str:
    """transient → worth a bounded same-tier retry; persistent → degrade now
    (compile/shape/encode errors replay identically); deadline → degrade now
    but half-open probes may reclaim the tier later."""
    if isinstance(exc, DeadlineExceeded):
        return DEADLINE
    if isinstance(exc, InjectedPersistentFault):
        return PERSISTENT
    if isinstance(exc, InjectedFault):
        return TRANSIENT
    if (isinstance(exc, AbandonedDispatch)
            or type(exc).__name__ == "MirrorDiscarded"):
        # zombie-thread bailouts: retrying replays the same stale epoch
        return PERSISTENT
    if type(exc).__name__ == "CompilePending":
        # aot background compile in flight: same-tier retries cannot succeed
        # until the compile thread lands the executable — open the breaker
        # now (hard) so cycles serve from the cpu/host tiers, and let the
        # half-open probe reclaim the tier once the store/memory cache is
        # populated (name check: aot must stay importable without jax init)
        return PERSISTENT
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "JaxRuntimeError", "XlaError"):
        msg = str(exc)
        for tok in ("INVALID_ARGUMENT", "UNIMPLEMENTED",
                    "FAILED_PRECONDITION", "NOT_FOUND"):
            if tok in msg:
                return PERSISTENT
        # UNAVAILABLE / INTERNAL / RESOURCE_EXHAUSTED / ABORTED /
        # DEADLINE_EXCEEDED / transfer failures: the runtime may recover
        return TRANSIENT
    if isinstance(exc, (TypeError, ValueError, AssertionError, KeyError,
                        IndexError, AttributeError, NotImplementedError)):
        # tracing/shape/encoding bugs: deterministic on identical inputs
        return PERSISTENT
    if isinstance(exc, (OSError, ConnectionError, TimeoutError)):
        return TRANSIENT
    return TRANSIENT


import dataclasses


@dataclasses.dataclass
class SupervisorOptions:
    """Robustness knobs (conf robustness.* keys).

    deadline_s is deliberately generous by default: a first-touch compile at
    a big bucket legitimately takes minutes on some backends (remote-compile
    relays); the prewarm path keeps compiles out of production cycles, and
    the deadline exists to catch WEDGED dispatches, not slow ones."""
    deadline_s: float = 300.0
    max_retries: int = 2
    backoff_base_s: float = 0.05
    breaker_threshold: int = 3
    probe_interval_s: float = 30.0
    # half-open probes get a SHORT deadline: a probe exists to ask "is the
    # backend back?", and a healthy backend answers a cached program in
    # seconds — re-paying the full deadline per probe would stall most of
    # the wall clock against a still-wedged device. A probe abandoned while
    # legitimately recompiling still warms the jit cache on its watchdog
    # thread, so a following probe closes the circuit.
    probe_deadline_s: float = 20.0
    # cap on concurrently-outstanding abandoned watchdog threads: past it,
    # half-open probes are refused (the circuit stays open) so a permanent
    # wedge cannot accumulate zombies + orphaned mirrors without bound
    max_abandoned: int = 4

    @classmethod
    def from_conf(cls, conf) -> "SupervisorOptions":
        return cls(
            deadline_s=max(float(getattr(
                conf, "robustness_dispatch_deadline_s", 300.0)), 0.0),
            max_retries=max(int(getattr(
                conf, "robustness_max_retries", 2)), 0),
            breaker_threshold=max(int(getattr(
                conf, "robustness_breaker_threshold", 3)), 1),
            probe_interval_s=max(float(getattr(
                conf, "robustness_probe_interval_s", 30.0)), 0.01),
            probe_deadline_s=max(float(getattr(
                conf, "robustness_probe_deadline_s", 20.0)), 0.0),
        )


CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """One (path, tier) circuit. Not thread-safe on its own: the executor
    serializes access under its mutex."""

    def __init__(self, threshold: int, probe_interval_s: float):
        self.threshold = max(int(threshold), 1)
        self.probe_interval_s = probe_interval_s
        self.state = CLOSED
        self.failures = 0          # consecutive
        self.opened_at = 0.0

    def allow(self, now: float) -> bool:
        """Whether a dispatch may use this tier right now. An open circuit
        past its probe interval half-opens (the caller's dispatch IS the
        probe)."""
        if self.state == OPEN:
            if now - self.opened_at >= self.probe_interval_s:
                self.state = HALF_OPEN
                return True
            return False
        return True

    def record_success(self, commit: bool = True) -> bool:
        """Returns True when the circuit re-closed (recovery)."""
        self.failures = 0
        if self.state == HALF_OPEN and commit:
            self.state = CLOSED
            return True
        return False

    def record_failure(self, now: float, hard: bool = False) -> bool:
        """Returns True when the circuit opened."""
        self.failures += 1
        if (self.state == HALF_OPEN or hard
                or self.failures >= self.threshold):
            was_open = self.state == OPEN
            self.state = OPEN
            self.opened_at = now
            return not was_open
        return False


class SupervisedExecutor:
    def __init__(self, options: Optional[SupervisorOptions] = None,
                 registry=None, tracer=None,
                 faults: Optional[FaultPlane] = None):
        self.options = options or SupervisorOptions()
        self.faults = faults or FaultPlane()
        self.tracer = tracer
        # the committing cycle id, stamped by the core per cycle so
        # degrade/recover spans land on the right cycle lane
        self.cycle_id = 0
        # solver.policy of the cycle being dispatched ("greedy"/"optimal"),
        # stamped by the core per dispatch: supervised_dispatch_total carries
        # it as a label so dashboards separate the two solve paths without
        # new series names
        self.policy_label = "greedy"
        # control-plane sharding (core/shard.py): shards share one metrics
        # registry, so each shard's supervisor prefixes its path LABEL
        # (e.g. "s2/assign") to keep per-shard series distinct — breakers,
        # ladders and degraded_paths() stay keyed by the bare path name.
        self.path_label_prefix = ""
        # optional context-manager factory entered around every tier fn ON
        # the watchdog thread that runs it (thread-local state like the
        # shard's AOT fingerprint namespace must be set there, not on the
        # scheduler thread that called execute())
        self.dispatch_cm: Optional[Callable] = None
        self._mu = threading.Lock()
        self._breakers: Dict[Tuple[str, str], CircuitBreaker] = {}
        self._ladders: Dict[str, Tuple[str, ...]] = {}
        self._tier_state: Dict[str, str] = {}
        self._transitions: collections.deque = collections.deque(maxlen=256)
        self._abandoned = 0       # cumulative deadline abandonments
        self._live_abandoned = 0  # abandoned watchdogs still running
        self._live_watchdogs = 0  # watchdog threads currently running
        # called as on_abandon(path, tier) after a deadline abandonment,
        # OUTSIDE the mutex. The abandoned daemon thread is still running
        # the dispatch and may yet mutate whatever shared state the call
        # touches (device mirror, jit caches); the owner uses this hook to
        # orphan that state (core: encoder.discard_device_mirror) so the
        # late writes land on unreferenced objects.
        self.on_abandon: Optional[Callable[[str, str], None]] = None
        # called as on_exhausted(path) when execute() walks OFF the end of
        # a ladder (AllTiersFailed — even the host fallback refused), just
        # before the raise and outside the mutex. The flight recorder
        # hangs its breaker_exhausted trigger here; its sources re-enter
        # snapshot()/degraded_paths(), so firing under _mu would deadlock.
        self.on_exhausted: Optional[Callable[[str], None]] = None
        self._m_dispatch = self._m_transitions = self._g_state = None
        self._g_watchdogs = None
        if registry is not None:
            self.attach_metrics(registry)

    def attach_metrics(self, registry) -> None:
        self._m_dispatch = registry.counter(
            "supervised_dispatch_total",
            "supervised device-path attempts by path, outcome and the "
            "cycle's solver.policy (greedy | optimal)",
            labelnames=("path", "outcome", "policy"))
        self._m_transitions = registry.counter(
            "circuit_transitions_total",
            "circuit-breaker state transitions by path/tier",
            labelnames=("path", "tier", "state"))
        self._g_state = registry.gauge(
            "solver_degradation_state",
            "current degradation tier per supervised path "
            "(0=device, 1=cpu re-jit, 2=host, 3=external fallback)",
            labelnames=("path",))
        self._g_watchdogs = registry.gauge(
            "watchdog_threads",
            "supervised-dispatch watchdog threads currently alive: "
            "running = watchdogs executing a live dispatch, abandoned = "
            "deadline-abandoned zombies still wedged in their call (bounded "
            "by robustness max_abandoned: past it half-open probes are "
            "refused so a permanent wedge cannot grow zombies forever)",
            labelnames=("state",))
        self._publish_watchdogs()

    # -- breaker plumbing ---------------------------------------------------
    def _breaker(self, path: str, tier: str) -> CircuitBreaker:
        key = (path, tier)
        br = self._breakers.get(key)
        if br is None:
            br = self._breakers[key] = CircuitBreaker(
                self.options.breaker_threshold,
                self.options.probe_interval_s)
        return br

    def _register_ladder(self, path: str, ladder: Sequence[str]) -> None:
        self._ladders.setdefault(path, tuple(ladder))
        if path not in self._tier_state:
            self._tier_state[path] = ladder[0]
            if self._g_state is not None:
                self._g_state.set(TIER_GAUGE.get(ladder[0], 0),
                                  path=self.path_label_prefix + path)

    def _effective_tier(self, path: str) -> str:
        """First tier whose circuit is not open (half-open counts: it is
        being probed). With EVERY circuit open, a ladder ending in "host"
        has nothing left (unserviceable); any other path degrades outside
        the supervisor and reports the FALLBACK_TIER pseudo-tier so the
        gauge/bench/health all see the silent-fallback state."""
        ladder = self._ladders.get(path, ("device",))
        for tier in ladder:
            if self._breaker(path, tier).state != OPEN:
                return tier
        return ladder[-1] if ladder[-1] == "host" else FALLBACK_TIER

    def _note_transition(self, path: str, tier: str, state: str) -> None:
        """Breaker state changed (mutex held): re-derive the path's tier and
        publish degrade/recover when it moved."""
        if self._m_transitions is not None:
            self._m_transitions.inc(path=self.path_label_prefix + path,
                                    tier=tier, state=state)
        ladder = self._ladders.get(path, ("device",))
        old = self._tier_state.get(path, ladder[0])
        new = self._effective_tier(path)
        if new == old:
            return
        self._tier_state[path] = new

        def rank(t: str) -> int:  # FALLBACK_TIER sits past the ladder's end
            return ladder.index(t) if t in ladder else len(ladder)

        now = time.time()
        event = "degrade" if rank(new) > rank(old) else "recover"
        self._transitions.append({"at": round(now, 3), "path": path,
                                  "from": old, "to": new, "event": event})
        if self._g_state is not None:
            self._g_state.set(TIER_GAUGE.get(new, 3),
                              path=self.path_label_prefix + path)
        if self.tracer is not None:
            self.tracer.add(event, self.cycle_id, now, now, path=path,
                            from_tier=old, to_tier=new)
        (logger.warning if event == "degrade" else logger.info)(
            "supervised path %r %s: %s -> %s", path,
            "degraded" if event == "degrade" else "recovered", old, new)

    # -- watchdog -----------------------------------------------------------
    def _run_deadline(self, fn: Callable, deadline_s: Optional[float]):
        """Execute fn on a fresh watchdog thread, joined with the deadline.

        Per-call threads (≈50 µs spawn) rather than a pooled worker: the
        supervised paths nest — the device-mirror upload is supervised
        INSIDE the supervised assign dispatch — and a shared single worker
        would deadlock on itself. A call that blows its deadline is
        abandoned: the daemon thread keeps running the wedged dispatch to
        completion, its result is dropped, and the caller gets
        DeadlineExceeded instead of a wedged scheduling loop."""
        if not deadline_s or deadline_s <= 0:
            return fn()
        box: dict = {}
        done = threading.Event()

        def job():
            try:
                box["result"] = fn()
            except BaseException as e:  # delivered to the waiter
                box["error"] = e
            finally:
                # done.set + the zombie-exit decrement are atomic with the
                # waiter's stamp below, so the live count can't leak on the
                # finished-right-at-the-deadline race
                with self._mu:
                    done.set()
                    self._live_watchdogs -= 1
                    if getattr(worker, "_yk_abandoned", False):
                        self._live_abandoned -= 1
                    self._publish_watchdogs()

        worker = threading.Thread(target=job, name="supervised-dispatch",
                                  daemon=True)
        with self._mu:
            self._live_watchdogs += 1
            self._publish_watchdogs()
        worker.start()
        if not done.wait(deadline_s):
            with self._mu:
                abandoned = not done.is_set()
                if abandoned:
                    # stamp the zombie: its nested supervised calls bail
                    # instead of running (and recording outcomes) against
                    # the live state
                    worker._yk_abandoned = True
                    self._abandoned += 1
                    self._live_abandoned += 1
                    self._publish_watchdogs()
            if abandoned:
                raise DeadlineExceeded(
                    f"supervised dispatch exceeded its {deadline_s:g}s "
                    "deadline and was abandoned")
        if "error" in box:
            raise box["error"]
        return box["result"]

    # -- the supervised call ------------------------------------------------
    def allow(self, path: str, tier: str = "device",
              ladder: Sequence[str] = ("device",)) -> bool:
        """Gate for callers that skip dispatch entirely when a tier's circuit
        is open (the preempt path: an open device circuit means the host
        planner covers the cycle). An open circuit past its probe interval
        admits the call — that call is the probe."""
        if _call_abandoned():
            # a zombie must neither dispatch nor half-open/re-open live
            # circuits (the allow() analog of the execute()/_record() guard)
            return False
        with self._mu:
            self._register_ladder(path, ladder)
            br = self._breaker(path, tier)
            ok = br.allow(time.time())
            if ok and br.state == HALF_OPEN and not self._probe_budget():
                br.state = OPEN
                br.opened_at = time.time()
                return False
            return ok

    def _publish_watchdogs(self) -> None:
        """(mutex held) Refresh the watchdog_threads gauge. A shard's
        supervisor prefixes its state values like its path labels, so N
        shards sharing one registry keep distinct series."""
        if self._g_watchdogs is None:
            return
        p = self.path_label_prefix
        running = max(self._live_watchdogs - self._live_abandoned, 0)
        self._g_watchdogs.set(running, state=p + "running")
        self._g_watchdogs.set(self._live_abandoned, state=p + "abandoned")

    def watchdog_counts(self) -> Tuple[int, int]:
        """(running, abandoned) live watchdog threads — the chaos suite's
        no-thread-leak assertion reads this directly."""
        with self._mu:
            running = max(self._live_watchdogs - self._live_abandoned, 0)
            return running, self._live_abandoned

    def _probe_budget(self) -> bool:
        """(mutex held) Whether another half-open probe may run: refused
        while too many abandoned watchdogs are still wedged, so a permanent
        wedge can't grow zombies + orphaned mirrors without bound."""
        return self._live_abandoned < max(int(self.options.max_abandoned), 1)

    def current_tier(self, path: str,
                     ladder: Sequence[str] = ("device",)) -> str:
        with self._mu:
            self._register_ladder(path, ladder)
            return self._effective_tier(path)

    def execute(self, path: str, tiers: Sequence[Tuple[str, Callable]],
                start_tier: Optional[str] = None,
                deadline_s: Optional[float] = None,
                commit_success: bool = True):
        """Run one operation through the tier ladder.

        tiers: ordered [(tier_name, fn)] — fn performs the complete
        operation for that tier. Starts at the first tier whose circuit
        admits traffic (at or after start_tier); transient failures retry
        the same tier with jittered backoff; deadline/persistent failures
        (and exhausted retries) degrade to the next tier. Returns
        (result, tier). Raises AllTiersFailed (chained to the last error)
        when nothing answered.
        """
        if _call_abandoned():
            raise AbandonedDispatch(
                f"supervised path {path!r} invoked from an abandoned "
                "watchdog thread")
        ladder = tuple(t for t, _ in tiers)
        with self._mu:
            self._register_ladder(path, ladder)
        deadline_s = self.options.deadline_s if deadline_s is None else deadline_s
        skipping = start_tier is not None
        last_exc: Optional[BaseException] = None
        for tier, fn in tiers:
            if skipping:
                if tier != start_tier:
                    continue
                skipping = False
            with self._mu:
                br = self._breaker(path, tier)
                admitted = br.allow(time.time())
                probing = admitted and br.state == HALF_OPEN
                if probing and not self._probe_budget():
                    br.state = OPEN
                    br.opened_at = time.time()
                    admitted = False
            if not admitted:
                continue
            # probes answer "is the backend back?" — a healthy backend
            # replies from its jit cache in seconds, so they get a short
            # deadline instead of re-stalling a full dispatch deadline
            # against a still-wedged device on every probe interval
            tier_deadline = deadline_s
            if probing and deadline_s and self.options.probe_deadline_s:
                tier_deadline = min(deadline_s, self.options.probe_deadline_s)
            attempts = 0
            while True:
                try:
                    result = self._attempt(path, tier, fn, tier_deadline)
                except Exception as e:
                    last_exc = e
                    cls = classify_error(e)
                    self._record(path, tier, cls)
                    logger.warning(
                        "supervised %s/%s attempt %d failed (%s): %s: %s",
                        path, tier, attempts + 1, cls, type(e).__name__,
                        str(e)[:200])
                    if cls == TRANSIENT and attempts < self.options.max_retries:
                        with self._mu:
                            retry_ok = self._breaker(path, tier).allow(
                                time.time())
                        if retry_ok:
                            attempts += 1
                            time.sleep(self.options.backoff_base_s
                                       * (2 ** (attempts - 1))
                                       * (0.5 + random.random()))
                            continue
                    break  # degrade to the next tier
                self._record(path, tier, "ok", commit=commit_success)
                return result, tier
        hook = self.on_exhausted
        if hook is not None:
            try:
                hook(path)
            except Exception:
                logger.exception("on_exhausted hook failed for %s", path)
        raise AllTiersFailed(
            f"every tier of supervised path {path!r} failed") from last_exc

    def run(self, path: str, fn: Callable, tier: str = "device",
            deadline_s: Optional[float] = None, commit_success: bool = True):
        """Single-tier supervised call (upload, mesh, preempt paths).
        Re-raises the underlying error on failure."""
        try:
            result, _ = self.execute(path, [(tier, fn)],
                                     deadline_s=deadline_s,
                                     commit_success=commit_success)
            return result
        except AllTiersFailed as e:
            raise e.__cause__ if e.__cause__ is not None else e

    def _attempt(self, path: str, tier: str, fn: Callable,
                 deadline_s: Optional[float]):
        def wrapped():
            self.faults.on_attempt(path, tier)
            cm = self.dispatch_cm
            if cm is None:
                return fn()
            with cm():
                return fn()

        try:
            return self._run_deadline(wrapped, deadline_s)
        except DeadlineExceeded:
            hook = self.on_abandon
            if hook is not None:
                try:
                    hook(path, tier)
                except Exception:
                    logger.exception("on_abandon hook failed for %s/%s",
                                     path, tier)
            raise

    def _record(self, path: str, tier: str, outcome: str,
                commit: bool = True) -> None:
        if _call_abandoned():
            return  # a zombie's outcome must not move live circuits/metrics
        if self._m_dispatch is not None:
            self._m_dispatch.inc(path=self.path_label_prefix + path,
                                 outcome=outcome,
                                 policy=self.policy_label)
        with self._mu:
            br = self._breaker(path, tier)
            if outcome == "ok":
                if br.record_success(commit=commit):
                    self._note_transition(path, tier, CLOSED)
            else:
                # deadline counts as hard too: a wedged dispatch already
                # cost a full deadline of stall — paying that threshold
                # times before opening would stall scheduling for minutes
                if br.record_failure(time.time(),
                                     hard=(outcome in (PERSISTENT, DEADLINE))):
                    self._note_transition(path, tier, OPEN)

    # -- introspection ------------------------------------------------------
    def degradations(self) -> List[dict]:
        """Per-path tier changes, oldest first (bench JSON + health)."""
        with self._mu:
            return list(self._transitions)

    def snapshot(self) -> dict:
        """Health-report view: per-path tier + circuit states."""
        with self._mu:
            out: Dict[str, dict] = {}
            for path, ladder in self._ladders.items():
                out[path] = {
                    "tier": self._tier_state.get(path, ladder[0]),
                    "ladder": list(ladder),
                    "circuits": {
                        tier: {"state": self._breaker(path, tier).state,
                               "failures": self._breaker(path, tier).failures}
                        for tier in ladder},
                }
            if self._abandoned:
                out["_abandoned_dispatches"] = self._abandoned
            if self._live_abandoned:
                out["_live_abandoned"] = self._live_abandoned
            return out

    def degraded_paths(self) -> Dict[str, str]:
        """{path: tier} for every path not on its primary tier."""
        with self._mu:
            return {p: t for p, t in self._tier_state.items()
                    if self._ladders.get(p, (t,))[0] != t}

    def close(self) -> None:
        """No persistent threads to reap (watchdog threads are per-call
        daemons); kept as the lifecycle seam the core's stop() calls."""
