"""The host-tier assignment solve: the degradation ladder's last resort.

When both device tiers (native backend, CPU-backend re-jit) are circuit-open
the scheduler must still place pods — the reference scheduler IS a host
loop, so the exact host path exists by construction: the same predicate
helpers the required-node path and the differential oracle
(tests/test_solver_differential.py) already use, driven in the solve's rank
order over the encoder's quantized tensors.

Arithmetic matches the device solve deliberately: quantized int fit against
floor(free) - ceil(overlay) (the shared ops.assign.apply_free_delta), node
scores from the same normalized-free formula (models/policies.py), ties
broken by lowest row index (the device's stable argsort does the same).
Feasibility matches too: the per-group host mask the device solve ANDs in
(volume/PV node affinity, DRA, overflowed locality groups) plus the exact
per-pod locality evaluation (snapshot.locality.host_locality_mask) with an
intra-solve placement overlay. For homogeneous batches this reproduces the
device water-fill placement exactly; for constraint-heavy batches it stays
feasible-correct (every placement passes the host predicates) — slower and
possibly coarser, never silent.

Cost: O(pods × nodes) Python/numpy — acceptable for an emergency tier whose
job is liveness, not throughput.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from yunikorn_tpu.ops.assign import apply_free_delta
from yunikorn_tpu.ops.host_predicates import (
    host_ports_of,
    node_selector_matches,
    tolerates_node_taints,
)
from yunikorn_tpu.snapshot.locality import (
    _pod_constraints,
    all_anti_terms,
    host_locality_mask,
)


def host_assign(admitted: List, batch, encoder, cache,
                policy: str = "binpacking",
                free_delta: Optional[np.ndarray] = None,
                node_mask: Optional[np.ndarray] = None,
                ports_delta: Optional[np.ndarray] = None) -> np.ndarray:
    """Place one batch entirely on the host. Returns [num_pods] int32 of
    node rows (-1 = unplaced), aligned with `admitted` like the device
    solve's `assigned`."""
    na = encoder.nodes
    M = na.capacity
    n = batch.num_pods
    assigned = np.full((n,), -1, np.int32)
    if n == 0:
        return assigned

    ok = na.valid & na.schedulable
    if node_mask is not None:
        ok = ok & node_mask[:M]
    free = np.floor(na.free).astype(np.int64)
    if free_delta is not None:
        free = apply_free_delta(free, free_delta)
    cap = np.maximum(na.capacity_arr.astype(np.float64), 1.0)
    req = np.ceil(batch.req[:n]).astype(np.int64)
    R = min(req.shape[1], free.shape[1])

    # per-group host feasibility the device solve also ANDs in
    # (ops.assign._finish_solve_args): volume/PV node affinity, DRA,
    # host-evaluated affinity operators, overflowed locality groups
    hm = batch.g_host_mask
    hm_cols = 0 if hm is None else min(M, hm.shape[1])

    # exact per-pod locality (spread / affinity / anti-affinity + symmetry),
    # the host twin of the in-solve _loc_rules_mask; placements made by THIS
    # solve feed back through the extra_placed overlay
    sym_terms = all_anti_terms(cache)
    loc_overlay: List = []  # [(Pod, node_name)] placed by this solve

    # host-port occupancy: cache-visible pods + pods this solve places
    ports_used = {}  # row -> set[(proto, port)]

    def node_ports(row: int, name: str) -> set:
        cached = ports_used.get(row)
        if cached is not None:
            return cached
        used: set = set()
        info = cache.snapshot_node(name)
        if info is not None:
            for p in info.pods.values():
                used |= host_ports_of(p)
        ports_used[row] = used
        return used

    order = np.argsort(batch.rank[:n], kind="stable")
    for i in order.tolist():
        if not batch.valid[i]:
            continue
        ask = admitted[i] if i < len(admitted) else None
        pod = getattr(ask, "pod", None)
        row = req[i, :R]
        feasible = ok & (free[:, :R] >= row).all(axis=1)
        if hm is not None:
            gmask = np.zeros(M, bool)
            gmask[:hm_cols] = hm[int(batch.group_id[i]), :hm_cols]
            feasible &= gmask
        if pod is not None and (_pod_constraints(pod)
                                or any(t.counts_pod(pod)
                                       for t in sym_terms)):
            feasible &= host_locality_mask(
                pod, cache, na, extra_placed=loc_overlay)[:M]
        if not feasible.any():
            continue
        # same score the device computes per round (models/policies.py):
        # mean normalized free, packed for binpacking/align, spread inverted
        norm_free = (free.astype(np.float64) / cap).mean(axis=1)
        scores = norm_free if policy == "spread" else 1.0 - norm_free
        scores = np.where(feasible, scores, -np.inf)
        wanted_ports = host_ports_of(pod) if pod is not None else set()
        # committed-but-not-assumed allocations hold ports the cache can't
        # see yet — the same [capacity, Wp] u32 overlay the device tiers
        # receive as ports_delta (core._inflight_ports)
        inflight_mask = None
        if ports_delta is not None and wanted_ports:
            from yunikorn_tpu.snapshot.vocab import port_bit

            pv = encoder.vocabs.ports
            inflight_mask = np.zeros(ports_delta.shape[1], np.uint32)
            for proto, port in wanted_ports:
                b = pv.lookup(port_bit(proto, port))
                if b >= 0:
                    inflight_mask[b // 32] |= np.uint32(1 << (b % 32))
        placed = False
        for _ in range(int(feasible.sum())):
            best = int(np.argmax(scores))  # ties -> lowest row index
            if not np.isfinite(scores[best]):
                break
            name = na.name_of(best)
            if name is None:
                scores[best] = -np.inf
                continue
            if (inflight_mask is not None and best < ports_delta.shape[0]
                    and (ports_delta[best] & inflight_mask).any()):
                scores[best] = -np.inf
                continue
            if pod is not None:
                info = cache.snapshot_node(name)
                node = info.node if info is not None else None
                if node is not None and (
                        not node_selector_matches(pod, node)
                        or not tolerates_node_taints(pod, node)
                        or (wanted_ports
                            and wanted_ports & node_ports(best, name))):
                    scores[best] = -np.inf
                    continue
            assigned[i] = best
            free[best, :R] -= row
            if wanted_ports:
                node_ports(best, name)
                ports_used[best] |= wanted_ports
            if pod is not None:
                loc_overlay.append((pod, name))
            placed = True
            break
        if not placed:
            continue
    return assigned
