"""A small event-driven finite state machine.

Equivalent in role to the reference's `looplab/fsm` dependency, which drives the
Application and Task lifecycles (reference: pkg/cache/application_state.go:364-470,
pkg/cache/task_state.go:322-449). The design is deliberately minimal: transitions
are declared as (event, sources, destination), callbacks are keyed the same way the
reference keys them ("enter_state", "leave_<state>", "after_<event>", ...), and an
`Event` call either transitions or raises. No threading — the dispatcher serializes
events per object, exactly like the reference's single consumer goroutine.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Sequence


class FSMError(Exception):
    """Base error for FSM misuse."""


class InvalidEventError(FSMError):
    """Event is not permitted from the current state."""

    def __init__(self, event: str, state: str):
        super().__init__(f"event {event} inappropriate in current state {state}")
        self.event = event
        self.state = state


class UnknownEventError(FSMError):
    def __init__(self, event: str):
        super().__init__(f"event {event} does not exist")
        self.event = event


@dataclasses.dataclass(frozen=True)
class Transition:
    """One row of the transition table."""

    event: str
    sources: Sequence[str]
    destination: str


class EventContext:
    """Passed to every callback; mirrors looplab/fsm's *fsm.Event argument."""

    __slots__ = ("fsm", "event", "src", "dst", "args")

    def __init__(self, fsm: "FSM", event: str, src: str, dst: str, args: tuple):
        self.fsm = fsm
        self.event = event
        self.src = src
        self.dst = dst
        self.args = args


# Callback key prefixes (matching looplab/fsm naming used throughout the reference).
BEFORE = "before_"  # before_<event>
LEAVE = "leave_"    # leave_<state>
ENTER = "enter_"    # enter_<state>
AFTER = "after_"    # after_<event>
ENTER_STATE = "enter_state"  # fires on every state change


class FSM:
    """Event-driven FSM with looplab-style callbacks.

    callbacks maps keys like ``"enter_Running"``, ``"before_SubmitTask"``,
    ``"enter_state"`` to ``fn(EventContext) -> None``.
    """

    def __init__(
        self,
        initial: str,
        transitions: Sequence[Transition],
        callbacks: Dict[str, Callable[[EventContext], None]] | None = None,
    ):
        self._current = initial
        self._table: Dict[str, Dict[str, str]] = {}
        self._events: set[str] = set()
        for t in transitions:
            self._events.add(t.event)
            for src in t.sources:
                self._table.setdefault(t.event, {})[src] = t.destination
        self._callbacks = dict(callbacks or {})

    @property
    def current(self) -> str:
        return self._current

    def set_current(self, state: str) -> None:
        """Force the state (used only by recovery fast-forward paths)."""
        self._current = state

    def is_state(self, *states: str) -> bool:
        return self._current in states

    def can(self, event: str) -> bool:
        return self._current in self._table.get(event, {})

    def event(self, event: str, *args: Any) -> bool:
        """Fire an event. Returns True if a transition happened.

        Raises InvalidEventError when the event is known but not allowed from the
        current state, UnknownEventError when it was never declared.
        """
        if event not in self._events:
            raise UnknownEventError(event)
        dst = self._table[event].get(self._current)
        if dst is None:
            raise InvalidEventError(event, self._current)
        src = self._current
        ctx = EventContext(self, event, src, dst, args)
        self._fire(BEFORE + event, ctx)
        changed = src != dst
        if changed:
            self._fire(LEAVE + src, ctx)
        self._current = dst
        if changed:
            self._fire(ENTER + dst, ctx)
            self._fire(ENTER_STATE, ctx)
        self._fire(AFTER + event, ctx)
        return changed

    def _fire(self, key: str, ctx: EventContext) -> None:
        cb = self._callbacks.get(key)
        if cb is not None:
            cb(ctx)


def all_states(transitions: Sequence[Transition]) -> List[str]:
    seen: Dict[str, None] = {}
    for t in transitions:
        for s in t.sources:
            seen.setdefault(s)
        seen.setdefault(t.destination)
    return list(seen)
