"""A bounded daemon-thread worker pool.

concurrent.futures.ThreadPoolExecutor spawns NON-daemon workers and joins
them at interpreter exit, so one hung task (e.g. a bind blocked on an
unresponsive API server) would block process shutdown forever. This pool
keeps the bounded-concurrency property with daemon workers and a plain
drop-after-shutdown submit, which is the semantics the bind path wants:
after shutdown the caller's failure handling is responsible, not the pool.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from yunikorn_tpu.log.logger import log

logger = log("shim.utils")


class DaemonPool:
    def __init__(self, max_workers: int = 32, name: str = "worker"):
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._shutdown = threading.Event()
        self._threads = []
        for i in range(max_workers):
            t = threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _run(self) -> None:
        while True:
            fn = self._queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                logger.exception("pool task failed")

    def submit(self, fn: Callable[[], None]) -> bool:
        """Enqueue fn; returns False (not an exception) after shutdown so
        callers can run their own failure path."""
        if self._shutdown.is_set():
            return False
        self._queue.put(fn)
        return True

    def shutdown(self) -> None:
        """Stop accepting work and wake idle workers; running tasks are
        daemon threads and never block interpreter exit."""
        self._shutdown.set()
        for _ in self._threads:
            self._queue.put(None)
