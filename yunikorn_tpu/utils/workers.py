"""A bounded daemon-thread worker pool.

concurrent.futures.ThreadPoolExecutor spawns NON-daemon workers and joins
them at interpreter exit, so one hung task (e.g. a bind blocked on an
unresponsive API server) would block process shutdown forever. This pool
keeps the bounded-concurrency property with daemon workers and a plain
drop-after-shutdown submit, which is the semantics the bind path wants:
after shutdown the caller's failure handling is responsible, not the pool.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Optional

from yunikorn_tpu.log.logger import log

logger = log("shim.utils")


class DaemonPool:
    def __init__(self, max_workers: int = 32, name: str = "worker"):
        self._queue: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
        self._shutdown = threading.Event()
        self._threads = []
        for i in range(max_workers):
            t = threading.Thread(target=self._run, name=f"{name}-{i}", daemon=True)
            t.start()
            self._threads.append(t)

    def _run(self) -> None:
        while True:
            fn = self._queue.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                logger.exception("pool task failed")

    def submit(self, fn: Callable[[], None], key=None, shard=None) -> bool:
        """Enqueue fn; returns False (not an exception) after shutdown so
        callers can run their own failure path. key/shard are accepted for
        ShardedBindPool signature compatibility and ignored (one queue)."""
        if self._shutdown.is_set():
            return False
        self._queue.put(fn)
        return True

    def shutdown(self) -> None:
        """Stop accepting work and wake idle workers; running tasks are
        daemon threads and never block interpreter exit."""
        self._shutdown.set()
        for _ in self._threads:
            self._queue.put(None)


class ShardedBindPool:
    """Per-shard bind worker groups with per-key FIFO ordering.

    The round-20 async front end drains each shard's scheduling output
    concurrently, so one shared bind queue re-serializes what the shards
    just parallelized — and worse, a bind storm on one shard's nodes
    starves every other shard's binds behind it in the single FIFO. This
    pool gives each shard its own small worker group (AllocationResponse
    binds fan out per shard) while keeping the ONE ordering that matters:
    tasks submitted with the same key (the pod UID / task_id) run in
    submission order, never concurrently.

    Ordering is by striping, not bookkeeping: each worker owns a private
    queue and a key always hashes to the same worker, so same-key tasks
    share one FIFO end-to-end. Cross-key ordering is explicitly NOT
    promised — that is the parallelism. Keyless submits round-robin.

    Same lifecycle contract as DaemonPool: daemon workers (a bind hung on
    an unresponsive API server never blocks interpreter exit), and
    submit() returns False after shutdown so the caller runs its own
    failure path instead of leaking a forever-ALLOCATED task.
    """

    def __init__(self, n_shards: int = 1, workers_per_shard: int = 8,
                 name: str = "bind"):
        self.n = max(1, int(n_shards))
        self.workers_per_shard = max(1, int(workers_per_shard))
        self._shutdown = threading.Event()
        self._rr = 0
        self._mu = threading.Lock()        # depth counters + round-robin
        self._depth = [0] * self.n         # queued + inflight, per shard
        self._m_depth = None
        self._m_tasks = None
        self._threads = []
        self._lanes = []                   # [shard][worker] -> private queue
        for s in range(self.n):
            lanes = []
            for i in range(self.workers_per_shard):
                q: "queue.Queue[Optional[Callable[[], None]]]" = queue.Queue()
                t = threading.Thread(target=self._run, args=(s, q),
                                     name=f"{name}-s{s}w{i}", daemon=True)
                t.start()
                lanes.append(q)
                self._threads.append(t)
            self._lanes.append(lanes)

    def attach_metrics(self, registry) -> None:
        """bind_pool_depth{shard} (queued+inflight) and
        bind_pool_tasks_total{shard} into the core's MetricsRegistry; both
        publish stable zeros from boot so dashboards never gap."""
        self._m_depth = registry.gauge(
            "bind_pool_depth", "bind tasks queued or running, per shard",
            labelnames=("shard",))
        self._m_tasks = registry.counter(
            "bind_pool_tasks_total", "bind tasks completed, per shard",
            labelnames=("shard",))
        for s in range(self.n):
            self._m_depth.set(0, shard=str(s))
            self._m_tasks.inc(0, shard=str(s))

    def _run(self, shard: int, q) -> None:
        while True:
            fn = q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:
                logger.exception("bind pool task failed (shard %d)", shard)
            with self._mu:
                self._depth[shard] -= 1
                depth = self._depth[shard]
            if self._m_depth is not None:
                self._m_depth.set(depth, shard=str(shard))
            if self._m_tasks is not None:
                self._m_tasks.inc(shard=str(shard))

    def submit(self, fn: Callable[[], None], key=None, shard=None) -> bool:
        """Enqueue fn on `shard`'s worker group (0 when unattributed).
        Same-`key` submits land on the same worker — per-key FIFO."""
        if self._shutdown.is_set():
            return False
        s = 0 if shard is None else int(shard) % self.n
        if key is not None:
            import zlib

            lane = zlib.crc32(str(key).encode()) % self.workers_per_shard
        else:
            with self._mu:
                lane = self._rr % self.workers_per_shard
                self._rr += 1
        with self._mu:
            self._depth[s] += 1
            depth = self._depth[s]
        self._lanes[s][lane].put(fn)
        if self._m_depth is not None:
            self._m_depth.set(depth, shard=str(s))
        return True

    def depth(self, shard: int = 0) -> int:
        with self._mu:
            return self._depth[int(shard) % self.n]

    def stats(self) -> dict:
        with self._mu:
            return {"shards": self.n,
                    "workers_per_shard": self.workers_per_shard,
                    "depth": list(self._depth)}

    def shutdown(self) -> None:
        self._shutdown.set()
        for lanes in self._lanes:
            for q in lanes:
                q.put(None)
