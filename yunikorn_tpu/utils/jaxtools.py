"""JAX runtime configuration helpers.

Central place for compilation-cache setup: solver shapes are bucketed, so every
distinct (N, M, G, ...) bucket pays one XLA compile — with the persistent cache
enabled that cost is paid once per machine, not once per process. Called by the
core scheduler, bench.py and the graft entry before the first solve.
"""
from __future__ import annotations

import os
import re

_initialized = False


def force_cpu_platform(n_devices: int = 8) -> None:
    """Force JAX onto a virtual n-device CPU platform, beating the axon plugin.

    The environment's axon TPU plugin registers at interpreter start and sets
    jax_platforms via jax.config, which overrides the JAX_PLATFORMS env var —
    so both the env var *and* the config key must be (re)forced before the
    backend initializes. If XLA_FLAGS already pins a different
    host-platform device count, it is rewritten, not kept.

    Shared by the root conftest.py, __graft_entry__.dryrun_multichip and any
    CPU-only script; must run before the first backend use.
    """
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


# What a dial probe runs: a fresh process dials the backend and reports the
# platform it got. Probing in a SUBPROCESS matters because a wedged TPU-relay
# claim BLOCKS jax.devices() indefinitely (observed: a 1502 s hang inside the
# claim) and cannot be interrupted in-process. Shared by bench.py,
# scripts/tpu_ab.py, and backend_or_cpu below — one probe, one behavior.
_PROBE_SRC = (
    "import jax\n"
    "ds = jax.devices()\n"
    "print(ds[0].platform, len(ds), flush=True)\n"
)


def probe_backend(timeout: float):
    """Dial the JAX backend in a subprocess with its own deadline.

    Returns (platform, n_devices, cause): platform is None when the dial
    failed, with `cause` a one-line reason for the attempt log."""
    import subprocess
    import sys

    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC], capture_output=True,
            text=True, timeout=timeout)
    except (subprocess.TimeoutExpired, OSError):
        return None, 0, (f"dial timed out after {timeout:.0f}s "
                         "(relay claim wedged or queued)")
    if r.returncode != 0:
        tail = (r.stderr or r.stdout or "").strip().splitlines()
        return None, 0, (tail[-1][:300] if tail else f"exit {r.returncode}")
    try:
        platform, n = r.stdout.split()[:2]
        return platform, int(n), "ok"
    except (ValueError, IndexError):
        return None, 0, f"unparseable probe output: {r.stdout[:200]!r}"


def backend_or_cpu() -> str:
    """Initialize the default JAX backend; fall back to CPU when the TPU
    relay is unavailable. Returns the platform in use.

    When a non-CPU platform might dial the relay, a probe_backend subprocess
    with its own deadline (YK_BACKEND_PROBE_TIMEOUT, default 120 s) decides
    whether the in-process dial is safe; on probe failure the process forces
    CPU without ever dialing."""
    import jax

    platforms = jax.config.jax_platforms or ""
    if platforms.split(",")[0] != "cpu":
        import os

        timeout = float(os.environ.get("YK_BACKEND_PROBE_TIMEOUT", 120))
        platform, _, cause = probe_backend(timeout)
        if platform is None:
            import logging

            logging.getLogger(__name__).warning(
                "backend probe failed within %.0fs (%s); forcing CPU without "
                "dialing — solves will run minutes-slow until the TPU "
                "returns", timeout, cause)
            jax.config.update("jax_platforms", "cpu")
            return jax.devices("cpu")[0].platform
    try:
        return jax.devices()[0].platform
    except Exception as e:
        import logging

        logging.getLogger(__name__).warning(
            "default JAX backend unavailable (%s: %s); falling back to CPU — "
            "solves will run minutes-slow until the TPU returns",
            type(e).__name__, e)
        jax.config.update("jax_platforms", "cpu")
        return jax.devices("cpu")[0].platform


def warm_bucket(n_nodes: int, n_pods: int, core=None) -> None:
    """Compile (or AOT-store-load) one standard solve bucket's variants.

    Builds throwaway synthetic problems through the real encoder and
    compile_only-routes the solve for the static variants production uses —
    both nodesort policies, with and without soft/locality constraints.
    With an AOT runtime installed (aot/), compile_only checks the store
    first: a prebuilt bucket LOADS its executables in milliseconds instead
    of re-compiling, and a fresh compile is serialized back into the store.
    Isolated caches/encoders; never touches live state. Shared by the
    background prewarm thread (prewarm_buckets) and the offline builder
    (scripts/aot_build.py), so the two cannot drift on variant coverage."""
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.objects import (Affinity, NodeSelectorRequirement,
                                             NodeSelectorTerm,
                                             TopologySpreadConstraint)
    from yunikorn_tpu.common.resource import get_pod_resource
    from yunikorn_tpu.common.si import AllocationAsk
    from yunikorn_tpu.ops.assign import solve_batch
    from yunikorn_tpu.snapshot.encoder import SnapshotEncoder

    cache = SchedulerCache()
    for node in make_kwok_nodes(n_nodes):
        cache.update_node(node)
    enc = SnapshotEncoder(cache)
    enc.sync_nodes(full=True)
    pods = make_sleep_pods(n_pods, "prewarm", queue="root.prewarm")
    # make the last pod carry soft + locality constraints so the
    # locality/soft static variants of the solve compile too — those are
    # exactly the configurations whose first cycle hurts the most
    rich = pods[-1]
    rich.spec.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key="zone", when_unsatisfiable="ScheduleAnyway",
        label_selector={"matchLabels": {"prewarm": "1"}})]
    rich.metadata.labels["prewarm"] = "1"
    rich.spec.affinity = Affinity(node_preferred_terms=[
        (10, NodeSelectorTerm(match_expressions=[
            NodeSelectorRequirement("zone", "In", ["z0"])]))])
    asks = [AllocationAsk(p.uid, "prewarm", get_pod_resource(p), pod=p)
            for p in pods]
    plain = enc.build_batch(asks[:-1])
    rich_batch = enc.build_batch(asks)
    # resolve the production variant when a core was handed in; the
    # no-core fallback takes SolverOptions() so defaults cannot drift
    from yunikorn_tpu.core.scheduler import SolverOptions

    so = SolverOptions()
    use_pallas, mesh = False, None
    if core is not None:
        core._resolve_solver_runtime()
        so = core.solver
        use_pallas, mesh = core._use_pallas, core._mesh
    max_rounds, chunk = so.max_rounds, so.chunk
    use_mesh = (mesh is not None
                and enc.nodes.capacity % mesh.devices.size == 0)
    # AOT compile (no execution): both nodesort policies × plain and
    # soft/locality variants — the static combinations production uses.
    # This also covers the pipelined cycle's persistent-device-buffer
    # path with no extra work: device-resident and host node inputs have
    # identical avals (ops.assign._finish_solve_args), so they share one
    # compiled program — there is no separate variant to warm, and
    # production's own DeviceNodeState does its first upload lazily.
    for policy in ("binpacking", "spread"):
        for b in (plain, rich_batch):
            if use_mesh:
                from yunikorn_tpu.parallel.mesh import solve_sharded

                solve_sharded(b, enc.nodes, mesh, max_rounds=max_rounds,
                              chunk=chunk, policy=policy, compile_only=True,
                              max_batch=so.max_batch)
            else:
                solve_batch(b, enc.nodes, policy=policy,
                            max_rounds=max_rounds, chunk=chunk,
                            use_pallas=use_pallas, compile_only=True,
                            max_batch=so.max_batch)


def prewarm_buckets(spec: str, results: "list | None" = None,
                    core=None) -> "object":
    """Warm standard solve buckets in a background thread (see warm_bucket).

    spec: comma-separated "NODESxPODS" pairs (e.g. "1024x4096,16384x65536").
    With an AOT store attached the warmup is artifact LOADS, not compiles —
    a prebuilt process is solve-ready in seconds. Without one this is the
    legacy trace+compile per process. Returns the daemon thread (join it in
    tests).

    core: the production CoreScheduler, when available — prewarm then
    compiles the VARIANT production will run (conf-driven max_rounds/chunk,
    sharded over the resolved mesh, pallas gate, and the pipelined cycle's
    persistent device-resident node tensors) instead of solve_batch
    defaults, so the warmed cache entries actually match the first cycle's
    program."""
    import threading

    def run():
        ensure_compilation_cache()
        import logging

        for pair in spec.split(","):
            pair = pair.strip().lower()
            if not pair:
                continue
            try:
                nodes_s, pods_s = pair.split("x")
                n_nodes, n_pods = int(nodes_s), int(pods_s)
            except ValueError:
                logging.getLogger(__name__).warning(
                    "invalid prewarm bucket %r (want NODESxPODS)", pair)
                continue
            try:  # per bucket: one failure must not abort the rest
                warm_bucket(n_nodes, n_pods, core=core)
                if results is not None:
                    results.append((n_nodes, n_pods, True))
            except Exception:
                logging.getLogger(__name__).exception(
                    "prewarm of bucket %dx%d failed", n_nodes, n_pods)
                if results is not None:
                    results.append((n_nodes, n_pods, False))

    t = threading.Thread(target=run, name="bucket-prewarm", daemon=True)
    t.start()
    return t


def compile_cache_dir() -> str:
    """The persistent XLA compilation cache directory (single source of the
    env-var name + default; bench.py counts entries here)."""
    return os.environ.get("YUNIKORN_TPU_COMPILE_CACHE",
                          os.path.expanduser("~/.cache/yunikorn_tpu_xla"))


def ensure_compilation_cache(path: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    cache_dir = path or compile_cache_dir()
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail on it
        pass
    _initialized = True
