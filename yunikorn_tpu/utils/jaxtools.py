"""JAX runtime configuration helpers.

Central place for compilation-cache setup: solver shapes are bucketed, so every
distinct (N, M, G, ...) bucket pays one XLA compile — with the persistent cache
enabled that cost is paid once per machine, not once per process. Called by the
core scheduler, bench.py and the graft entry before the first solve.
"""
from __future__ import annotations

import os

_initialized = False


def ensure_compilation_cache(path: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    cache_dir = path or os.environ.get(
        "YUNIKORN_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/yunikorn_tpu_xla")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail on it
        pass
    _initialized = True
