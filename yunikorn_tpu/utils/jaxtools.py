"""JAX runtime configuration helpers.

Central place for compilation-cache setup: solver shapes are bucketed, so every
distinct (N, M, G, ...) bucket pays one XLA compile — with the persistent cache
enabled that cost is paid once per machine, not once per process. Called by the
core scheduler, bench.py and the graft entry before the first solve.
"""
from __future__ import annotations

import os
import re

_initialized = False


def force_cpu_platform(n_devices: int = 8) -> None:
    """Force JAX onto a virtual n-device CPU platform, beating the axon plugin.

    The environment's axon TPU plugin registers at interpreter start and sets
    jax_platforms via jax.config, which overrides the JAX_PLATFORMS env var —
    so both the env var *and* the config key must be (re)forced before the
    backend initializes. If XLA_FLAGS already pins a different
    host-platform device count, it is rewritten, not kept.

    Shared by the root conftest.py, __graft_entry__.dryrun_multichip and any
    CPU-only script; must run before the first backend use.
    """
    flag = f"--xla_force_host_platform_device_count={n_devices}"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", flag, flags)
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")


def ensure_compilation_cache(path: str | None = None) -> None:
    global _initialized
    if _initialized:
        return
    import jax

    cache_dir = path or os.environ.get(
        "YUNIKORN_TPU_COMPILE_CACHE", os.path.expanduser("~/.cache/yunikorn_tpu_xla")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization; never fail on it
        pass
    _initialized = True
