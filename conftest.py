"""Root conftest: force JAX onto a virtual 8-device CPU platform for tests.

The environment ships an axon TPU plugin that registers at interpreter start
(sitecustomize) and forces jax_platforms="axon,cpu" via jax.config — overriding
the JAX_PLATFORMS env var. Tests must be hermetic (and must not dial the TPU
relay), so this conftest re-forces the config to cpu before any backend is
initialized. Only bench.py keeps the real backend; the graft entry also forces
the virtual-CPU platform (its job is validating the multi-chip sharding).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from yunikorn_tpu.utils.jaxtools import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

# Bound the process's memory-map count: every LLVM-JIT'd XLA executable adds
# mappings, the full suite compiles hundreds of programs, and once the process
# nears vm.max_map_count (65530 here) further compiles SEGFAULT inside XLA
# (observed at ~607/628 tests: >50k maps and climbing). Dropping JAX's
# executable caches at each module boundary unmaps finished modules' programs;
# cross-module recompiles are mostly avoided by the persistent compilation
# cache (loads, not compiles).
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    import jax

    jax.clear_caches()


# ---------------------------------------------------------------------------
# Durations ledger guard: tier-1 sits at ~97-101% of its 870 s wall, so a
# multi-second test that forgets @pytest.mark.slow silently eats the margin
# until the whole run times out. When a measured-durations ledger exists
# (tests/.durations.json: {nodeid: mean seconds}, generated offline from
# `pytest --durations=0` output or a CI timing export), collection FAILS for
# any collected non-slow test whose recorded average exceeds the budget.
# The ledger is not checked in — without it the guard is inert, so tier-1
# can never break on a stale file.
# ---------------------------------------------------------------------------
DURATIONS_LEDGER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "tests", ".durations.json")
SLOW_BUDGET_S = 2.0


def overlong_unmarked(entries, ledger, budget=SLOW_BUDGET_S):
    """Pure core of the guard (unit-tested): entries is
    [(nodeid, has_slow_mark)], ledger {nodeid: mean seconds}. Returns the
    nodeids that exceed the budget without the slow mark, with their
    recorded averages."""
    return [(nid, ledger[nid]) for nid, has_slow in entries
            if not has_slow and ledger.get(nid, 0.0) > budget]


def pytest_collection_modifyitems(config, items):
    if not os.path.exists(DURATIONS_LEDGER):
        return
    import json

    with open(DURATIONS_LEDGER) as f:
        ledger = json.load(f)
    bad = overlong_unmarked(
        [(it.nodeid, it.get_closest_marker("slow") is not None)
         for it in items], ledger)
    if bad:
        lines = "\n".join(f"  {nid}: {avg:.1f}s" for nid, avg in bad)
        raise pytest.UsageError(
            f"tests averaging > {SLOW_BUDGET_S:.0f}s must carry "
            f"@pytest.mark.slow (tier-1 runs -m 'not slow'):\n{lines}")
