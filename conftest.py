"""Root conftest: force JAX onto a virtual 8-device CPU platform for tests.

The environment ships an axon TPU plugin that registers at interpreter start
(sitecustomize) and forces jax_platforms="axon,cpu" via jax.config — overriding
the JAX_PLATFORMS env var. Tests must be hermetic (and must not dial the TPU
relay), so this conftest re-forces the config to cpu before any backend is
initialized. Bench (bench.py) and the graft entry run outside pytest and keep
the real TPU.
"""
import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402  (already imported by sitecustomize; cheap)

jax.config.update("jax_platforms", "cpu")
