"""Root conftest: force JAX onto a virtual 8-device CPU platform for tests.

Must run before jax is imported anywhere. Bench (bench.py) and the graft entry
are run outside pytest and therefore use the real TPU.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
