"""Root conftest: force JAX onto a virtual 8-device CPU platform for tests.

The environment ships an axon TPU plugin that registers at interpreter start
(sitecustomize) and forces jax_platforms="axon,cpu" via jax.config — overriding
the JAX_PLATFORMS env var. Tests must be hermetic (and must not dial the TPU
relay), so this conftest re-forces the config to cpu before any backend is
initialized. Only bench.py keeps the real backend; the graft entry also forces
the virtual-CPU platform (its job is validating the multi-chip sharding).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from yunikorn_tpu.utils.jaxtools import force_cpu_platform  # noqa: E402

force_cpu_platform(8)

# Bound the process's memory-map count: every LLVM-JIT'd XLA executable adds
# mappings, the full suite compiles hundreds of programs, and once the process
# nears vm.max_map_count (65530 here) further compiles SEGFAULT inside XLA
# (observed at ~607/628 tests: >50k maps and climbing). Dropping JAX's
# executable caches at each module boundary unmaps finished modules' programs;
# cross-module recompiles are mostly avoided by the persistent compilation
# cache (loads, not compiles).
import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    import jax

    jax.clear_caches()
