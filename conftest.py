"""Root conftest: force JAX onto a virtual 8-device CPU platform for tests.

The environment ships an axon TPU plugin that registers at interpreter start
(sitecustomize) and forces jax_platforms="axon,cpu" via jax.config — overriding
the JAX_PLATFORMS env var. Tests must be hermetic (and must not dial the TPU
relay), so this conftest re-forces the config to cpu before any backend is
initialized. Only bench.py keeps the real backend; the graft entry also forces
the virtual-CPU platform (its job is validating the multi-chip sharding).
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from yunikorn_tpu.utils.jaxtools import force_cpu_platform  # noqa: E402

force_cpu_platform(8)
