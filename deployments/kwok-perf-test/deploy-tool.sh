#!/usr/bin/env bash
# Create or delete sleep-pod deployments targeting the yunikorn-tpu scheduler
# (analog of the reference's deploy-tool.sh:35-67 workload driver).
#
# Each deployment is one application: pods carry the applicationId/queue
# labels the shim's metadata extraction reads, set schedulerName so the
# default scheduler leaves them alone, and tolerate the kwok node taint.
#
# Usage:
#   ./deploy-tool.sh [-i <seconds>] <deployment_count> <replicas_per_deployment>
#   ./deploy-tool.sh -d <deployment_count>            # delete
set -euo pipefail

SCHEDULER_NAME="${SCHEDULER_NAME:-yunikorn}"
QUEUE="${QUEUE:-root.default}"
delete=false
interval=0

while getopts ":di:" opt; do
  case $opt in
    d) delete=true ;;
    i) interval="$OPTARG" ;;
    *) echo "usage: $0 [-d] [-i interval] <count> [replicas]" >&2; exit 1 ;;
  esac
done
shift $((OPTIND - 1))
COUNT="${1:?usage: $0 [-d] [-i interval] <count> [replicas]}"

if $delete; then
  for ((i = 0; i < COUNT; i++)); do
    kubectl delete "deploy/sleep-app-${i}" --ignore-not-found
  done
  exit 0
fi

REPLICAS="${2:?replicas_per_deployment required when creating}"
for ((i = 0; i < COUNT; i++)); do
  kubectl apply -f - <<EOF
apiVersion: apps/v1
kind: Deployment
metadata:
  name: sleep-app-${i}
  labels: {app: sleep, applicationId: "sleep-app-${i}", queue: "${QUEUE}"}
spec:
  replicas: ${REPLICAS}
  selector:
    matchLabels: {deployment: sleep-app-${i}}
  template:
    metadata:
      labels:
        deployment: sleep-app-${i}
        applicationId: "sleep-app-${i}"
        queue: "${QUEUE}"
    spec:
      schedulerName: ${SCHEDULER_NAME}
      containers:
        - name: sleep
          image: alpine:latest
          command: ["sleep", "300"]
          resources:
            requests: {cpu: 100m, memory: 128Mi}
      tolerations:
        - {key: kwok.x-k8s.io/node, operator: Exists, effect: NoSchedule}
EOF
  [ "$interval" != 0 ] && sleep "$interval"
done
echo "created ${COUNT} deployments x ${REPLICAS} replicas"
