#!/usr/bin/env bash
# Run the yunikorn-tpu scheduler binary against a live cluster (kind, kwok,
# or real): the counterpart of deploying the reference's scheduler image
# (deployments/scheduler/scheduler.yaml) for an out-of-cluster perf run.
#
# Usage: ./run-scheduler.sh [kubeconfig] [extra scheduler args...]
set -euo pipefail

KUBECONFIG_PATH="${1:-${KUBECONFIG:-$HOME/.kube/config}}"
shift || true
REPO_ROOT="$(cd "$(dirname "$0")/../.." && pwd)"

PYTHONPATH="${REPO_ROOT}${PYTHONPATH:+:$PYTHONPATH}" \
exec python -m yunikorn_tpu.cmd.scheduler \
  --kubeconfig "$KUBECONFIG_PATH" "$@"
