#!/usr/bin/env bash
# Provision kwok-simulated nodes for scheduler perf testing (analog of the
# reference's kwok setup, deployments/kwok-perf-test/kwok-setup.sh:30-60).
#
# Installs the kwok controller + fast stages into the current kube context,
# then registers N fake nodes shaped like the BASELINE.md perf fixture
# (32 cpu / 256Gi / 110 pods). Nodes carry the kwok NoSchedule taint so real
# workloads stay off them; the deploy-tool's pods tolerate it.
#
# Usage: ./kwok-setup.sh <number_of_nodes> [node_prefix]
set -euo pipefail

NODES="${1:?usage: $0 <number_of_nodes> [node_prefix]}"
PREFIX="${2:-kwok-node}"
KWOK_REPO="kubernetes-sigs/kwok"

if ! kubectl get deployment -n kube-system kwok-controller >/dev/null 2>&1; then
  TAG=$(curl -s "https://api.github.com/repos/${KWOK_REPO}/releases/latest" \
        | sed -n 's/.*"tag_name": *"\([^"]*\)".*/\1/p')
  echo "installing kwok ${TAG}"
  kubectl apply -f "https://github.com/${KWOK_REPO}/releases/download/${TAG}/kwok.yaml"
  kubectl apply -f "https://github.com/${KWOK_REPO}/releases/download/${TAG}/stage-fast.yaml"
fi

# One generated manifest, one server-side apply: registering 10k nodes via
# per-node kubectl round-trips takes ~hours; this takes ~a minute.
MANIFEST=$(mktemp /tmp/kwok-nodes-XXXX.yaml)
trap 'rm -f "$MANIFEST"' EXIT
for ((i = 0; i < NODES; i++)); do
  cat >>"$MANIFEST" <<EOF
apiVersion: v1
kind: Node
metadata:
  name: ${PREFIX}-${i}
  annotations:
    node.alpha.kubernetes.io/ttl: "0"
    kwok.x-k8s.io/node: fake
  labels:
    kubernetes.io/hostname: ${PREFIX}-${i}
    kubernetes.io/os: linux
    node-role.kubernetes.io/agent: ""
    type: kwok
spec:
  taints:
    - key: kwok.x-k8s.io/node
      value: fake
      effect: NoSchedule
status:
  allocatable: {cpu: "32", memory: 256Gi, pods: "110"}
  capacity: {cpu: "32", memory: 256Gi, pods: "110"}
  nodeInfo: {kubeletVersion: fake, operatingSystem: linux, architecture: amd64}
  phase: Running
---
EOF
done
kubectl apply --server-side -f "$MANIFEST"
echo "registered ${NODES} kwok nodes (${PREFIX}-0 .. ${PREFIX}-$((NODES - 1)))"
