#!/usr/bin/env python
"""Benchmark: end-to-end scheduling throughput on the north-star configuration.

Reference counterpart: BenchmarkSchedulingThroughPut
(pkg/shim/scheduler_perf_test.go:73-149) measures end-to-end bind throughput
over 5,000 mock nodes / 50,000 pods. The driver's north star (BASELINE.json):
schedule 50k pending pods against 10k nodes in <1s wall-clock on one TPU v5e.

This bench runs the REAL framework path — CoreScheduler.schedule_once with 50k
registered asks against 10k kwok-shaped nodes: quota gate → DRF/FIFO rank →
snapshot encode → one batched TPU solve → allocation commit — and reports
pods-scheduled/sec. vs_baseline is the ratio against the 50k-pods-in-1s target
(1.0 == exactly the north-star rate; higher is better).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""
import json
import os
import sys
import time

N_NODES = int(os.environ.get("YK_BENCH_NODES", 10_000))
N_PODS = int(os.environ.get("YK_BENCH_PODS", 50_000))
TARGET_PODS_PER_S = 50_000.0  # north star: 50k pods in 1s
# core  — the batched-solve cycle only (north-star configuration)
# shim  — BindStats end-to-end: pods in via informer events, first→last bind
#         (the reference's measurement, scheduler_perf_test.go:138-142)
# both  — run core first (warms the compile caches), then shim; publish shim
MODE = os.environ.get("YK_BENCH_MODE", "both")


def _trace_out_path() -> str:
    """--trace-out PATH (or YK_BENCH_TRACE_OUT): dump the measured run's
    cycle tracer as Chrome trace-event JSON (loads in Perfetto). Parsed by
    hand so the env-var driven invocation surface stays unchanged."""
    argv = sys.argv[1:]
    for i, a in enumerate(argv):
        if a == "--trace-out" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--trace-out="):
            return a.split("=", 1)[1]
    return os.environ.get("YK_BENCH_TRACE_OUT", "")


TRACE_OUT = _trace_out_path()


def _dump_trace(core, label: str) -> None:
    if not TRACE_OUT or core is None:
        return
    with open(TRACE_OUT, "w") as f:
        json.dump(core.tracer.chrome_trace(), f)
    print(f"# {label} cycle trace written to {TRACE_OUT}",
          file=sys.stderr, flush=True)


# The PARENT never dials until a subprocess probe has succeeded, so a wedged
# relay claim can only ever cost one bounded probe attempt — never the whole
# retry budget (the r4 failure: one jax.devices() call blocked 1502 s inside
# the relay claim and consumed the 600 s budget in a single attempt). The
# probe itself is shared infrastructure (jaxtools.probe_backend).
def _probe_backend(timeout: float):
    from yunikorn_tpu.utils.jaxtools import probe_backend

    return probe_backend(timeout)


# One OVERALL wall-clock budget for the whole bench process. The r5 failure
# mode: nine 150 s dial retries consumed the driver's entire window and the
# process died rc=124 with parsed:null — the dial loop honored only its own
# budget, not the process's. Now the dial window is derived from the total
# budget minus a reserve big enough to run the CPU-fallback measurement, so
# a wedged relay yields a parsed, self-labelled CPU result, never a timeout.
TOTAL_BUDGET = float(os.environ.get("YK_BENCH_TOTAL_BUDGET", 1500))
CPU_RESERVE = float(os.environ.get("YK_BENCH_CPU_RESERVE", 600))
# HARD ceiling on the whole dial phase, independent of the per-attempt
# math (BENCH_r04/r05: 9 wedged dial attempts still summed to 1666 s
# because attempts x timeout grew with the knobs). Whatever the attempt
# cap, timeout, and window say, dialing ends here — and a real-time
# watchdog backs the arithmetic up: if the dial phase is somehow still
# alive past the wall (+grace), the process emits the parseable
# backend-unavailable JSON shape and exits 0, so the bench row is a
# labelled unavailable result, never a driver rc=124 with parsed:null.
DIAL_WALL = float(os.environ.get("YK_BENCH_DIAL_WALL", 300))
_T_START = time.time()
_HARD_DEADLINE = _T_START + TOTAL_BUDGET


def _cpu_fallback_platform() -> str:
    """Force CPU before first backend init (the parent never dialed)."""
    from yunikorn_tpu.utils.jaxtools import force_cpu_platform

    force_cpu_platform(1)
    import jax

    return jax.devices()[0].platform


def _downshift_for_cpu_fallback() -> None:
    """A CPU fallback at the 10k×50k TPU bucket cannot finish inside the
    reserve; drop to the documented CPU bucket (1k nodes × 10k pods) unless
    the operator pinned sizes explicitly. The metric string carries both the
    platform and the sizes, so the result stays self-labelled."""
    global N_NODES, N_PODS
    if "YK_BENCH_NODES" not in os.environ:
        N_NODES = int(os.environ.get("YK_BENCH_CPU_NODES", 1000))
    if "YK_BENCH_PODS" not in os.environ:
        N_PODS = int(os.environ.get("YK_BENCH_CPU_PODS", 10000))


# injectable for the wedge regression tests (a real wedged dial can only be
# abandoned by killing the process; tests substitute a raiser)
_hard_exit = os._exit


def _backend_unavailable_json(error: str, init_secs: float) -> str:
    """The backend-unavailable JSON shape: every bench exit path emits a
    PARSEABLE line with the full key set (the BENCH_r05 regression was
    rc=124 with parsed:null — the driver window died before any JSON)."""
    return json.dumps({
        "metric": "backend-unavailable",
        "value": 0.0,
        "unit": "pods/s",
        "vs_baseline": 0.0,
        "error": error[:400],
        "init_secs": round(init_secs, 1),
        "degradations": {"transitions": [], "final": {}},
        "gate_ms": 0.0,
        "pod_encode_ms": 0.0,
        "solver_policy": "greedy",
        "pack_util": 0.0,
        "pack_plan_ms": 0.0,
        "cvx_solve_ms": 0.0,
        "cvx_iters": 0,
        "cold_first_cycle_ms": 0.0,
        "aot_hits": 0,
        "aot_compiles": 0,
        "slo": {},
        "topology": {"mode": "off", "gangs_total": 0,
                     "cross_domain_gangs": 0, "fragmentation": 0.0},
        "policy": {"active": "greedy", "checkpoint_hash": "",
                   "checkpoint_epoch": 0, "duels": {}, "duel_wins": {},
                   "last_inference_ms": 0.0},
        "trace": {"spans_by_stage": {}, "journeys": 0,
                  "journey_complete_ratio": 1.0, "recordings": 0,
                  "recordings_by_trigger": {}},
        "ledger": {"mode": "local", "rpc": False},
    })


def _init_backend_or_die(probe_fn=None, clock=time.time, sleep=time.sleep,
                         cpu_fallback=None, parent_dial=None) -> str:
    """Initialize the JAX backend up front, retrying the TPU relay.

    Failure history: r1 died on a raw UNAVAILABLE; r2/r3 fell back to CPU on
    the FIRST exception from jax.devices() and published CPU numbers while
    the chip was reachable minutes later (VERDICT r3 item 1); r4's retry loop
    made exactly one attempt because a single blocking jax.devices() call
    consumed the whole budget (VERDICT r4 item 2); r5's retries were bounded
    but their sum consumed the driver window (rc=124, parsed:null). Hence:
    every dial happens in a SUBPROCESS with its own deadline
    (YK_BENCH_TPU_DIAL_TIMEOUT, default 150 s); the retry window is the
    OVERALL budget minus the CPU reserve (YK_BENCH_TPU_WAIT can shrink it
    further, never extend past the reserve line); and after the window the
    process concedes to CPU with enough budget left to produce a parsed
    result — the metric string always carries the platform, so a CPU result
    can never masquerade as the TPU north star.

    Attempts are ALSO capped outright (YK_BENCH_TPU_DIAL_ATTEMPTS, default
    2): the BENCH_r01–r05 wedge was 9+ dial retries chewing through the
    driver's window before the budget math could save it — two failed
    probes are ample evidence the relay is down this round, and conceding
    early leaves the CPU fallback its whole reserve, so every bench round
    emits a parseable JSON result.

    The attempt cap also bounds TOTAL dial wall time (BENCH_r05 follow-up:
    the cap alone did not stop a post-probe parent dial from wedging past
    every budget — 9 x 150 s on the relay, rc=124): the whole dial phase is
    bounded by min(window, attempts x per-dial timeout + slack), the
    post-probe parent dial inherits the REMAINDER of that wall budget on a
    joined thread instead of waiting forever, and a parent dial that blows
    it emits the backend-unavailable JSON shape and exits — well inside
    the dial budget, parseable, labeled.

    probe_fn/clock/sleep/cpu_fallback/parent_dial are injectable for the
    wedged-relay regression tests (a fake dialer must drive this loop
    without a relay).
    """
    if probe_fn is None:
        probe_fn = _probe_backend
    if cpu_fallback is None:
        cpu_fallback = _cpu_fallback_platform
    if parent_dial is None:
        def parent_dial():
            import jax

            return jax.devices()
    if os.environ.get("YK_BENCH_FORCE_CPU"):
        # explicit CPU run (local testing): beat the axon plugin before any
        # backend init — the env var alone cannot (plugin overrides it).
        # Same bucket downshift as every other CPU outcome (explicit sizes
        # are honored): the TPU bucket cannot finish on CPU in the budget.
        _downshift_for_cpu_fallback()
        return cpu_fallback()

    import threading

    t0 = clock()
    budget = max(TOTAL_BUDGET - CPU_RESERVE, 60.0)
    if "YK_BENCH_TPU_WAIT" in os.environ:
        budget = min(budget, float(os.environ["YK_BENCH_TPU_WAIT"]))
    dial_timeout = float(os.environ.get("YK_BENCH_TPU_DIAL_TIMEOUT", 150))
    max_attempts = max(1, int(os.environ.get("YK_BENCH_TPU_DIAL_ATTEMPTS", 2)))
    # the attempt cap bounds WALL TIME too: N capped probes plus one
    # parent dial plus backoff slack — 2 attempts documents as ~5 min of
    # dialing, never the whole driver window
    wall_cap = min(budget, max_attempts * dial_timeout + 60.0, DIAL_WALL)
    # the real-time backstop: per-attempt math can only bound what it can
    # see (injected clocks, subprocess deadlines); a dial phase wedged in
    # a way none of that math covers still ends at the wall. Daemon timer,
    # disarmed the moment the dial phase resolves either way.
    def _wall_tripped():
        print(f"# bench: dial watchdog tripped at the hard dial wall "
              f"({DIAL_WALL:.0f}s + grace); emitting backend-unavailable",
              file=sys.stderr, flush=True)
        print(_backend_unavailable_json(
            "hard dial wall exceeded (watchdog)", time.time() - _T_START),
            flush=True)
        sys.stderr.flush()
        _hard_exit(0)

    watchdog = threading.Timer(DIAL_WALL + min(60.0, DIAL_WALL * 0.2),
                               _wall_tripped)
    watchdog.daemon = True
    watchdog.start()
    attempt = 0
    backoff = 5.0
    probed = None
    devs = None
    try:
        while True:
            if attempt >= max_attempts:
                print(f"# bench: dial attempt cap ({max_attempts}) reached; "
                      f"conceding to the CPU fallback early",
                      file=sys.stderr, flush=True)
                break
            attempt += 1
            remaining = min(budget, wall_cap) - (clock() - t0)
            if remaining <= 0:
                break
            # the last attempt may not stretch past the budget: a wedged probe
            # consumes min(dial_timeout, remaining), so the retries' SUM stays
            # inside the window and the CPU reserve survives (r5 regression)
            t_a = clock()
            platform, n, cause = probe_fn(min(dial_timeout, remaining))
            if platform is not None:
                probed = (platform, n)
                print(f"# bench: dial attempt {attempt} ok in "
                      f"{clock() - t_a:.1f}s: {n}x {platform}",
                      file=sys.stderr, flush=True)
                # The probe just held and released a relay claim, so the parent's
                # own dial is expected to be fast — but it can still wedge
                # (another client stole the claim) or raise. A raise resumes the
                # probe loop. A wedge can't be killed in-process, so the dial
                # runs on a joined thread bounded by the REMAINING dial wall
                # budget (heartbeat-logged while waiting): r05's parent dial
                # waited on the claim queue until the driver window died rc=124
                # with parsed:null — now a blown wall budget emits the
                # backend-unavailable JSON shape and exits while the budget
                # still has headroom.
                t_d = time.time()
                hb_stop = threading.Event()

                def _hb():
                    while not hb_stop.wait(30):
                        print(f"# bench: parent dial still waiting "
                              f"({time.time() - t_d:.0f}s; claim queued behind "
                              f"another client?)", file=sys.stderr, flush=True)

                threading.Thread(target=_hb, daemon=True).start()
                dial_box: dict = {}

                def _dial():
                    try:
                        dial_box["devs"] = parent_dial()
                    except Exception as e:  # delivered to the waiter below
                        dial_box["error"] = e

                dial_thread = threading.Thread(target=_dial, daemon=True)
                dial_thread.start()
                dial_wall = max(wall_cap - (clock() - t0),
                                float(os.environ.get(
                                    "YK_BENCH_PARENT_DIAL_MIN", 30)))
                dial_thread.join(dial_wall)
                hb_stop.set()
                if dial_thread.is_alive():
                    # wedged past the whole dial wall budget: the zombie thread
                    # cannot be reclaimed and the backend is half-initialized,
                    # so a CPU fallback in this process is not safe — emit the
                    # parseable backend-unavailable shape and exit NOW, inside
                    # the driver budget (os._exit: interpreter teardown under a
                    # wedged XLA dial can segfault after the verdict printed)
                    print(f"# bench: parent dial wedged past the dial wall "
                          f"budget ({dial_wall:.0f}s); emitting "
                          f"backend-unavailable and exiting",
                          file=sys.stderr, flush=True)
                    print(_backend_unavailable_json(
                        "parent dial wedged past the dial wall budget",
                        clock() - t0), flush=True)
                    sys.stderr.flush()
                    # exit 0: the driver keeps the labelled unavailable row
                    # instead of losing the round to a timeout/rc
                    _hard_exit(0)
                if "error" in dial_box:
                    e = dial_box["error"]
                    print(f"# bench: parent dial failed after "
                          f"{time.time() - t_d:.1f}s: {type(e).__name__}: "
                          f"{str(e)[:300]}; resuming probe loop",
                          file=sys.stderr, flush=True)
                    probed = None
                    try:
                        # drop the failed backend-init memo so the next dial
                        # actually re-dials instead of replaying the error
                        import jax.extend.backend as jeb
                        jeb.clear_backends()
                    except Exception:
                        pass
                else:
                    devs = dial_box.get("devs")
                if devs is not None:
                    break
            else:
                print(f"# bench: dial attempt {attempt} failed after "
                      f"{clock() - t_a:.1f}s ({clock() - t0:.0f}s total): "
                      f"{cause}", file=sys.stderr, flush=True)
            if clock() - t0 >= budget:
                break
            sleep(min(backoff, max(budget - (clock() - t0), 1.0)))
            backoff = min(backoff * 2, 60.0)
    finally:
        # disarm on EVERY exit — an exceptional unwind (a raising
        # parent_dial, or a test's _hard_exit stand-in raising
        # SystemExit) must not leave a live timer whose os._exit
        # fires into whatever process is still alive 6 minutes later
        watchdog.cancel()
    if probed is None or devs is None:
        print(f"# bench: TPU dial window ({wall_cap:.0f}s of the "
              f"{TOTAL_BUDGET:.0f}s total budget) exhausted after {attempt} "
              f"dial attempts; falling back to CPU (labeled)",
              file=sys.stderr, flush=True)
        _downshift_for_cpu_fallback()
        try:
            # the parent never dialed, so its backend is still unset: force
            # CPU before first init rather than unwinding a failed TPU claim
            return cpu_fallback()
        except Exception as e2:  # no backend at all: one diagnostic JSON line
            print(_backend_unavailable_json(f"{type(e2).__name__}: {e2}",
                                            clock() - t0))
            sys.exit(0)
    platform = devs[0].platform
    print(f"# bench: backend up in {clock() - t0:.1f}s "
          f"({attempt} dial attempts): {len(devs)}x {platform} ({devs[0]})",
          file=sys.stderr, flush=True)
    if platform == "cpu":
        # a dial that SUCCEEDS on a CPU backend (no relay configured) must
        # take the same bucket downshift as the exhausted-window fallback:
        # the 10k×50k TPU bucket cannot finish on CPU inside the budget
        _downshift_for_cpu_fallback()
    return platform


def _degradations(core) -> dict:
    """Per-path solver degradation record for the bench JSON: tier changes
    that happened during the run plus the final tier of any path not on its
    primary. A clean device run emits {"transitions": [], "final": {}} —
    BENCH_* trajectories can tell a genuine device number from one that
    silently fell back mid-run."""
    try:
        sup = core.supervisor
        return {"transitions": sup.degradations(),
                "final": sup.degraded_paths()}
    except Exception:
        return {"transitions": [], "final": {}}


def _cycle_stats(core) -> dict:
    """Host-path stats of the most recent cycle with admitted pods: the gate
    (quota/limit admission) and pod-encode stage latencies, plus how many
    rows the encoder actually re-derived (the O(changed) contract). Zeros
    when no cycle recorded one."""
    try:
        timing = (core.metrics.get("last_cycle") or {}).get("default") or {}
        return {
            "gate_ms": float(timing.get("gate_ms", 0.0)),
            "pod_encode_ms": float(timing.get("encode_ms", 0.0)),
            "gate_path": timing.get("gate_path", ""),
            "encode_reencoded": int(timing.get("encode_reencoded", 0)),
            # device gate+encode pipeline (round 11): scan wall, bounded
            # pass count, and the row-store's O(changed) upload evidence
            "gate_device_ms": float(timing.get("gate_device_ms", 0.0)),
            "gate_passes": int(timing.get("gate_passes", 0)),
            "encode_device_rows": int(timing.get("encode_device_rows", 0)),
            "encode_device_bytes": int(timing.get("encode_device_bytes", 0)),
            # optimal packing A/B (round 12): which policy committed, the
            # pack/greedy packed-units ratio, and the pack plan latency
            "solver_policy": timing.get("solver_policy", "greedy"),
            "pack_util": float(timing.get("pack_util", 0.0)),
            "pack_plan_ms": float(timing.get("pack_plan_ms", 0.0)),
            # cvx solver arm (round 19): full-fleet convex-relaxation solve
            # latency + fixed trip count of the committed-or-duelled plan
            "cvx_solve_ms": float(timing.get("cvx_solve_ms", 0.0)),
            "cvx_iters": int(timing.get("cvx_iters", 0)),
        }
    except Exception:
        return {"gate_ms": 0.0, "pod_encode_ms": 0.0, "gate_path": "",
                "encode_reencoded": 0, "gate_device_ms": 0.0,
                "gate_passes": 0, "encode_device_rows": 0,
                "encode_device_bytes": 0, "solver_policy": "greedy",
                "pack_util": 0.0, "pack_plan_ms": 0.0,
                "cvx_solve_ms": 0.0, "cvx_iters": 0}


def _slo_block(core) -> dict:
    """Per-objective SLO summary for the bench JSON (round 14): verdict +
    worst burn rate across the fast/slow windows, from the streaming engine
    (obs/slo.py). The microbench's own SLO story is thin (one process, two
    cycles) — the block's job is making the engine's verdicts ride every
    published number so a bench run that violated an objective (e.g. the
    cold-start budget) can never publish a clean-looking line."""
    try:
        rep = core.slo.report()
        return {name: {"verdict": o["verdict"],
                       "worst_burn": core.slo.worst_burn(name)}
                for name, o in rep["objectives"].items()}
    except Exception as e:
        # a broken engine must be distinguishable from a passing one: an
        # empty block is the backend-unavailable shape, not an error
        print(f"# bench: slo block unavailable: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _topology_block(core) -> dict:
    """Topology-aware-placement evidence for the bench JSON (round 15):
    whether steering was active this run, the gang-contiguity counters and
    the final ICI-domain fragmentation gauge. The microbench's synthetic
    nodes carry no topology labels, so the default shape is mode
    "unlabeled" with zero counts — scripts/topology_bench.py is where the
    steering quality is measured and gated."""
    try:
        na = core.encoder.nodes
        t = getattr(core.solver, "topology", None)
        mode = ("off" if t is False
                else ("on" if na.has_topology else "unlabeled"))
        return {
            "mode": mode,
            "gangs_total": int(core.obs.get("topology_gangs_total").value()),
            "cross_domain_gangs": int(
                core.obs.get("topology_cross_domain_gangs_total").value()),
            "fragmentation": float(
                core.obs.get("topology_domain_fragmentation").value()),
        }
    except Exception as e:
        # a broken evidence path must not masquerade as topology-disabled
        # (same contract as _slo_block): the block stays present in every
        # JSON shape, carrying the error instead of fabricated zeros
        return {"mode": "error", "error": f"{type(e).__name__}: {e}"[:200]}


def _trace_block(core) -> dict:
    """Observability evidence for the bench JSON (round 20): span counts
    by stage from the tracer (the fleet-merged one when sharded), the
    journey-complete ratio from the per-pod journey ledger, and how many
    flight-recorder bundles fired this run. Same contract as
    _slo_block/_topology_block: present in every JSON shape (incl.
    backend-unavailable), carrying the error instead of fabricated
    zeros when the evidence path breaks."""
    try:
        by_stage: dict = {}
        for s in core.tracer.spans(pods=True):
            by_stage[s.name] = by_stage.get(s.name, 0) + 1
        j = core.journey.stats()
        fr = core.flightrec.stats()
        return {
            "spans_by_stage": by_stage,
            "journeys": j["admitted"],
            "journey_complete_ratio": j["complete_ratio"],
            "recordings": fr["recordings"],
            "recordings_by_trigger": fr["by_trigger"],
        }
    except Exception as e:
        return {"error": f"{type(e).__name__}: {e}"[:200]}


def _ledger_block(core) -> dict:
    """Quota-boundary evidence for the bench JSON (round 22): which
    admission plane the run used. mode "local" is the direct in-process
    ledger object; rpc=true means the shards rode the
    core/ledger_service.py socket boundary, and mode then reports the
    client's live state (remote / degraded / fail_closed) plus its
    degraded-admission and replay counters. The microbench itself always
    runs the direct ledger — the direct-vs-socket overhead table lives in
    PERF.md — but the block rides every JSON shape (incl.
    backend-unavailable) so a socket-coupled run is always attributable.
    Same contract as _slo_block: errors carried, never fabricated
    zeros."""
    try:
        rpc = bool(getattr(core, "_ledger_rpc", False))
        ledger = (getattr(core, "ledger", None)
                  or getattr(core, "quota_ledger", None))
        block = {"rpc": rpc,
                 "mode": str(getattr(ledger, "mode", "local"))}
        if rpc and ledger is not None:
            block.update({
                "degraded_admits": int(ledger.degraded_admits),
                "degraded_rejects": int(ledger.degraded_rejects),
                "replayed_ops": int(ledger.replayed_ops),
                "contention_retries": int(ledger.contention_retries),
            })
        return block
    except Exception as e:
        return {"mode": "error", "error": f"{type(e).__name__}: {e}"[:200]}


def _duel_wins(core) -> dict:
    """Committed-plan mix by winning arm (duel_wins_total{arm}): one count
    per duel CYCLE, unlike policy_duels_total's per-participant rows."""
    wins = {}
    w = core.obs.get("duel_wins_total")
    if w is not None:
        for arm in ("greedy", "optimal", "cvx", "learned"):
            n = int(w.value(arm=arm))
            if n:
                wins[arm] = n
    return wins


def _policy_block(core) -> dict:
    """Learned-dispatch-policy evidence for the bench JSON (round 17): the
    active solver.policy mode, the validated checkpoint (hash + epoch) if
    one is loaded, committed-duel counts per policy, and the most recent
    learned-plan inference latency. The microbench's homogeneous pods give
    the learned arm nothing to win — scripts/policy_bench.py is where the
    packed-units win is measured and gated — but the block rides every
    JSON shape (incl. backend-unavailable) so a run with a checkpoint
    attached is always attributable to its exact params."""
    try:
        ck = getattr(core, "_policy_ckpt", None)
        duels = {}
        c = core.obs.get("policy_duels_total")
        if c is not None:
            for pol in ("greedy", "optimal", "cvx", "learned"):
                won = int(c.sum_over(policy=pol, outcome="won"))
                if won:
                    duels[pol] = won
        g = core.obs.get("policy_last_inference_ms")
        solver = getattr(core, "solver", None)
        return {
            "active": str(getattr(solver, "policy", "greedy")),
            "checkpoint_hash": ck.hash if ck is not None else "",
            "checkpoint_epoch": int(ck.epoch) if ck is not None else 0,
            "duels": duels,
            "duel_wins": _duel_wins(core),
            "last_inference_ms": (round(float(g.value()), 2)
                                  if g is not None else 0.0),
        }
    except Exception as e:
        # same contract as _slo_block/_topology_block: present in every
        # shape, carrying the error instead of fabricated zeros
        return {"active": "error", "error": f"{type(e).__name__}: {e}"[:200]}


def _preempt_stat(core) -> float:
    """Latest preemption-planning latency (ms) recorded by the core
    registry this run. 0.0 when no pressure cycle planned."""
    try:
        g = core.obs.get("preemption_last_plan_ms")
        return round(float(g.value()), 2) if g is not None else 0.0
    except Exception:
        return 0.0


def _preempt_pressure_cycle(core, platform: str) -> float:
    """One preemption-pressure cycle on the (full) bench cluster: submit a
    high-priority ask that cannot fit, let the cycle's second stage — the
    batched victim-selection solve — plan against it, and return the
    recorded plan latency (ms). The bench JSON carries it as
    `preempt_plan_ms` so pressure-path regressions are visible next to the
    headline throughput."""
    try:
        from yunikorn_tpu.common.objects import make_pod
        from yunikorn_tpu.common.resource import get_pod_resource
        from yunikorn_tpu.common.si import AllocationAsk, AllocationRequest

        # no node can hold these, whatever the cluster's fill level: each
        # ask is guaranteed unplaced and preemption-eligible, so the plan
        # pass runs (it finds nothing to evict — the latency of the pass
        # itself is the stat). Two probes through two cycles: the first
        # pays the kernel's one-time compile + full victim-table sync, the
        # second measures the warm steady-state pass the stat reports.
        # (Distinct probes: a failed attempt puts its ask on cooldown.)
        t0 = time.time()
        cold = warm = 0.0
        for tag in ("cold", "warm"):
            hp = make_pod(f"preempt-probe-{tag}", cpu_milli=10**9,
                          priority=1000)
            core.update_allocation(AllocationRequest(asks=[AllocationAsk(
                hp.uid, "bench-app-0", get_pod_resource(hp), priority=1000,
                pod=hp)]))
            core.schedule_once()
            cold, warm = warm, _preempt_stat(core)
        print(f"# preemption pressure cycles ({platform}): plan pass "
              f"cold {cold:.2f} ms -> warm {warm:.2f} ms "
              f"({time.time() - t0:.2f}s total)",
              file=sys.stderr, flush=True)
        return warm
    except Exception as e:
        print(f"# preemption pressure cycle failed: {type(e).__name__}: {e}",
              file=sys.stderr, flush=True)
        return 0.0


def _install_aot_store() -> None:
    """Attach the AOT executable store named by YK_AOT_STORE (aot/): a
    prebuilt store (scripts/aot_build.py) serves the first full-bucket cycle
    from deserialized executables — cold_first_cycle_ms then measures
    artifact-load + execute instead of the XLA compile stall."""
    path = os.environ.get("YK_AOT_STORE", "")
    if not path:
        return
    from yunikorn_tpu import aot

    rt = aot.install(path,
                     background=os.environ.get(
                         "YK_AOT_BACKGROUND", "0") == "1")
    print(f"# bench: aot store attached at {path} "
          f"({rt.store.entry_count()} entries)", file=sys.stderr, flush=True)


def _aot_stats() -> dict:
    """AOT store evidence for the bench JSON: store hits this run (0 with
    no store attached) and whether any dispatch compiled."""
    try:
        from yunikorn_tpu import aot

        rt = aot.get_runtime()
        if rt is None:
            return {"aot_hits": 0, "aot_compiles": 0}
        s = rt.stats()
        return {"aot_hits": s["hits"], "aot_compiles": s["compiles"]}
    except Exception:
        return {"aot_hits": 0, "aot_compiles": 0}


def _cache_entries() -> int:
    """Entry count of the persistent XLA compilation cache (cross-process
    cold-start evidence: a backend whose compiles don't serialize — e.g. a
    remote-compile relay — writes nothing, and cold cost recurs per process)."""
    from yunikorn_tpu.utils.jaxtools import compile_cache_dir

    try:
        return len(os.listdir(compile_cache_dir()))
    except OSError:
        return 0


def run_shim_mode(shim_pods: int, shim_nodes: int):
    """BindStats end-to-end: the full framework path — informer events →
    app/task FSMs → dispatcher → core batched solve → AssumePod → bind pool →
    FakeCluster binding — measured first-bind→last-bind like the reference's
    BenchmarkSchedulingThroughPut (scheduler_perf_test.go:73-149).

    Returns (pods_per_s, wall_s, bound, total, preempt_plan_ms)."""
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.shim.mock_scheduler import MockScheduler

    n_queues = 5
    ms = MockScheduler()
    # WARN logging: per-transition INFO lines would add ~6 log records per
    # pod (300k at 50k pods) of pure formatting overhead to the measurement
    ms.init(interval=0.05, core_interval=0.05,
            conf_extra={"log.level": "WARN"})
    try:
        for node in make_kwok_nodes(shim_nodes):
            ms.cluster.add_node(node)
        # Prewarm the intermediate pod buckets the streaming waves will hit
        # (the production deployment does this with --prewarm): informer
        # waves land at arbitrary bucket sizes, and an unwarmed bucket pays
        # jit trace+compile INSIDE the measured bind window (observed: a 4 s
        # first-wave stall at the 4096 bucket). In "both" mode the core
        # phase already warmed the 512 and top buckets, so only the middle
        # ones are compiled here. Skipped when the overall budget is nearly
        # spent — a late CPU fallback still publishes a parsed result.
        if (os.environ.get("YK_BENCH_SHIM_PREWARM", "1") != "0"
                and _HARD_DEADLINE - time.time() > 180):
            from yunikorn_tpu.utils.jaxtools import prewarm_buckets

            cap = 1 << max(shim_pods - 1, 511).bit_length()
            buckets, b = [], 512
            while b <= cap:
                buckets.append(b)
                b *= 2
            if MODE == "both":
                buckets = buckets[1:-1]  # core phase warmed the ends
            if buckets:
                t_pw = time.time()
                t = prewarm_buckets(",".join(f"{shim_nodes}x{b}"
                                             for b in buckets), core=ms.core)
                # bounded join: a wedged compile must not consume the whole
                # budget — the thread is a daemon, the measurement proceeds
                # (merely unwarmed) and the result still parses
                t.join(timeout=max(_HARD_DEADLINE - time.time() - 120, 1.0))
                state = "timed out; continuing unwarmed" if t.is_alive() \
                    else "done"
                print(f"# shim bucket prewarm "
                      f"({','.join(str(b) for b in buckets)} pods) {state} "
                      f"after {time.time() - t_pw:.1f}s",
                      file=sys.stderr, flush=True)
        pods = []
        for q in range(n_queues):
            pods.extend(make_sleep_pods(
                shim_pods // n_queues, f"bench-shim-{q}", queue=f"root.q{q}",
                name_prefix=f"sq{q}"))
        # pods land before the shim starts: InitializeState replays them in
        # creation order (recovery path), then the pump schedules everything
        for p in pods:
            ms.cluster.add_pod(p)
        t_start = time.time()
        ms.start()
        # clamped to the overall budget (minus teardown margin): a slow shim
        # run publishes a partial, labelled count instead of dying rc=124
        deadline = min(t_start + float(os.environ.get("YK_BENCH_SHIM_TIMEOUT", 1800)),
                       _HARD_DEADLINE - 30)
        stats = ms.cluster.get_client().bind_stats
        while time.time() < deadline:
            if stats.success_count >= len(pods):
                break
            time.sleep(0.25)
        wall = time.time() - t_start
        # shim runs last in "both" mode, so its e2e trace (encode/solve/
        # commit/publish + sampled bind spans) is the one that lands on disk
        _dump_trace(ms.core, "shim e2e")
        return (stats.throughput(), wall, stats.success_count, len(pods),
                _preempt_stat(ms.core), _degradations(ms.core),
                _cycle_stats(ms.core), _slo_block(ms.core),
                _topology_block(ms.core), _policy_block(ms.core),
                _trace_block(ms.core), _ledger_block(ms.core))
    finally:
        ms.stop()


def main() -> int:
    platform = _init_backend_or_die()

    from yunikorn_tpu.utils.jaxtools import ensure_compilation_cache

    _install_aot_store()
    ensure_compilation_cache()
    cache_entries_before = _cache_entries()

    if MODE == "shim":
        print(json.dumps(_shim_result(platform)))
        return 0

    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.client.synthetic import make_kwok_nodes, make_sleep_pods
    from yunikorn_tpu.common.resource import ResourceBuilder, get_pod_resource
    from yunikorn_tpu.common.si import (
        AddApplicationRequest,
        AllocationAsk,
        AllocationRequest,
        ApplicationRequest,
        NodeAction,
        NodeInfo,
        NodeRequest,
        RegisterResourceManagerRequest,
        UserGroupInfo,
    )
    from yunikorn_tpu.core.scheduler import CoreScheduler

    class NullCallback:
        def update_allocation(self, response):
            self.last = response

        def update_application(self, response):
            pass

        def update_node(self, response):
            pass

        def predicates(self, args):
            return None

        def preemption_predicates(self, args):
            return None

        def send_event(self, events):
            pass

        def update_container_scheduling_state(self, request):
            pass

        def get_state_dump(self):
            return "{}"

    cache = SchedulerCache()
    core = CoreScheduler(cache)
    cb = NullCallback()
    core.register_resource_manager(
        RegisterResourceManagerRequest(rm_id="bench", policy_group="queues"), cb)

    nodes = make_kwok_nodes(N_NODES)
    infos = []
    for n in nodes:
        cache.update_node(n)
        infos.append(NodeInfo(node_id=n.name, action=NodeAction.CREATE))
    core.update_node(NodeRequest(nodes=infos))

    n_queues = 5  # reference perf test spreads pods over 5 queues
    for q in range(n_queues):
        core.update_application(ApplicationRequest(new=[AddApplicationRequest(
            application_id=f"bench-app-{q}", queue_name=f"root.q{q}",
            user=UserGroupInfo(user="bench"))]))

    pods = []
    for q in range(n_queues):
        pods.extend(make_sleep_pods(N_PODS // n_queues, f"bench-app-{q}",
                                    queue=f"root.q{q}", name_prefix=f"q{q}"))
    asks = [
        AllocationAsk(p.uid, p.metadata.labels["applicationId"],
                      get_pod_resource(p), pod=p)
        for p in pods
    ]

    def run_cycle(ask_list):
        core.update_allocation(AllocationRequest(asks=list(ask_list)))
        t0 = time.time()
        n = core.schedule_once()
        dt = time.time() - t0
        return n, dt

    # warm-up on a small batch (compile at the small bucket), then release
    warm = asks[:512]
    n, _ = run_cycle(warm)
    from yunikorn_tpu.common.si import AllocationRelease, TerminationType

    core.update_allocation(AllocationRequest(releases=[
        AllocationRelease(a.application_id, a.allocation_key,
                          TerminationType.STOPPED_BY_RM) for a in warm]))
    core.schedule_once()

    # full-batch compile pass (cold at the 50k bucket), then measure warm:
    # release everything, re-ask, measure
    n_cold, dt_cold = run_cycle(asks)
    core.update_allocation(AllocationRequest(releases=[
        AllocationRelease(a.application_id, a.allocation_key,
                          TerminationType.STOPPED_BY_RM) for a in asks]))
    core.schedule_once()
    n_warm, dt_warm = run_cycle(asks)

    if n_warm < N_PODS * 0.99:
        print(f"WARNING: only {n_warm}/{N_PODS} scheduled", file=sys.stderr)

    pods_per_s = n_warm / dt_warm if dt_warm > 0 else 0.0
    print(f"# cold cycle: {n_cold} pods in {dt_cold:.2f}s; warm cycle: {n_warm} pods in {dt_warm:.3f}s",
          file=sys.stderr)
    # compile-vs-execute split: warm == execute-only, so cold - warm is the
    # XLA (or relay remote_compile) compile stall at this bucket; the
    # persistent-cache delta says whether a future process can skip it
    print(f"# compile overhead at this bucket ≈ {max(dt_cold - dt_warm, 0):.2f}s "
          f"(persistent cache wrote {_cache_entries() - cache_entries_before} "
          f"new entries this run)", file=sys.stderr)
    timing = core.metrics.get("last_cycle") or {}
    if timing:
        print(f"# warm cycle split: {timing}", file=sys.stderr)
    # preemption pressure: the cluster is full after the measured warm
    # cycle — one unplaceable high-priority ask drives the batched
    # victim-selection solve and stamps its plan latency
    preempt_ms = _preempt_pressure_cycle(core, platform)
    if MODE != "both":
        # core-only run: this tracer is the final word (in "both" the shim
        # phase overwrites with the full e2e trace)
        _dump_trace(core, "core cycle")

    core_cycle_stats = _cycle_stats(core)
    result = {
        "metric": f"pods-scheduled/sec (e2e core cycle: quota+rank+encode+{platform} solve+commit; {N_NODES} nodes, {N_PODS} pods, 5 queues)",
        "value": round(pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(pods_per_s / TARGET_PODS_PER_S, 3),
        "preempt_plan_ms": preempt_ms,
        "degradations": _degradations(core),
        # cold-start evidence (round 13): the first full-bucket cycle's
        # wall — with a prebuilt AOT store (YK_AOT_STORE) this is
        # artifact-load + execute; without one it is the compile stall
        "cold_first_cycle_ms": round(dt_cold * 1000, 1),
        **_aot_stats(),
        **core_cycle_stats,
        "slo": _slo_block(core),
        "topology": _topology_block(core),
        "policy": _policy_block(core),
        "trace": _trace_block(core),
        "ledger": _ledger_block(core),
    }

    if MODE == "both":
        # BindStats end-to-end through the whole shim (the reference's own
        # measurement methodology, scheduler_perf_test.go:138-142). The
        # headline value/vs_baseline stay the core-cycle number — that is
        # what BASELINE.json's north star (50k x 10k < 1s batched solve)
        # defines the target against — with the shim-measured e2e riding in
        # the same line so the comparable number is never hidden.
        result = _shim_result(platform, core_pods_per_s=pods_per_s,
                              core_warm_s=dt_warm, preempt_ms=preempt_ms,
                              core_cycle_stats=core_cycle_stats,
                              cold_first_cycle_ms=round(dt_cold * 1000, 1))
    print(json.dumps(result))
    return 0


def _shim_result(platform: str, core_pods_per_s=None, core_warm_s=None,
                 preempt_ms=None, core_cycle_stats=None,
                 cold_first_cycle_ms: float = 0.0) -> dict:
    """Run the BindStats shim mode and build the bench JSON for it. With a
    core-cycle number, that stays the headline (north-star metric) and the
    shim e2e rides along; standalone shim mode publishes the shim number."""
    (shim_tp, shim_wall, bound, total, shim_preempt_ms, shim_degr,
     shim_cycle_stats, shim_slo, shim_topo,
     shim_policy, shim_trace, shim_ledger) = run_shim_mode(N_PODS, N_NODES)
    print(f"# shim e2e: {bound}/{total} bound in {shim_wall:.1f}s "
          f"(first→last bind throughput {shim_tp:.0f} pods/s)", file=sys.stderr)
    if core_pods_per_s is None:
        return {
            "metric": (f"pods-bound/sec (BindStats e2e: informers+FSMs+dispatcher+"
                       f"{platform} solve+assume+bind; {N_NODES} nodes, {N_PODS} pods)"),
            "value": round(shim_tp, 1),
            "unit": "pods/s",
            "vs_baseline": round(shim_tp / TARGET_PODS_PER_S, 3),
            "shim_e2e_bound": bound,
            "preempt_plan_ms": shim_preempt_ms,
            "degradations": shim_degr,
            "cold_first_cycle_ms": cold_first_cycle_ms,
            **_aot_stats(),
            **shim_cycle_stats,
            "slo": shim_slo,
            "topology": shim_topo,
            "policy": shim_policy,
            "trace": shim_trace,
            "ledger": shim_ledger,
        }
    return {
        "metric": (f"pods-scheduled/sec (core cycle: quota+rank+encode+"
                   f"{platform} solve+commit; {N_NODES} nodes, {N_PODS} pods, "
                   f"5 queues; BindStats shim e2e: {round(shim_tp, 1)} pods/s "
                   f"host-bound)"),
        "value": round(core_pods_per_s, 1),
        "unit": "pods/s",
        "vs_baseline": round(core_pods_per_s / TARGET_PODS_PER_S, 3),
        "shim_e2e_pods_per_s": round(shim_tp, 1),
        "shim_e2e_bound": bound,
        "core_cycle_warm_s": round(core_warm_s, 3),
        "preempt_plan_ms": (preempt_ms if preempt_ms is not None
                            else shim_preempt_ms),
        "degradations": shim_degr,
        "cold_first_cycle_ms": cold_first_cycle_ms,
        **_aot_stats(),
        # headline gate/encode stats stay the core cycle's (the north-star
        # comparable); the shim-phase numbers ride alongside
        **(core_cycle_stats or shim_cycle_stats),
        "shim_gate_ms": shim_cycle_stats["gate_ms"],
        "shim_pod_encode_ms": shim_cycle_stats["pod_encode_ms"],
        # the shim phase ran last and bound real pods — its engine carries
        # the run's delivered-latency verdicts
        "slo": shim_slo,
        "topology": shim_topo,
        "policy": shim_policy,
        "trace": shim_trace,
        "ledger": shim_ledger,
    }


if __name__ == "__main__":
    sys.exit(main())
