"""Real-K8s adapter tests: client/kube.py driven against an in-process API
server speaking the K8s REST protocol (LIST/WATCH/bind/create). The full-stack
test is the kwok-smoke analog the reference runs via
deployments/kwok-perf-test/kwok-setup.sh: sleep pods bound onto fake nodes by
the real scheduler path, through HTTP."""
import ssl
import time

import pytest

from tests.fake_apiserver import FakeAPIServer
from yunikorn_tpu.client.interfaces import InformerType, ResourceEventHandlers
from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider, RealKubeClient


@pytest.fixture
def api():
    server = FakeAPIServer()
    port = server.start()
    cfg = KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context())
    yield server, cfg
    server.stop()


def test_list_and_watch_nodes(api):
    server, cfg = api
    server.add_node_doc("n0")
    provider = RealAPIProvider(cfg)
    seen = []
    provider.add_event_handler(InformerType.NODE, ResourceEventHandlers(
        add_fn=lambda n: seen.append(("add", n.name)),
        delete_fn=lambda n: seen.append(("del", n.name))))
    provider.start()
    provider.wait_for_sync(timeout=10)
    assert ("add", "n0") in seen
    server.add_node_doc("n1")  # via watch
    deadline = time.time() + 5
    while ("add", "n1") not in seen and time.time() < deadline:
        time.sleep(0.05)
    assert ("add", "n1") in seen
    server.delete("nodes", "", "n0")
    deadline = time.time() + 5
    while ("del", "n0") not in seen and time.time() < deadline:
        time.sleep(0.05)
    assert ("del", "n0") in seen
    provider.stop()


def test_pod_decode_and_bind_roundtrip(api):
    server, cfg = api
    server.add_pod_doc("p0", app_id="app-x")
    client = RealKubeClient(cfg)
    provider = RealAPIProvider(cfg)
    provider.start()
    provider.wait_for_sync(timeout=10)
    pods = provider.list_pods()
    assert len(pods) == 1
    p = pods[0]
    assert p.name == "p0" and p.metadata.labels["applicationId"] == "app-x"
    assert p.spec.containers[0].resources_requests["cpu"] == "500m"
    server.add_node_doc("n0")
    client.bind(p, "n0")
    assert server.bindings == [("p0", "n0")]
    provider.stop()


def test_configmap_bootstrap(api):
    server, cfg = api
    server.add("configmaps", {
        "metadata": {"name": "yunikorn-defaults", "namespace": "yunikorn"},
        "data": {"service.schedulingInterval": "2s"}})
    from yunikorn_tpu.client.kube import load_bootstrap_configmaps

    client = RealKubeClient(cfg)
    maps, binary = load_bootstrap_configmaps(client, "yunikorn")
    assert maps[0] == {"service.schedulingInterval": "2s"}
    assert maps[1] is None  # yunikorn-configs absent
    assert binary == [{}, {}]


def test_full_scheduler_stack_against_api_server(api):
    """The kwok-smoke analog: real shim + core + adapter scheduling sleep
    pods onto API-server nodes over HTTP (reference bar: kwok-setup.sh)."""
    server, cfg = api
    from yunikorn_tpu.cache.context import Context
    from yunikorn_tpu.cache import task as task_mod
    from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
    from yunikorn_tpu.conf.schedulerconf import get_holder, reset_for_tests
    from yunikorn_tpu.core.scheduler import CoreScheduler
    from yunikorn_tpu.dispatcher import dispatcher as dispatch_mod
    from yunikorn_tpu.shim.scheduler import KubernetesShim

    for i in range(3):
        server.add_node_doc(f"kwok-{i}")
    for i in range(6):
        server.add_pod_doc(f"sleep-{i}", app_id="kwok-app")

    reset_for_tests()
    get_holder().update_config_maps(
        [{"service.schedulingInterval": "0.05"}], initial=True)
    dispatch_mod.reset_dispatcher()
    provider = RealAPIProvider(cfg)
    cache = SchedulerCache()
    core = CoreScheduler(cache, interval=0.02)
    ctx = Context(provider, core, cache=cache)
    shim = KubernetesShim(provider, core, context=ctx)
    core.start()
    shim.run()
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            app = ctx.get_application("kwok-app")
            if app is not None:
                tasks = [app.get_task(p.uid) for p in provider.list_pods()]
                if (len(tasks) == 6 and all(
                        t is not None and t.state == task_mod.BOUND for t in tasks)):
                    break
            time.sleep(0.1)
        assert len(server.bindings) == 6
        bound_nodes = {n for _, n in server.bindings}
        assert bound_nodes <= {"kwok-0", "kwok-1", "kwok-2"}
    finally:
        core.stop()
        shim.stop()
        provider.stop()


def test_bootstrap_binary_data_decoded(api):
    server, cfg = api
    import base64, gzip

    payload = gzip.compress(b"queues-config-bytes")
    server.add("configmaps", {
        "metadata": {"name": "yunikorn-defaults", "namespace": "yunikorn"},
        "data": {"a": "1"},
        "binaryData": {"queues.yaml": base64.b64encode(payload).decode()}})
    from yunikorn_tpu.client.kube import load_bootstrap_configmaps

    maps, binary = load_bootstrap_configmaps(RealKubeClient(cfg), "yunikorn")
    assert maps[0] == {"a": "1"}
    assert binary[0]["queues.yaml"] == payload


def test_namespaced_configmap_informer_path(api):
    server, cfg = api
    provider = RealAPIProvider(cfg, namespace="yunikorn")
    from yunikorn_tpu.client.kube import _Informer

    inf = provider._informers[InformerType.CONFIGMAP]
    assert inf._list_path(False) == "/api/v1/namespaces/yunikorn/configmaps"


def test_csi_informers_over_real_protocol():
    """CSIDriver/CSIStorageCapacity/VolumeAttachment informers LIST+WATCH
    over HTTP and land decoded in the stores (completes the reference's
    storage informer set, apifactory.go:39-59)."""
    import ssl

    from tests.fake_apiserver import FakeAPIServer
    from yunikorn_tpu.client.interfaces import InformerType
    from yunikorn_tpu.client.kube import KubeConfig, RealAPIProvider

    server = FakeAPIServer()
    port = server.start()
    try:
        server.add("csidrivers", {
            "metadata": {"name": "csi.x.io"},
            "spec": {"attachRequired": True, "storageCapacity": True}})
        server.add("csistoragecapacities", {
            "metadata": {"name": "seg-1", "namespace": "default"},
            "storageClassName": "fast",
            "nodeTopology": {"matchLabels": {"zone": "a"}},
            "capacity": "100Gi"})
        server.add("volumeattachments", {
            "metadata": {"name": "va-1"},
            "spec": {"attacher": "csi.x.io", "nodeName": "n0",
                     "source": {"persistentVolumeName": "pv-9"}},
            "status": {"attached": True}})
        cfg = KubeConfig(f"http://127.0.0.1:{port}", ssl.create_default_context())
        provider = RealAPIProvider(cfg)
        seen = {"drv": [], "cap": [], "va": []}
        from yunikorn_tpu.client.interfaces import ResourceEventHandlers
        provider.add_event_handler(InformerType.CSI_DRIVER,
                                   ResourceEventHandlers(add_fn=seen["drv"].append))
        provider.add_event_handler(InformerType.CSI_STORAGE_CAPACITY,
                                   ResourceEventHandlers(add_fn=seen["cap"].append))
        provider.add_event_handler(InformerType.VOLUME_ATTACHMENT,
                                   ResourceEventHandlers(add_fn=seen["va"].append))
        provider.start()
        try:
            provider.wait_for_sync(timeout=10)
            assert seen["drv"][0].storage_capacity is True
            cap = seen["cap"][0]
            assert cap.storage_class == "fast" and cap.capacity == 100 * 2**30
            assert cap.node_topology == {"zone": "a"}
            va = seen["va"][0]
            assert va.node_name == "n0" and va.pv_name == "pv-9" and va.attached
        finally:
            provider.stop()
    finally:
        server.stop()
