"""Fault injection through the client seams (reference pattern:
NewMockedAPIProvider(showError) + mockable Bind/Create/Delete,
apifactory_mock.go:137-165): bind failures release and fail the task,
placeholder-create failures fall back Soft, delete failures orphan-retry.
"""
import json
import time

import pytest

from yunikorn_tpu.cache import application as app_mod
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import make_node, make_pod
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


@pytest.fixture
def sched():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    yield ms
    ms.stop()


def yk_pod(name, app_id="app-1", cpu=500):
    return make_pod(name, cpu_milli=cpu, memory=2**27,
                    labels={constants.LABEL_APPLICATION_ID: app_id},
                    scheduler_name=constants.SCHEDULER_NAME)


def test_bind_failure_fails_task_and_releases(sched):
    sched.add_node(make_node("node-1", cpu_milli=2000))
    client = sched.cluster.get_client()
    calls = {"n": 0}

    def failing_bind(pod, node):
        calls["n"] += 1
        raise RuntimeError("api server unavailable")

    client.bind_fn = failing_bind
    p = sched.add_pod(yk_pod("doomed"))
    sched.wait_for_task_state("app-1", p.uid, task_mod.FAILED)
    assert calls["n"] >= 1
    assert client.bind_stats.fail_count >= 1
    # the core released the allocation: capacity is whole again and a healthy
    # bind path can use all of it
    client.bind_fn = None
    deadline = time.time() + 5
    while time.time() < deadline:
        leaf = sched.core.queues.resolve("root.default", create=False)
        if leaf is not None and leaf.allocated.get("cpu") == 0:
            break
        time.sleep(0.05)
    p2 = sched.add_pod(yk_pod("healthy", cpu=2000))
    sched.wait_for_task_state("app-1", p2.uid, task_mod.BOUND)


def test_bind_failure_transient_retries_then_binds(sched):
    """A bind that races cluster state (node gone mid-bind) is NOT terminal:
    the allocation is released and the task re-queues (Allocated → Pending →
    fresh ask), binding on a later cycle once the failure clears — the
    node-remove-with-pods-in-flight scenario's recovery contract."""
    sched.add_node(make_node("node-1", cpu_milli=2000))
    client = sched.cluster.get_client()
    calls = {"n": 0}

    def flaky_bind(pod, node):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise KeyError(f"bind: node {node} not found")
        client._cluster.bind_pod(pod.uid, node)

    client.bind_fn = flaky_bind
    p = sched.add_pod(yk_pod("survivor"))
    sched.wait_for_task_state("app-1", p.uid, task_mod.BOUND, timeout=20)
    assert calls["n"] >= 3
    task = sched.context.get_application("app-1").get_task(p.uid)
    assert task.bind_retries == 2
    # accounting is clean after the release/re-admit round trips
    leaf = sched.core.queues.resolve("root.default", create=False)
    assert leaf.allocated.get("cpu") == 500


def test_placeholder_create_failure_soft_fallback(sched):
    sched.add_node(make_node("node-1", cpu_milli=8000))
    client = sched.cluster.get_client()

    def failing_create(pod):
        raise RuntimeError("quota webhook rejected the pod")

    client.create_fn = failing_create
    tg = [{"name": "g", "minMember": 2, "minResource": {"cpu": "500m", "memory": "64Mi"}}]
    origin = make_pod("driver", cpu_milli=500, memory=2**26,
                      labels={constants.LABEL_APPLICATION_ID: "gang-f"},
                      annotations={constants.ANNOTATION_TASK_GROUPS: json.dumps(tg)},
                      scheduler_name=constants.SCHEDULER_NAME)
    sched.add_pod(origin)
    # Soft fallback: app runs without the gang, driver binds anyway
    sched.wait_for_app_state("gang-f", app_mod.RUNNING, timeout=15)
    client.create_fn = None
    sched.wait_for_task_state("gang-f", origin.uid, task_mod.BOUND, timeout=15)


def test_placeholder_delete_failure_orphan_retry(sched):
    import yunikorn_tpu.cache.placeholder_manager as pm_mod

    sched.add_node(make_node("node-1", cpu_milli=8000))
    pm = sched.context.placeholder_manager
    client = sched.cluster.get_client()
    tg = [{"name": "g", "minMember": 2, "minResource": {"cpu": "100m", "memory": "64Mi"}}]
    origin = make_pod("driver", cpu_milli=100, memory=2**26,
                      labels={constants.LABEL_APPLICATION_ID: "gang-d"},
                      annotations={constants.ANNOTATION_TASK_GROUPS: json.dumps(tg)},
                      scheduler_name=constants.SCHEDULER_NAME)
    sched.add_pod(origin)
    sched.wait_for_app_state("gang-d", app_mod.RUNNING, timeout=15)
    fails = {"n": 0}
    real_delete = sched.cluster.delete_pod

    def failing_delete(pod):
        fails["n"] += 1
        raise RuntimeError("transient delete failure")

    client.delete_fn = failing_delete
    app = sched.context.get_application("gang-d")
    pm.clean_up(app)
    assert pm.orphan_count() > 0  # parked for retry
    client.delete_fn = None       # heal; the 5s retry loop drains orphans
    # force one retry tick quickly instead of waiting the full interval
    deadline = time.time() + pm_mod.ORPHAN_RETRY_INTERVAL + 5
    while time.time() < deadline and pm.orphan_count() > 0:
        time.sleep(0.2)
    assert pm.orphan_count() == 0
