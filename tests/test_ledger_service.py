"""Ledger-as-a-service suite (core/ledger_service.py + the round-22 fault
plane in robustness/faults.py):

  * wire parity: a scripted reserve/commit/release workload driven through
    LedgerServer/LedgerClient leaves the authority bit-equal to the same
    workload applied to an in-process GlobalQuotaLedger;
  * randomized idempotency property: every op delivered 1-3 times in
    shuffled order (the exact abuse the RPC retry path produces) leaves
    audit() clean and usage equal to exactly-once delivery; a second arm
    drops per-key suffixes entirely (0 deliveries — the client gave up)
    and the audit must STILL be clean;
  * the server's duplicate cache and per-(client,key) seq fence, counted;
  * degraded mode: a netsplit pushes the client into conservative local
    admission, the unacked journal replays on reconnect, and the
    authority's usage re-converges bit-equal with audit() clean;
  * failClosed admits nothing while partitioned and recovers cleanly;
  * a flapping transport neither wedges the caller nor leaks threads;
  * victim-credit ops round-trip the socket (one credit = one attempt);
  * HostLeaseMonitor: an expired peer lease quarantines exactly that
    peer's shards; an expired OWN lease re-registers instead of
    self-amputating; ShardSupervisor.note_quarantined records the
    lease-driven quarantine in the failover report;
  * the DeviceUsageMirror journal fence: a zombie refresh presenting a
    stale epoch folds nothing, its drained deltas requeue, and
    divergence() stays 0.

Multi-second scenarios (the flap storm) carry @pytest.mark.slow; the
fast tests ride tier-1.
"""
import json
import random
import threading
import time

import pytest

from yunikorn_tpu.core.ledger_service import (
    MODE_DEGRADED,
    MODE_FAIL_CLOSED,
    MODE_REMOTE,
    LedgerClient,
    LedgerClientOptions,
    LedgerServer,
)
from yunikorn_tpu.core.shard import GlobalQuotaLedger
from yunikorn_tpu.robustness.failover import (
    QUARANTINED,
    FailoverOptions,
    HostLeaseMonitor,
    ShardSupervisor,
)
from yunikorn_tpu.robustness.faults import NetFaultPlane


def _ch(tid, lim, amt, rk="vcore"):
    """One-tracker charge list in gate.ledger_charges shape."""
    return [(tid, [(rk, lim)], [(rk, amt)])]


def _snapshot(ledger):
    return json.dumps(ledger.usage_snapshot(), sort_keys=True)


class _Served:
    """Authority ledger behind a LedgerServer plus one LedgerClient,
    torn down reliably."""

    def __init__(self, options=None, faults=None, server_faults=None):
        self.authority = GlobalQuotaLedger()
        self.server = LedgerServer(self.authority, faults=server_faults)
        self.server.start()
        self.client = LedgerClient(
            self.server.endpoint,
            options or LedgerClientOptions(deadline_s=2.0),
            faults=faults, client_id="t")

    def close(self):
        self.client.close()
        self.server.stop()


@pytest.fixture
def served():
    s = _Served()
    yield s
    s.close()


# ---------------------------------------------------------------------------
# wire parity
# ---------------------------------------------------------------------------
def _scripted_workload(led):
    """A lifecycle mix: confirmed, reserved-then-dropped, released,
    refused (tight limit), and an empty-charge no-op."""
    out = []
    out.append(led.reserve("a1", _ch("tq", 100, 40)))
    led.commit("a1", _ch("tq", 100, 40))
    out.append(led.reserve("a2", _ch("tq", 100, 30)))
    led.commit("a2", _ch("tq", 100, 30))
    out.append(led.reserve("a3", _ch("tq", 100, 50)))   # 40+30+50 > 100
    out.append(led.reserve("a4", _ch("tq", 100, 20)))
    led.release_reservation("a4")
    out.append(led.reserve("a5", _ch("uq", 10, 10)))
    led.commit("a5", _ch("uq", 10, 10))
    led.release("a2")
    out.append(led.reserve("a6", []))                    # no limits anywhere
    out.extend(led.reserve_many([
        ("b1", _ch("tq", 100, 25)),
        ("b2", _ch("tq", 100, 60)),                      # 40+25+60 > 100
        ("b3", []),
    ]))
    led.commit("b1", _ch("tq", 100, 25))
    return out


def test_wire_parity_scripted(served):
    direct = GlobalQuotaLedger()
    want = _scripted_workload(direct)
    got = _scripted_workload(served.client)
    assert got == want
    assert _snapshot(served.authority) == _snapshot(direct)
    assert served.client.audit() == direct.audit() == []
    ds, ss = direct.stats(), served.authority.stats()
    for k in ("trackers", "reservations", "charged_keys", "reserve_held"):
        assert ss[k] == ds[k], k
    # refusal counters piggyback on reserve responses
    assert served.client.reserve_held == direct.reserve_held > 0
    assert served.client.mode == MODE_REMOTE
    assert served.server.requests > 0


# ---------------------------------------------------------------------------
# randomized idempotency property
# ---------------------------------------------------------------------------
def _random_tape(rng, n_keys):
    """Per-key op tapes in the shapes the client actually produces:
    commit only ever follows an acked reserve; limits are generous so
    every reserve succeeds (the client never commits a refused ask)."""
    tape = []
    for i in range(n_keys):
        key = f"k{i}"
        tid = f"t{rng.randrange(3)}"
        amt = rng.randrange(1, 9)
        charges = _ch(tid, 10_000, amt)
        tape.append(("reserve", key, charges))
        shape = rng.randrange(4)
        if shape == 0:
            tape.append(("release_reservation", key, None))
        elif shape >= 1:
            tape.append(("commit", key, charges))
            if shape == 3:
                tape.append(("release", key, None))
    return tape


def _apply_direct(led, op, key, charges):
    if op == "reserve":
        led.reserve(key, charges)
    elif op == "commit":
        led.commit(key, charges)
    elif op == "release":
        led.release(key)
    else:
        led.release_reservation(key)


def _frame(op, key, charges, seq):
    args = {"key": key}
    if op in ("reserve", "commit"):
        args["charges"] = charges
    return {"op": op, "args": args, "client": "c", "seq": seq,
            "id": f"c:{seq}"}


def test_idempotency_dup_reorder_property():
    """Every op delivered 1-3 times, fully shuffled: the duplicate cache
    and the per-key seq fence must make the result equal to exactly-once
    in-order delivery — clean audit, identical usage AND reservations."""
    for trial in range(6):
        rng = random.Random(4200 + trial)
        tape = _random_tape(rng, n_keys=12)
        direct = GlobalQuotaLedger()
        for op, key, charges in tape:
            _apply_direct(direct, op, key, charges)

        authority = GlobalQuotaLedger()
        server = LedgerServer(authority)
        deliveries = []
        for seq, (op, key, charges) in enumerate(tape, start=1):
            deliveries += [_frame(op, key, charges, seq)] * rng.randrange(
                1, 4)
        rng.shuffle(deliveries)
        for frame in deliveries:
            resp = server._apply(frame)
            assert resp["ok"], resp
        assert _snapshot(authority) == _snapshot(direct), f"trial {trial}"
        assert authority.audit() == direct.audit() == []
        assert (authority.stats()["reservations"]
                == direct.stats()["reservations"])
        assert server.duplicates > 0


def test_idempotency_dropped_suffix_stays_clean():
    """0-delivery arm: per key, a random SUFFIX of its ops never arrives
    (the client died with them journaled). The audit must stay clean,
    and the end state must equal exactly-once in-order delivery of the
    ops that DID arrive."""
    rng = random.Random(77)
    tape = _random_tape(rng, n_keys=15)
    drop_from = {}   # key -> tape position past which its ops are dropped
    for i in range(15):
        if rng.random() < 0.4:
            drop_from[f"k{i}"] = rng.randrange(len(tape))
    delivered = [(seq, op, key, charges)
                 for seq, (op, key, charges) in enumerate(tape, start=1)
                 if seq - 1 < drop_from.get(key, len(tape))]
    assert len(delivered) < len(tape)         # the drops actually happened
    direct = GlobalQuotaLedger()
    for _seq, op, key, charges in delivered:
        _apply_direct(direct, op, key, charges)

    authority = GlobalQuotaLedger()
    server = LedgerServer(authority)
    deliveries = []
    for seq, op, key, charges in delivered:
        deliveries += [_frame(op, key, charges, seq)] * rng.randrange(1, 4)
    rng.shuffle(deliveries)
    for frame in deliveries:
        assert server._apply(frame)["ok"]
    assert authority.audit() == []
    assert _snapshot(authority) == _snapshot(direct)


def test_server_duplicate_cache_and_stale_fence():
    authority = GlobalQuotaLedger()
    server = LedgerServer(authority)
    f1 = _frame("reserve", "x", _ch("tq", 100, 10), seq=1)
    r1 = server._apply(f1)
    assert r1 == server._apply(f1)          # cached byte-equal response
    assert server.duplicates == 1
    f3 = _frame("release", "x", None, seq=3)
    assert server._apply(f3)["ok"]
    # a stale reorder (seq 2 < applied seq 3 on key x) is a success no-op
    f2 = _frame("commit", "x", _ch("tq", 100, 10), seq=2)
    r2 = server._apply(f2)
    assert r2["ok"] and r2.get("stale")
    assert server.stale_drops == 1
    assert authority.usage_snapshot() == {}
    assert authority.stats()["reservations"] == 0


# ---------------------------------------------------------------------------
# degraded mode
# ---------------------------------------------------------------------------
def _chaos_options(**kw):
    base = dict(deadline_s=0.2, max_retries=0, backoff_base_s=0.01,
                backoff_cap_s=0.02, breaker_threshold=1,
                probe_interval_s=0.15)
    base.update(kw)
    return LedgerClientOptions(**base)


def test_degraded_reconverges_bit_equal():
    faults = NetFaultPlane()
    s = _Served(options=_chaos_options(), faults=faults)
    try:
        c = s.client
        assert c.reserve("a1", _ch("tq", 100, 40))
        c.commit("a1", _ch("tq", 100, 40))
        assert c.usage_snapshot() == {"tq": {"vcore": 40}}  # warms the cache
        faults.partition()
        # conservative local admission: last cached usage (40) + pending
        assert c.reserve("a2", _ch("tq", 100, 30))
        c.commit("a2", _ch("tq", 100, 30))
        assert not c.reserve("a3", _ch("tq", 100, 50))   # 40+30+50 > 100
        assert c.mode == MODE_DEGRADED
        assert c.degraded_admits == 1 and c.degraded_rejects == 1
        # the authority saw none of it yet
        assert s.authority.usage_snapshot() == {"tq": {"vcore": 40}}
        faults.heal()
        time.sleep(c.options.probe_interval_s + 0.05)
        # the next call is the half-open probe: journal replays FIRST
        assert c.reserve("a4", _ch("tq", 100, 20))
        assert c.mode == MODE_REMOTE
        assert c.replayed_ops >= 2        # reserve(a2) + commit(a2)
        assert not c._unacked and not c._local_charges
        # bit-equal to the same workload applied exactly once in-process
        direct = GlobalQuotaLedger()
        direct.reserve("a1", _ch("tq", 100, 40))
        direct.commit("a1", _ch("tq", 100, 40))
        direct.reserve("a2", _ch("tq", 100, 30))
        direct.commit("a2", _ch("tq", 100, 30))
        direct.reserve("a4", _ch("tq", 100, 20))
        assert _snapshot(s.authority) == _snapshot(direct)
        assert s.authority.audit() == []
    finally:
        s.close()


def test_fail_closed_admits_nothing():
    faults = NetFaultPlane()
    s = _Served(options=_chaos_options(fail_closed=True), faults=faults)
    try:
        c = s.client
        assert c.reserve("a1", _ch("tq", 100, 40))
        faults.partition()
        assert not c.reserve("a2", _ch("tq", 100, 1))
        assert not c.reserve("a3", _ch("tq", 100, 1))
        assert c.mode == MODE_FAIL_CLOSED
        assert c.degraded_admits == 0 and c.degraded_rejects == 2
        assert not c._local_charges
        faults.heal()
        time.sleep(c.options.probe_interval_s + 0.05)
        assert c.reserve("a4", _ch("tq", 100, 20))
        assert c.mode == MODE_REMOTE
        # refused degraded reserves must not have replayed as reserves
        assert s.authority.stats()["reservations"] == 2   # a1 + a4
        assert s.authority.audit() == []
    finally:
        s.close()


@pytest.mark.slow
def test_flap_storm_never_wedges_or_leaks():
    """Repeated open/half-open/close breaker cycles with journal replay
    on every heal: the pump thread never wedges and nothing leaks."""
    faults = NetFaultPlane()
    s = _Served(options=_chaos_options(deadline_s=0.1), faults=faults)
    try:
        c = s.client
        before = threading.active_count()
        faults.flap(period_s=0.3, down_fraction=0.5)
        deadline = time.time() + 2.5
        i = 0
        while time.time() < deadline:
            key = f"f{i}"
            if c.reserve(key, _ch("tq", 1_000_000, 1)):
                c.commit(key, _ch("tq", 1_000_000, 1))
            i += 1
            time.sleep(0.01)
        assert i > 50, "caller wedged under flap"
        faults.heal()
        time.sleep(c.options.probe_interval_s + 0.05)
        for _ in range(3):                 # drain the journal fully
            assert c.reserve("final", _ch("tq", 1_000_000, 1))
            if not c._unacked:
                break
        assert c.mode == MODE_REMOTE
        assert not c._unacked
        assert s.authority.audit() == []
        assert threading.active_count() <= before + 1
    finally:
        s.close()
    time.sleep(0.1)


# ---------------------------------------------------------------------------
# victim credits + host leases over the boundary
# ---------------------------------------------------------------------------
def test_victim_credits_over_socket(served):
    c = served.client
    c.post_victim_credit("pod-1", shard=1)
    c.post_victim_credit("pod-2", shard=0)
    assert c.victim_credits(1) == ["pod-1"]
    assert c.consume_victim_credit("pod-1") is True
    assert c.consume_victim_credit("pod-1") is False   # one credit, once
    c.clear_victim_credit("pod-2")
    assert c.victim_credits(0) == []
    assert served.authority.stats()["victim_credits"] == 0


def test_host_lease_monitor_quarantines_expired_peer():
    led = GlobalQuotaLedger()
    calls = []
    mon = HostLeaseMonitor(led, "h0", [0], lambda i, r: calls.append((i, r)),
                           ttl_s=0.08, interval_s=60.0)
    mon.poll_once()
    led.register_host_shards("h1", [1, 2])   # peer that never heartbeats
    t0 = time.time()
    while time.time() - t0 < 0.2:
        mon.poll_once()                       # own heartbeats keep h0 alive
        if calls:
            break
        time.sleep(0.02)
    assert calls == [(1, "lease:h1"), (2, "lease:h1")]
    assert mon.expiries_seen == 1             # counted per host, not shard
    assert "h0" in led.host_leases() and "h1" not in led.host_leases()
    assert mon.poll_once() == []              # expiry fired exactly once


def test_host_lease_monitor_own_expiry_reregisters():
    led = GlobalQuotaLedger()
    calls = []
    mon = HostLeaseMonitor(led, "h0", [0], lambda i, r: calls.append((i, r)),
                           ttl_s=0.05, interval_s=60.0)
    mon.poll_once()
    time.sleep(0.1)                           # let our own lease lapse
    dead = mon.poll_once()                    # sees itself expired
    assert dead == [] and calls == []         # never self-amputates
    mon.poll_once()                           # re-registers
    assert "h0" in led.host_leases()


def test_note_quarantined_records_lease_driven_quarantine():
    sup = ShardSupervisor(2, FailoverOptions(), lambda i, r: True,
                          lambda i: True)
    sup.note_quarantined(1, "lease:h1", rehome_s=0.02)
    rep = sup.report()
    assert rep["states"]["1"] == QUARANTINED
    assert rep["quarantines"] == 1
    assert sup.last_event["reason"] == "lease:h1"
    sup.note_quarantined(1, "lease:h1")       # idempotent on a dead shard
    assert sup.report()["quarantines"] == 1


# ---------------------------------------------------------------------------
# mirror journal fence
# ---------------------------------------------------------------------------
def test_mirror_epoch_fence_requeues_and_divergence_zero():
    from yunikorn_tpu.ops.ledger_mirror import DeviceUsageMirror

    led = GlobalQuotaLedger()
    mirror = DeviceUsageMirror(2)
    led.attach_mirror(mirror)
    led.reserve("a1", _ch("tq", 100, 40))
    led.commit("a1", _ch("tq", 100, 40))
    stale = mirror.epoch_of(0)
    mirror.fence_shard(0)                     # quarantine bumps the epoch
    # the zombie presents its pre-fence stamp: nothing folds, the drained
    # deltas land back on the ledger journal
    assert mirror.refresh(0, led, epoch=stale) == 0
    assert mirror.stats()["fenced_refreshes"] >= 1
    assert mirror.host_usage().get("tq", {}).get("vcore", 0) == 0
    # a live refresh with the current stamp applies the requeued deltas
    assert mirror.refresh(0, led, epoch=mirror.epoch_of(0)) >= 1
    assert mirror.host_usage() == {"tq": {"vcore": 40}}
    assert mirror.divergence(led) == 0
