"""Preemption tests: victim-subset search (startIndex contract), planner
selection, and the end-to-end evict→reschedule flow (reference e2e suites:
preemption / simple_preemptor / priority_scheduling).
"""
import time

import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import ObjectMeta, PriorityClass, make_node, make_pod
from yunikorn_tpu.common.si import PreemptionPredicatesArgs
from yunikorn_tpu.core.preemption import plan_preemptions
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.preempt import preemption_victim_search
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


def setup_node_with_victims():
    cache = SchedulerCache()
    cache.update_node(make_node("n1", cpu_milli=4000, memory=8 * 2**30))
    victims = []
    for i in range(4):
        v = make_pod(f"victim-{i}", cpu_milli=1000, node_name="n1",
                     phase="Running", priority=i)
        cache.update_pod(v)
        victims.append(v)
    return cache, victims


def test_victim_search_returns_first_fitting_index():
    cache, victims = setup_node_with_victims()
    # node full (4x1000m); pod needs 2000m → 2 victims must go
    pod = make_pod("preemptor", cpu_milli=2000, priority=100)
    cache.update_pod(pod)
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=0))
    assert resp.success and resp.index == 1  # removing victims[0..1] fits


def test_victim_search_start_index_contract():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=3000, priority=100)
    cache.update_pod(pod)
    # startIndex=2: victims 0,1 removed unconditionally, then one at a time
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=2))
    assert resp.success and resp.index == 2


def test_victim_search_no_fit():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=16000, priority=100)
    cache.update_pod(pod)
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=0))
    assert not resp.success and resp.index == -1


def test_planner_picks_cheapest_victims():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod),
                        priority=100, pod=pod)
    app_of_pod = {v.uid: "app-lo" for v in victims}
    plans, _ = plan_preemptions(cache, [ask], app_of_pod)
    assert len(plans) == 1
    assert plans[0].node_id == "n1"
    # exactly one victim, the lowest priority one (priority 0)
    assert [v.uid for v in plans[0].victims] == [victims[0].uid]


def test_planner_respects_allow_preemption_annotation():
    cache, victims = setup_node_with_victims()
    # protect the two lowest-priority victims via PriorityClass opt-out
    pc = PriorityClass(metadata=ObjectMeta(
        name="protected", annotations={constants.ANNOTATION_ALLOW_PREEMPTION: "false"}))
    cache.update_priority_class(pc)
    for v in victims[:2]:
        v.spec.priority_class_name = "protected"
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {v.uid: "app-lo" for v in victims})
    assert len(plans) == 1
    assert plans[0].victims[0].uid == victims[2].uid  # cheapest unprotected


def test_planner_preemptor_never_policy():
    cache, victims = setup_node_with_victims()
    pod = make_pod("pacifist", cpu_milli=1000, priority=100)
    pod.spec.preemption_policy = "Never"
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {v.uid: "app-lo" for v in victims})
    assert plans == []


def test_planner_ignores_foreign_pods():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {})  # no yunikorn-managed victims
    assert plans == []


# ---------------------------------------------------------------------------
# End-to-end
# ---------------------------------------------------------------------------

def test_preemption_e2e_evicts_and_reschedules():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        ms.add_node(make_node("n1", cpu_milli=2000, memory=4 * 2**30))
        low = [ms.add_pod(make_pod(f"low-{i}", cpu_milli=1000, memory=2**27,
                                   labels={"applicationId": "app-low"},
                                   scheduler_name="yunikorn", priority=0))
               for i in range(2)]
        for p in low:
            ms.wait_for_task_state("app-low", p.uid, task_mod.BOUND)
        # node is full; high-priority pod arrives
        high = ms.add_pod(make_pod("high", cpu_milli=1000, memory=2**27,
                                   labels={"applicationId": "app-high"},
                                   scheduler_name="yunikorn", priority=100))
        # a low-priority pod gets evicted and the high pod binds
        ms.wait_for_task_state("app-high", high.uid, task_mod.BOUND, timeout=20)
        assert ms.get_pod_assignment(high) == "n1"
        remaining_low = [p for p in low if ms.cluster.get_pod(p.uid) is not None]
        assert len(remaining_low) == 1  # exactly one victim evicted
    finally:
        ms.stop()
