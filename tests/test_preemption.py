"""Preemption tests: victim-subset search (startIndex contract), planner
selection, and the end-to-end evict→reschedule flow (reference e2e suites:
preemption / simple_preemptor / priority_scheduling).
"""
import time

import pytest

from yunikorn_tpu.cache.external.scheduler_cache import SchedulerCache
from yunikorn_tpu.cache import task as task_mod
from yunikorn_tpu.common import constants
from yunikorn_tpu.common.objects import ObjectMeta, PriorityClass, make_node, make_pod
from yunikorn_tpu.common.si import PreemptionPredicatesArgs
from yunikorn_tpu.core.preemption import plan_preemptions
from yunikorn_tpu.common.resource import get_pod_resource
from yunikorn_tpu.common.si import AllocationAsk
from yunikorn_tpu.ops.preempt import preemption_victim_search
from yunikorn_tpu.shim.mock_scheduler import MockScheduler


def setup_node_with_victims():
    cache = SchedulerCache()
    cache.update_node(make_node("n1", cpu_milli=4000, memory=8 * 2**30))
    victims = []
    for i in range(4):
        v = make_pod(f"victim-{i}", cpu_milli=1000, node_name="n1",
                     phase="Running", priority=i)
        cache.update_pod(v)
        victims.append(v)
    return cache, victims


def test_victim_search_returns_first_fitting_index():
    cache, victims = setup_node_with_victims()
    # node full (4x1000m); pod needs 2000m → 2 victims must go
    pod = make_pod("preemptor", cpu_milli=2000, priority=100)
    cache.update_pod(pod)
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=0))
    assert resp.success and resp.index == 1  # removing victims[0..1] fits


def test_victim_search_start_index_contract():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=3000, priority=100)
    cache.update_pod(pod)
    # startIndex=2: victims 0,1 removed unconditionally, then one at a time
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=2))
    assert resp.success and resp.index == 2


def test_victim_search_duplicate_keys_no_double_count():
    """A key appearing twice in preempt_allocation_keys must not re-add the
    victim's resources (the free.add is guarded on ACTUAL removal): the
    duplicate frees nothing, so a pod needing more than the real evictions
    provide must not be reported as fitting."""
    cache, victims = setup_node_with_victims()
    # node full (4x1000m); pod needs 3000m. Keys list the SAME two victims
    # twice: only 2000m can actually free — success would double-count.
    pod = make_pod("dup-preemptor", cpu_milli=3000, priority=100)
    cache.update_pod(pod)
    keys = [victims[0].uid, victims[1].uid, victims[0].uid, victims[1].uid]
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=keys, start_index=0))
    assert not resp.success and resp.index == -1
    # duplicates across the start_index boundary double-count the same way
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=keys, start_index=2))
    assert not resp.success and resp.index == -1
    # sanity: with three DISTINCT victims the same pod does fit
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims[:3]], start_index=0))
    assert resp.success and resp.index == 2


def test_victim_search_foreign_node_key_frees_nothing():
    """A key resolving to a pod on a DIFFERENT node (cache fallback lookup)
    must not credit that pod's resources to this node."""
    cache, victims = setup_node_with_victims()
    cache.update_node(make_node("n2", cpu_milli=4000, memory=8 * 2**30))
    elsewhere = make_pod("other-node-pod", cpu_milli=4000, node_name="n2",
                         phase="Running", priority=0)
    cache.update_pod(elsewhere)
    pod = make_pod("xn-preemptor", cpu_milli=3000, priority=100)
    cache.update_pod(pod)
    # the foreign pod's 4000m would "fit" the ask if it were credited
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[elsewhere.uid, victims[0].uid],
        start_index=0))
    assert not resp.success


def test_victim_search_no_fit():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=16000, priority=100)
    cache.update_pod(pod)
    resp = preemption_victim_search(cache, PreemptionPredicatesArgs(
        allocation_key=pod.uid, node_id="n1",
        preempt_allocation_keys=[v.uid for v in victims], start_index=0))
    assert not resp.success and resp.index == -1


def test_planner_picks_cheapest_victims():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod),
                        priority=100, pod=pod)
    app_of_pod = {v.uid: "app-lo" for v in victims}
    plans, _ = plan_preemptions(cache, [ask], app_of_pod)
    assert len(plans) == 1
    assert plans[0].node_id == "n1"
    # exactly one victim, the lowest priority one (priority 0)
    assert [v.uid for v in plans[0].victims] == [victims[0].uid]


def test_planner_respects_allow_preemption_annotation():
    cache, victims = setup_node_with_victims()
    # protect the two lowest-priority victims via PriorityClass opt-out
    pc = PriorityClass(metadata=ObjectMeta(
        name="protected", annotations={constants.ANNOTATION_ALLOW_PREEMPTION: "false"}))
    cache.update_priority_class(pc)
    for v in victims[:2]:
        v.spec.priority_class_name = "protected"
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {v.uid: "app-lo" for v in victims})
    assert len(plans) == 1
    assert plans[0].victims[0].uid == victims[2].uid  # cheapest unprotected


def test_planner_preemptor_never_policy():
    cache, victims = setup_node_with_victims()
    pod = make_pod("pacifist", cpu_milli=1000, priority=100)
    pod.spec.preemption_policy = "Never"
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {v.uid: "app-lo" for v in victims})
    assert plans == []


def test_planner_ignores_foreign_pods():
    cache, victims = setup_node_with_victims()
    pod = make_pod("preemptor", cpu_milli=1000, priority=100)
    cache.update_pod(pod)
    ask = AllocationAsk(pod.uid, "app-hi", get_pod_resource(pod), priority=100, pod=pod)
    plans, _ = plan_preemptions(cache, [ask], {})  # no yunikorn-managed victims
    assert plans == []


# ---------------------------------------------------------------------------
# End-to-end
# ---------------------------------------------------------------------------

def test_preemption_e2e_evicts_and_reschedules():
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        ms.add_node(make_node("n1", cpu_milli=2000, memory=4 * 2**30))
        low = [ms.add_pod(make_pod(f"low-{i}", cpu_milli=1000, memory=2**27,
                                   labels={"applicationId": "app-low"},
                                   scheduler_name="yunikorn", priority=0))
               for i in range(2)]
        for p in low:
            ms.wait_for_task_state("app-low", p.uid, task_mod.BOUND)
        # node is full; high-priority pod arrives
        high = ms.add_pod(make_pod("high", cpu_milli=1000, memory=2**27,
                                   labels={"applicationId": "app-high"},
                                   scheduler_name="yunikorn", priority=100))
        # a low-priority pod gets evicted and the high pod binds
        ms.wait_for_task_state("app-high", high.uid, task_mod.BOUND, timeout=20)
        assert ms.get_pod_assignment(high) == "n1"
        remaining_low = [p for p in low if ms.cluster.get_pod(p.uid) is not None]
        assert len(remaining_low) == 1  # exactly one victim evicted
    finally:
        ms.stop()


# ---------------------------------------------------------------------------
# Planner breadth: cooldown, disjoint victims, priority fences, overlay
# ---------------------------------------------------------------------------

def hi_ask(cache, key, cpu=2000, priority=100):
    pod = make_pod(key, cpu_milli=cpu, priority=priority)
    cache.update_pod(pod)          # victim search resolves the pod via cache
    return AllocationAsk(pod.uid, "hi-app", get_pod_resource(pod),
                         priority=priority, pod=pod)


def test_planner_two_asks_get_disjoint_victims():
    """Two preempting asks in one cycle must not claim the same victim."""
    cache = SchedulerCache()
    for n in ("pa", "pb"):
        cache.update_node(make_node(n, cpu_milli=4000, memory=8 * 2**30))
    app_of_pod = {}
    for n in ("pa", "pb"):
        for i in range(2):
            v = make_pod(f"{n}-v{i}", cpu_milli=2000, node_name=n,
                         phase="Running", priority=0)
            cache.update_pod(v)
            app_of_pod[v.uid] = "victim-app"
    plans, attempted = plan_preemptions(
        cache, [hi_ask(cache, "h1"), hi_ask(cache, "h2")], app_of_pod)
    assert len(plans) == 2 and len(attempted) == 2
    sets = [{v.uid for v in p.victims} for p in plans]
    assert not (sets[0] & sets[1])


def test_planner_equal_priority_never_preempted():
    """Victims at the SAME priority as the ask are fenced off — preemption
    only flows strictly downhill."""
    cache = SchedulerCache()
    cache.update_node(make_node("eq", cpu_milli=2000, memory=8 * 2**30))
    v = make_pod("peer", cpu_milli=2000, node_name="eq", phase="Running",
                 priority=100)
    cache.update_pod(v)
    plans, _ = plan_preemptions(cache, [hi_ask(cache, "h1", priority=100)],
                                {v.uid: "victim-app"})
    assert plans == []


def test_planner_inflight_overlay_blocks_eviction():
    """Capacity already committed this cycle (inflight overlay) must not be
    double-counted as freed by eviction: victims whose removal still leaves
    the ask unfit are not planned."""
    from yunikorn_tpu.common.resource import ResourceBuilder

    cache = SchedulerCache()
    cache.update_node(make_node("ov", cpu_milli=4000, memory=8 * 2**30))
    v = make_pod("small-victim", cpu_milli=1000, node_name="ov",
                 phase="Running", priority=0)
    cache.update_pod(v)
    app_of_pod = {v.uid: "victim-app"}
    # without overlay: evicting the 1000m victim frees enough for 2000m
    plans, _ = plan_preemptions(cache, [hi_ask(cache, "h1", cpu=2000)], app_of_pod)
    assert len(plans) == 1
    # with 3000m inflight on the node, eviction can never make 2000m fit
    overlay = {"ov": ResourceBuilder().cpu(3000).build()}
    blocked = hi_ask(cache, "h2", cpu=2000)
    plans, attempted = plan_preemptions(cache, [blocked],
                                        app_of_pod, inflight_by_node=overlay)
    assert plans == []
    assert attempted == [blocked.allocation_key]   # still reported for cooldown


def test_preemption_cooldown_prevents_rescan(sched_factory=None):
    """A failed preemption attempt puts the ask on cooldown: the next cycles
    must not rescan the cluster for it (core _preempted_for gate)."""
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        ms.add_node(make_node("cd0", cpu_milli=1000))
        # an unplaceable high-priority pod (too big for the cluster)
        big = make_pod("big", cpu_milli=4000, priority=100,
                       labels={constants.LABEL_APPLICATION_ID: "cd-app"},
                       scheduler_name=constants.SCHEDULER_NAME)
        ms.add_pod(big)
        deadline = time.time() + 10
        while time.time() < deadline and "big" not in ms.core._preempted_for:
            time.sleep(0.05)
        assert any(k.startswith("big") or "big" in k
                   for k in ms.core._preempted_for), "attempt not recorded"
        stamp = dict(ms.core._preempted_for)
        time.sleep(1.0)                  # several scheduling cycles
        # cooldown entry unchanged: no rescan re-stamped it
        for k, ts in stamp.items():
            assert ms.core._preempted_for.get(k) == ts
    finally:
        ms.stop()


def test_victims_released_with_accounting_intact():
    """E2E: after eviction + reschedule, queue accounting matches live
    allocations (release path + preemption interplay)."""
    ms = MockScheduler()
    ms.init("")
    ms.start()
    try:
        ms.add_node(make_node("acct", cpu_milli=4000, memory=8 * 2**30))
        low = [make_pod(f"low-{i}", cpu_milli=2000, priority=0,
                        labels={constants.LABEL_APPLICATION_ID: "low-app"},
                        scheduler_name=constants.SCHEDULER_NAME)
               for i in range(2)]
        for p in low:
            ms.add_pod(p)
        for p in low:
            ms.wait_for_task_state("low-app", p.uid, task_mod.BOUND, timeout=15)
        hi = make_pod("hi", cpu_milli=3000, priority=1000,
                      labels={constants.LABEL_APPLICATION_ID: "hi-app"},
                      scheduler_name=constants.SCHEDULER_NAME)
        ms.add_pod(hi)
        ms.wait_for_task_state("hi-app", hi.uid, task_mod.BOUND, timeout=30)
        time.sleep(0.5)
        total = {}
        for app in ms.core.partition.applications.values():
            for alloc in app.allocations.values():
                for k, v in alloc.resource.resources.items():
                    total[k] = total.get(k, 0) + v
        root = ms.core.queues.root
        for k in set(total) | set(root.allocated.resources):
            assert root.allocated.get(k) == total.get(k, 0), (
                k, root.allocated.get(k), total.get(k, 0))
    finally:
        ms.stop()
